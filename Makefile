# Convenience entry points. The pytest gates (tests/test_graftlint.py,
# tests/test_traceview.py) are the source of truth; `make lint` / `make
# obs` are the same checks, standalone.

PY ?= python
# Trace under inspection: defaults to the checked-in fixture so the obs
# gate is self-contained; point TRACE at a profiler log dir (e.g.
# `train_ppo --profile-dir`) to summarize/check a real run.
TRACE ?= tests/fixtures/traceview/fixture.trace.json.gz

.PHONY: lint lint-json lint-sarif test tier1 trace-summary obs chaos chaos-soak \
        serve-pool serve-soak rollout-drill eval-matrix scenario-bench \
        study study-list overlap-bench serve-report slo-check span-ab \
        fastpath-ab front-ab loop-drill loop-soak transfer-grid \
        mixture-smoke fleet-drill fleet-soak drift-report drift-drill \
        drift-soak daemon-drill daemon-soak

# Exit codes (all lint targets): 0 clean, 1 findings (or stale
# suppressions under --audit-suppressions), 2 usage/config error.
# `lint` runs the suppression audit too — a disable comment whose rule
# no longer fires is a gate failure, same as a finding.
lint:
	$(PY) -m tools.graftlint --check --audit-suppressions

lint-json:
	$(PY) -m tools.graftlint --check --json

# SARIF 2.1.0 artifact for CI annotators (GitHub code scanning et al).
lint-sarif:
	$(PY) -m tools.graftlint --check --audit-suppressions --sarif graftlint.sarif

trace-summary:
	$(PY) -m tools.traceview $(TRACE)

# lint's observability neighbor: phase budgets enforced the same way
# graftlint findings are (exit nonzero on a >tolerance regression).
obs:
	$(PY) -m tools.traceview --check --budgets tools/traceview/budgets.json $(TRACE)

test:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m 'not slow'

tier1: test

# graftguard chaos gate: the fault-injection suite (seeded FaultPlan
# attacks on every host-I/O boundary — checkpoint writes, scrapes, kube
# API, backend, preemption; docs/robustness.md). `chaos` is the fast
# deterministic gate; `chaos-soak` adds the long rate-based soak runs.
chaos:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_graftguard.py -q -m 'not slow'

chaos-soak:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_graftguard.py -q

# graftserve (docs/serving.md): run the multi-worker pool locally —
# WORKERS extender processes share PORT via SO_REUSEPORT behind a
# supervisor whose aggregated /stats + /metrics live on PORT+1. Point
# RUN at a checkpoint dir to serve a trained policy (default:
# auto-discover, greedy fallback).
WORKERS ?= 2
PORT ?= 8787
RUN ?=
serve-pool:
	$(PY) -m rl_scheduler_tpu.scheduler.extender --workers $(WORKERS) \
		--port $(PORT) $(if $(RUN),--run $(RUN))

# The pool soak gate: slow-marked tests driving the bench's --duration
# mode through a live pool (tests/test_pool.py), next to `make chaos`.
serve-soak:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_pool.py -q

# graftroll rollout drill (docs/serving.md), container-safe: a 2-worker
# pool absorbs a good promote (canary-gated rolling restart, all workers
# land the new generation), refuses a deliberately corrupted candidate
# at manifest verification, and auto-rolls-back a verifies-clean-but-
# regressing one — plus the bench-driven soak variant where both drills
# land mid-soak with zero failed requests and the durable trace log
# replaying every decision.
rollout-drill:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_pool.py -q -k rollout_drill

# graftloop drill (docs/serving.md "closing the loop"), container-safe
# and in tier-1: a 2-worker pool serves bench traffic continuously while
# one loop iteration snapshots the live trace, compiles the trace_replay
# scenario (round-trip pinned), retrains from the incumbent, wins the
# paired-seed verdict, and hot-promotes through the canary gates with
# zero failed requests — including a SIGKILLed loop resuming from its
# ledger, a regressing candidate rolling back, and the refusal paths.
# `loop-soak` adds the slow in-process retrain+verdict pass.
loop-drill:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_loopback.py -q \
		-m 'not slow' -k loop_drill

loop-soak:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_loopback.py -q

# graftpilot (docs/serving.md#graftpilot): the unattended drift-
# triggered retrain daemon drill, container-safe and in tier-1 — a
# 2-worker drift-armed pool serves bench traffic while the price regime
# flips mid-soak; the daemon detects the drift off /stats (driftview's
# own grading), confirms it across consecutive polls, retrains through
# graftloop, passes the LIVE shadow sign-test gate, and hot-promotes
# generation 0→1 with zero failed requests — SIGKILLed once
# mid-iteration and resuming its ledger byte-prefix-exact, while the
# stationary control records only no_drift decisions and provably never
# retrains. `daemon-soak` adds the slow kill-matrix/hysteresis passes.
daemon-drill:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_graftpilot.py -q \
		-m 'not slow' -k daemon_drill

daemon-soak:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_graftpilot.py -q

# graftfleet (docs/serving.md#graftfleet): the ROADMAP item-1 drill —
# a 3-pool fleet under continuous multi-target bench traffic where a
# fleet promote canaries, rolls pool by pool, and (with an injected
# regression) aborts and reverts every rolled pool, with zero failed
# requests in every phase, fleet-merged gauges pinned == the union of
# the pool scrapes, and a SIGKILLed fleet promote resuming its ledger
# byte-prefix-exact. `fleet-soak` adds the slow pass that retrains one
# graftloop iteration from the fleet-wide trace union.
fleet-drill:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_graftfleet.py -q \
		-m 'not slow' -k fleet_drill

fleet-soak:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_graftfleet.py -q

# graftlens (docs/observability.md): the serving perf report with
# regression gating — phase decomposition, per-generation latency, SLO
# attainment, budget + bench-history gates (exit 2 on a violation).
# Defaults to the checked-in fixture so the gate is self-contained
# off-network; point SERVE_STATS at a live pool's control plane
# (`make serve-report SERVE_STATS=http://127.0.0.1:8788/stats
# SERVE_TRACE=/var/trace SERVE_BENCH=BENCH_serving.jsonl`).
SERVE_STATS ?= tests/fixtures/decisionview/stats.json
SERVE_TRACE ?= tests/fixtures/decisionview/trace
SERVE_BENCH ?= tests/fixtures/decisionview/bench.jsonl
serve-report:
	$(PY) -m tools.decisionview --stats $(SERVE_STATS) \
		--trace $(SERVE_TRACE) --bench $(SERVE_BENCH) \
		--check --budgets tools/decisionview/budgets.json --check-history

# The SLO gate alone: exit 2 while any objective burns (wire it at the
# end of a soak/drill; serves the fixture off-network by default).
slo-check:
	$(PY) -m tools.decisionview --stats $(SERVE_STATS) --slo-check

# graftdrift (docs/observability.md §5): the distribution-shift report
# with retrain-trigger gating — per-stream PSI/KS vs the frozen
# reference, drifting verdicts (burn semantics), reference lineage,
# shadow agreement. Defaults to the checked-in fixture so the gate is
# self-contained off-network; point DRIFT_STATS at a live pool
# (`make drift-report DRIFT_STATS=http://127.0.0.1:8788/stats
# DRIFT_REF=/var/drift/reference.json`).
DRIFT_STATS ?= tests/fixtures/driftview/stats.json
DRIFT_REF ?= tests/fixtures/driftview/reference.json
drift-report:
	$(PY) -m tools.driftview --stats $(DRIFT_STATS) \
		--reference $(DRIFT_REF) \
		--check --budgets tools/driftview/budgets.json

# The graftdrift drill (tier-1, docs/serving.md): a drift-armed pool
# soaked by the bench, mid-soak regime flip (--flip-at swaps the
# price-replay tables) flips *_drifting within the short window and
# `driftview --check` exits 2, while the stationary control soak never
# flips it — with shadow scoring running concurrently at bitwise-zero
# effect on served decisions. `drift-soak` adds the slow passes.
drift-drill:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_graftdrift.py -q \
		-m 'not slow' -k drift_drill

drift-soak:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_graftdrift.py -q

# graftlens span-overhead A/B (docs/serving.md acceptance: spans-on
# within 2% of spans-off req/s and p50 at 8-way N=1024, interleaved).
SPAN_NODES ?= 1024
SPAN_ROUNDS ?= 2
SPAN_DURATION ?= 10
span-ab:
	JAX_PLATFORMS=cpu $(PY) loadgen/span_ab.py --nodes $(SPAN_NODES) \
		--threads 8 --workers 2 --rounds $(SPAN_ROUNDS) \
		--duration $(SPAN_DURATION)

# graftfwd lever matrix (docs/serving.md): off/batch/int8/cache/all,
# interleaved pools at the ROADMAP-item-2 regime, one ledger line per
# lever (BENCH_serving.jsonl; `make serve-report` gates the rows).
FP_NODES ?= 1024
FP_ROUNDS ?= 2
FP_DURATION ?= 15
FP_LEVERS ?= off,batch,int8,cache,all
fastpath-ab:
	JAX_PLATFORMS=cpu $(PY) loadgen/extender_bench.py \
		--levers $(FP_LEVERS) --nodes $(FP_NODES) --threads 8 \
		--workers 2 --rounds $(FP_ROUNDS) --duration $(FP_DURATION) \
		--history BENCH_serving.jsonl

# graftfront A/B (docs/serving.md): threading vs asyncio data-plane
# fronts, interleaved pools on the cache lever, keep-alive compact-wire
# traffic at each FRONT_THREADS concurrency; one ledger line per
# (front x concurrency), then the history gate judges the new rows
# against their own (front, keepalive) shapes.
FRONT_NODES ?= 1024
FRONT_ROUNDS ?= 2
FRONT_DURATION ?= 10
FRONT_THREADS ?= 8,64
FRONTS ?= threading,asyncio
front-ab:
	JAX_PLATFORMS=cpu $(PY) loadgen/extender_bench.py \
		--fronts $(FRONTS) --front-threads $(FRONT_THREADS) \
		--nodes $(FRONT_NODES) --workers 2 \
		--rounds $(FRONT_ROUNDS) --duration $(FRONT_DURATION) \
		--history BENCH_serving.jsonl
	$(PY) -m tools.decisionview --bench BENCH_serving.jsonl \
		--check-history

# graftscenario (docs/scenarios.md): the scenario x policy-family eval
# matrix — one schema_version-tagged JSON line per cell to
# results/scenario_matrix.jsonl + a summary grid. EPISODES sizes each
# cell; point RUN at a cluster_set checkpoint to add it as a policy
# column (MATRIX_ARGS for anything else, e.g. --best / --matrix-nodes).
EPISODES ?= 32
eval-matrix:
	JAX_PLATFORMS=cpu $(PY) -m rl_scheduler_tpu.agent.evaluate --matrix \
		--episodes $(EPISODES) $(if $(RUN),--run $(RUN)) $(MATRIX_ARGS)

# graftstudy (docs/studies.md): resumable (seed x variant) studies with
# statistical verdicts. STUDY names a protocol from studies/presets.py;
# the fleet64 anti-latch sweep (ROADMAP 3b) is the chip one-command:
#   make study STUDY=fleet64_antilatch JOBS=1
# JOBS>1 forks BLAS-pinned worker processes (CPU hosts only — on a chip
# trials share the accelerator, keep JOBS=1). Re-running resumes from
# the study ledger.
STUDY ?= study_smoke
JOBS ?= 1
study:
	$(PY) -m rl_scheduler_tpu.studies --study $(STUDY) --jobs $(JOBS)

study-list:
	$(PY) -m rl_scheduler_tpu.studies --list

# graftmix (docs/scenarios.md): the zero-shot transfer grid — the RUN
# checkpoint (a mixture-trained generalist) vs each per-family
# specialist (or the best paired baseline) across scenarios x node
# counts, one graftstudy Wilson/sign-test verdict per cell. Point RUN
# at the generalist; GRID_ARGS for specialists/seeds, e.g.
#   make transfer-grid RUN=runs/GENERALIST \
#     GRID_ARGS='--specialist churn=runs/CHURN --grid-nodes 8,16'
GRID_NODES ?= 8,16
transfer-grid:
	JAX_PLATFORMS=cpu $(PY) -m rl_scheduler_tpu.agent.evaluate \
		--transfer-grid $(if $(RUN),--run $(RUN)) \
		--grid-nodes $(GRID_NODES) $(GRID_ARGS)

# The graftmix drill (tier-1): a mixture smoke checkpoint trains through
# the real CLI, the full transfer grid renders with verdicts engaged,
# and provenance round-trips meta -> resume guards -> serving
# conformance (tests/test_mixtures.py).
mixture-smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_mixtures.py -q \
		-m 'not slow' -k mixture_smoke

# Scenario throughput A/B vs the CSV replay (training path + env-step
# microbench; BLAS pinned — the container's 2-thread default is measured
# slower AND noisier for perf A/Bs).
scenario-bench:
	OPENBLAS_NUM_THREADS=1 OMP_NUM_THREADS=1 JAX_PLATFORMS=cpu \
		$(PY) bench.py --scenario-bench

# graftpipe CPU A/B (docs/roofline.md): baseline vs pipelined-collect vs
# fused-prologue vs both, interleaved fetch-synced windows with the
# per-variant intercept decomposition, BLAS pinned (graftserve finding:
# the 2-thread default is slower AND noisier). The measured container
# line is checked in as BENCH_overlap_cpu.json; the chip decomposition
# is the one-command recipe in docs/roofline.md.
overlap-bench:
	OPENBLAS_NUM_THREADS=1 OMP_NUM_THREADS=1 JAX_PLATFORMS=cpu \
		$(PY) bench.py --overlap-bench
