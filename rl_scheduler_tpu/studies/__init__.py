"""graftstudy — resumable seed studies & intervention sweeps with
statistical verdicts (docs/studies.md).

A :class:`StudySpec` compiles a frozen ``(variant x seed)`` protocol
into a deterministic trial list; :class:`StudyRunner` executes it over
real training runs (resumable through the atomic :class:`StudyLedger`);
``analysis`` turns the ledger into Wilson-interval failure rates,
paired-seed deltas vs control, and an acceptance verdict.

CLI: ``python -m rl_scheduler_tpu.studies --study fleet64_antilatch``.
"""

from rl_scheduler_tpu.studies.analysis import (
    analyze_study,
    render_grid,
    sign_test_pvalue,
    summary_json_line,
    wilson_interval,
)
from rl_scheduler_tpu.studies.ledger import (
    LedgerMismatch,
    StudyLedger,
    load_spec,
)
from rl_scheduler_tpu.studies.presets import STUDIES, get_study, list_studies
from rl_scheduler_tpu.studies.runner import (
    StudyRunner,
    acquire_runner_lock,
    atomic_write_json,
    build_trial_config,
    configure_jax_cache,
    limit_blas_threads,
    run_trial,
    write_result,
)
from rl_scheduler_tpu.studies.spec import (
    OVERLAY_KEYS,
    StudySpec,
    TrialSpec,
    overlay,
    parse_seeds,
    spec_from_json,
)

__all__ = [
    "OVERLAY_KEYS", "STUDIES", "LedgerMismatch", "StudyLedger",
    "StudyRunner", "StudySpec", "TrialSpec", "acquire_runner_lock",
    "analyze_study",
    "atomic_write_json", "build_trial_config", "configure_jax_cache",
    "get_study", "limit_blas_threads", "list_studies", "load_spec",
    "overlay", "parse_seeds",
    "render_grid", "run_trial", "sign_test_pvalue", "spec_from_json",
    "summary_json_line", "wilson_interval", "write_result",
]
