"""Named graftstudy protocols — the chip harvest is one command each.

``fleet64_antilatch`` is the ROADMAP item 3(b) instrument: control vs
the three measured root-cause attempts (sampling-temperature annealing,
the argmax-concentration penalty, domain randomization) at the full
9-seed evidence standard, judged against the <20% failure-rate bar.
The ``*_seeds9`` studies raise the thin regimes item 3(c) names to the
same standard. ``study_smoke`` is the tier-1 gate: 2 seeds x 2 variants
on a preset tiny enough for container CPU.

Run: ``python -m rl_scheduler_tpu.studies --study <name>`` (or
``make study STUDY=<name>``). On a chip keep ``--jobs 1`` — trials
share the accelerator; the multi-process fold is for CPU hosts.
"""

from __future__ import annotations

from rl_scheduler_tpu.studies.spec import StudySpec, overlay

NINE_SEEDS = tuple(range(9))

STUDIES = {
    # The anti-latch intervention sweep (ROADMAP 3b): each variant is one
    # measured attempt at the root cause the rollout diagnostic pinned
    # (argmax latched onto static node premiums, docs/scaling.md §1b).
    "fleet64_antilatch": StudySpec(
        name="fleet64_antilatch",
        env="cluster_set", preset="set_fleet64", num_nodes=64,
        seeds=NINE_SEEDS, iterations=80,
        eval_every=8, eval_episodes=64, final_eval_episodes=100,
        stall_deadline=16, target_failure_rate=0.20,
        variants=(
            ("control", ()),
            # Anneal sampling toward determinism over the run: training
            # reward starts seeing what the argmax does instead of
            # collecting the spread bonus from near-uniform sampling.
            ("anneal", overlay(sample_temp_anneal=0.5)),
            # Differentiable penalty on the batch-pooled soft-argmax
            # collision probability (ops/losses.argmax_concentration).
            ("argmax_penalty", overlay(argmax_penalty=0.05)),
            # Domain randomization over node_jitter/drain/overload +
            # random table phase (scenario 'randomized'): no static
            # premium left to latch onto.
            ("randomized", overlay(scenario="randomized")),
        ),
    ),
    # Item 3(c): thin regimes raised to the 9-seed evidence standard.
    "fleet256_seeds9": StudySpec(
        name="fleet256_seeds9",
        env="cluster_set", preset="set_fleet256", num_nodes=256,
        seeds=NINE_SEEDS, iterations=80,
        eval_every=8, eval_episodes=64, final_eval_episodes=100,
        stall_deadline=16, target_failure_rate=0.20,
    ),
    "graph_seeds9": StudySpec(
        name="graph_seeds9",
        env="cluster_graph", preset="set_fleet64", num_nodes=64,
        seeds=NINE_SEEDS, iterations=80,
        eval_every=8, eval_episodes=64, final_eval_episodes=100,
        stall_deadline=16, target_failure_rate=0.20,
    ),
    # The flash-attention fleet-giant regime had ONE recorded seed.
    # Smaller env fold + fewer final episodes: each trial is a N=1024
    # memory-wall run (docs/scaling.md §3).
    "flash1024_seeds9": StudySpec(
        name="flash1024_seeds9",
        env="cluster_set", preset="set_fleet256", num_nodes=1024,
        seeds=NINE_SEEDS, iterations=80,
        eval_every=8, eval_episodes=32, final_eval_episodes=64,
        stall_deadline=16, target_failure_rate=0.20,
        base_overlay=overlay(flash_attn=True, num_envs=64,
                             minibatch_size=800),
    ),
    # Tier-1 smoke: the full machinery (spec -> trials -> runner ->
    # ledger -> verdicts) on a seconds-scale config. 2 seeds x 2
    # variants, 2 iterations, eval every iteration.
    "study_smoke": StudySpec(
        name="study_smoke",
        env="cluster_set", preset="quick", num_nodes=4,
        seeds=(0, 1), iterations=2,
        eval_every=1, eval_episodes=4, final_eval_episodes=8,
        stall_deadline=1,
        variants=(
            ("control", ()),
            ("anneal", overlay(sample_temp_anneal=0.5)),
        ),
        base_overlay=overlay(num_envs=8, rollout_steps=8,
                             minibatch_size=64, num_epochs=1),
    ),
}


def get_study(name: str) -> StudySpec:
    if name not in STUDIES:
        raise ValueError(
            f"unknown study {name!r}; registered: {sorted(STUDIES)}")
    return STUDIES[name]


def list_studies() -> list:
    return sorted(STUDIES)
