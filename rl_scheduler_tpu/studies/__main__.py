"""graftstudy CLI: run a named study to a complete, analyzed ledger.

Usage::

    python -m rl_scheduler_tpu.studies --list
    python -m rl_scheduler_tpu.studies --study study_smoke --jobs 2
    python -m rl_scheduler_tpu.studies --study fleet64_antilatch   # chip

Resume is automatic: re-running the same command continues from the
study dir's ledger (completed trials skipped, the in-flight one
restarted). ``--fresh`` wipes the study dir first. The final summary is
printed as the human grid AND one ``schema_version``-tagged JSON line
(driver-tracked, bench.py convention), and written to
``<study_dir>/summary.json``.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import shutil
import sys
from pathlib import Path

# Runnable from a source checkout without an install, like bench.py.
sys.path.insert(0, str(Path(__file__).resolve().parents[2]))


def main(argv: list | None = None) -> dict | None:
    from rl_scheduler_tpu.config import RuntimeConfig
    from rl_scheduler_tpu.studies import (
        StudyRunner,
        analyze_study,
        get_study,
        list_studies,
        parse_seeds,
        render_grid,
        summary_json_line,
    )

    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--study", default=None,
                   help=f"named study protocol ({', '.join(list_studies())})")
    p.add_argument("--list", action="store_true",
                   help="list the registered studies and exit")
    p.add_argument("--study-root",
                   default=str(Path(RuntimeConfig().checkpoint_dir)
                               / "studies"),
                   help="parent dir; the study runs (and resumes) under "
                        "<root>/<study-name>")
    p.add_argument("--jobs", type=int, default=1,
                   help="concurrent trial worker processes (each trial is "
                        "one fresh process, BLAS pinned to cores/jobs). "
                        "0 runs trials sequentially IN-process. On a chip "
                        "keep 1: trials share the accelerator")
    p.add_argument("--blas-threads", type=int, default=None,
                   help="BLAS threads per worker (default cores//jobs; "
                        "the graftserve oversubscription finding, "
                        "docs/serving.md)")
    p.add_argument("--seeds", default=None,
                   help="override the study's seed set (e.g. 0-8 or "
                        "0,2,7) — a DIFFERENT protocol, so a different "
                        "ledger fingerprint")
    p.add_argument("--iterations", type=int, default=None,
                   help="override the study's per-trial iteration count "
                        "(different protocol -> different fingerprint)")
    p.add_argument("--fresh", action="store_true",
                   help="wipe the study dir first instead of resuming")
    p.add_argument("--dry-run", action="store_true",
                   help="print the compiled trial list and exit (no "
                        "training, no ledger)")
    args = p.parse_args(argv)

    if args.list:
        for name in list_studies():
            spec = get_study(name)
            print(f"{name}: {spec.env} N={spec.num_nodes} preset="
                  f"{spec.preset}, {len(spec.seeds)} seeds x "
                  f"{len(spec.variants)} variants x {spec.iterations} iters")
        return None
    if args.study is None:
        raise SystemExit("pass --study <name> (or --list)")
    try:
        spec = get_study(args.study)
    except ValueError as e:
        raise SystemExit(str(e))
    if args.seeds is not None:
        spec = dataclasses.replace(spec, seeds=tuple(parse_seeds(args.seeds)))
    if args.iterations is not None:
        spec = dataclasses.replace(spec, iterations=args.iterations)

    if args.dry_run:
        for t in spec.trials():
            print(json.dumps({"trial_id": t.trial_id, "variant": t.variant,
                              "seed": t.seed, "overlay": t.overlay},
                             sort_keys=True))
        return None

    if args.jobs == 0:
        # In-process trials recompile the same tiny programs per trial;
        # the shared persistent cache pays each compile once per STUDY
        # (workers configure their own copy, studies/worker.py).
        from rl_scheduler_tpu.studies.runner import configure_jax_cache

        configure_jax_cache()

    dir_name = spec.name
    if args.seeds is not None or args.iterations is not None:
        # An overridden protocol is a DIFFERENT study: give it its own
        # dir keyed by fingerprint, so a quick --seeds 0-2 check can
        # never LedgerMismatch against (and --fresh can never destroy)
        # the canonical completed study's ledger.
        dir_name = f"{spec.name}-{spec.fingerprint()[:8]}"
        print(f"# overridden protocol -> study dir {dir_name}")
    study_dir = Path(args.study_root) / dir_name
    if args.fresh and study_dir.exists():
        # Never rmtree a LIVE study out from under its runner: HOLD the
        # single-writer lock while deleting (check-then-rmtree would
        # leave a window for a runner to start and lose its ledger).
        from rl_scheduler_tpu.studies.runner import acquire_runner_lock

        try:
            acquire_runner_lock(study_dir)
        except RuntimeError as e:
            raise SystemExit(f"--fresh: {e} (deleting a live study's dir "
                             "would corrupt it)")
        shutil.rmtree(study_dir)  # takes the held lock down with it
    runner = StudyRunner(spec, study_dir, jobs=args.jobs,
                         blas_threads=args.blas_threads)
    print(f"# study {spec.name}: {len(spec.trials())} trials "
          f"({len(spec.variants)} variants x {len(spec.seeds)} seeds), "
          f"jobs={args.jobs}, ledger {runner.ledger.path}")
    records = runner.run()

    summary = analyze_study(spec, records)
    from rl_scheduler_tpu.studies.runner import atomic_write_json

    atomic_write_json(study_dir / "summary.json", summary, indent=1)
    print(render_grid(summary))
    print(summary_json_line(summary))
    return summary


if __name__ == "__main__":
    main()
