"""The graftstudy ledger: an atomic, append-only JSONL study journal.

One file per study dir (``ledger.jsonl``): a header line binding the
ledger to its spec fingerprint, then one line per finished trial. Every
append rewrites the file **tmp-then-rename** (the graftguard manifest
discipline, ``utils/checkpoint.py``): the prior bytes are carried over
verbatim and ``os.replace`` is atomic, so a SIGKILL at any instant
leaves either the old complete ledger or the new complete ledger —
never a torn line. That is what makes resume exact: completed-trial
entries survive a mid-study kill **bitwise** (chaos-pinned,
``tests/test_graftguard.py``), and the runner re-executes only trials
with no ledger line.

Records are serialized with sorted keys so a record's bytes are a pure
function of its content — the bitwise-resume contract does not depend
on dict insertion order across processes.
"""

from __future__ import annotations

import json
import os
from pathlib import Path

from rl_scheduler_tpu.studies.spec import StudySpec, spec_from_json

LEDGER_SCHEMA_VERSION = 1
LEDGER_NAME = "ledger.jsonl"


class LedgerMismatch(RuntimeError):
    """The study dir's ledger was written under a DIFFERENT spec
    fingerprint: continuing would silently mix two protocols' trials
    into one statistics table. Start a fresh study dir (or ``--fresh``)."""


def _dumps(record: dict) -> str:
    return json.dumps(record, sort_keys=True, separators=(", ", ": "))


class StudyLedger:
    """Open-or-create the ledger for ``study_dir`` under ``spec``.

    On open of an existing ledger the header's fingerprint must match
    ``spec.fingerprint()`` (:class:`LedgerMismatch` otherwise). A missing
    or empty file is initialized with the header line.
    """

    def __init__(self, study_dir: str | Path, spec: StudySpec):
        self.path = Path(study_dir) / LEDGER_NAME
        self.spec = spec
        self.path.parent.mkdir(parents=True, exist_ok=True)
        if self.path.exists() and self.path.stat().st_size:
            header = self.header()
            if header.get("spec_sha") != spec.fingerprint():
                raise LedgerMismatch(
                    f"{self.path} was written for spec "
                    f"{header.get('spec_sha')} (study "
                    f"{header.get('study')!r}); this run's spec is "
                    f"{spec.fingerprint()} — a changed protocol cannot "
                    "resume into the same ledger (new study dir, or "
                    "--fresh to discard)")
        else:
            self._rewrite([_dumps({
                "kind": "header",
                "schema_version": LEDGER_SCHEMA_VERSION,
                "study": spec.name,
                "spec_sha": spec.fingerprint(),
                "spec": spec.to_json(),
            })])

    # -------------------------------------------------------------- io

    def _rewrite(self, lines: list) -> None:
        # Whole-file tmp-then-rename: prior lines ride over as the exact
        # bytes read back (bitwise resume), the replace is atomic.
        tmp = self.path.with_suffix(".jsonl.tmp")
        data = "".join(line + "\n" for line in lines)
        with open(tmp, "w") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)

    def _raw_lines(self) -> list:
        if not self.path.exists():
            return []
        return self.path.read_text().splitlines()

    def append(self, record: dict) -> None:
        """Append one trial record atomically (sorted keys, schema tag)."""
        record = {"kind": "trial",
                  "schema_version": LEDGER_SCHEMA_VERSION, **record}
        self._rewrite(self._raw_lines() + [_dumps(record)])

    # ----------------------------------------------------------- reads

    def header(self) -> dict:
        lines = self._raw_lines()
        if not lines:
            raise FileNotFoundError(f"{self.path}: empty ledger")
        head = json.loads(lines[0])
        if head.get("kind") != "header":
            raise ValueError(f"{self.path}: first line is not a header")
        return head

    def records(self) -> list:
        return [json.loads(l) for l in self._raw_lines()[1:]]

    def completed_ids(self) -> set:
        return {r["trial_id"] for r in self.records()}


def load_spec(study_dir: str | Path) -> StudySpec:
    """The spec a study dir's ledger was written under — what a worker
    subprocess (and a bare resume) runs from, so the executed protocol
    is the LEDGER's, never a drifted caller's."""
    path = Path(study_dir) / LEDGER_NAME
    head = json.loads(path.read_text().splitlines()[0])
    return spec_from_json(head["spec"])
