"""graftstudy trial execution: one trial in-process, a study across jobs.

:func:`run_trial` is the single-trial recipe — build the variant's
config/bundle, train with the study's eval protocol (optionally under
the reseed guard, each attempt keeping its OWN ``best_attempt<k>/``
lineage), score the deliverable checkpoint with the paired greedy
evaluation, and return the ledger record. :class:`StudyRunner` drives
the ``(variant x seed)`` matrix over it: ``jobs=0`` runs trials
sequentially in this process (tests, the seed_study compat wrapper);
``jobs >= 1`` forks one worker subprocess per trial
(``studies/worker.py``) with BLAS pinned per trial via environment —
the graftserve finding (docs/serving.md): default OpenBLAS pools
oversubscribe the host the moment two trials share it, and lose even
single-stream.

Resume is ledger-driven (``studies/ledger.py``): completed trials are
skipped (their entries untouched — bitwise), an orphaned
``result.json`` from a kill between result write and ledger append is
adopted without re-running, and an in-flight trial dir with no result
is wiped and restarted.
"""

from __future__ import annotations

import json
import logging
import os
import shutil
import subprocess
import sys
import time
from pathlib import Path

from rl_scheduler_tpu.studies.ledger import StudyLedger
from rl_scheduler_tpu.studies.spec import StudySpec, TrialSpec
# atomic_write_json moved to utils/fsio.py when the discipline went
# repo-wide (graftlint GL013); re-exported here for existing importers.
from rl_scheduler_tpu.utils.fsio import atomic_write_json  # noqa: F401
from rl_scheduler_tpu.utils.pidlock import acquire_pidfile_lock, read_live_pid

logger = logging.getLogger(__name__)

RESULT_NAME = "result.json"
TRIALS_DIR = "trials"
WORKER_PID_NAME = "worker.pid"
RUNNER_PID_NAME = "runner.pid"


# The pidfile parse+liveness check behind the runner lock, the
# orphaned-worker guard, and the CLI's --fresh refusal — shared with
# graftroll's promotion lock (one implementation, utils/pidlock.py).
_read_live_pid = read_live_pid


def acquire_runner_lock(study_dir: str | Path) -> Path:
    """Take the study dir's single-writer lock via exclusive create
    (stale locks from dead pids are cleared and retried; the O_EXCL
    discipline lives in ``utils/pidlock.py``, shared with graftroll's
    promotion lock). Raises RuntimeError naming the live holder
    otherwise. The one acquisition path for both ``StudyRunner.run``
    and the CLI's ``--fresh`` (which must hold the lock BEFORE deleting
    the dir, or a runner started in the check-to-rmtree window loses
    its ledger mid-run)."""
    return acquire_pidfile_lock(
        Path(study_dir) / RUNNER_PID_NAME,
        f"study dir {study_dir} is already being run by pid {{pid}} "
        "({lock}); a second writer would corrupt its in-flight trial "
        "dirs — wait for it or kill it first")

_CFG_KEYS = ("num_envs", "rollout_steps", "minibatch_size", "num_epochs",
             "lr", "gamma", "entropy_coeff", "clip_eps", "compute_dtype",
             "argmax_penalty_sharpness")


def build_trial_config(spec: StudySpec, trial: TrialSpec):
    """``(PPOTrainConfig, bundle_kwargs, reseed_budget)`` for one trial:
    the study preset + eval protocol with the variant overlay applied
    (the same knob semantics as the train_ppo CLI flags)."""
    import dataclasses

    from rl_scheduler_tpu.agent.presets import PPO_PRESETS

    ov = dict(trial.overlay)
    cfg = dataclasses.replace(
        PPO_PRESETS[spec.preset],
        eval_every=spec.eval_every,
        eval_episodes=spec.eval_episodes,
        **{k: ov[k] for k in _CFG_KEYS if k in ov})
    if "sample_temp_anneal" in ov:
        cfg = dataclasses.replace(
            cfg,
            sample_temp_end=float(ov["sample_temp_anneal"]),
            # Same default as the CLI: anneal across the whole run.
            sample_temp_iters=int(ov.get("sample_temp_iters",
                                         spec.iterations)))
    if "argmax_penalty" in ov:
        cfg = dataclasses.replace(
            cfg, argmax_penalty_coeff=float(ov["argmax_penalty"]))
    bundle_kwargs = {"num_nodes": spec.num_nodes}
    if ov.get("flash_attn"):
        bundle_kwargs["flash_attn"] = True
    if ov.get("num_heads") is not None:
        bundle_kwargs["num_heads"] = int(ov["num_heads"])
    if ov.get("scenario"):
        from rl_scheduler_tpu.scenarios import get_scenario

        bundle_kwargs["scenario"] = get_scenario(
            ov["scenario"], seed=int(ov.get("scenario_seed", 0)))
    return cfg, bundle_kwargs, int(ov.get("reseed_on_stall", 0))


def _argmax_collision(bundle, net, params, episodes: int, seed: int) -> float:
    """Collision probability of the GREEDY action distribution over a
    seeded rollout batch — the study's measured latch diagnostic: a
    policy funneling placements onto one favorite node scores near 1,
    an argmax rotating over k nodes scores ~1/k (the differentiable
    training-time proxy is ``ops/losses.argmax_concentration``)."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def run(key):
        state, obs = bundle.reset_batch(key, episodes)

        def step(carry, _):
            state, obs = carry
            logits, _ = net.apply(params, obs)
            action = jnp.argmax(logits, axis=-1)
            counts = jnp.sum(
                jax.nn.one_hot(action, bundle.num_actions), axis=0)
            state, ts = bundle.step_batch(state, action)
            return (state, ts.obs), counts

        _, counts = jax.lax.scan(step, (state, obs), None,
                                 length=bundle.episode_steps)
        total = counts.sum()
        p = counts.sum(axis=0) / jnp.maximum(total, 1.0)
        return jnp.sum(p * p)

    return float(run(jax.random.PRNGKey(seed)))


def run_trial(spec: StudySpec, trial: TrialSpec, trial_dir: str | Path,
              baseline_threshold: float | None = None) -> dict:
    """Execute one trial end-to-end in this process; returns the ledger
    record (also written to ``<trial_dir>/result.json`` tmp-then-rename).

    ``baseline_threshold`` overrides the computed node-baseline bar —
    the tests' seam for forcing the stall guard deterministically (the
    same monkeypatch point ``tests/test_reseed.py`` uses on the CLI).
    """
    import jax

    from rl_scheduler_tpu.agent.evaluate import (
        best_node_baseline_reward,
        structured_evaluate,
    )
    from rl_scheduler_tpu.agent.ppo import ppo_train
    from rl_scheduler_tpu.agent.train_ppo import (
        EvalStall,
        make_bundle_and_net,
        make_stall_guard,
    )
    from rl_scheduler_tpu.agent.loop import make_best_checkpoint_hook
    from rl_scheduler_tpu.utils.checkpoint import CheckpointManager

    trial_dir = Path(trial_dir)
    trial_dir.mkdir(parents=True, exist_ok=True)
    # perf_counter, not time.time(): wall_s is a DURATION and a mid-trial
    # NTP step must not corrupt the ledger's wall times (GL011).
    t0 = time.perf_counter()
    cfg, bundle_kwargs, reseed_budget = build_trial_config(spec, trial)
    bundle, net = make_bundle_and_net(spec.env, cfg, **bundle_kwargs)
    if baseline_threshold is not None:
        threshold = baseline_threshold
    else:
        # The node-baseline bar is a constant of the VARIANT (seeded
        # rollouts on that variant's bundle — seeds only change the
        # policy init), so the first trial of each variant memoizes it
        # in the study dir and the other 8 seeds (and every resumed
        # worker process) read it back instead of re-running the
        # baseline evaluation. Concurrent writers race benignly: the
        # value is deterministic and the write atomic.
        cache = trial_dir.parent / f"threshold_{trial.variant}.json"
        threshold = None
        if cache.exists():
            try:
                threshold = json.loads(cache.read_text())["threshold"]
            except (ValueError, KeyError):
                # Unreadable cache (e.g. torn by a pre-atomic-write
                # kill): recompute and overwrite rather than poisoning
                # every later trial of the variant.
                threshold = None
        if threshold is None:
            threshold = best_node_baseline_reward(
                spec.env, bundle, cfg.eval_episodes, seed=0)
            atomic_write_json(cache, {"variant": trial.variant,
                                      "threshold": threshold})

    # Eval firings land on multiples of eval_every; the guard's two
    # checkpoints are the last firing at/before the deadline and the
    # run's final firing (train_ppo CLI semantics).
    decision_iter = final_iter = 0
    if cfg.eval_every > 0:
        decision_iter = (spec.stall_deadline // cfg.eval_every) * cfg.eval_every
        final_iter = (spec.iterations // cfg.eval_every) * cfg.eval_every

    def tree_fn(runner):
        return {"params": runner.params, "opt_state": runner.opt_state}

    attempt = 0
    attempt_log: list = []
    evals: dict = {}
    while True:
        evals.clear()
        attempt_seed = trial.seed + attempt

        def eval_log(i, metrics, _evals=evals):
            _evals[i + 1] = metrics["eval_episode_reward_mean"]

        sink = eval_log
        if reseed_budget > 0 and decision_iter > 0:
            sink = make_stall_guard(
                eval_log, decision_iter, final_iter, threshold,
                raise_on_stall=attempt < reseed_budget)
        # Satellite fix (ISSUE 9): each reseed attempt keeps its OWN
        # best-eval lineage. The train CLI clears best/ on reseed (its
        # deliverable is one run dir); a study is evidence — an
        # abandoned attempt's peak checkpoint is part of the record,
        # and the ledger names the attempt the verdict was scored from.
        best_mgr = on_eval = None
        if cfg.eval_every > 0:
            best_mgr = CheckpointManager(
                trial_dir / f"best_attempt{attempt}", keep=1)
            on_eval = make_best_checkpoint_hook(
                best_mgr, tree_fn,
                extras={"trial_id": trial.trial_id, "variant": trial.variant,
                        "seed": attempt_seed, "attempt": attempt,
                        "env": spec.env, "preset": spec.preset,
                        "num_nodes": spec.num_nodes})
        try:
            runner, _ = ppo_train(
                bundle, cfg, spec.iterations, seed=attempt_seed, net=net,
                log_fn=lambda *a: None, eval_log_fn=sink, on_eval=on_eval)
            if best_mgr is not None:
                best_mgr.close()
            break
        except EvalStall as stall:
            if best_mgr is not None:
                best_mgr.close()  # finalize; the lineage dir STAYS
            attempt_log.append({
                "attempt": attempt, "seed": attempt_seed,
                "stall_iteration": stall.iteration,
                "best_eval": stall.best_eval,
                "evals": {str(k): round(v, 3) for k, v in evals.items()},
            })
            attempt += 1

    # ------------------------------------------------ verdict scoring
    # spec.score_source picks the weights the verdict measures: "final"
    # (the run's last params — the §1b protocol the recorded baselines
    # used) or "best" (the surviving attempt's best-eval keeper, item
    # 3a's deliverable). The ledger records which attempt and source the
    # verdict actually came from either way.
    scored_source, scored_step = "final", None
    score_params = runner.params
    if spec.score_source == "best" and cfg.eval_every > 0:
        best_mgr = CheckpointManager(
            trial_dir / f"best_attempt{attempt}", keep=1)
        step = best_mgr.latest_verified_step()
        if step is not None:
            tree, _ = best_mgr.restore(step)
            score_params = tree["params"]
            scored_source, scored_step = "best", step
        best_mgr.close()

    report = structured_evaluate(
        spec.env, bundle, net, score_params,
        num_episodes=spec.final_eval_episodes, seed=0)
    concentration = _argmax_collision(
        bundle, net, score_params,
        episodes=min(32, spec.final_eval_episodes), seed=1)

    by_deadline = max(
        (v for i, v in evals.items() if i <= spec.stall_deadline),
        default=None)
    eval_final = evals[max(evals)] if evals else None
    record = {
        "trial_id": trial.trial_id,
        "variant": trial.variant,
        "seed": trial.seed,
        "status": "ok",
        "attempts": attempt + 1,
        "scored_attempt": attempt,
        "scored_seed": trial.seed + attempt,
        "scored_source": scored_source,
        "scored_step": scored_step,
        "attempt_log": attempt_log,
        "threshold": round(threshold, 3),
        "eval_at_deadline": (None if by_deadline is None
                             else round(by_deadline, 3)),
        "eval_final": None if eval_final is None else round(eval_final, 3),
        "flagged_early": (None if by_deadline is None
                          else bool(by_deadline < threshold)),
        "flagged_final": (None if eval_final is None
                          else bool(eval_final < threshold)),
        "improvement_pct": round(report.improvement_vs_best_baseline_pct, 2),
        "failed": bool(report.improvement_vs_best_baseline_pct < 0),
        "avg_episode_reward": round(report.avg_episode_reward, 3),
        "argmax_collision": round(concentration, 4),
        "wall_s": round(time.perf_counter() - t0, 1),
        "backend": jax.devices()[0].platform,
    }
    write_result(trial_dir, record)
    return record


def write_result(trial_dir: str | Path, record: dict) -> None:
    """Atomic ``result.json`` — the worker->runner handoff file the
    resumed study adopts without re-running."""
    atomic_write_json(Path(trial_dir) / RESULT_NAME, record)


def limit_blas_threads(threads: int) -> bool:
    """Best-effort threadpoolctl clamp of the ALREADY-LIVE BLAS pools
    (the in-process path; fresh workers pin via environment instead,
    which is the reliable window). Returns whether the clamp applied."""
    try:
        from threadpoolctl import threadpool_limits

        threadpool_limits(limits=threads)
        return True
    except Exception:  # noqa: BLE001 — pinning is an optimization; the
        # study still runs correct (just slower) on library defaults
        logger.warning("threadpoolctl unavailable; BLAS pools keep "
                       "library defaults (wanted %d threads)", threads)
        return False


def configure_jax_cache() -> None:
    """Point jax at the shared persistent compilation cache (env
    override ``GRAFTSTUDY_JAX_CACHE``) so a study's repeated tiny-trial
    compiles are paid once per STUDY, not once per worker/trial — the
    one implementation behind the worker, the in-process CLI path, and
    the chaos driver."""
    try:
        import jax

        jax.config.update("jax_compilation_cache_dir",
                          os.environ.get("GRAFTSTUDY_JAX_CACHE",
                                         "/tmp/jax_cache"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.2)
    except Exception:  # noqa: BLE001 — cache config is version-
        pass           # dependent; purely an optimization


class StudyRunner:
    """Drive a study's trial matrix to a complete ledger (module
    docstring). ``jobs=0``: in-process sequential; ``jobs >= 1``: up to
    ``jobs`` concurrent worker subprocesses, each BLAS-pinned to
    ``blas_threads`` threads (default ``max(1, cores // jobs)``)."""

    def __init__(self, spec: StudySpec, study_dir: str | Path,
                 jobs: int = 1, blas_threads: int | None = None):
        if jobs < 0:
            raise ValueError(f"jobs={jobs}: 0 (in-process) or a worker count")
        self.spec = spec
        self.study_dir = Path(study_dir)
        self.jobs = jobs
        if blas_threads is None and jobs > 0:
            blas_threads = max(1, (os.cpu_count() or 1) // jobs)
        self.blas_threads = blas_threads
        if jobs == 0 and blas_threads:
            # In-process trials can't be pinned via environment (numpy
            # is long imported); clamp the live pools best-effort so
            # --blas-threads is never silently ignored.
            limit_blas_threads(blas_threads)
        self.ledger = StudyLedger(self.study_dir, spec)

    def trial_dir(self, trial_id: str) -> Path:
        return self.study_dir / TRIALS_DIR / trial_id

    def _prepare_resume(self) -> list:
        """Adopt orphaned results, wipe in-flight dirs, return the trials
        still to run (spec order)."""
        done = self.ledger.completed_ids()
        remaining = []
        for trial in self.spec.trials():
            if trial.trial_id in done:
                continue
            tdir = self.trial_dir(trial.trial_id)
            result = tdir / RESULT_NAME
            if result.exists():
                # Killed between result write and ledger append: the
                # result is complete (atomic rename) — adopt it.
                self.ledger.append(json.loads(result.read_text()))
                logger.info("adopted orphaned result for %s", trial.trial_id)
                continue
            if tdir.exists():
                # In-flight when the study died: partial checkpoints,
                # no verdict — restart it from scratch. UNLESS a live
                # orphaned worker (runner killed without its process
                # group) is still writing there: wiping under it would
                # interleave two trainers into one trial dir.
                wpid_file = tdir / WORKER_PID_NAME
                wpid = _read_live_pid(wpid_file)
                if wpid is not None:
                    raise RuntimeError(
                        f"trial {trial.trial_id!r} has a live worker "
                        f"(pid {wpid}, {wpid_file}) from a previous "
                        "runner — wait for it or kill it before "
                        "resuming (if the pid was recycled by an "
                        "unrelated process, delete the pid file)")
                shutil.rmtree(tdir)
                logger.info("restarting in-flight trial %s", trial.trial_id)
            remaining.append(trial)
        return remaining

    def run(self, progress=print) -> list:
        """Execute every remaining trial; returns the full record list
        (ledger order). Idempotent: a completed study returns instantly.

        Single-writer lock: the study dir carries a ``runner.pid`` while
        a runner is live, so a concurrent ``run()`` refuses instead of
        wiping the first runner's in-flight trial dirs; a stale lock
        (dead pid) is overridden. Workers orphaned by a killed runner
        are covered separately: each trial dir carries the worker's
        ``worker.pid`` and ``_prepare_resume`` refuses to wipe a dir
        whose worker is still alive."""
        lock = acquire_runner_lock(self.study_dir)
        try:
            return self._run_locked(progress)
        finally:
            lock.unlink(missing_ok=True)

    def _run_locked(self, progress) -> list:
        remaining = self._prepare_resume()
        total = len(self.spec.trials())
        if progress is not None and not remaining:
            progress(f"# study {self.spec.name}: all {total} trials "
                     "already in the ledger")
        if self.jobs == 0:
            for trial in remaining:
                record = run_trial(self.spec, trial,
                                   self.trial_dir(trial.trial_id))
                self.ledger.append(record)
                if progress is not None:
                    progress(f"# [{len(self.ledger.records())}/{total}] "
                             + json.dumps(record, sort_keys=True))
        else:
            self._run_subprocess(remaining, total, progress)
        return self.ledger.records()

    # --------------------------------------------------- subprocess pool

    def _worker_env(self) -> dict:
        env = dict(os.environ)
        # The package is run from a source tree (no install): workers
        # must resolve rl_scheduler_tpu the same way this process did.
        repo_root = str(Path(__file__).resolve().parents[2])
        env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
        if self.blas_threads:
            # Per-trial BLAS pinning, the graftserve finding: env vars
            # land BEFORE numpy/jax import in a fresh process (the one
            # window where they reliably size the pools); the worker
            # adds a best-effort threadpoolctl clamp on top.
            for var in ("OPENBLAS_NUM_THREADS", "OMP_NUM_THREADS",
                        "MKL_NUM_THREADS"):
                env[var] = str(self.blas_threads)
            env["GRAFTSTUDY_BLAS_THREADS"] = str(self.blas_threads)
        return env

    def _run_subprocess(self, remaining: list, total: int, progress) -> None:
        env = self._worker_env()
        queue = list(remaining)
        live: dict = {}
        try:
            while queue or live:
                while queue and len(live) < self.jobs:
                    trial = queue.pop(0)
                    tdir = self.trial_dir(trial.trial_id)
                    tdir.mkdir(parents=True, exist_ok=True)
                    log = open(tdir / "worker.log", "w")
                    proc = subprocess.Popen(
                        [sys.executable, "-m",
                         "rl_scheduler_tpu.studies.worker",
                         "--study-dir", str(self.study_dir),
                         "--trial-id", trial.trial_id],
                        stdout=log, stderr=subprocess.STDOUT, env=env)
                    # Orphan evidence for _prepare_resume: if THIS
                    # runner dies without its process group, a resume
                    # must not wipe the dir while the worker lives.
                    (tdir / WORKER_PID_NAME).write_text(str(proc.pid))
                    live[trial.trial_id] = (trial, proc, log)
                time.sleep(0.2)
                for tid in list(live):
                    trial, proc, log = live[tid]
                    rc = proc.poll()
                    if rc is None:
                        continue
                    log.close()
                    del live[tid]
                    self._collect(trial, rc, total, progress)
        finally:
            for _, proc, log in live.values():
                proc.kill()
                log.close()

    def _collect(self, trial: TrialSpec, rc: int, total: int,
                 progress) -> None:
        tdir = self.trial_dir(trial.trial_id)
        # The worker exited: its pid file is no longer orphan evidence
        # (and a recycled pid must not block a later resume).
        (tdir / WORKER_PID_NAME).unlink(missing_ok=True)
        result = tdir / RESULT_NAME
        if rc == 0 and result.exists():
            record = json.loads(result.read_text())
        else:
            # A crashed trial is evidence too: recorded (and skipped on
            # resume — --fresh re-runs), excluded from the rates, and
            # surfaced in the grid's error column.
            tail = ""
            log = tdir / "worker.log"
            if log.exists():
                tail = "\n".join(log.read_text().splitlines()[-5:])
            record = {"trial_id": trial.trial_id, "variant": trial.variant,
                      "seed": trial.seed, "status": "error",
                      "returncode": rc, "log_tail": tail}
            logger.error("trial %s failed (rc=%s): %s",
                         trial.trial_id, rc, tail)
        self.ledger.append(record)
        if progress is not None:
            progress(f"# [{len(self.ledger.records())}/{total}] "
                     + json.dumps(record, sort_keys=True))
