"""graftstudy worker: run ONE trial in a fresh process.

Launched by :class:`~rl_scheduler_tpu.studies.runner.StudyRunner` with
BLAS pools already pinned through the environment (set before this
process imported numpy/jax — the window where the env vars actually
size the pools). The trial's protocol comes from the study dir's LEDGER
header, not from argv: a worker can never execute a spec that drifted
from the one the ledger's completed trials ran under.

Exit 0 with ``<trial_dir>/result.json`` written (atomically) on
success; any failure exits nonzero and the runner records an error
entry from the log tail.
"""

from __future__ import annotations

import argparse
import os
from pathlib import Path


def _pin_runtime() -> None:
    """Best-effort threadpoolctl clamp on top of the env-var pinning,
    plus the shared persistent compilation cache so repeated tiny-trial
    compiles are paid once per study, not once per worker."""
    from rl_scheduler_tpu.studies.runner import (
        configure_jax_cache,
        limit_blas_threads,
    )

    threads = int(os.environ.get("GRAFTSTUDY_BLAS_THREADS", "0") or 0)
    if threads > 0:
        # On top of the env-var pinning the runner already applied
        # before this process imported numpy/jax.
        limit_blas_threads(threads)
    configure_jax_cache()


def main(argv: list | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--study-dir", required=True)
    p.add_argument("--trial-id", required=True)
    args = p.parse_args(argv)

    _pin_runtime()

    from rl_scheduler_tpu.studies.ledger import load_spec
    from rl_scheduler_tpu.studies.runner import TRIALS_DIR, run_trial

    spec = load_spec(args.study_dir)
    matching = [t for t in spec.trials() if t.trial_id == args.trial_id]
    if not matching:
        raise SystemExit(
            f"trial {args.trial_id!r} is not in study {spec.name!r} "
            f"({[t.trial_id for t in spec.trials()]})")
    record = run_trial(
        spec, matching[0],
        Path(args.study_dir) / TRIALS_DIR / args.trial_id)
    print(f"worker done: {record['trial_id']} status={record['status']}",
          flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
