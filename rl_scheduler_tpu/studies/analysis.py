"""graftstudy statistics: per-variant verdicts from the trial ledger.

Pure stdlib (``math``), deliberately: the analysis must produce the
same verdict on the container, the driver, and anyone's laptop reading
a copied ledger.

Per variant: the failure count over completed trials with a **Wilson
score interval** (the right small-n interval for 9-seed studies — a
normal approximation at n=9, p~0.4 is garbage), the mean greedy
improvement, and the mean argmax-collision diagnostic. Against the
control variant: **paired-seed deltas** (same seed, two variants —
the pairing removes the dominant seed-to-seed variance), the
fixed/broken counts, and a two-sided **sign test** p-value on them.
Against the acceptance bar (``spec.target_failure_rate``): the variant
``verdict`` is graded —

- ``confirmed_below``: the Wilson UPPER bound clears the bar (the
  strong claim; at n=9 even 0 failures cannot make it — hi(0/9)=0.30 —
  which is the honest arithmetic of a thin seed set, ROADMAP 3c),
- ``point_below`` / ``point_above``: the point estimate is on that
  side but the interval straddles the bar,
- ``confirmed_above``: the Wilson LOWER bound exceeds the bar (the
  variant measurably fails the target).
"""

from __future__ import annotations

import json
import math

STUDY_SCHEMA_VERSION = 1


def wilson_interval(failures: int, n: int, z: float = 1.96) -> tuple:
    """Wilson score interval for a binomial proportion: ``(lo, hi)``."""
    if n <= 0:
        return (0.0, 1.0)
    p = failures / n
    z2 = z * z
    denom = 1.0 + z2 / n
    center = (p + z2 / (2 * n)) / denom
    half = z * math.sqrt(p * (1 - p) / n + z2 / (4 * n * n)) / denom
    return (max(0.0, center - half), min(1.0, center + half))


def sign_test_pvalue(wins: int, losses: int) -> float:
    """Two-sided sign test on paired outcomes (ties dropped by the
    caller): P(this lopsided or worse | fair coin)."""
    n = wins + losses
    if n == 0:
        return 1.0
    k = min(wins, losses)
    tail = sum(math.comb(n, i) for i in range(k + 1)) / 2.0 ** n
    return min(1.0, 2.0 * tail)


def _mean(xs: list) -> float | None:
    return round(sum(xs) / len(xs), 3) if xs else None


def analyze_study(spec, records: list) -> dict:
    """The ``schema_version``-tagged study summary (module docstring):
    one dict the CLI emits as the driver-tracked JSON line and renders
    as the human grid. ``spec`` is a :class:`~rl_scheduler_tpu.studies.
    spec.StudySpec`; ``records`` the ledger's trial entries."""
    by_variant: dict = {v: [] for v in spec.variant_names()}
    for r in records:
        if r.get("variant") in by_variant:
            by_variant[r["variant"]].append(r)

    control_ok = {r["seed"]: r for r in by_variant.get(spec.control, ())
                  if r.get("status") == "ok"}
    variants: dict = {}
    for vname, rows in by_variant.items():
        ok = [r for r in rows if r.get("status") == "ok"]
        errors = len(rows) - len(ok)
        failures = sum(1 for r in ok if r["failed"])
        n = len(ok)
        lo, hi = wilson_interval(failures, n)
        entry = {
            "trials": n,
            "errors": errors,
            "failures": failures,
            "failure_rate": round(failures / n, 3) if n else None,
            "wilson95": [round(lo, 3), round(hi, 3)],
            "mean_improvement_pct": _mean(
                [r["improvement_pct"] for r in ok]),
            "mean_improvement_converged_pct": _mean(
                [r["improvement_pct"] for r in ok if not r["failed"]]),
            "mean_argmax_collision": _mean(
                [r["argmax_collision"] for r in ok
                 if r.get("argmax_collision") is not None]),
            "reseeds": sum(r.get("attempts", 1) - 1 for r in ok),
        }
        if spec.target_failure_rate is not None and n:
            target = spec.target_failure_rate
            if hi < target:
                entry["verdict"] = "confirmed_below"
            elif lo > target:
                entry["verdict"] = "confirmed_above"
            elif failures / n < target:
                entry["verdict"] = "point_below"
            else:
                entry["verdict"] = "point_above"
        if vname != spec.control and control_ok:
            paired = [(r, control_ok[r["seed"]]) for r in ok
                      if r["seed"] in control_ok]
            deltas = [r["improvement_pct"] - c["improvement_pct"]
                      for r, c in paired]
            fixed = sum(1 for r, c in paired
                        if c["failed"] and not r["failed"])
            broken = sum(1 for r, c in paired
                         if not c["failed"] and r["failed"])
            entry["vs_control"] = {
                "paired_seeds": len(paired),
                "mean_delta_pct": _mean(deltas),
                "seeds_fixed": fixed,
                "seeds_broken": broken,
                "sign_test_p": round(sign_test_pvalue(fixed, broken), 4),
            }
        variants[vname] = entry

    return {
        "schema_version": STUDY_SCHEMA_VERSION,
        "metric": "study_summary",
        "study": spec.name,
        "spec_sha": spec.fingerprint(),
        "env": spec.env,
        "preset": spec.preset,
        "num_nodes": spec.num_nodes,
        "seeds": len(spec.seeds),
        "iterations": spec.iterations,
        "control": spec.control,
        "target_failure_rate": spec.target_failure_rate,
        "completed_trials": sum(v["trials"] + v["errors"]
                                for v in variants.values()),
        "total_trials": len(spec.trials()),
        "variants": variants,
    }


def render_grid(summary: dict) -> str:
    """The human study grid for one summary dict."""
    cols = ("variant", "n", "fail", "rate [wilson95]", "impr%", "argmaxP2",
            "d-ctrl%", "fix/brk", "p", "verdict")
    rows = [cols]
    for vname, v in summary["variants"].items():
        vs = v.get("vs_control") or {}
        rate = ("-" if v["failure_rate"] is None else
                f"{v['failure_rate']:.2f} [{v['wilson95'][0]:.2f},"
                f"{v['wilson95'][1]:.2f}]")
        rows.append((
            vname + (" (ctrl)" if vname == summary["control"] else ""),
            str(v["trials"]) + (f"+{v['errors']}E" if v["errors"] else ""),
            str(v["failures"]),
            rate,
            "-" if v["mean_improvement_pct"] is None
            else f"{v['mean_improvement_pct']:+.1f}",
            "-" if v["mean_argmax_collision"] is None
            else f"{v['mean_argmax_collision']:.3f}",
            "-" if vs.get("mean_delta_pct") is None
            else f"{vs['mean_delta_pct']:+.1f}",
            f"{vs['seeds_fixed']}/{vs['seeds_broken']}" if vs else "-",
            f"{vs['sign_test_p']:.3f}" if vs else "-",
            v.get("verdict", "-"),
        ))
    widths = [max(len(r[i]) for r in rows) for i in range(len(cols))]
    lines = ["  ".join(c.ljust(w) for c, w in zip(r, widths)).rstrip()
             for r in rows]
    lines.insert(1, "  ".join("-" * w for w in widths))
    header = (f"study {summary['study']} ({summary['env']} "
              f"N={summary['num_nodes']}, preset {summary['preset']}, "
              f"{summary['seeds']} seeds x {summary['iterations']} iters; "
              f"{summary['completed_trials']}/{summary['total_trials']} "
              "trials)")
    if summary.get("target_failure_rate") is not None:
        header += f"; target failure rate < {summary['target_failure_rate']}"
    return header + "\n" + "\n".join(lines)


def summary_json_line(summary: dict) -> str:
    """The one driver-tracked line (bench.py convention: a single
    ``schema_version``-tagged JSON object on its own stdout line)."""
    return json.dumps(summary, sort_keys=True)
