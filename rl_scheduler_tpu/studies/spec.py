"""graftstudy specs: a frozen experiment protocol, compiled to trials.

A :class:`StudySpec` names everything a seed-study / intervention-sweep
needs to be reproducible and resumable: the env + preset under study, the
seed set, the named variants (CLI-overlay dicts on top of the preset),
the iteration/eval protocol, and the acceptance bar. ``trials()``
compiles it into a deterministic ``(variant x seed)`` trial list — the
unit of execution, resume, and statistics — and ``fingerprint()`` hashes
the canonical spec so a resumed study refuses a silently-changed
protocol (``studies/ledger.py``).

The overlay vocabulary is a closed whitelist (:data:`OVERLAY_KEYS`): a
variant is a *measured intervention*, not a junk drawer — an unknown key
fails at spec construction, before any trial burns a run.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
from typing import Any, NamedTuple

# Every knob a variant (or the study-wide base_overlay) may set. The
# first group maps onto PPOTrainConfig fields; the second onto
# train-level knobs the trial runner threads through
# ``agent/train_ppo.make_bundle_and_net`` and the attempt loop.
OVERLAY_KEYS = frozenset({
    # anti-latch interventions (ROADMAP 3b; agent/ppo.py)
    "sample_temp_anneal", "sample_temp_iters", "argmax_penalty",
    "argmax_penalty_sharpness",
    # PPOTrainConfig passthrough
    "num_envs", "rollout_steps", "minibatch_size", "num_epochs", "lr",
    "gamma", "entropy_coeff", "clip_eps", "compute_dtype",
    # env/bundle knobs
    "scenario", "scenario_seed", "flash_attn", "num_heads",
    # per-trial guard budget (0 = observe failures, the study default)
    "reseed_on_stall",
})

STUDY_ENVS = ("cluster_set", "cluster_graph")


class TrialSpec(NamedTuple):
    """One executable cell of the study matrix."""

    trial_id: str
    variant: str
    seed: int
    overlay: dict


@dataclasses.dataclass(frozen=True)
class StudySpec:
    """Frozen study protocol (module docstring). ``variants`` and
    ``base_overlay`` are sorted ``(key, value)`` tuples so the spec stays
    hashable; use :meth:`overlay_for` to read a variant's dict."""

    name: str
    env: str = "cluster_set"
    preset: str = "set_fleet64"
    num_nodes: int = 64
    seeds: tuple = (0,)
    variants: tuple = (("control", ()),)
    iterations: int = 80
    eval_every: int = 8
    eval_episodes: int = 64
    final_eval_episodes: int = 100
    stall_deadline: int = 16
    control: str = "control"
    target_failure_rate: float | None = None
    base_overlay: tuple = ()
    # What the verdict is scored on: "final" (the run's last params —
    # the historical docs/scaling.md §1b protocol, and what the
    # measured 4/9 fleet64 baseline was recorded against) or "best"
    # (the surviving attempt's best-eval keeper — item 3a's deliverable
    # semantics). Keep "final" when comparing against the recorded
    # baselines: scoring "best" conflates intervention effect with
    # keeper salvage.
    score_source: str = "final"

    def __post_init__(self):
        from rl_scheduler_tpu.agent.presets import PPO_PRESETS

        if self.env not in STUDY_ENVS:
            raise ValueError(
                f"env={self.env!r}: studies score trials against the "
                f"structured node baselines; choose from {STUDY_ENVS}")
        if self.preset not in PPO_PRESETS:
            raise ValueError(
                f"preset={self.preset!r}: not a PPO preset "
                f"({sorted(PPO_PRESETS)})")
        if not self.seeds:
            raise ValueError("seeds: a study needs at least one seed")
        if len(set(self.seeds)) != len(self.seeds):
            raise ValueError(f"seeds {self.seeds}: duplicates would "
                             "double-count in the per-variant rates")
        if not self.variants:
            raise ValueError("variants: a study needs at least one variant")
        names = [n for n, _ in self.variants]
        if len(set(names)) != len(names):
            raise ValueError(f"variant names {names}: duplicates")
        if self.control not in names:
            raise ValueError(
                f"control variant {self.control!r} is not among "
                f"{names}: paired deltas need a control column")
        if self.iterations < 1:
            raise ValueError(f"iterations={self.iterations}: >= 1")
        if self.eval_every < 0 or self.eval_episodes < 1:
            raise ValueError(
                f"eval protocol eval_every={self.eval_every}/"
                f"eval_episodes={self.eval_episodes}: eval_every >= 0, "
                "eval_episodes >= 1")
        if self.final_eval_episodes < 1:
            raise ValueError(
                f"final_eval_episodes={self.final_eval_episodes}: the "
                "paired greedy verdict needs at least one episode")
        if self.score_source not in ("final", "best"):
            raise ValueError(
                f"score_source={self.score_source!r}: 'final' (last "
                "params — the §1b baseline protocol) or 'best' (the "
                "best-eval keeper)")
        if self.score_source == "best" and self.eval_every <= 0:
            raise ValueError(
                "score_source='best' needs the in-training eval signal "
                "(eval_every > 0): with no evals there is no best-eval "
                "keeper and every verdict would silently degrade to "
                "final params")
        for vname, knobs in list(self.variants) + [("base", self.base_overlay)]:
            bad = sorted(set(k for k, _ in knobs) - OVERLAY_KEYS)
            if bad:
                raise ValueError(
                    f"variant {vname!r} overlay keys {bad} are not in the "
                    f"study vocabulary (allowed: {sorted(OVERLAY_KEYS)})")
        for vname in [n for n, _ in self.variants]:
            merged = self.overlay_for(vname)
            # Companion-key rules mirror the train CLI's refusals: a
            # spec-valid-but-inert knob would burn a whole chip arm on a
            # variant that trained identical to control.
            if ("sample_temp_iters" in merged
                    and "sample_temp_anneal" not in merged):
                raise ValueError(
                    f"variant {vname!r}: sample_temp_iters shapes the "
                    "sample_temp_anneal schedule; set both (alone it "
                    "would train identical to control)")
            if "scenario_seed" in merged and not merged.get("scenario"):
                raise ValueError(
                    f"variant {vname!r}: scenario_seed without scenario "
                    "is inert (the trial would train identical to "
                    "control)")
            if merged.get("sample_temp_anneal") == 1.0:
                raise ValueError(
                    f"variant {vname!r}: sample_temp_anneal=1.0 is the "
                    "identity temperature — the variant would train "
                    "identical to control (anneal TOWARD determinism, "
                    "e.g. 0.5)")
            if ("argmax_penalty" in merged
                    and not merged["argmax_penalty"]):
                raise ValueError(
                    f"variant {vname!r}: argmax_penalty=0 disables the "
                    "penalty — the variant would train identical to "
                    "control")
            if ("argmax_penalty_sharpness" in merged
                    and not merged.get("argmax_penalty")):
                raise ValueError(
                    f"variant {vname!r}: argmax_penalty_sharpness "
                    "without argmax_penalty is inert (the loss never "
                    "reads the sharpness when the coefficient is 0)")
            if merged.get("scenario"):
                # Resolve the scenario NOW, not per-trial: a typo'd name
                # or env-incompatible family must fail at construction,
                # before any trial burns a run (same gating as the
                # train CLI's --scenario refusals).
                from rl_scheduler_tpu.scenarios import get_scenario

                try:
                    scn = get_scenario(merged["scenario"])
                except ValueError as e:
                    raise ValueError(f"variant {vname!r}: {e}")
                allowed = {
                    "cluster_set": ("bursty_diurnal", "heterogeneous",
                                    "churn", "price_spike",
                                    "domain_random", "trace_replay"),
                    "cluster_graph": ("price_spike",),
                }[self.env]
                if scn.family not in allowed:
                    raise ValueError(
                        f"variant {vname!r}: scenario "
                        f"{merged['scenario']!r} (family {scn.family}) "
                        f"does not shape env {self.env!r} (that env "
                        f"takes: {', '.join(allowed)})")
            if int(merged.get("reseed_on_stall", 0) or 0) > 0:
                # Same eligibility arithmetic as the runner/CLI: the
                # guard's decision iteration must actually fire.
                if self.eval_every <= 0:
                    raise ValueError(
                        f"variant {vname!r}: reseed_on_stall needs the "
                        "in-training eval signal (eval_every > 0)")
                if self.stall_deadline < self.eval_every:
                    raise ValueError(
                        f"variant {vname!r}: stall_deadline="
                        f"{self.stall_deadline} fires no eval at or "
                        f"before it (eval_every={self.eval_every}) — "
                        "the reseed guard would be silently disabled")

    def variant_names(self) -> list:
        return [n for n, _ in self.variants]

    def overlay_for(self, variant: str) -> dict:
        """The merged base+variant overlay dict for one variant."""
        for n, knobs in self.variants:
            if n == variant:
                merged = dict(self.base_overlay)
                merged.update(dict(knobs))
                return merged
        raise KeyError(f"unknown variant {variant!r}; "
                       f"study has {self.variant_names()}")

    def trials(self) -> list:
        """The deterministic trial list: variants in spec order, seeds in
        spec order within each — the execution, resume, and ledger order."""
        return [
            TrialSpec(trial_id=f"{vname}-seed{seed}", variant=vname,
                      seed=seed, overlay=self.overlay_for(vname))
            for vname, _ in self.variants
            for seed in self.seeds
        ]

    def to_json(self) -> dict:
        d = dataclasses.asdict(self)
        # Tuples -> lists happen in asdict/json anyway; keep knobs as
        # [key, value] pairs (canonical, order-preserved).
        return json.loads(json.dumps(d))

    def fingerprint(self) -> str:
        """sha256 over the canonical spec JSON — the resume-compatibility
        key: a ledger written under a different fingerprint refuses to
        continue (same study dir, changed protocol)."""
        blob = json.dumps(self.to_json(), sort_keys=True,
                          separators=(",", ":"))
        return hashlib.sha256(blob.encode()).hexdigest()[:16]


def overlay(**kw) -> tuple:
    """Sorted ``(key, value)`` knob tuple — the frozen form of an overlay
    dict (``studies/presets.py`` builds every variant through this)."""
    return tuple(sorted(kw.items()))


def spec_from_json(d: dict) -> StudySpec:
    """Rebuild a :class:`StudySpec` from :meth:`StudySpec.to_json` output
    (the ledger header's record — what a resumed study and its worker
    processes run from)."""
    kw = dict(d)
    kw["seeds"] = tuple(kw["seeds"])
    kw["variants"] = tuple(
        (name, tuple((k, _detuple(v)) for k, v in knobs))
        for name, knobs in kw["variants"])
    kw["base_overlay"] = tuple(
        (k, _detuple(v)) for k, v in kw["base_overlay"])
    return StudySpec(**kw)


def _detuple(v: Any) -> Any:
    # JSON round-trips tuples as lists; overlay values must compare equal
    # to the originals for the fingerprint check.
    return tuple(v) if isinstance(v, list) else v


def parse_seeds(spec: str) -> list:
    """``"0-5"`` / ``"0,2,7"`` / mixes -> explicit seed list (the
    seed_study CLI convention, kept by ``python -m
    rl_scheduler_tpu.studies --seeds``)."""
    out: list = []
    for part in spec.split(","):
        if "-" in part:
            lo, hi = part.split("-")
            out.extend(range(int(lo), int(hi) + 1))
        else:
            out.append(int(part))
    return out
