"""graftloop part 1: the trace→Scenario compiler.

The serving plane durably logs every decision (graftroll,
``scheduler/tracelog.py``); nothing ever turned those logs back into
training data. This module is the turn: it snapshots a live pool's trace
directory, merges the per-worker streams into one timestamp-ordered
decision sequence (``tracelog.iter_trace_merged``), and compiles the
sequence into the table space the env layer already replays — the new
``trace_replay`` scenario family (``scenarios/families.py``).

**What is reconstructed, and from what.** A trace record carries the
telemetry replay position (``telemetry_pos`` — the raw monotonic counter
the worker's ``TableTelemetry`` consumed for that observation) and, since
schema 2, the parsed pod request (``pod_cpu``) and candidate-cloud layout
(``clouds``). The cost/latency half of every served observation is a
pure function of ``telemetry_pos`` and the serving table (the same
normalized CSV training replays), so the compiler rebuilds it exactly:
``costs[t] = table.costs[pos_t % len(table)]``. The CPU half of a served
observation comes from LIVE telemetry (RandomCpu / Prometheus) and is
deliberately NOT reconstructible — that is the documented digest
semantics: a record's ``obs_sha`` fingerprints the full served array
(including the live half) for provenance joins, while the compiler's
fidelity contract covers the deterministic half plus the pod sizes, and
:func:`verify_roundtrip` pins THAT contract bit-exactly through the real
env (``cluster_set`` reset/step on the compiled tables reproduces the
trace's cost/latency/pod columns).

**Determinism.** Same (trace snapshot, steps, seed, mix_frac) ⇒
bitwise-identical tables (pinned by test): the merged replay order is
deterministic (stable tie-break), the seed only places the episode
window inside a longer trace and draws the anti-forgetting mixture
interleave, and every draw comes from one ``np.random.RandomState`` with
a fixed order — the ``data/generate.py`` discipline every family
follows.

**Tolerance.** The compiler must survive what a crashed pool leaves
behind: orphaned ``.part`` segments (sealed into the snapshot), torn
trailing lines (skipped by ``iter_trace``), counted queue drops (gaps in
the sequence are fine — the table rows are self-describing), probe
records (``endpoint=probe`` synthetic gate traffic, excluded), fail-open
records (no decision was served — excluded, counted), and schema-1
records without pod fields (the pod trace degrades to the env's default
draw, counted).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import logging
import shutil
from pathlib import Path

import numpy as np

from rl_scheduler_tpu.utils.fsio import atomic_write_json, fresh_dir

logger = logging.getLogger(__name__)

SNAPSHOT_META = "snapshot.json"


class TraceCompileError(ValueError):
    """The trace (snapshot) cannot compile into a scenario — too few
    usable decision records, or no snapshot where one was named."""


# ------------------------------------------------------------- snapshot


def snapshot_trace(trace_dir: str | Path, dest: str | Path,
                   fault_plan=None) -> dict:
    """Copy a (possibly live) trace directory into a stable snapshot.

    Sealed segments copy verbatim; active/orphan ``.part`` files copy
    WITHOUT the suffix (sealing the copy — the flushed lines are whole,
    and a torn trailing line in a mid-write copy is exactly what
    ``iter_trace`` already tolerates). The source is never touched, so a
    live pool keeps serving — and the ``--trace-max-segments`` retention
    cap keeps pruning — while graftloop compiles from the frozen copy.

    Writes ``snapshot.json`` (source, per-file sha256+size, record
    count, content digest) atomically and returns it. Re-running over an
    existing snapshot replaces it wholesale (the resume unit is the
    ledger stage, not the copy).
    """
    from rl_scheduler_tpu.scheduler.tracelog import _SEG_RE, iter_trace

    if fault_plan is not None:
        fault_plan.check("loopback.compile", OSError)
    trace_dir = Path(trace_dir)
    if not trace_dir.is_dir():
        raise TraceCompileError(
            f"trace dir {trace_dir} does not exist — point --trace-dir at "
            "the pool's trace directory")
    dest = fresh_dir(dest)
    files = {}
    for path in sorted(trace_dir.iterdir()):
        m = _SEG_RE.match(path.name)
        if m is None:
            continue
        out_name = path.name[:-len(".part")] if m.group("part") else path.name
        out = dest / out_name
        try:
            shutil.copyfile(path, out)
        except OSError:
            # A segment pruned/renamed between listing and copy (live
            # retention, a sealing writer): the snapshot simply carries
            # the segments that held still — gaps are tolerated by
            # construction.
            logger.warning("snapshot: %s vanished mid-copy (live "
                           "retention?); skipping", path.name)
            continue
        digest = hashlib.sha256(out.read_bytes()).hexdigest()
        files[out_name] = {"sha256": digest, "size": out.stat().st_size}
    records = sum(1 for _ in iter_trace(dest))
    meta = {
        "source": str(trace_dir),
        "files": files,
        "records": records,
        "digest": snapshot_digest(dest),
    }
    atomic_write_json(dest / SNAPSHOT_META, meta, indent=2)
    return meta


def snapshot_digest(snapshot_dir: str | Path) -> str:
    """Content digest of a snapshot's segment bytes (sorted by name,
    ``snapshot.json`` excluded) — the compile-provenance key the loop
    ledger records, so "same snapshot" is checkable, not assumed."""
    snapshot_dir = Path(snapshot_dir)
    h = hashlib.sha256()
    for path in sorted(snapshot_dir.iterdir()):
        if path.name == SNAPSHOT_META or not path.is_file():
            continue
        h.update(path.name.encode())
        h.update(path.read_bytes())
    return h.hexdigest()[:16]


# -------------------------------------------------------------- compile


@dataclasses.dataclass(frozen=True)
class CompiledTrace:
    """The compiled replay: env-ready tables plus the compile report."""

    costs: np.ndarray        # [T, 2] f32 — replayed normalized costs
    latencies: np.ndarray    # [T, 2] f32
    pod_scale: np.ndarray | None  # [T] f32 — recorded pod sizes (or None)
    pod_from_trace: bool
    stats: dict

    @property
    def steps(self) -> int:
        return int(self.costs.shape[0])


def usable_records(trace_dir: str | Path) -> tuple[list, dict]:
    """``(records, exclusion_counts)``: the merged decision sequence a
    compile consumes — probes and fail-opens out, a telemetry position
    required (the one field the reconstruction is a function of)."""
    from rl_scheduler_tpu.scheduler.tracelog import (
        is_synthetic_endpoint,
        iter_trace_merged,
    )

    used: list = []
    stats = {"records_total": 0, "probes_excluded": 0,
             "fail_open_excluded": 0, "missing_pos_excluded": 0,
             "generations": set()}
    for record in iter_trace_merged(trace_dir):
        stats["records_total"] += 1
        if is_synthetic_endpoint(record.get("endpoint")):
            # Probes AND shadow scores: neither consumed a telemetry
            # position on the serving path, so neither can anchor a
            # reconstruction step.
            stats["probes_excluded"] += 1
            continue
        if record.get("fail_open"):
            stats["fail_open_excluded"] += 1
            continue
        if record.get("telemetry_pos") is None:
            stats["missing_pos_excluded"] += 1
            continue
        stats["generations"].add(record.get("generation", 0))
        used.append(record)
    stats["generations"] = sorted(stats["generations"])
    return used, stats


def compile_trace(trace_dir: str | Path, steps: int = 256, seed: int = 0,
                  mix_frac: float = 0.0, data_path: str | None = None,
                  fault_plan=None) -> CompiledTrace:
    """Compile a trace snapshot into :class:`CompiledTrace` (module doc).

    ``steps`` caps the table length: a longer trace contributes a
    seeded contiguous window (the seed's first draw), a shorter one
    compiles whole. ``mix_frac`` interleaves that share of base-workload
    rows (the serving table walked cyclically from a seeded start, pod
    sizes redrawn from the env's default range) — the anti-forgetting
    mixture a fine-tune-from-trace job trains on. ``fault_plan`` is the
    ``loopback.compile`` chaos seam."""
    if fault_plan is not None:
        fault_plan.check("loopback.compile", OSError)
    if steps < 2:
        raise TraceCompileError(f"steps={steps}: a compiled table needs "
                                "at least 2 rows")
    from rl_scheduler_tpu.data.loader import load_table

    table = load_table(data_path)
    costs_src = np.asarray(table.costs, np.float32)
    lats_src = np.asarray(table.latencies, np.float32)
    used, stats = usable_records(trace_dir)
    if len(used) < 2:
        raise TraceCompileError(
            f"trace under {trace_dir} holds {len(used)} usable decision "
            f"records (of {stats['records_total']} total; "
            f"{stats['probes_excluded']} probes, "
            f"{stats['fail_open_excluded']} fail-open, "
            f"{stats['missing_pos_excluded']} without a telemetry "
            "position) — a replay scenario needs at least 2")

    rng = np.random.RandomState(seed)
    t = min(steps, len(used))
    # Draw order is FIXED (determinism contract): window offset first,
    # then the mixture mask, then the mixture phase, then mixture pods.
    offset = int(rng.randint(0, len(used) - t + 1))
    window = used[offset:offset + t]
    rows = np.array([r["telemetry_pos"] % len(costs_src) for r in window],
                    np.int64)
    costs = costs_src[rows]
    lats = lats_src[rows]
    pods = [r.get("pod_cpu") for r in window]
    missing_pods = sum(1 for p in pods if p is None)
    pod_from_trace = missing_pods == 0
    # Clipped to the env's [0, 1] fraction space: the env clips its pod
    # draw the same way, and the round-trip pin compares exactly.
    pod_scale = (np.clip(np.asarray(pods, np.float32), 0.0, 1.0)
                 if pod_from_trace else None)

    mixed_rows = 0
    if mix_frac > 0.0:
        mask = rng.uniform(size=t) < mix_frac
        phase = int(rng.randint(0, len(costs_src)))
        base_rows = (phase + np.arange(t)) % len(costs_src)
        costs = np.where(mask[:, None], costs_src[base_rows], costs)
        lats = np.where(mask[:, None], lats_src[base_rows], lats)
        if pod_from_trace:
            # Mixture rows re-draw pod sizes from the env's default
            # range: the base workload must look like the base workload,
            # not like frozen trace pods on CSV prices.
            from rl_scheduler_tpu.env.cluster_set import (
                DEFAULT_POD_CPU_HIGH,
                DEFAULT_POD_CPU_LOW,
            )

            base_pods = rng.uniform(DEFAULT_POD_CPU_LOW,
                                    DEFAULT_POD_CPU_HIGH,
                                    size=t).astype(np.float32)
            pod_scale = np.where(mask, base_pods, pod_scale)
        mixed_rows = int(mask.sum())

    stats.update({
        "usable_records": len(used),
        "steps": t,
        "window_offset": offset,
        "seed": seed,
        "mix_frac": mix_frac,
        "mixed_rows": mixed_rows,
        "pod_from_trace": pod_from_trace,
        "records_without_pod": missing_pods,
    })
    return CompiledTrace(
        costs=costs.astype(np.float32),
        latencies=lats.astype(np.float32),
        pod_scale=None if pod_scale is None
        else pod_scale.astype(np.float32),
        pod_from_trace=pod_from_trace,
        stats=stats,
    )


def compiled_tables(trace_dir: str | Path, steps: int = 256, seed: int = 0,
                    mix_frac: float = 0.0) -> dict:
    """The family-dispatch entry (``scenarios/families.
    trace_replay_tables``): :func:`compile_trace` as the plain table
    dict the scenario layer compiles every family into."""
    compiled = compile_trace(trace_dir, steps=steps, seed=seed,
                             mix_frac=mix_frac)
    return {
        "costs": compiled.costs,
        "latencies": compiled.latencies,
        "pod_scale": compiled.pod_scale,
        "pod_from_trace": compiled.pod_from_trace,
    }


def trace_scenario_name(snapshot_dir: str | Path, steps: int | None = None,
                        mix_frac: float | None = None) -> str:
    """The canonical ``trace_replay:<dir>[?steps=N&mix=F]`` scenario name
    for a snapshot — the one string that round-trips through
    ``--scenario``, checkpoint meta, resume guards, and the extender's
    conformance demand (``scenarios/spec.get_scenario`` parses it)."""
    name = f"trace_replay:{snapshot_dir}"
    params = []
    if steps is not None:
        params.append(f"steps={steps}")
    if mix_frac:
        params.append(f"mix={mix_frac:g}")
    return name + ("?" + "&".join(params) if params else "")


# ------------------------------------------------------------ round trip


class RoundTripError(AssertionError):
    """The compiled scenario does NOT replay the trace through the env —
    the compile is wrong, and training on it would not be training on
    served traffic. Never promoted past."""


def verify_roundtrip(scenario, num_nodes: int = 8,
                     max_check_steps: int = 64) -> dict:
    """Pin the compile: step the REAL env (``env/cluster_set``) over the
    scenario's compiled tables and require the observation columns to
    reproduce the trace-derived rows bit-exactly.

    Checked per step t: every node's cost/latency columns equal the
    compiled table row for its cloud (zero node premium by construction
    — the trace_replay scenario params pin ``node_jitter=0``), and, when
    the trace recorded pod sizes, the broadcast ``pod_cpu`` column
    equals the recorded request. The live-CPU column is NOT checked —
    the documented digest semantics (module doc): that half of the
    served observation was live telemetry, reconstructible by nobody.

    Raises :class:`RoundTripError` on the first mismatch; returns the
    check report. A pure-mix row checks identically (its table row IS
    the compiled row, wherever it came from)."""
    import jax

    from rl_scheduler_tpu.env import cluster_set as cs
    from rl_scheduler_tpu.scenarios.spec import _compiled, cluster_set_params

    tables = _compiled(scenario)
    params = cluster_set_params(scenario, num_nodes=num_nodes)
    costs = np.asarray(tables["costs"])
    lats = np.asarray(tables["latencies"])
    pod_scale = tables.get("pod_scale")
    pod_from_trace = bool(tables.get("pod_from_trace"))
    cloud = np.asarray(params.cloud_of_node)
    state, obs = cs.reset(params, jax.random.PRNGKey(0))
    steps_checked = 0
    t_max = min(costs.shape[0] - 1, max_check_steps)
    for t in range(t_max):
        row = np.asarray(obs)
        want_cost = costs[t][cloud]
        want_lat = lats[t][cloud]
        if not (np.allclose(row[:, 0], want_cost, atol=1e-6)
                and np.allclose(row[:, 1], want_lat, atol=1e-6)):
            raise RoundTripError(
                f"step {t}: env observed cost/latency "
                f"{row[:, 0]}/{row[:, 1]} != compiled trace rows "
                f"{want_cost}/{want_lat}")
        if pod_from_trace and pod_scale is not None:
            want_pod = np.float32(pod_scale[t])
            if not np.allclose(row[:, 4], want_pod, atol=1e-6):
                raise RoundTripError(
                    f"step {t}: env pod_cpu {row[0, 4]} != recorded "
                    f"pod {want_pod}")
        steps_checked += 1
        state, ts = cs.step(params, state, 0)
        obs = ts.obs
    return {"steps_checked": steps_checked, "num_nodes": num_nodes,
            "pod_checked": pod_from_trace}
