"""graftloop — the continual-learning subsystem that closes the
decision loop: trace → scenario → retrain → promote (ROADMAP item 1).

The serving plane's two ends already existed — graftroll's durable
decision trace (1a) and canary-gated hot rollout (1d) — joined only by
a checkpoint path a human had to carry. graftloop is the middle:

- ``compile.py``  — the trace→Scenario compiler (1b): snapshot a live
  pool's trace dir, merge the per-worker streams by timestamp, and
  compile the served telemetry rows + pod sizes into the new
  ``trace_replay`` scenario family — bitwise-deterministic per
  (snapshot, seed), round-trip-pinned through the real env.
- ``retrain.py``  — fine-tune-from-trace jobs (1c): warm-start from the
  incumbent's verified checkpoint, train on the compiled trace with a
  seeded anti-forgetting mixture of the original workload, keep
  best-eval, and grade the candidate vs the incumbent with a
  paired-seed Wilson/sign-test verdict (graftstudy's statistics).
- ``orchestrator.py`` + ``python -m rl_scheduler_tpu.loopback`` — one
  resumable command: snapshot, compile, retrain, evaluate, and on a
  ``confirmed_above`` verdict POST ``/promote`` to the live pool,
  riding graftroll's canary/SLO gates and automatic rollback; every
  stage lands in a SIGKILL-safe atomic ledger.

Drills: ``make loop-drill`` (fast, tier-1) / ``make loop-soak`` (slow
serving soak). Design doc: docs/serving.md "closing the loop".
"""

from rl_scheduler_tpu.loopback.compile import (
    CompiledTrace,
    RoundTripError,
    TraceCompileError,
    compile_trace,
    compiled_tables,
    snapshot_digest,
    snapshot_trace,
    trace_scenario_name,
    usable_records,
    verify_roundtrip,
)
from rl_scheduler_tpu.loopback.orchestrator import (
    LoopLedger,
    LoopLedgerMismatch,
    LoopRunner,
    LoopSpec,
    fault_plan_from_env,
    loop_spec_from_json,
)
from rl_scheduler_tpu.loopback.retrain import (
    VERDICTS,
    FinetuneSpec,
    finetune_spec_from_json,
    grade_pairs,
    incumbent_meta,
    run_finetune,
    score_candidate,
    verdict_rank,
)

__all__ = [
    "CompiledTrace",
    "FinetuneSpec",
    "LoopLedger",
    "LoopLedgerMismatch",
    "LoopRunner",
    "LoopSpec",
    "RoundTripError",
    "TraceCompileError",
    "VERDICTS",
    "compile_trace",
    "compiled_tables",
    "fault_plan_from_env",
    "finetune_spec_from_json",
    "grade_pairs",
    "incumbent_meta",
    "loop_spec_from_json",
    "run_finetune",
    "score_candidate",
    "snapshot_digest",
    "snapshot_trace",
    "trace_scenario_name",
    "usable_records",
    "verdict_rank",
    "verify_roundtrip",
]
