"""graftpilot: the unattended drift-triggered retrain daemon.

graftloop closed the decision loop as ONE command a human runs after
deciding "the traffic has shifted, retrain now". graftpilot removes the
human: a long-running controller that watches the serving pool's own
observability plane and runs the loop only when the evidence says to —
then holds the result to a HIGHER bar than a hand-run loop, because
nobody is watching.

**Trigger on evidence, not a timer.** Every ``poll_interval_s`` the
daemon GETs the pool's ``/stats`` and grades the drift section with the
SAME logic as ``driftview --check`` (``tools.driftview.grade_report`` —
one grading implementation, three surfaces), plus the graftlens SLO
burn verdict: the trigger is "any drifting stream OR any burning
objective". A trigger only ARMS an iteration after it persists across
``confirm_checks`` consecutive polls (a transient spike never
retrains), after the trace corpus clears ``min_trace_records`` (a
retrain from thin evidence is worse than none), and outside the
anti-churn windows below. Every poll lands a ``decision`` record —
``no_drift`` / ``confirming`` / ``armed`` / ``suppressed_cooldown`` /
``suppressed_spacing`` / ``insufficient_trace`` / ``breaker_open`` /
``poll_error`` — so a stationary soak can PROVE the daemon never
retrained (the drill asserts only ``no_drift`` decisions).

**The live shadow promote gate.** An armed iteration drives graftloop's
orchestrator as a child stage (``LoopRunner.run_stages(until=
"evaluate")``), then inserts a promotion gate the offline verdict
cannot provide: the candidate is deployed via the pool's runtime
``/shadow`` surface, every worker scores IDENTICAL live traffic with
both checkpoints, and the summed win/loss counters feed graftstudy's
two-sided sign test. Only ``wins > losses`` at ``shadow_alpha``
proceeds to ``run_stages(until="promote")`` — the offline verdict says
"better on the replayed past", the shadow gate says "better on the
traffic of the last N seconds", and an unattended promote needs both.
The gate disarms the shadow in a ``finally`` (the pool never keeps
paying double-inference for a dead gate) and a rejection is a RECORDED
outcome (``shadow_rejected``) that never retries.

**Survive everything.** ``daemon_ledger.jsonl`` carries the graftstudy
ledger discipline (fingerprint-bound header, whole-file atomic
rewrites: a SIGKILL at any instant leaves a byte-prefix-exact ledger).
A restart reconstructs the confirm streak, hysteresis windows, breaker
seed, and the in-flight iteration from the ledger alone, then resumes
the iteration's OWN loop ledger mid-stage. Transient stage failures
retry in-process (``utils/retry.RetryPolicy`` backoff); consecutive
failed iterations trip a ``CircuitBreaker`` into observe-only mode
(polls continue, decisions record ``breaker_open``, nothing retrains
until the reset timeout). Post-promote ``cooldown_s`` plus
``min_spacing_s`` between iterations is the anti-churn hysteresis — a
noisy boundary regime cannot flap generations. Chaos seams
``daemon.poll`` / ``daemon.trigger`` / ``daemon.shadow_gate``
(``utils/faults``, armed via ``GRAFTPILOT_FAULTS``) make each failure
window drillable on purpose.

Surfaces: ``python -m rl_scheduler_tpu.loopback.daemon run|status|stop``
and a tiny status plane (``GET /status`` / ``/metrics`` / ``/healthz``)
with the breaker state, decision/iteration outcome counters, streak and
hysteresis gauges. docs/serving.md §graftpilot; drill:
``make daemon-drill``.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import logging
import os
import signal
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path

from rl_scheduler_tpu.loopback.orchestrator import (
    TRANSIENT_STAGE_ERRORS,
    LoopSpec,
    fault_plan_from_env,
)
from rl_scheduler_tpu.utils.retry import CircuitBreaker, RetryPolicy

logger = logging.getLogger(__name__)

DAEMON_SCHEMA_VERSION = 1
DAEMON_LEDGER_NAME = "daemon_ledger.jsonl"
DAEMON_LOCK_NAME = "daemon.lock"
DAEMON_STATE_NAME = "daemon_state.json"
ITER_DIR_FMT = "iter-{:04d}"

# Every poll records exactly one decision (the stationary-control proof
# depends on the exhaustiveness of this set).
DECISION_OUTCOMES = ("no_drift", "confirming", "armed",
                     "suppressed_cooldown", "suppressed_spacing",
                     "insufficient_trace", "breaker_open", "poll_error")
# Daemon-ledger iteration stages (the loop's five stages live in the
# iteration's OWN loop_ledger.jsonl; these are the daemon's coarser
# checkpoints around them). `cooldown` is ALWAYS the terminal record and
# carries the iteration outcome + the hysteresis window timestamps.
ITERATION_STAGES = ("armed", "retrain", "shadow_gate", "promote",
                    "cooldown")
ITERATION_OUTCOMES = ("promoted", "refused", "shadow_rejected",
                      "rolled_back")


class DaemonDrained(Exception):
    """Raised internally when SIGTERM lands mid-iteration: unwind to the
    main loop without recording a stage (the ledger stays resumable),
    releasing the shadow gate on the way out."""


@dataclasses.dataclass(frozen=True)
class DaemonSpec:
    """The daemon's frozen protocol. Its fingerprint binds the daemon
    ledger exactly as ``LoopSpec`` binds a loop ledger: changed trigger
    thresholds or loop knobs refuse to resume into the same history
    (``--fresh`` or a new out dir)."""

    trace_dir: str                    # the pool's trace directory
    incumbent: str                    # run dir serving at daemon start
    pool_url: str                     # pool control plane base URL
    # ------------------------------------------------------- trigger
    poll_interval_s: float = 30.0
    poll_retries: int = 2             # transient /stats retries per poll
    confirm_checks: int = 2           # consecutive drifting polls to arm
    min_trace_records: int = 50       # trace-volume floor before arming
    # ---------------------------------------------------- hysteresis
    cooldown_s: float = 300.0         # post-PROMOTE quiet period
    min_spacing_s: float = 60.0       # min gap between ANY iterations
    # -------------------------------------------------- shadow gate
    shadow_min_scored: int = 50       # paired verdicts before grading
    shadow_alpha: float = 0.05        # two-sided sign-test bar
    shadow_timeout_s: float = 120.0   # collection deadline (transient)
    # ------------------------------------------------------ breaker
    breaker_threshold: int = 3        # consecutive failures to open
    breaker_reset_s: float = 600.0
    # ------------------------------------------------------- bounds
    max_iterations: int = 0           # 0 = unbounded
    max_polls: int = 0                # 0 = unbounded (soak/test bound)
    # ---------------------------------------------- loop iteration
    steps: int = 256
    mix_frac: float = 0.25
    compile_seed: int = 0
    iterations: int = 8
    seed: int = 0
    eval_every: int = 2
    eval_episodes: int = 32
    verdict_seeds: tuple = (0, 1, 2, 3, 4)
    verdict_episodes: int = 64
    required_verdict: str = "confirmed_above"
    forgetting_tolerance_pct: float = 10.0
    num_nodes: int | None = None
    max_stage_retries: int = 2
    rollout_timeout_s: float = 120.0

    def __post_init__(self):
        if not self.pool_url:
            raise ValueError("pool_url: the daemon watches (and promotes "
                             "through) a pool control plane")
        if self.poll_interval_s <= 0:
            raise ValueError(f"poll_interval_s={self.poll_interval_s}: > 0")
        if self.confirm_checks < 1:
            raise ValueError(f"confirm_checks={self.confirm_checks}: >= 1")
        if self.shadow_min_scored < 1:
            raise ValueError(
                f"shadow_min_scored={self.shadow_min_scored}: >= 1")
        if not 0.0 < self.shadow_alpha <= 1.0:
            raise ValueError(f"shadow_alpha={self.shadow_alpha}: (0, 1]")
        if self.breaker_threshold < 1:
            raise ValueError(
                f"breaker_threshold={self.breaker_threshold}: >= 1")
        if self.cooldown_s < 0 or self.min_spacing_s < 0:
            raise ValueError("cooldown_s/min_spacing_s: >= 0")
        self.loop_spec(self.incumbent)  # validates the loop knobs

    def loop_spec(self, incumbent: str) -> LoopSpec:
        """The loop iteration this daemon arms. ``incumbent`` moves as
        promotes land (the ledger's last promoted candidate), so each
        iteration warm-starts from — and verdicts against — what the
        pool actually serves."""
        return LoopSpec(
            trace_dir=self.trace_dir,
            incumbent=incumbent,
            pool_url=self.pool_url,
            steps=self.steps,
            mix_frac=self.mix_frac,
            compile_seed=self.compile_seed,
            iterations=self.iterations,
            seed=self.seed,
            eval_every=self.eval_every,
            eval_episodes=self.eval_episodes,
            verdict_seeds=tuple(self.verdict_seeds),
            verdict_episodes=self.verdict_episodes,
            required_verdict=self.required_verdict,
            forgetting_tolerance_pct=self.forgetting_tolerance_pct,
            num_nodes=self.num_nodes,
            dry_run=False,
        )

    def to_json(self) -> dict:
        return json.loads(json.dumps(dataclasses.asdict(self)))

    def fingerprint(self) -> str:
        blob = json.dumps(self.to_json(), sort_keys=True,
                          separators=(",", ":"))
        return hashlib.sha256(blob.encode()).hexdigest()[:16]


def daemon_spec_from_json(d: dict) -> DaemonSpec:
    kw = dict(d)
    kw["verdict_seeds"] = tuple(kw["verdict_seeds"])
    return DaemonSpec(**kw)


class DaemonLedgerMismatch(RuntimeError):
    """The daemon dir's ledger was written under a different spec."""


class DaemonLedger:
    """The daemon's cross-iteration journal — the graftstudy/graftloop
    ledger discipline (whole-file tmp-then-rename appends, sorted-key
    records, header bound to the spec fingerprint) over two record
    kinds: per-poll ``decision`` records and per-iteration ``iteration``
    stage records. A SIGKILL at any instant leaves either the old or the
    new complete ledger — prior lines survive bitwise, which the kill
    matrix asserts with byte-prefix checks."""

    def __init__(self, daemon_dir: str | Path, spec: DaemonSpec):
        self.path = Path(daemon_dir) / DAEMON_LEDGER_NAME
        self.spec = spec
        self.path.parent.mkdir(parents=True, exist_ok=True)
        if self.path.exists() and self.path.stat().st_size:
            header = json.loads(self.path.read_text().splitlines()[0])
            if header.get("spec_sha") != spec.fingerprint():
                raise DaemonLedgerMismatch(
                    f"{self.path} was written for spec "
                    f"{header.get('spec_sha')}; this run's spec is "
                    f"{spec.fingerprint()} — a changed daemon protocol "
                    "cannot resume into the same ledger (new out dir, "
                    "or --fresh to discard)")
        else:
            self._rewrite([self._dumps({
                "kind": "header",
                "schema_version": DAEMON_SCHEMA_VERSION,
                "spec_sha": spec.fingerprint(),
                "spec": spec.to_json(),
            })])

    @staticmethod
    def _dumps(record: dict) -> str:
        return json.dumps(record, sort_keys=True, separators=(", ", ": "))

    def _rewrite(self, lines: list) -> None:
        tmp = self.path.with_suffix(f".tmp.{os.getpid()}")
        data = "".join(line + "\n" for line in lines)
        with open(tmp, "w") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)

    def _append(self, record: dict) -> None:
        lines = self.path.read_text().splitlines() if self.path.exists() \
            else []
        self._rewrite(lines + [self._dumps(record)])

    def append_decision(self, outcome: str, detail: dict) -> None:
        if outcome not in DECISION_OUTCOMES:
            raise ValueError(f"outcome={outcome!r}: one of "
                             f"{DECISION_OUTCOMES}")
        self._append({"kind": "decision", "seq": self.next_seq(),
                      "ts": round(time.time(), 3), "outcome": outcome,
                      "detail": detail})

    def append_iteration(self, iteration: int, stage: str, status: str,
                         out: dict) -> None:
        if stage not in ITERATION_STAGES:
            raise ValueError(f"stage={stage!r}: one of "
                             f"{ITERATION_STAGES}")
        self._append({"kind": "iteration", "iter": iteration,
                      "stage": stage, "status": status,
                      "ts": round(time.time(), 3), "out": out})

    def records(self) -> list:
        return [json.loads(line)
                for line in self.path.read_text().splitlines()[1:]]

    def decisions(self) -> list:
        return [r for r in self.records() if r["kind"] == "decision"]

    def next_seq(self) -> int:
        return len(self.decisions()) + 1

    def iterations(self) -> dict:
        """``{iter: {stage: record}}`` (newest wins — at most one per
        stage per iteration in a healthy ledger)."""
        out: dict = {}
        for r in self.records():
            if r["kind"] == "iteration":
                out.setdefault(r["iter"], {})[r["stage"]] = r
        return out

    def confirm_streak(self) -> int:
        """Trailing consecutive ``confirming`` decisions — the streak a
        restart resumes instead of re-counting from zero (the trigger's
        persistence requirement survives the process)."""
        streak = 0
        for r in reversed(self.decisions()):
            if r["outcome"] != "confirming":
                break
            streak += 1
        return streak

    def inflight_iteration(self) -> int | None:
        """The armed iteration missing its terminal ``cooldown`` record,
        if any — what a restart must resume before polling again."""
        iters = self.iterations()
        open_ = [i for i, stages in iters.items()
                 if "cooldown" not in stages]
        return max(open_) if open_ else None

    def current_incumbent(self) -> str:
        """The run dir the pool serves NOW: the last promoted
        candidate, else the spec's initial incumbent."""
        incumbent = self.spec.incumbent
        for i in sorted(self.iterations()):
            stages = self.iterations()[i]
            cool = stages.get("cooldown")
            if cool and cool["out"].get("outcome") == "promoted":
                incumbent = stages["retrain"]["out"]["candidate"]
        return incumbent

    def hysteresis(self) -> tuple:
        """``(cooldown_until, next_allowed_at)`` from the newest
        terminal record (absolute epoch seconds; ``(0, 0)`` before the
        first iteration completes)."""
        newest = (0.0, 0.0)
        for stages in self.iterations().values():
            cool = stages.get("cooldown")
            if cool:
                pair = (float(cool["out"].get("cooldown_until", 0.0)),
                        float(cool["out"].get("next_allowed_at", 0.0)))
                newest = max(newest, pair)
        return newest

    def trailing_failures(self) -> int:
        """Consecutive ``rolled_back`` outcomes ending the iteration
        history — the breaker's resume seed (a restart must not reset an
        almost-open breaker to closed)."""
        streak = 0
        for i in sorted(self.iterations(), reverse=True):
            cool = self.iterations()[i].get("cooldown")
            if cool is None:
                continue  # the in-flight iteration has no outcome yet
            if cool["out"].get("outcome") != "rolled_back":
                break
            streak += 1
        return streak


class Daemon:
    """The graftpilot controller: poll → confirm → iterate → gate →
    promote → cool down, forever, resumable from the ledger alone."""

    def __init__(self, spec: DaemonSpec, daemon_dir: str | Path,
                 fault_plan=None):
        self.spec = spec
        self.daemon_dir = Path(daemon_dir)
        self.fault_plan = fault_plan
        self.daemon_dir.mkdir(parents=True, exist_ok=True)
        self.ledger = DaemonLedger(self.daemon_dir, spec)
        self.breaker = CircuitBreaker(
            "graftpilot.iteration",
            failure_threshold=spec.breaker_threshold,
            reset_timeout_s=spec.breaker_reset_s)
        for _ in range(self.ledger.trailing_failures()):
            self.breaker.record_failure()
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._state = "starting"
        self.polls_total = 0
        # Outcome counters seed from the ledger so /status and /metrics
        # survive restarts exactly like the ledger does.
        self.decision_counts = {o: 0 for o in DECISION_OUTCOMES}
        self.iteration_counts = {o: 0 for o in ITERATION_OUTCOMES}
        for r in self.ledger.decisions():
            self.decision_counts[r["outcome"]] += 1
        for stages in self.ledger.iterations().values():
            cool = stages.get("cooldown")
            if cool:
                self.iteration_counts[cool["out"]["outcome"]] += 1

    # ------------------------------------------------------- plumbing

    def request_stop(self) -> None:
        """Graceful drain: finish nothing new, unwind the in-flight
        stage at the next boundary (the SIGTERM handler and ``stop``
        subcommand land here)."""
        self._stop.set()

    def _set_state(self, state: str) -> None:
        with self._lock:
            self._state = state

    def _record_decision(self, outcome: str, detail: dict) -> None:
        self.ledger.append_decision(outcome, detail)
        with self._lock:
            self.decision_counts[outcome] += 1
        logger.info("graftpilot: decision %s %s", outcome, detail)

    def _http(self, path: str, payload: dict | None = None,
              timeout_s: float = 10.0) -> dict:
        url = self.spec.pool_url.rstrip("/") + path
        if payload is None:
            req = urllib.request.Request(url)
        else:
            req = urllib.request.Request(
                url, data=json.dumps(payload).encode(),
                headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=timeout_s) as resp:
                return json.load(resp)
        except urllib.error.HTTPError as e:
            # Control-plane refusals (partial fan-out 409, 5xx) are
            # transient to the daemon: RuntimeError rides the
            # TRANSIENT_STAGE_ERRORS family, the iteration resumes.
            raise RuntimeError(
                f"pool answered {e.code} on {path}") from e

    # ----------------------------------------------------------- poll

    def _get_stats(self) -> dict:
        if self.fault_plan is not None:
            self.fault_plan.check("daemon.poll", OSError)
        return self._http("/stats")

    def _poll_stats(self) -> dict:
        """One poll under the transient retry budget (the orchestrator's
        manual-loop-over-``RetryPolicy.delays()`` idiom, so exhaustion
        re-raises the original error type for the ``poll_error``
        record)."""
        if self.spec.poll_retries == 0:
            return self._get_stats()
        delays = RetryPolicy(max_attempts=self.spec.poll_retries + 1,
                             base_delay_s=0.05, max_delay_s=1.0,
                             seed=self.spec.seed).delays()
        for attempt in range(1, self.spec.poll_retries + 2):
            try:
                return self._get_stats()
            except TRANSIENT_STAGE_ERRORS:
                if attempt > self.spec.poll_retries:
                    raise
                self._stop.wait(delays[attempt - 1])
        raise AssertionError("unreachable: the final attempt re-raises")

    def evaluate_trigger(self, stats: dict) -> dict:
        """The evidence one poll produces: driftview-graded streams
        (``grade_report`` — the SAME grading as ``driftview --check``),
        burning SLO objectives, and the trace-volume floor input."""
        from tools.driftview import build_report, grade_report

        grade = grade_report(build_report(stats=stats), budgets={})
        drifting = sorted(s for s, g in grade["streams"].items()
                          if g == "drifting")
        slo = stats.get("slo") or {}
        burning = sorted(
            name for name, obj in (slo.get("objectives") or {}).items()
            if obj.get("burning"))
        pool = stats.get("pool") or {}
        return {
            "drifting": drifting,
            "burning": burning,
            "trace_records": (stats.get("trace") or {})
            .get("records_total", 0),
            "generation": pool.get("generation",
                                   stats.get("generation", 0)),
        }

    def _tick_poll(self) -> bool:
        """One poll → exactly one decision record. Returns True when an
        iteration was armed (the caller runs it without waiting)."""
        self.polls_total += 1
        try:
            stats = self._poll_stats()
        except TRANSIENT_STAGE_ERRORS as exc:
            self._record_decision("poll_error", {"error": repr(exc)})
            return False
        evidence = self.evaluate_trigger(stats)
        now = time.time()
        cooldown_until, next_allowed = self.ledger.hysteresis()
        if not (evidence["drifting"] or evidence["burning"]):
            self._record_decision("no_drift", evidence)
            return False
        if evidence["trace_records"] < self.spec.min_trace_records:
            self._record_decision("insufficient_trace", {
                **evidence, "floor": self.spec.min_trace_records})
            return False
        if now < cooldown_until:
            self._record_decision("suppressed_cooldown", {
                **evidence, "cooldown_until": cooldown_until})
            return False
        if now < next_allowed:
            self._record_decision("suppressed_spacing", {
                **evidence, "next_allowed_at": next_allowed})
            return False
        if not self.breaker.allow():
            # Observe-only mode: the trigger is real, the daemon refuses
            # to act on it until the breaker's reset timeout.
            self._record_decision("breaker_open", {
                **evidence, "breaker": self.breaker.snapshot()})
            return False
        streak = self.ledger.confirm_streak()
        if streak + 1 < self.spec.confirm_checks:
            self._record_decision("confirming", {
                **evidence, "streak": streak + 1,
                "needed": self.spec.confirm_checks})
            return False
        if self.fault_plan is not None:
            # The crash window between the trigger verdict and arming:
            # nothing recorded yet, so a resume re-polls live evidence
            # and can never double-arm a phantom iteration.
            self.fault_plan.check("daemon.trigger", OSError)
        iteration = max(self.ledger.iterations(), default=-1) + 1
        loop_dir = self.daemon_dir / ITER_DIR_FMT.format(iteration)
        incumbent = self.ledger.current_incumbent()
        # Iteration record FIRST, then the decision: a kill between the
        # two leaves an in-flight iteration a resume finds (the reverse
        # order would leave an `armed` decision pointing at nothing).
        self.ledger.append_iteration(iteration, "armed", "ok", {
            "loop_dir": str(loop_dir), "incumbent": incumbent,
            "evidence": evidence})
        self._record_decision("armed", {"iter": iteration, **evidence})
        return True

    # ------------------------------------------------------ iteration

    def _shadow_gate(self, candidate: str) -> dict:
        """Deploy the candidate on the pool's runtime ``/shadow``
        surface, collect paired live verdicts on identical traffic, and
        grade incumbent-vs-candidate with the two-sided sign test (ties
        dropped). ALWAYS disarms on the way out — timeout, drain, and
        chaos paths included."""
        from rl_scheduler_tpu.studies.analysis import sign_test_pvalue

        if self.fault_plan is not None:
            self.fault_plan.check("daemon.shadow_gate", OSError)
        armed = self._http("/shadow", {"path": candidate}, timeout_s=60.0)
        if armed.get("errors"):
            raise RuntimeError(
                f"shadow arm was partial: {armed['errors']}")
        shadow: dict = {}
        try:
            deadline = time.monotonic() + self.spec.shadow_timeout_s
            while True:
                if self._stop.is_set():
                    raise DaemonDrained("SIGTERM mid shadow gate")
                stats = self._http("/stats")
                shadow = stats.get("shadow") or {}
                if shadow.get("scored_total", 0) \
                        >= self.spec.shadow_min_scored:
                    break
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"shadow gate scored "
                        f"{shadow.get('scored_total', 0)} < "
                        f"{self.spec.shadow_min_scored} paired verdicts "
                        f"in {self.spec.shadow_timeout_s:.0f}s — is the "
                        "pool receiving traffic?")
                self._stop.wait(0.25)
        finally:
            try:
                self._http("/shadow", {"path": None}, timeout_s=30.0)
            except Exception as exc:  # noqa: BLE001 — disarm is
                # best-effort on the unwind path; the next arm swaps in
                # fresh scorers anyway, and the original error matters
                # more than a failed cleanup.
                logger.warning("graftpilot: shadow disarm failed: %s",
                               exc)
        wins = int(shadow.get("wins_total", 0))
        losses = int(shadow.get("losses_total", 0))
        pvalue = sign_test_pvalue(wins, losses)
        confirmed = wins > losses and pvalue <= self.spec.shadow_alpha
        return {
            "confirmed": confirmed,
            "wins": wins,
            "losses": losses,
            "ties": int(shadow.get("ties_total", 0)),
            "scored": int(shadow.get("scored_total", 0)),
            "pvalue": round(pvalue, 6),
            "alpha": self.spec.shadow_alpha,
            "verdict": "confirmed_above" if confirmed
            else "not_confirmed",
        }

    def _adopt_landed_promote(self, armed_generation: int) -> dict | None:
        """Recover from the promote crash window: a kill can land AFTER
        graftloop's ``POST /promote`` dispatched but BEFORE its ledger
        record — the loop's at-least-once resume would re-roll the same
        candidate and bump the generation twice. The daemon is the
        pool's single promoting writer, so a pool already past the
        generation this iteration armed against IS our promote landing:
        adopt it (waiting out an in-flight rollout first) instead of
        re-posting. Returns the promote `out` to record, or ``None``
        when the pool still serves the armed generation (promote never
        dispatched — run the stage normally)."""
        deadline = time.monotonic() + self.spec.rollout_timeout_s
        while True:
            rollout = self._http("/rollout")
            if not rollout.get("active"):
                break
            if time.monotonic() > deadline:
                raise TimeoutError(
                    "a rollout was already in flight on resume and "
                    "stayed active past "
                    f"{self.spec.rollout_timeout_s:.0f}s")
            self._stop.wait(0.2)
        generation = int(rollout.get("generation", 0))
        if generation <= armed_generation:
            return None
        logger.info("graftpilot: pool already serves generation %d "
                    "(armed against %d) — adopting the landed promote "
                    "instead of re-rolling", generation,
                    armed_generation)
        return {"generation": generation, "adopted": True,
                "rollout": rollout}

    def _finish_iteration(self, iteration: int, outcome: str) -> None:
        now = time.time()
        cooldown_until = now + self.spec.cooldown_s \
            if outcome == "promoted" else now
        self.ledger.append_iteration(iteration, "cooldown", "ok", {
            "outcome": outcome,
            "cooldown_until": round(cooldown_until, 3),
            "next_allowed_at": round(now + self.spec.min_spacing_s, 3),
        })
        with self._lock:
            self.iteration_counts[outcome] += 1
        if outcome == "promoted":
            self.breaker.record_success()
        elif outcome == "rolled_back":
            # The pool's own gates refused a candidate BOTH offline and
            # live evidence endorsed: that is the daemon malfunction the
            # breaker counts. Refusals and shadow rejections are the
            # gates WORKING — breaker-neutral.
            self.breaker.record_failure()
        logger.info("graftpilot: iteration %d finished: %s",
                    iteration, outcome)

    def _run_iteration(self, iteration: int) -> None:
        """Drive (or resume) one armed iteration through retrain →
        shadow_gate → promote → cooldown. Each daemon stage is recorded
        after it completes; the loop stages inside `retrain`/`promote`
        resume from the iteration's own loop ledger, so a SIGKILL
        anywhere re-enters exactly the interrupted work."""
        from rl_scheduler_tpu.loopback.orchestrator import LoopRunner

        stages = self.ledger.iterations()[iteration]
        armed = stages["armed"]["out"]
        runner = LoopRunner(
            self.spec.loop_spec(armed["incumbent"]),
            armed["loop_dir"], fault_plan=self.fault_plan,
            rollout_timeout_s=self.spec.rollout_timeout_s,
            max_stage_retries=self.spec.max_stage_retries)
        if "retrain" not in stages:
            self._set_state("retraining")
            done = runner.run_stages(until="evaluate")
            verdict = done["evaluate"]["out"]
            status = "ok" if verdict.get("promote") else "refused"
            self.ledger.append_iteration(iteration, "retrain", status, {
                "candidate": done["retrain"]["out"]["candidate"],
                "verdict": verdict.get("verdict"),
            })
            stages = self.ledger.iterations()[iteration]
        if self._stop.is_set():
            raise DaemonDrained("SIGTERM between stages")
        retrain = stages["retrain"]
        if retrain["status"] != "ok":
            # The offline verdict refused the candidate: a recorded
            # outcome, never retried (a fresh trigger arms a fresh
            # iteration over fresh traffic).
            self._finish_iteration(iteration, "refused")
            return
        candidate = retrain["out"]["candidate"]
        if "shadow_gate" not in stages:
            self._set_state("shadow_gating")
            gate = self._shadow_gate(candidate)
            self.ledger.append_iteration(
                iteration, "shadow_gate",
                "ok" if gate["confirmed"] else "shadow_rejected", gate)
            stages = self.ledger.iterations()[iteration]
        if stages["shadow_gate"]["status"] != "ok":
            self._finish_iteration(iteration, "shadow_rejected")
            return
        if self._stop.is_set():
            raise DaemonDrained("SIGTERM between stages")
        if "promote" not in stages:
            self._set_state("promoting")
            adopted = self._adopt_landed_promote(
                int(armed["evidence"].get("generation", 0)))
            if adopted is not None:
                self.ledger.append_iteration(
                    iteration, "promote", "ok",
                    {**adopted, "candidate": candidate})
            else:
                done = runner.run_stages(until="promote")
                promote = done["promote"]
                self.ledger.append_iteration(
                    iteration, "promote", promote["status"],
                    {**promote["out"], "candidate": candidate})
            stages = self.ledger.iterations()[iteration]
        outcome = {"ok": "promoted", "refused": "refused",
                   "rolled_back": "rolled_back"}[
                       stages["promote"]["status"]]
        self._finish_iteration(iteration, outcome)

    # ------------------------------------------------------ main loop

    def completed_iterations(self) -> int:
        return sum(1 for s in self.ledger.iterations().values()
                   if "cooldown" in s)

    def run_forever(self) -> dict:
        """The daemon main loop, until drained or a ``max_*`` bound.
        Returns the final status body (the CLI's summary line)."""
        logger.info("graftpilot: watching %s (spec %s)",
                    self.spec.pool_url, self.spec.fingerprint())
        while not self._stop.is_set():
            if self.spec.max_iterations and self.completed_iterations() \
                    >= self.spec.max_iterations:
                break
            inflight = self.ledger.inflight_iteration()
            if inflight is not None:
                if not self.breaker.allow():
                    # Observe-only with work parked in flight: each
                    # refused resume counts as (and is bounded like) a
                    # poll, so a soak bound still terminates the loop.
                    if self.spec.max_polls and self.polls_total \
                            >= self.spec.max_polls:
                        break
                    self.polls_total += 1
                    self._set_state("observe_only")
                    self._record_decision("breaker_open", {
                        "iter": inflight,
                        "breaker": self.breaker.snapshot()})
                    self._stop.wait(self.spec.poll_interval_s)
                    continue
                try:
                    self._run_iteration(inflight)
                except DaemonDrained:
                    break
                except TRANSIENT_STAGE_ERRORS as exc:
                    # In-process retries exhausted: the iteration stays
                    # in-flight (its ledgers resume), the breaker counts
                    # the failure, the loop backs off one poll interval.
                    self.breaker.record_failure()
                    logger.warning(
                        "graftpilot: iteration %d failed transiently "
                        "(%s); will resume", inflight, exc)
                    self._stop.wait(self.spec.poll_interval_s)
                continue
            if self.spec.max_polls and self.polls_total \
                    >= self.spec.max_polls:
                break
            self._set_state("polling")
            armed = False
            try:
                armed = self._tick_poll()
            except TRANSIENT_STAGE_ERRORS as exc:
                # daemon.trigger's crash window: seen but unrecorded —
                # the next poll re-derives the verdict from live
                # evidence.
                logger.warning("graftpilot: poll tick failed (%s); "
                               "re-polling", exc)
            if not armed:
                self._stop.wait(self.spec.poll_interval_s)
        self._set_state("stopped")
        logger.info("graftpilot: drained")
        return self.status_body()

    # ------------------------------------------------------- surfaces

    def status_body(self) -> dict:
        with self._lock:
            state = self._state
            decisions = dict(self.decision_counts)
            iterations = dict(self.iteration_counts)
        cooldown_until, next_allowed = self.ledger.hysteresis()
        return {
            "schema_version": DAEMON_SCHEMA_VERSION,
            "daemon": "graftpilot",
            "state": state,
            "spec_sha": self.spec.fingerprint(),
            "pool_url": self.spec.pool_url,
            "incumbent": self.ledger.current_incumbent(),
            "polls_total": self.polls_total,
            "decisions": decisions,
            "iterations": iterations,
            "iterations_completed": self.completed_iterations(),
            "inflight_iteration": self.ledger.inflight_iteration(),
            "confirm_streak": self.ledger.confirm_streak(),
            "cooldown_until": cooldown_until,
            "next_allowed_at": next_allowed,
            "breaker": self.breaker.snapshot(),
        }

    def metrics_body(self) -> str:
        """Prometheus exposition for the daemon's own plane (the pool
        keeps its own ``/metrics``; one scrape config reads both)."""
        body = self.status_body()
        breaker = body["breaker"]
        now = time.time()
        lines = [
            "# HELP graftpilot_breaker_state Iteration breaker state "
            "(0=closed, 1=half_open, 2=open; open = observe-only).",
            "# TYPE graftpilot_breaker_state gauge",
            f"graftpilot_breaker_state "
            f"{CircuitBreaker.STATE_CODES[breaker['state']]}",
            "# HELP graftpilot_breaker_consecutive_failures Consecutive "
            "failed iterations counted toward the open threshold.",
            "# TYPE graftpilot_breaker_consecutive_failures gauge",
            f"graftpilot_breaker_consecutive_failures "
            f"{breaker['consecutive_failures']}",
            "# HELP graftpilot_breaker_opens_total Times the iteration "
            "breaker opened (daemon lifetime).",
            "# TYPE graftpilot_breaker_opens_total counter",
            f"graftpilot_breaker_opens_total {breaker['opens_total']}",
            "# HELP graftpilot_decisions_total Poll decisions by "
            "outcome (one per poll; ledger-backed, survives restarts).",
            "# TYPE graftpilot_decisions_total counter",
        ]
        lines += [
            f'graftpilot_decisions_total{{outcome="{o}"}} {n}'
            for o, n in sorted(body["decisions"].items())
        ]
        lines += [
            "# HELP graftpilot_iterations_total Finished retrain "
            "iterations by outcome.",
            "# TYPE graftpilot_iterations_total counter",
        ]
        lines += [
            f'graftpilot_iterations_total{{outcome="{o}"}} {n}'
            for o, n in sorted(body["iterations"].items())
        ]
        lines += [
            "# HELP graftpilot_confirm_streak Consecutive drifting "
            "polls toward the confirm_checks arming bar.",
            "# TYPE graftpilot_confirm_streak gauge",
            f"graftpilot_confirm_streak {body['confirm_streak']}",
            "# HELP graftpilot_cooldown_active Whether the post-promote "
            "cool-down window is suppressing triggers.",
            "# TYPE graftpilot_cooldown_active gauge",
            f"graftpilot_cooldown_active "
            f"{1 if now < body['cooldown_until'] else 0}",
            "# HELP graftpilot_polls_total /stats polls this process "
            "has issued.",
            "# TYPE graftpilot_polls_total counter",
            f"graftpilot_polls_total {body['polls_total']}",
        ]
        return "\n".join(lines) + "\n"


# ------------------------------------------------------------ status plane


class _DaemonHandler(BaseHTTPRequestHandler):
    daemon: Daemon = None  # set by serve_status

    def log_message(self, *args):  # noqa: A002 — silence stdlib logging
        pass

    def _send(self, code: int, body, content_type="application/json"):
        data = body.encode() if isinstance(body, str) \
            else json.dumps(body, sort_keys=True).encode()
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self):  # noqa: N802 (stdlib API)
        if self.path == "/status":
            self._send(200, self.daemon.status_body())
        elif self.path == "/metrics":
            self._send(200, self.daemon.metrics_body(),
                       content_type="text/plain; version=0.0.4")
        elif self.path == "/healthz":
            self._send(200, {"status": "ok", "pid": os.getpid()})
        else:
            self._send(404, {"error": f"unknown path {self.path}"})


def serve_status(daemon: Daemon, host: str = "127.0.0.1",
                 port: int = 0) -> ThreadingHTTPServer:
    """Start the daemon's status plane on a background thread; returns
    the bound server (``server_address[1]`` is the ephemeral port)."""
    handler = type("_BoundHandler", (_DaemonHandler,),
                   {"daemon": daemon})
    server = ThreadingHTTPServer((host, port), handler)
    thread = threading.Thread(target=server.serve_forever,
                              name="graftpilot-status", daemon=True)
    thread.start()
    return server


# -------------------------------------------------------------------- CLI


def _read_state(daemon_dir: Path) -> dict:
    state_path = daemon_dir / DAEMON_STATE_NAME
    if not state_path.exists():
        raise SystemExit(
            f"no {DAEMON_STATE_NAME} under {daemon_dir} — is a daemon "
            "running over this dir?")
    return json.loads(state_path.read_text())


def _cmd_run(args) -> int:
    from rl_scheduler_tpu.studies.spec import parse_seeds
    from rl_scheduler_tpu.utils.fsio import atomic_write_json
    from rl_scheduler_tpu.utils.pidlock import acquire_pidfile_lock

    try:
        spec = DaemonSpec(
            trace_dir=args.trace_dir,
            incumbent=args.incumbent,
            pool_url=args.pool,
            poll_interval_s=args.poll_interval,
            poll_retries=args.poll_retries,
            confirm_checks=args.confirm_checks,
            min_trace_records=args.min_trace_records,
            cooldown_s=args.cooldown,
            min_spacing_s=args.min_spacing,
            shadow_min_scored=args.shadow_min_scored,
            shadow_alpha=args.shadow_alpha,
            shadow_timeout_s=args.shadow_timeout,
            breaker_threshold=args.breaker_threshold,
            breaker_reset_s=args.breaker_reset,
            max_iterations=args.max_iterations,
            max_polls=args.max_polls,
            steps=args.steps,
            mix_frac=args.mix,
            compile_seed=args.compile_seed,
            iterations=args.iterations,
            seed=args.seed,
            eval_every=args.eval_every,
            eval_episodes=args.eval_episodes,
            verdict_seeds=tuple(parse_seeds(args.verdict_seeds)),
            verdict_episodes=args.verdict_episodes,
            required_verdict=args.required_verdict,
            forgetting_tolerance_pct=args.forgetting_tolerance,
            num_nodes=args.num_nodes,
            max_stage_retries=args.max_stage_retries,
            rollout_timeout_s=args.rollout_timeout,
        )
    except ValueError as e:
        raise SystemExit(str(e))

    daemon_dir = Path(args.out)
    daemon_dir.mkdir(parents=True, exist_ok=True)
    try:
        lock = acquire_pidfile_lock(
            daemon_dir / DAEMON_LOCK_NAME,
            "a graftpilot daemon is already running over this dir (pid "
            "{pid} holds {lock}); two controllers would interleave "
            "iterations")
    except RuntimeError as e:
        raise SystemExit(str(e))
    server = None
    try:
        if args.fresh:
            import shutil

            for entry in list(daemon_dir.iterdir()):
                if entry.name == DAEMON_LOCK_NAME:
                    continue
                shutil.rmtree(entry) if entry.is_dir() \
                    else entry.unlink()
        fault_plan = fault_plan_from_env(
            os.environ.get("GRAFTPILOT_FAULTS"))
        try:
            daemon = Daemon(spec, daemon_dir, fault_plan=fault_plan)
        except DaemonLedgerMismatch as e:
            raise SystemExit(str(e))
        server = serve_status(daemon, port=args.status_port)
        atomic_write_json(daemon_dir / DAEMON_STATE_NAME, {
            "pid": os.getpid(),
            "status_port": server.server_address[1],
            "started_at": round(time.time(), 3),
            "spec_sha": spec.fingerprint(),
        })
        signal.signal(signal.SIGTERM,
                      lambda *_: daemon.request_stop())
        summary = daemon.run_forever()
    finally:
        if server is not None:
            server.shutdown()
        lock.unlink(missing_ok=True)
    print(json.dumps({"metric": "graftpilot_summary", **summary},
                     sort_keys=True))
    return 0


def _cmd_status(args) -> int:
    state = _read_state(Path(args.out))
    url = f"http://127.0.0.1:{state['status_port']}/status"
    try:
        with urllib.request.urlopen(url, timeout=5) as resp:
            body = json.load(resp)
    except OSError as e:
        raise SystemExit(
            f"daemon status plane unreachable at {url} ({e}) — the "
            f"recorded pid is {state['pid']}; stale state file?")
    print(json.dumps(body, sort_keys=True))
    return 0


def _cmd_stop(args) -> int:
    state = _read_state(Path(args.out))
    pid = state["pid"]
    try:
        os.kill(pid, signal.SIGTERM)
    except ProcessLookupError:
        print(json.dumps({"stopped": False, "pid": pid,
                          "reason": "not running"}))
        return 0
    deadline = time.monotonic() + args.timeout
    while time.monotonic() < deadline:
        try:
            os.kill(pid, 0)
        except ProcessLookupError:
            print(json.dumps({"stopped": True, "pid": pid}))
            return 0
        time.sleep(0.2)
    print(json.dumps({"stopped": False, "pid": pid,
                      "reason": f"still running after "
                                f"{args.timeout:.0f}s"}))
    return 1


def main(argv: list | None = None) -> int:
    import argparse

    p = argparse.ArgumentParser(
        prog="python -m rl_scheduler_tpu.loopback.daemon",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    sub = p.add_subparsers(dest="cmd", required=True)

    run = sub.add_parser(
        "run", help="start the controller (foreground; SIGTERM drains)")
    run.add_argument("--trace-dir", required=True,
                     help="the pool's trace directory (extender "
                          "--trace-dir)")
    run.add_argument("--incumbent", required=True,
                     help="run dir the pool serves at daemon start; "
                          "moves automatically as promotes land")
    run.add_argument("--pool", required=True, metavar="URL",
                     help="pool control-plane base URL (polled for "
                          "/stats, armed via /shadow, promoted via "
                          "/promote)")
    run.add_argument("--out", required=True,
                     help="daemon working dir: ledger, state file, "
                          "per-iteration loop dirs. Re-running resumes")
    run.add_argument("--status-port", type=int, default=0,
                     help="status-plane port (default 0 = ephemeral; "
                          "recorded in daemon_state.json)")
    run.add_argument("--poll-interval", type=float, default=30.0,
                     help="seconds between /stats polls (default 30)")
    run.add_argument("--poll-retries", type=int, default=2,
                     help="transient /stats retries per poll before a "
                          "poll_error decision (default 2)")
    run.add_argument("--confirm-checks", type=int, default=2,
                     help="consecutive drifting polls required to arm "
                          "(default 2 — one spike never retrains)")
    run.add_argument("--min-trace-records", type=int, default=50,
                     help="trace-volume floor before arming (default 50)")
    run.add_argument("--cooldown", type=float, default=300.0,
                     help="post-PROMOTE quiet seconds (default 300)")
    run.add_argument("--min-spacing", type=float, default=60.0,
                     help="minimum seconds between iterations of any "
                          "outcome (default 60)")
    run.add_argument("--shadow-min-scored", type=int, default=50,
                     help="paired live verdicts the shadow gate "
                          "collects before grading (default 50)")
    run.add_argument("--shadow-alpha", type=float, default=0.05,
                     help="two-sided sign-test significance bar "
                          "(default 0.05)")
    run.add_argument("--shadow-timeout", type=float, default=120.0,
                     help="shadow collection deadline, transient on "
                          "expiry (default 120)")
    run.add_argument("--breaker-threshold", type=int, default=3,
                     help="consecutive failed iterations before "
                          "observe-only mode (default 3)")
    run.add_argument("--breaker-reset", type=float, default=600.0,
                     help="observe-only cool-down seconds (default 600)")
    run.add_argument("--max-iterations", type=int, default=0,
                     help="stop after N completed iterations "
                          "(default 0 = unbounded)")
    run.add_argument("--max-polls", type=int, default=0,
                     help="stop after N polls with no iteration "
                          "in flight (default 0 = unbounded)")
    run.add_argument("--steps", type=int, default=256)
    run.add_argument("--mix", type=float, default=0.25)
    run.add_argument("--compile-seed", type=int, default=0)
    run.add_argument("--iterations", type=int, default=8,
                     help="fine-tune iterations per retrain (default 8)")
    run.add_argument("--seed", type=int, default=0)
    run.add_argument("--eval-every", type=int, default=2)
    run.add_argument("--eval-episodes", type=int, default=32)
    run.add_argument("--verdict-seeds", default="0-4", metavar="SPEC")
    run.add_argument("--verdict-episodes", type=int, default=64)
    run.add_argument("--required-verdict", default="confirmed_above",
                     choices=("point_above", "confirmed_above"))
    run.add_argument("--forgetting-tolerance", type=float, default=10.0,
                     metavar="PCT")
    run.add_argument("--num-nodes", type=int, default=None)
    run.add_argument("--max-stage-retries", type=int, default=2)
    run.add_argument("--rollout-timeout", type=float, default=120.0)
    run.add_argument("--fresh", action="store_true",
                     help="discard the daemon dir's ledger/iterations "
                          "and start over (refused while another "
                          "daemon holds the lock)")
    run.set_defaults(fn=_cmd_run)

    status = sub.add_parser(
        "status", help="print a running daemon's /status body")
    status.add_argument("--out", required=True)
    status.set_defaults(fn=_cmd_status)

    stop = sub.add_parser(
        "stop", help="SIGTERM the recorded pid and wait for the drain")
    stop.add_argument("--out", required=True)
    stop.add_argument("--timeout", type=float, default=30.0)
    stop.set_defaults(fn=_cmd_stop)

    args = p.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    return args.fn(args)


if __name__ == "__main__":
    raise SystemExit(main())
