"""graftloop part 3: the loop orchestrator — one resumable command that
closes trace → scenario → retrain → promote.

``LoopRunner.run()`` drives five stages over one working directory::

    snapshot  copy the live trace dir into <out>/trace_snapshot (stable
              under serving + retention pruning)
    compile   trace→Scenario (loopback/compile.py): pure-replay scenario
              round-trip-PINNED through the real env, training scenario
              with the anti-forgetting mixture
    retrain   fine-tune-from-trace subprocess (loopback/retrain.py):
              --warm-start incumbent, best-eval keeper armed
    evaluate  the graded paired-seed verdict vs the incumbent (+ the
              anti-forgetting gate)
    promote   POST /promote to the live pool and poll GET /rollout —
              riding graftroll's canary gates, SLO gate, and automatic
              rollback unchanged

Every finished stage appends one record to a graftstudy-style ledger
(atomic tmp-then-rename whole-file rewrites, header bound to the
``LoopSpec`` fingerprint): a SIGKILL at ANY instant leaves either the
old or the new complete ledger, so a re-run skips completed stages and
re-enters exactly the interrupted one. Stages are idempotent at stage
granularity (retrain wipes its partial candidate dir; promote is
at-least-once — re-promoting an already-landed candidate re-rolls the
same checkpoint through the same gates, wasteful but safe).

**Refusal is a recorded outcome, not an error.** A failing verdict
records ``promote: false`` in the evaluate stage and the promote stage
records ``refused`` — the loop completes with ``promoted: false`` and a
re-run does NOT retry the refused candidate (a fresh loop dir does). A
promote the POOL rolls back records ``rolled_back`` the same way. Only
transient failures (HTTP errors, crashes) leave no record and re-run.

Chaos seams (``utils/faults.py``): ``loopback.compile`` fires inside
the snapshot/compile stages, ``loopback.promote`` before the POST —
armed deterministically via ``GRAFTLOOP_FAULTS`` (e.g.
``loopback.promote:1``) for the drill's refusal/rollback rehearsals.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import logging
import os
import time
import urllib.error
import urllib.request
from pathlib import Path

from rl_scheduler_tpu.loopback.retrain import (
    FinetuneSpec,
    run_finetune,
    score_candidate,
)

logger = logging.getLogger(__name__)

LOOP_SCHEMA_VERSION = 1
LEDGER_NAME = "loop_ledger.jsonl"
SNAPSHOT_DIR = "trace_snapshot"
RETRAIN_DIR = "retrain"
CANDIDATE_NAME = "candidate"
LOOP_LOCK_NAME = "loop.lock"
STAGES = ("snapshot", "compile", "retrain", "evaluate", "promote")


@dataclasses.dataclass(frozen=True)
class LoopSpec:
    """One loop iteration's frozen protocol. The fingerprint binds the
    ledger: a changed protocol refuses to resume into the same loop dir
    (the graftstudy rule — two protocols must not interleave stages)."""

    trace_dir: str                   # the live pool's trace directory
    incumbent: str                   # run dir serving today's generation
    pool_url: str | None = None      # control plane, e.g. http://host:8788
    steps: int = 256
    mix_frac: float = 0.25
    compile_seed: int = 0
    iterations: int = 8
    seed: int = 0
    eval_every: int = 2
    eval_episodes: int = 32
    verdict_seeds: tuple = (0, 1, 2, 3, 4)
    verdict_episodes: int = 64
    required_verdict: str = "confirmed_above"
    forgetting_tolerance_pct: float = 10.0
    num_nodes: int | None = None
    dry_run: bool = False

    def __post_init__(self):
        if not self.trace_dir:
            raise ValueError("trace_dir: the loop compiles FROM a trace")
        if not self.incumbent:
            raise ValueError("incumbent: the loop warm-starts from (and "
                             "verdicts against) the serving checkpoint")
        if self.pool_url is None and not self.dry_run:
            raise ValueError(
                "pool_url: a live loop promotes through the pool control "
                "plane — pass one, or --dry-run to stop before promote")
        if self.steps < 2:
            raise ValueError(f"steps={self.steps}: >= 2")
        if not 0.0 <= self.mix_frac < 1.0:
            raise ValueError(f"mix_frac={self.mix_frac}: [0, 1)")
        self.finetune()  # validates the retrain/verdict knobs

    def finetune(self, scenario: str | None = None) -> FinetuneSpec:
        """The retrain job this loop runs (scenario filled at the
        compile stage; the placeholder only validates knobs)."""
        return FinetuneSpec(
            incumbent=self.incumbent,
            scenario=scenario or "trace_replay:<pending>",
            scenario_seed=self.compile_seed,
            iterations=self.iterations,
            seed=self.seed,
            eval_every=self.eval_every,
            eval_episodes=self.eval_episodes,
            verdict_seeds=tuple(self.verdict_seeds),
            verdict_episodes=self.verdict_episodes,
            required_verdict=self.required_verdict,
            forgetting_tolerance_pct=self.forgetting_tolerance_pct,
            num_nodes=self.num_nodes,
        )

    def to_json(self) -> dict:
        return json.loads(json.dumps(dataclasses.asdict(self)))

    def fingerprint(self) -> str:
        blob = json.dumps(self.to_json(), sort_keys=True,
                          separators=(",", ":"))
        return hashlib.sha256(blob.encode()).hexdigest()[:16]


def loop_spec_from_json(d: dict) -> LoopSpec:
    kw = dict(d)
    kw["verdict_seeds"] = tuple(kw["verdict_seeds"])
    return LoopSpec(**kw)


class LoopLedgerMismatch(RuntimeError):
    """The loop dir's ledger was written under a different spec."""


class LoopLedger:
    """The loop's stage journal: the graftstudy ledger discipline
    (whole-file tmp-then-rename appends, sorted-key records, header
    bound to the spec fingerprint) applied to stages instead of trials.
    A SIGKILL leaves a complete ledger; completed stage records survive
    bitwise."""

    def __init__(self, loop_dir: str | Path, spec: LoopSpec):
        self.path = Path(loop_dir) / LEDGER_NAME
        self.spec = spec
        self.path.parent.mkdir(parents=True, exist_ok=True)
        if self.path.exists() and self.path.stat().st_size:
            header = json.loads(self.path.read_text().splitlines()[0])
            if header.get("spec_sha") != spec.fingerprint():
                raise LoopLedgerMismatch(
                    f"{self.path} was written for spec "
                    f"{header.get('spec_sha')}; this run's spec is "
                    f"{spec.fingerprint()} — a changed loop protocol "
                    "cannot resume into the same ledger (new loop dir, "
                    "or --fresh to discard)")
        else:
            self._rewrite([self._dumps({
                "kind": "header",
                "schema_version": LOOP_SCHEMA_VERSION,
                "spec_sha": spec.fingerprint(),
                "spec": spec.to_json(),
            })])

    @staticmethod
    def _dumps(record: dict) -> str:
        return json.dumps(record, sort_keys=True, separators=(", ", ": "))

    def _rewrite(self, lines: list) -> None:
        tmp = self.path.with_suffix(f".tmp.{os.getpid()}")
        data = "".join(line + "\n" for line in lines)
        with open(tmp, "w") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)

    def append_stage(self, stage: str, status: str, out: dict) -> None:
        record = {"kind": "stage", "stage": stage, "status": status,
                  "ts": round(time.time(), 3), "out": out}
        lines = self.path.read_text().splitlines() if self.path.exists() \
            else []
        self._rewrite(lines + [self._dumps(record)])

    def append_attempt(self, stage: str, attempt: int,
                       error: str) -> None:
        """Record one FAILED-but-retried transient attempt (the in-loop
        RetryPolicy). Attempt records never mark a stage done —
        :meth:`stages` skips them — they make the retry budget auditable
        after the fact."""
        record = {"kind": "attempt", "stage": stage, "attempt": attempt,
                  "ts": round(time.time(), 3), "error": error}
        lines = self.path.read_text().splitlines() if self.path.exists() \
            else []
        self._rewrite(lines + [self._dumps(record)])

    def stages(self) -> dict:
        """``{stage: record}`` for every recorded stage (newest wins —
        there is at most one per stage in a healthy ledger)."""
        out = {}
        for line in self.path.read_text().splitlines()[1:]:
            record = json.loads(line)
            if record.get("kind") == "stage":
                out[record["stage"]] = record
        return out


# The exception families a stage may raise TRANSIENTLY (transport
# errors, subprocess crashes, pool 5xx/409 re-raised as RuntimeError,
# rollout TimeoutError). Anything else — spec validation ValueErrors,
# ledger mismatches — propagates immediately: retrying a deterministic
# error burns the budget to reach the identical failure.
TRANSIENT_STAGE_ERRORS = (OSError, TimeoutError, RuntimeError)


class LoopRunner:
    """Execute (or resume) one loop iteration over ``loop_dir``.

    ``max_stage_retries`` bounds IN-PROCESS retries of a transiently
    failing stage (``utils/retry.RetryPolicy`` backoff; each failed
    attempt lands a ``kind=attempt`` ledger record). The default is 0 —
    identical single-shot semantics to the pre-retry orchestrator; the
    CLI passes ``--max-stage-retries`` (default 2). On exhaustion the
    LAST underlying exception re-raises unchanged, so callers (and the
    chaos suite) see the same error types with retries on or off.
    Refusals are recorded outcomes, not errors — they stay single-shot
    regardless of the budget."""

    def __init__(self, spec: LoopSpec, loop_dir: str | Path,
                 fault_plan=None, rollout_timeout_s: float = 120.0,
                 max_stage_retries: int = 0):
        if max_stage_retries < 0:
            raise ValueError(
                f"max_stage_retries={max_stage_retries}: >= 0")
        self.spec = spec
        self.loop_dir = Path(loop_dir)
        self.fault_plan = fault_plan
        self.rollout_timeout_s = rollout_timeout_s
        self.max_stage_retries = max_stage_retries
        self.loop_dir.mkdir(parents=True, exist_ok=True)
        self.ledger = LoopLedger(self.loop_dir, spec)

    # --------------------------------------------------------- stages

    def _stage_snapshot(self) -> dict:
        from rl_scheduler_tpu.loopback.compile import snapshot_trace

        meta = snapshot_trace(self.spec.trace_dir,
                              self.loop_dir / SNAPSHOT_DIR,
                              fault_plan=self.fault_plan)
        return {"snapshot": str(self.loop_dir / SNAPSHOT_DIR),
                "digest": meta["digest"], "records": meta["records"],
                "segments": len(meta["files"])}

    def _stage_compile(self, snapshot: str) -> dict:
        from rl_scheduler_tpu.loopback.compile import (
            compile_trace,
            trace_scenario_name,
            verify_roundtrip,
        )
        from rl_scheduler_tpu.scenarios import get_scenario

        compiled = compile_trace(
            snapshot, steps=self.spec.steps, seed=self.spec.compile_seed,
            fault_plan=self.fault_plan)
        # The round-trip pin runs on the PURE replay scenario (mix=0):
        # the compiled tables must reproduce the trace's recorded
        # observations through the real env before anything trains on
        # them. The training scenario adds the anti-forgetting mixture
        # on top of the SAME pinned reconstruction.
        pure_name = trace_scenario_name(snapshot, steps=self.spec.steps)
        roundtrip = verify_roundtrip(
            get_scenario(pure_name, seed=self.spec.compile_seed),
            num_nodes=self.spec.num_nodes or 8)
        train_name = trace_scenario_name(
            snapshot, steps=self.spec.steps, mix_frac=self.spec.mix_frac)
        stats = dict(compiled.stats)
        if self.spec.mix_frac:
            # The ledger reports what the candidate will actually train
            # on: the same compile with the anti-forgetting mixture
            # drawn in (cheap — one more pass over the snapshot).
            train = compile_trace(
                snapshot, steps=self.spec.steps,
                seed=self.spec.compile_seed, mix_frac=self.spec.mix_frac)
            stats["mix_frac"] = train.stats["mix_frac"]
            stats["mixed_rows"] = train.stats["mixed_rows"]
        return {"scenario": pure_name, "train_scenario": train_name,
                "stats": stats, "roundtrip": roundtrip}

    def _stage_retrain(self, train_scenario: str) -> dict:
        run_dir = run_finetune(
            self.spec.finetune(train_scenario),
            self.loop_dir / RETRAIN_DIR, run_name=CANDIDATE_NAME,
            log_path=self.loop_dir / "retrain.log")
        return {"candidate": str(run_dir)}

    def _stage_evaluate(self, candidate: str, pure_scenario: str) -> dict:
        # The verdict pairs on the PURE replay (mix=0): the promotion
        # question is "better on the traffic we serve?", and the
        # anti-forgetting mixture is a training-only device — the base
        # workload already gets its own gate (original_workload pairing).
        return score_candidate(candidate, self.spec.incumbent,
                               self.spec.finetune(pure_scenario))

    def _stage_promote(self, candidate: str, verdict: dict) -> tuple:
        """``(status, out)``: ``ok`` (landed), ``refused`` (verdict /
        dry-run / a pool 4xx that judges the candidate, e.g. 422 on a
        failed verify), or ``rolled_back`` (the pool's gates refused it
        live). Transient failures raise instead — transport errors,
        5xx, and 409 rollout-in-flight — no record, so a resume
        retries."""
        if not verdict.get("promote"):
            return "refused", {
                "reason": f"verdict {verdict.get('verdict')!r} is below "
                          f"required {self.spec.required_verdict!r}"}
        if self.spec.dry_run:
            return "refused", {"reason": "--dry-run stops before promote",
                               "would_promote": candidate}
        if self.fault_plan is not None:
            # The chaos seam fires BEFORE the POST: a refused promote
            # must leave the pool untouched on the incumbent generation.
            self.fault_plan.check("loopback.promote", OSError)
        url = self.spec.pool_url.rstrip("/")
        req = urllib.request.Request(
            url + "/promote",
            data=json.dumps({"checkpoint": candidate}).encode(),
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=30) as resp:
                body = json.load(resp)
        except urllib.error.HTTPError as e:
            try:
                detail = json.loads(e.read()).get("error", "")
            except Exception:  # noqa: BLE001 — body is advisory
                detail = ""
            if e.code == 409 or e.code >= 500:
                # Transient, not a verdict on the candidate: 409 means a
                # rollout is already in flight (possibly OUR earlier POST
                # whose polling was interrupted), 5xx is a control-plane
                # hiccup. Raise so no ledger record lands and a resume
                # retries once the pool is idle — recording `refused`
                # here would permanently mislabel a promote the pool may
                # actually be landing.
                why = detail or "rollout in flight / server error"
                raise RuntimeError(
                    f"pool answered {e.code} on /promote ({why}) — "
                    "transient; re-run to resume once the pool is "
                    "idle") from e
            return "refused", {"reason": f"pool refused the promote "
                                         f"({e.code}): {detail}"}
        target = body.get("target_generation")
        deadline = time.monotonic() + self.rollout_timeout_s
        while time.monotonic() < deadline:
            with urllib.request.urlopen(url + "/rollout",
                                        timeout=10) as resp:
                status = json.load(resp)
            if not status.get("active"):
                if status.get("generation") == target:
                    return "ok", {"generation": target,
                                  "verified_step": body.get("verified_step"),
                                  "rollout": status}
                return "rolled_back", {
                    "reason": status.get("last_error")
                    or "pool stayed on the incumbent generation",
                    "rollout": status}
            time.sleep(0.2)
        raise TimeoutError(
            f"rollout to generation {target} still in flight after "
            f"{self.rollout_timeout_s:.0f}s — poll {url}/rollout and "
            "re-run to resume")

    # ------------------------------------------------------------- run

    def _attempt_stage(self, stage: str, fn):
        """Run one stage body under the bounded transient-retry budget.
        RetryPolicy supplies the (seeded, jittered) backoff schedule;
        the loop re-raises the LAST exception itself so exhaustion
        surfaces the original error type, not a wrapper."""
        if self.max_stage_retries == 0:
            return fn()
        from rl_scheduler_tpu.utils.retry import RetryPolicy

        delays = RetryPolicy(max_attempts=self.max_stage_retries + 1,
                             base_delay_s=0.05, max_delay_s=2.0,
                             seed=self.spec.seed).delays()
        for attempt in range(1, self.max_stage_retries + 2):
            try:
                return fn()
            except TRANSIENT_STAGE_ERRORS as exc:
                if attempt > self.max_stage_retries:
                    raise
                logger.warning(
                    "loopback: stage %s attempt %d/%d failed "
                    "transiently (%s); retrying in %.2fs", stage,
                    attempt, self.max_stage_retries + 1, exc,
                    delays[attempt - 1])
                self.ledger.append_attempt(stage, attempt, repr(exc))
                time.sleep(delays[attempt - 1])
        raise AssertionError("unreachable: the final attempt re-raises")

    def run_stages(self, until: str | None = None) -> dict:
        """Drive the stages in order up to and including ``until``
        (default: all five), skipping completed ones (ledger resume);
        returns :meth:`LoopLedger.stages`. graftpilot's daemon runs
        ``until="evaluate"``, holds its live shadow gate, then calls
        back with ``until="promote"`` — both halves resume from the
        same ledger."""
        if until is not None and until not in STAGES:
            raise ValueError(f"until={until!r}: one of {STAGES}")
        last = STAGES.index(until) if until is not None else len(STAGES) - 1
        done = self.ledger.stages()
        for stage in STAGES[:last + 1]:
            if stage in done:
                logger.info("loopback: stage %s already recorded "
                            "(%s) — skipping", stage,
                            done[stage]["status"])
                continue
            logger.info("loopback: stage %s", stage)
            if stage == "snapshot":
                out = self._attempt_stage(stage, self._stage_snapshot)
                status = "ok"
            elif stage == "compile":
                out = self._attempt_stage(
                    stage, lambda: self._stage_compile(
                        done["snapshot"]["out"]["snapshot"]))
                status = "ok"
            elif stage == "retrain":
                out = self._attempt_stage(
                    stage, lambda: self._stage_retrain(
                        done["compile"]["out"]["train_scenario"]))
                status = "ok"
            elif stage == "evaluate":
                out = self._attempt_stage(
                    stage, lambda: self._stage_evaluate(
                        done["retrain"]["out"]["candidate"],
                        done["compile"]["out"]["scenario"]))
                status = "ok"
            else:
                status, out = self._attempt_stage(
                    stage, lambda: self._stage_promote(
                        done["retrain"]["out"]["candidate"],
                        done["evaluate"]["out"]))
            self.ledger.append_stage(stage, status, out)
            done = self.ledger.stages()
        return done

    def run(self) -> dict:
        """Drive the stages, skipping completed ones (ledger resume),
        and return the loop summary (one ``schema_version``-tagged
        dict — the CLI prints it as the driver JSON line)."""
        done = self.run_stages()
        promote = done["promote"]
        return {
            "schema_version": LOOP_SCHEMA_VERSION,
            "metric": "loopback_summary",
            "spec_sha": self.spec.fingerprint(),
            "loop_dir": str(self.loop_dir),
            "trace_records": done["snapshot"]["out"]["records"],
            "compile": done["compile"]["out"]["stats"],
            "roundtrip": done["compile"]["out"]["roundtrip"],
            "candidate": done["retrain"]["out"]["candidate"],
            "verdict": done["evaluate"]["out"]["verdict"],
            "matrix": done["evaluate"]["out"]["matrix"],
            "promoted": promote["status"] == "ok",
            "promote_status": promote["status"],
            "promote": promote["out"],
        }


def fault_plan_from_env(value: str | None):
    """Parse ``GRAFTLOOP_FAULTS`` into a deterministic FaultPlan
    schedule: ``site:idx[,idx...]`` entries joined by ``;`` — e.g.
    ``loopback.promote:1`` fires the first promote attempt,
    ``loopback.compile:1,2;loopback.promote:1`` both seams. ``None``/
    empty disarms (the production default — the plan is plumbed, never
    ambient)."""
    if not value:
        return None
    from rl_scheduler_tpu.utils.faults import FaultPlan

    schedule: dict = {}
    for entry in value.split(";"):
        entry = entry.strip()
        if not entry:
            continue
        site, _, idxs = entry.partition(":")
        if not idxs:
            raise ValueError(
                f"GRAFTLOOP_FAULTS entry {entry!r}: expected "
                "site:call_index[,call_index...]")
        try:
            schedule[site.strip()] = tuple(
                int(i) for i in idxs.split(","))
        except ValueError:
            raise ValueError(
                f"GRAFTLOOP_FAULTS entry {entry!r}: call indices must "
                "be integers")
    return FaultPlan(schedule=schedule)
