"""graftloop CLI: one resumable command that closes the decision loop.

Usage (docs/serving.md "closing the loop")::

    # dry rehearsal: snapshot + compile + retrain + verdict, no promote
    python -m rl_scheduler_tpu.loopback --trace-dir /var/trace \\
        --incumbent runs/PPO_fleet --out /tmp/loop0 --dry-run

    # the live loop against a serving pool's control plane
    python -m rl_scheduler_tpu.loopback --trace-dir /var/trace \\
        --incumbent runs/PPO_fleet --out /tmp/loop0 \\
        --pool http://127.0.0.1:8788

Re-running the same command over the same ``--out`` resumes from the
loop ledger: completed stages are skipped bitwise (SIGKILL-safe — the
graftstudy ledger discipline). ``GRAFTLOOP_FAULTS`` arms the
``loopback.compile``/``loopback.promote`` chaos seams
(docs/robustness.md). Prints ONE ``schema_version``-tagged JSON summary
line (the driver convention).
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import shutil
import sys
from pathlib import Path


def main(argv: list | None = None) -> int:
    from rl_scheduler_tpu.loopback.orchestrator import (
        LOOP_LOCK_NAME,
        LoopRunner,
        LoopSpec,
        fault_plan_from_env,
    )
    from rl_scheduler_tpu.studies.spec import parse_seeds

    p = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("--trace-dir", required=True,
                   help="the pool's trace directory (extender "
                        "--trace-dir): snapshotted, never mutated")
    p.add_argument("--incumbent", required=True,
                   help="run dir of the checkpoint the pool serves today "
                        "— the warm-start source AND the verdict's "
                        "control arm")
    p.add_argument("--out", required=True,
                   help="loop working dir: ledger, trace snapshot, "
                        "candidate run. Re-running resumes from it")
    p.add_argument("--pool", default=None, metavar="URL",
                   help="pool control-plane base URL (e.g. "
                        "http://127.0.0.1:8788) for the promote stage; "
                        "required unless --dry-run")
    p.add_argument("--steps", type=int, default=256,
                   help="compiled scenario table length (a longer trace "
                        "contributes a seeded window; default 256)")
    p.add_argument("--mix", type=float, default=0.25,
                   help="anti-forgetting mixture: share of base-workload "
                        "rows interleaved into the TRAINING scenario "
                        "(the pure replay scenario stays mix-free for "
                        "the round-trip pin; default 0.25)")
    p.add_argument("--iterations", type=int, default=8,
                   help="fine-tune iterations (default 8)")
    p.add_argument("--seed", type=int, default=0,
                   help="retrain seed (compile window/mixture draw from "
                        "--compile-seed)")
    p.add_argument("--compile-seed", type=int, default=0)
    p.add_argument("--eval-every", type=int, default=2,
                   help="in-training eval cadence — arms the best-eval "
                        "keeper the candidate is scored from (default 2)")
    p.add_argument("--eval-episodes", type=int, default=32)
    p.add_argument("--verdict-seeds", default="0-4", metavar="SPEC",
                   help="paired-verdict seeds, '0-4' / '0,2,7' style "
                        "(default 0-4)")
    p.add_argument("--verdict-episodes", type=int, default=64)
    p.add_argument("--required-verdict", default="confirmed_above",
                   choices=("point_above", "confirmed_above"),
                   help="minimum graded verdict to promote (default "
                        "confirmed_above — the robust bar)")
    p.add_argument("--forgetting-tolerance", type=float, default=10.0,
                   metavar="PCT",
                   help="max mean regression vs the incumbent on its "
                        "ORIGINAL workload before a passing verdict is "
                        "demoted (default 10%%)")
    p.add_argument("--num-nodes", type=int, default=None,
                   help="node-set size (default: the incumbent's "
                        "recorded N)")
    p.add_argument("--dry-run", action="store_true",
                   help="run every stage but stop before the promote "
                        "(recorded as a refusal; the candidate and "
                        "verdict stay in the loop dir)")
    p.add_argument("--rollout-timeout", type=float, default=120.0)
    p.add_argument("--max-stage-retries", type=int, default=2,
                   help="bounded in-process retries of a TRANSIENTLY "
                        "failing stage (transport error, subprocess "
                        "crash, pool 5xx) with RetryPolicy backoff; "
                        "failed attempts land kind=attempt ledger "
                        "records. Refusals never retry (default 2; 0 "
                        "restores single-shot)")
    p.add_argument("--fresh", action="store_true",
                   help="discard an existing loop dir's ledger/artifacts "
                        "and start over (refused while another loop "
                        "holds the dir's lock)")
    args = p.parse_args(argv)

    logging.basicConfig(level=logging.INFO)
    try:
        spec = LoopSpec(
            trace_dir=args.trace_dir,
            incumbent=args.incumbent,
            pool_url=args.pool,
            steps=args.steps,
            mix_frac=args.mix,
            compile_seed=args.compile_seed,
            iterations=args.iterations,
            seed=args.seed,
            eval_every=args.eval_every,
            eval_episodes=args.eval_episodes,
            verdict_seeds=tuple(parse_seeds(args.verdict_seeds)),
            verdict_episodes=args.verdict_episodes,
            required_verdict=args.required_verdict,
            forgetting_tolerance_pct=args.forgetting_tolerance,
            num_nodes=args.num_nodes,
            dry_run=args.dry_run,
        )
    except ValueError as e:
        raise SystemExit(str(e))

    loop_dir = Path(args.out)
    loop_dir.mkdir(parents=True, exist_ok=True)
    # Single-writer: two loops interleaving stages over one ledger would
    # wipe each other's candidate dirs (the graftstudy runner-lock
    # discipline, shared utils/pidlock.py). --fresh deletes WHILE
    # holding the lock — the check-then-rmtree TOCTOU graftstudy fixed.
    from rl_scheduler_tpu.utils.pidlock import acquire_pidfile_lock

    try:
        lock = acquire_pidfile_lock(
            loop_dir / LOOP_LOCK_NAME,
            "a loop is already running over this dir (pid {pid} holds "
            "{lock}); two writers would interleave stages")
    except RuntimeError as e:
        raise SystemExit(str(e))
    try:
        if args.fresh:
            for entry in list(loop_dir.iterdir()):
                if entry.name == LOOP_LOCK_NAME:
                    continue
                shutil.rmtree(entry) if entry.is_dir() else entry.unlink()
        fault_plan = fault_plan_from_env(os.environ.get("GRAFTLOOP_FAULTS"))
        runner = LoopRunner(spec, loop_dir, fault_plan=fault_plan,
                            rollout_timeout_s=args.rollout_timeout,
                            max_stage_retries=args.max_stage_retries)
        summary = runner.run()
    finally:
        lock.unlink(missing_ok=True)
    print(json.dumps(summary, sort_keys=True))
    if summary["promoted"]:
        print(f"loopback: promoted {summary['candidate']} "
              f"(verdict {summary['verdict']})", file=sys.stderr)
        return 0
    print(f"loopback: NOT promoted — {summary['promote_status']} "
          f"(verdict {summary['verdict']})", file=sys.stderr)
    # A completed-but-refused loop is a successful run of the loop
    # program: exit 0 with promoted:false in the summary line (the
    # drill asserts on the field, not the exit code).
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
