"""graftloop part 2: fine-tune-from-trace jobs and the promotion verdict.

A :class:`FinetuneSpec` names one retrain job end to end: warm-start
from the incumbent generation's checkpoint (graftguard-verified restore,
``train_ppo --warm-start``), train on the compiled trace scenario with a
seeded share of the incumbent's original workload mixed back in
(anti-forgetting — the ``mix`` parameter of the ``trace_replay`` family),
keep the best in-training eval via the existing ``on_eval`` keeper
(``--eval-every`` arms it), and score the candidate against the
incumbent with a graftstudy-grade verdict.

**The verdict is graded, not a point estimate.** ``score_candidate``
runs PAIRED seeded greedy evaluations — candidate and incumbent on the
IDENTICAL episode draws per verdict seed (the pairing removes the
dominant seed-to-seed variance, exactly graftstudy's paired-delta
discipline) — on the trace scenario, then grades the per-seed win/loss
record with the shared statistics (``studies/analysis.py`` Wilson
interval + two-sided sign test):

- ``confirmed_above``: the Wilson LOWER bound of the candidate's
  paired win rate clears 0.5 — the candidate beats the incumbent
  robustly across seeds (the promotion bar; at 5 seeds only 5/5 makes
  it, which is the honest arithmetic of a thin seed set).
- ``point_above`` / ``point_below``: wins lead / trail but the interval
  straddles 0.5.
- ``confirmed_below``: the Wilson UPPER bound is under 0.5 — the
  candidate measurably loses.

An **anti-forgetting gate** rides along: the candidate is also paired
against the incumbent on the incumbent's ORIGINAL workload (its
checkpoint-meta scenario, or the CSV replay), and a mean regression
beyond ``forgetting_tolerance_pct`` demotes any passing verdict to
``point_above`` — a retrain that aces the trace by forgetting the base
workload is not promotable (docs/serving.md).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import logging
import os
import shutil
import subprocess
import sys
from pathlib import Path

logger = logging.getLogger(__name__)

# Verdict grades, worst to best — comparison by rank implements
# "confirmed_above or better required to promote".
VERDICTS = ("confirmed_below", "point_below", "point_above",
            "confirmed_above")


def verdict_rank(verdict: str) -> int:
    if verdict not in VERDICTS:
        raise ValueError(f"unknown verdict {verdict!r}; graded scale is "
                         f"{list(VERDICTS)}")
    return VERDICTS.index(verdict)


@dataclasses.dataclass(frozen=True)
class FinetuneSpec:
    """One resumable fine-tune-from-trace job (module docstring)."""

    incumbent: str                   # run dir of the serving checkpoint
    scenario: str                    # trace_replay:<snapshot>[?steps&mix]
    scenario_seed: int = 0
    iterations: int = 8
    seed: int = 0
    eval_every: int = 2              # arms the best-eval keeper
    eval_episodes: int = 32
    verdict_seeds: tuple = (0, 1, 2, 3, 4)
    verdict_episodes: int = 64
    required_verdict: str = "confirmed_above"
    forgetting_tolerance_pct: float = 10.0
    num_nodes: int | None = None     # None = the incumbent's recorded N

    def __post_init__(self):
        if not self.scenario.startswith("trace_replay:"):
            raise ValueError(
                f"scenario={self.scenario!r}: a fine-tune-from-trace job "
                "trains on a compiled trace (trace_replay:<snapshot_dir>)")
        if self.iterations < 1:
            raise ValueError(f"iterations={self.iterations}: >= 1")
        if self.eval_every < 1:
            raise ValueError(
                f"eval_every={self.eval_every}: the job keeps best-eval "
                "via the on_eval keeper, which needs the in-training "
                "eval signal (>= 1)")
        if not self.verdict_seeds:
            raise ValueError("verdict_seeds: the paired sign test needs "
                             "at least one seed")
        if len(set(self.verdict_seeds)) != len(self.verdict_seeds):
            raise ValueError(f"verdict_seeds {self.verdict_seeds}: "
                             "duplicates would double-count pairs")
        if self.verdict_episodes < 1:
            raise ValueError(f"verdict_episodes={self.verdict_episodes}: "
                             ">= 1")
        verdict_rank(self.required_verdict)  # validates the name

    def to_json(self) -> dict:
        return json.loads(json.dumps(dataclasses.asdict(self)))

    def fingerprint(self) -> str:
        """Canonical-JSON sha — the loop ledger's resume-compatibility
        key, the graftstudy discipline."""
        blob = json.dumps(self.to_json(), sort_keys=True,
                          separators=(",", ":"))
        return hashlib.sha256(blob.encode()).hexdigest()[:16]


def finetune_spec_from_json(d: dict) -> FinetuneSpec:
    kw = dict(d)
    kw["verdict_seeds"] = tuple(kw["verdict_seeds"])
    return FinetuneSpec(**kw)


# -------------------------------------------------------------- retrain


def incumbent_meta(run_dir: str | Path) -> dict:
    """The incumbent's newest verified checkpoint meta (graftguard
    selection — corrupt steps fall back), without loading params."""
    from rl_scheduler_tpu.utils.checkpoint import CheckpointManager

    mgr = CheckpointManager(run_dir)
    try:
        latest = mgr.latest_verified_step()
        if latest is None:
            raise ValueError(
                f"incumbent {run_dir} has no verified checkpoint steps")
        return mgr.restore_meta(latest)
    finally:
        mgr.close()


def run_finetune(spec: FinetuneSpec, out_root: str | Path,
                 run_name: str = "candidate",
                 log_path: str | Path | None = None) -> Path:
    """Execute the retrain as a fresh ``train_ppo`` subprocess (the
    graftstudy worker discipline: a clean process per job, so the
    orchestrator stays light and a crashed trainer cannot wedge the
    loop) and return the candidate run dir.

    The job is stage-idempotent, not step-resumable: a re-run WIPES any
    partial candidate dir and retrains whole (the loop ledger only
    records the stage once the subprocess exits 0, so a SIGKILL mid-train
    re-enters here). The subprocess inherits the environment —
    ``JAX_PLATFORMS=cpu`` flows through to container drills."""
    meta = incumbent_meta(spec.incumbent)
    if meta.get("env") != "cluster_set":
        raise ValueError(
            f"incumbent {spec.incumbent} trained env {meta.get('env')!r}; "
            "fine-tune-from-trace retrains the set family (the trace "
            "compiles cluster_set tables)")
    out_root = Path(out_root)
    run_dir = out_root / run_name
    try:  # EAFP: no exists()/rmtree window for a concurrent stage re-run
        shutil.rmtree(run_dir)
    except FileNotFoundError:
        pass
    else:
        logger.warning("retrain: wiped partial candidate dir %s "
                       "(stage re-run)", run_dir)
    out_root.mkdir(parents=True, exist_ok=True)
    num_nodes = spec.num_nodes or meta.get("num_nodes") or 8
    argv = [
        sys.executable, "-m", "rl_scheduler_tpu.agent.train_ppo",
        "--preset", meta.get("preset") or "quick",
        "--env", "cluster_set",
        "--scenario", spec.scenario,
        "--scenario-seed", str(spec.scenario_seed),
        "--warm-start", str(spec.incumbent),
        "--iterations", str(spec.iterations),
        "--seed", str(spec.seed),
        "--eval-every", str(spec.eval_every),
        "--eval-episodes", str(spec.eval_episodes),
        "--num-nodes", str(num_nodes),
        "--reseed-on-stall", "0",
        "--run-name", run_name,
        "--run-root", str(out_root),
    ]
    num_heads = meta.get("num_heads")
    if num_heads is not None:
        argv += ["--num-heads", str(num_heads)]
    logger.info("retrain: %s", " ".join(argv))
    # Source-tree resolution, the graftstudy worker discipline: the
    # subprocess must import rl_scheduler_tpu the same way this process
    # did, wherever the orchestrator was launched from.
    env = dict(os.environ)
    repo_root = str(Path(__file__).resolve().parents[2])
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    log_fh = open(log_path, "ab") if log_path is not None else None
    try:
        proc = subprocess.run(
            argv, stdout=log_fh or None, stderr=subprocess.STDOUT
            if log_fh else None, check=False, env=env)
    finally:
        if log_fh is not None:
            log_fh.close()
    if proc.returncode != 0:
        tail = ""
        if log_path is not None and Path(log_path).exists():
            tail = Path(log_path).read_text()[-2000:]
        raise RuntimeError(
            f"retrain subprocess exited {proc.returncode}"
            + (f"; log tail:\n{tail}" if tail else ""))
    return run_dir


# -------------------------------------------------------------- scoring


def _load_set_policy(run_dir: str | Path, best: bool = False):
    """``(net, params, meta)`` for a cluster_set checkpoint — the
    evaluate-CLI rebuild, shared here so candidate and incumbent load
    through one path. ``best`` reads the best-eval keeper when present
    (falling back to latest — a short job may never have saved one)."""
    from rl_scheduler_tpu.agent.loop import BEST_DIR
    from rl_scheduler_tpu.models import SetTransformerPolicy
    from rl_scheduler_tpu.utils.checkpoint import load_policy_params

    run_dir = Path(run_dir)
    source = run_dir
    if best and (run_dir / BEST_DIR / "checkpoints").is_dir():
        source = run_dir / BEST_DIR
    params, meta = load_policy_params(source)
    if meta.get("env") != "cluster_set":
        raise ValueError(f"{run_dir} trained env {meta.get('env')!r}; "
                         "the verdict evaluates the set family")
    num_heads = meta.get("num_heads")
    net = SetTransformerPolicy(dim=64, depth=2,
                               num_heads=4 if num_heads is None
                               else num_heads)
    return net, params, meta


def _paired_rewards(bundle, net_a, params_a, net_b, params_b,
                    seeds: tuple, episodes: int) -> list:
    """Per-seed ``(reward_a, reward_b)`` mean episode rewards, both
    policies greedy on the IDENTICAL episode draws (same
    ``PRNGKey(seed)`` through ``run_bundle_episodes`` — the paired
    protocol that makes a sign test meaningful at few seeds)."""
    from rl_scheduler_tpu.agent.evaluate import (
        greedy_policy_fn,
        run_bundle_episodes,
    )

    out = []
    for seed in seeds:
        r_a, _ = run_bundle_episodes(bundle, greedy_policy_fn(net_a, params_a),
                                     episodes, seed=seed)
        r_b, _ = run_bundle_episodes(bundle, greedy_policy_fn(net_b, params_b),
                                     episodes, seed=seed)
        out.append((float(r_a.mean()), float(r_b.mean())))
    return out


def grade_pairs(pairs: list) -> dict:
    """Grade paired (candidate, incumbent) rewards into the module's
    verdict scale: Wilson 95% on the win rate vs the 0.5 bar, plus the
    two-sided sign test p-value on wins/losses (ties dropped)."""
    from rl_scheduler_tpu.studies.analysis import (
        sign_test_pvalue,
        wilson_interval,
    )

    wins = sum(1 for c, i in pairs if c > i)
    losses = sum(1 for c, i in pairs if c < i)
    ties = len(pairs) - wins - losses
    decided = wins + losses
    lo, hi = wilson_interval(losses, decided) if decided else (0.0, 1.0)
    # wilson_interval bounds the LOSS rate; win-rate bounds mirror it.
    win_lo, win_hi = 1.0 - hi, 1.0 - lo
    if decided == 0:
        verdict = "point_below"    # all ties: nothing demonstrated
    elif win_lo > 0.5:
        verdict = "confirmed_above"
    elif win_hi < 0.5:
        verdict = "confirmed_below"
    elif wins > losses:
        verdict = "point_above"
    else:
        verdict = "point_below"
    deltas = [c - i for c, i in pairs]
    return {
        "pairs": len(pairs),
        "wins": wins,
        "losses": losses,
        "ties": ties,
        "win_rate_wilson95": [round(win_lo, 3), round(win_hi, 3)],
        "sign_test_p": round(sign_test_pvalue(wins, losses), 4),
        "mean_delta": round(sum(deltas) / len(deltas), 3),
        "per_seed_delta": [round(d, 3) for d in deltas],
        "verdict": verdict,
    }


def score_candidate(candidate: str | Path, incumbent: str | Path,
                    spec: FinetuneSpec) -> dict:
    """The promotion verdict (module docstring): paired seeded greedy
    evals of candidate-vs-incumbent on the trace scenario, graded; plus
    the anti-forgetting pairing on the incumbent's original workload.
    Returns the full eval matrix + the final ``verdict``/``promote``.

    The trace pairing evaluates with a per-episode RANDOM table phase
    (``random_phase``): a pure trace replay is otherwise fully
    deterministic (fixed window, recorded pods, zero jitter), so every
    verdict seed would replay the identical episode and the sign test
    would grade one sample n times. A random phase makes each seed a
    different window of the SAME logged traffic — honest seed-to-seed
    variance over the workload the verdict is about — while candidate
    and incumbent still see identical draws per seed (the pairing)."""
    import dataclasses as _dc

    from rl_scheduler_tpu.agent.ppo import PPOTrainConfig
    from rl_scheduler_tpu.agent.train_ppo import make_bundle_and_net
    from rl_scheduler_tpu.scenarios import get_scenario

    net_c, params_c, meta_c = _load_set_policy(candidate, best=True)
    net_i, params_i, meta_i = _load_set_policy(incumbent)
    num_nodes = spec.num_nodes or meta_i.get("num_nodes") or 8
    trace_scn = get_scenario(spec.scenario, seed=spec.scenario_seed)
    eval_scn = _dc.replace(
        trace_scn, knobs=trace_scn.knobs + (("random_phase", True),))
    trace_bundle, _ = make_bundle_and_net(
        "cluster_set", PPOTrainConfig(), scenario=eval_scn,
        num_nodes=num_nodes)
    trace_pairs = _paired_rewards(
        trace_bundle, net_c, params_c, net_i, params_i,
        spec.verdict_seeds, spec.verdict_episodes)
    trace_grade = grade_pairs(trace_pairs)

    # Anti-forgetting pairing: the incumbent's ORIGINAL workload — its
    # recorded scenario, or the plain CSV replay.
    orig_scn = None
    if meta_i.get("scenario"):
        orig_scn = get_scenario(meta_i["scenario"],
                                seed=meta_i.get("scenario_seed", 0))
    orig_bundle, _ = make_bundle_and_net(
        "cluster_set", PPOTrainConfig(), scenario=orig_scn,
        num_nodes=num_nodes)
    orig_pairs = _paired_rewards(
        orig_bundle, net_c, params_c, net_i, params_i,
        spec.verdict_seeds, spec.verdict_episodes)
    orig_grade = grade_pairs(orig_pairs)
    incumbent_means = [i for _, i in orig_pairs]
    mean_inc = sum(incumbent_means) / len(incumbent_means)
    regression_pct = (-orig_grade["mean_delta"] / abs(mean_inc) * 100.0
                      if mean_inc else 0.0)
    forgot = regression_pct > spec.forgetting_tolerance_pct

    verdict = trace_grade["verdict"]
    if forgot and verdict_rank(verdict) > verdict_rank("point_above"):
        verdict = "point_above"   # demoted: see module docstring
    promote = (verdict_rank(verdict)
               >= verdict_rank(spec.required_verdict))
    return {
        "matrix": {
            "trace_scenario": {"scenario": trace_scn.name,
                               **trace_grade},
            "original_workload": {
                "scenario": orig_scn.name if orig_scn else "csv",
                **orig_grade,
                "regression_pct": round(regression_pct, 2),
                "forgot": forgot,
            },
        },
        "candidate": str(candidate),
        "candidate_best_eval": meta_c.get("best_eval"),
        "incumbent": str(incumbent),
        "verdict": verdict,
        "required_verdict": spec.required_verdict,
        "promote": promote,
    }
