"""graftmix — external-trace import, mixture curricula, transfer grid.

The generalist subsystem (ROADMAP item 4): one policy over the scenario
universe. Three layers:

- **importer** (``importer.py`` + ``fixtures.py``): public cluster
  traces (Google ClusterData-style machine-event + task-usage CSVs,
  Alibaba cluster-trace-v2018-style machine/container tables) compiled
  through the shipped ``data/normalize`` pipeline into the
  ``external_trace:<dir>?format=...`` scenario family —
  schema-validated with counted row rejection, bitwise-deterministic
  per (trace digest, seed), seeded synthetic fixtures so tier-1 stays
  off-network.
- **curricula** (``curriculum.py`` + ``env.py``): ``MixtureSpec`` —
  named (family, weight) components, optional easy→adversarial anneal —
  compiled to stacked per-family env tables with a per-episode family
  index drawn from the vmapped reset key; ``train_ppo --mixture``.
- **transfer grid** (``grid.py``): ``evaluate --transfer-grid`` /
  ``make transfer-grid`` — the generalist vs each per-family specialist
  (or the best hand-coded baseline) on paired seeded episodes, one
  graftstudy Wilson/sign-test verdict per (scenario × node count) cell,
  held-out families flagged.

Design doc: ``docs/scenarios.md`` (graftmix sections).
"""

from rl_scheduler_tpu.mixtures.curriculum import (
    MIXTURES,
    MixtureSpec,
    get_mixture,
    list_mixtures,
    mixture_meta,
    parse_mixture,
)
from rl_scheduler_tpu.mixtures.env import (
    MixtureSetParams,
    MixtureState,
    mixture_bundle,
    mixture_set_params,
)
from rl_scheduler_tpu.mixtures.importer import (
    ImportedTrace,
    ImportReport,
    TraceImportError,
    import_external_trace,
    trace_digest,
)

__all__ = [
    "MIXTURES",
    "MixtureSpec",
    "get_mixture",
    "list_mixtures",
    "mixture_meta",
    "parse_mixture",
    "MixtureSetParams",
    "MixtureState",
    "mixture_bundle",
    "mixture_set_params",
    "ImportedTrace",
    "ImportReport",
    "TraceImportError",
    "import_external_trace",
    "trace_digest",
]
