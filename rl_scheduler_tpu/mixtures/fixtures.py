"""Seeded synthetic fixtures for both external-trace formats (graftmix).

The importer (``mixtures/importer.py``) parses Google ClusterData-style
and Alibaba cluster-trace-v2018-style CSVs; the real traces are
multi-GB downloads, so tier-1 must never touch the network. These
generators synthesize structurally-faithful miniature traces — the same
column orders, the same event/usage semantics, machine lifecycles and a
diurnal-ish load wave so the compiled tables have real structure — from
one ``np.random.RandomState(seed)`` with a fixed draw order (the
``data/generate.py`` determinism discipline: same seed ⇒ byte-identical
CSV files, which is what makes the importer's bitwise-determinism pin
testable end to end).

Both fixtures are deliberately imperfect in the ways real traces are:
events are written in slightly shuffled order (the importer must sort),
a machine mid-trace REMOVE/re-ADD cycle exercises the availability
reconstruction, and a duplicate ADD exercises the counted-rejection
path. Tests that need *broken* rows (truncated mid-row, junk fields)
corrupt these files themselves — the generators write valid traces.
"""

from __future__ import annotations

from pathlib import Path

import numpy as np

# File names the importer looks for per format (headerless CSVs, like
# the real releases; column orders in mixtures/importer.py).
GOOGLE_MACHINE_EVENTS = "machine_events.csv"
GOOGLE_TASK_USAGE = "task_usage.csv"
ALIBABA_MACHINE_USAGE = "machine_usage.csv"
ALIBABA_CONTAINER_META = "container_meta.csv"


def _write_rows(path: Path, rows: list) -> Path:
    path.parent.mkdir(parents=True, exist_ok=True)
    with path.open("w") as fh:
        for row in rows:
            fh.write(",".join(str(x) for x in row) + "\n")
    return path


def generate_google_fixture(
    out_dir: str | Path,
    machines: int = 8,
    tasks: int = 200,
    span: int = 10_000,
    seed: int = 0,
) -> dict:
    """Write a miniature Google ClusterData-style trace directory.

    ``machine_events.csv``: (timestamp, machine_id, event_type,
    platform_id, cpus, memory) — every machine ADDs near t=0, one seeded
    machine runs a REMOVE/re-ADD cycle mid-trace, and one duplicate ADD
    is planted (the importer counts it, idempotently). ``task_usage.csv``:
    (start_time, end_time, job_id, task_index, machine_id, cpu_rate,
    memory_usage) — task arrivals follow a sinusoidal day with seeded
    noise, cpu_rate follows the wave (peak-hours pods are bigger).
    Deterministic per seed; returns ``{"dir", "files", "machines",
    "tasks"}``.
    """
    rng = np.random.RandomState(seed)
    out_dir = Path(out_dir)
    machine_ids = [1000 + 7 * m for m in range(machines)]
    events = []
    for i, mid in enumerate(machine_ids):
        # Staggered ADDs near the trace start (event_type 0 = ADD).
        events.append((int(rng.randint(0, span // 50)), mid, 0,
                       f"plat{i % 2}", 1.0, 1.0))
    # One machine churns: REMOVE (1) mid-trace, re-ADD later.
    churner = machine_ids[int(rng.randint(0, machines))]
    down_at = int(span * 0.4 + rng.randint(0, span // 10))
    up_at = down_at + int(span * 0.2)
    events.append((down_at, churner, 1, "plat0", 1.0, 1.0))
    events.append((up_at, churner, 0, "plat0", 1.0, 1.0))
    # A duplicate ADD for an already-up machine (counted, idempotent).
    dup = machine_ids[0]
    events.append((int(span * 0.1), dup, 0, "plat0", 1.0, 1.0))

    usage = []
    for t in range(tasks):
        start = int(rng.uniform(0, span * 0.95))
        end = start + int(rng.uniform(span * 0.01, span * 0.1))
        mid = machine_ids[int(rng.randint(0, machines))]
        day = 0.5 + 0.5 * np.sin(2 * np.pi * start / span * 3.0)
        cpu = float(np.clip(0.05 + 0.4 * day + rng.uniform(-0.05, 0.05),
                            0.01, 1.0))
        mem = float(np.clip(rng.uniform(0.02, 0.3), 0.0, 1.0))
        usage.append((start, end, 5000 + t // 4, t % 4, mid,
                      round(cpu, 4), round(mem, 4)))
    # Realistic imperfection: rows land near-sorted but not sorted (the
    # importer must order by timestamp itself).
    rng.shuffle(events)
    rng.shuffle(usage)
    files = [
        _write_rows(out_dir / GOOGLE_MACHINE_EVENTS, events),
        _write_rows(out_dir / GOOGLE_TASK_USAGE, usage),
    ]
    return {"dir": str(out_dir), "files": [str(f) for f in files],
            "machines": machines, "tasks": tasks}


def generate_alibaba_fixture(
    out_dir: str | Path,
    machines: int = 8,
    containers: int = 150,
    span: int = 10_000,
    ticks: int = 40,
    seed: int = 0,
) -> dict:
    """Write a miniature Alibaba cluster-trace-v2018-style directory.

    ``machine_usage.csv``: (machine_id, time_stamp, cpu_util_percent,
    mem_util_percent) — one row per machine per tick over each machine's
    observed lifespan (one seeded machine joins late, one leaves early:
    the lifespan-availability reconstruction has something to find),
    cpu_util following a per-machine-offset diurnal wave.
    ``container_meta.csv``: (container_id, machine_id, time_stamp,
    app_du, status, cpu_request, cpu_limit, mem_size) with
    ``cpu_request`` in the v2018 convention of 1/100 cores (100 = 1
    core). Deterministic per seed.
    """
    rng = np.random.RandomState(seed)
    out_dir = Path(out_dir)
    machine_ids = [f"m_{m + 1}" for m in range(machines)]
    late = machine_ids[int(rng.randint(0, machines))]
    remaining = [m for m in machine_ids if m != late]
    early = remaining[int(rng.randint(0, len(remaining)))]
    usage = []
    tick_times = np.linspace(0, span, ticks, dtype=np.int64)
    for i, mid in enumerate(machine_ids):
        phase = rng.uniform(0, 2 * np.pi)
        for t in tick_times:
            if mid == late and t < span * 0.3:
                continue           # joins late
            if mid == early and t > span * 0.7:
                continue           # decommissioned early
            day = 0.5 + 0.5 * np.sin(2 * np.pi * t / span * 2.0 + phase)
            cpu = float(np.clip(10 + 60 * day + rng.uniform(-5, 5), 1, 100))
            mem = float(np.clip(rng.uniform(20, 70), 1, 100))
            usage.append((mid, int(t), round(cpu, 2), round(mem, 2)))
    meta = []
    for c in range(containers):
        t = int(rng.uniform(0, span))
        mid = machine_ids[int(rng.randint(0, machines))]
        day = 0.5 + 0.5 * np.sin(2 * np.pi * t / span * 2.0)
        req = int(np.clip(rng.uniform(20, 60) + 40 * day, 10, 400))
        meta.append((f"c_{c}", mid, t, f"app_{c % 5}", "started",
                     req, req * 2, round(rng.uniform(0.5, 8.0), 2)))
    rng.shuffle(usage)
    rng.shuffle(meta)
    files = [
        _write_rows(out_dir / ALIBABA_MACHINE_USAGE, usage),
        _write_rows(out_dir / ALIBABA_CONTAINER_META, meta),
    ]
    return {"dir": str(out_dir), "files": [str(f) for f in files],
            "machines": machines, "containers": containers}
