"""graftmix part 3: the zero-shot transfer grid.

One policy over the scenario universe is a CLAIM; this module is its
measurement. For every (scenario × node count) cell the GENERALIST (a
mixture-trained checkpoint) plays paired seeded episodes against an
OPPONENT — the per-family specialist checkpoint when one is named, else
the best hand-coded node baseline on the same paired seeds — and the
cell gets a graftstudy verdict: a Wilson 95% interval over the per-seed
win rate plus a two-sided sign test (``studies/analysis.py``, the same
arithmetic the anti-latch studies grade with), on the graded scale

- ``confirmed_above``  — Wilson LOWER bound > 0.5: the generalist is
  measurably better across seeds,
- ``point_above`` / ``point_below`` — the point estimate is on that
  side but the interval straddles 0.5 (the honest small-n answer),
- ``tied`` — every paired seed tied: zero evidence either way,
- ``confirmed_below`` — Wilson UPPER bound < 0.5.

Families the mixture never trained on are flagged ``held_out`` — those
columns ARE the zero-shot transfer claim. A cell whose scenario
observes a different width than the checkpoint trained (the
heterogeneous family vs a classic 6-feature generalist) reports
``incompatible`` with the structured ``reason`` the eval matrix also
carries, never a garbage score.

Pairing discipline: within a cell, every policy — generalist,
specialist, every candidate baseline — evaluates on the SAME seeded
episode batch per seed (one ``PRNGKey(seed)`` through
``run_bundle_episodes``), so the comparison removes the dominant
episode-draw variance exactly like ``structured_evaluate``'s baseline
convention and graftstudy's paired-seed deltas.

Entry points: ``evaluate --transfer-grid`` / ``make transfer-grid``
(docs/scenarios.md has the one-command chip protocol).
"""

from __future__ import annotations

from typing import Callable

import numpy as np

TRANSFER_GRID_SCHEMA_VERSION = 1


def incompatible_reason(ckpt_feat: int, scenario_feat: int,
                        ckpt_env: str = "cluster_set") -> dict:
    """The structured ``reason`` an incompatible cell carries — shared
    with the eval matrix (``evaluate --matrix``): ``obs_width`` (the
    embed kernel bakes the trained width), ``env_family`` (a non-set
    checkpoint has no per-node pointer logits to score nodes with), or
    ``scenario_meta`` (widths agree but the recorded provenance cannot
    — reserved for future families)."""
    if ckpt_env != "cluster_set":
        return {"reason": "env_family",
                "note": f"checkpoint trained env {ckpt_env!r}; the grid "
                        "scores per-node set policies"}
    if ckpt_feat != scenario_feat:
        return {"reason": "obs_width",
                "note": f"checkpoint trained at node_feat={ckpt_feat}, "
                        f"scenario observes {scenario_feat}"}
    return {"reason": "scenario_meta",
            "note": "widths agree but the scenario meta does not"}


def cell_verdict(wins: int, losses: int, ties: int) -> dict:
    """Grade one cell's paired-seed record (module docstring)."""
    from rl_scheduler_tpu.studies.analysis import (
        sign_test_pvalue,
        wilson_interval,
    )

    n = wins + losses
    if n == 0:
        # All ties (or no seeds): ZERO evidence either way — say so
        # instead of claiming a side (the summary/render treat `tied`
        # as the neutral middle of the graded scale).
        return {"wins": wins, "losses": losses, "ties": ties,
                "win_rate": None, "wilson95": None, "sign_test_p": 1.0,
                "verdict": "tied"}
    # wilson_interval counts "failures"; feed it the WINS so the
    # interval reads as the win-rate interval directly.
    lo, hi = wilson_interval(wins, n)
    rate = wins / n
    if lo > 0.5:
        verdict = "confirmed_above"
    elif hi < 0.5:
        verdict = "confirmed_below"
    elif rate >= 0.5:
        verdict = "point_above"
    else:
        verdict = "point_below"
    return {"wins": wins, "losses": losses, "ties": ties,
            "win_rate": round(rate, 3),
            "wilson95": [round(lo, 3), round(hi, 3)],
            "sign_test_p": round(sign_test_pvalue(wins, losses), 4),
            "verdict": verdict}


def _paired_means(bundle, policy_fn, episodes: int, seeds: tuple) -> list:
    """Per-seed mean episode rewards, ONE compiled program for all seeds
    (vmapped over the seed axis). Key-split order matches
    ``run_bundle_episodes(seed=s)`` exactly, so the pairing contract is
    the same — but a grid run touches dozens of (bundle, policy) pairs,
    and per-seed recompiles would dominate its wall clock."""
    import jax
    import jax.numpy as jnp

    steps = bundle.episode_steps

    def one(key):
        reset_key, policy_key = jax.random.split(key)
        state, obs = bundle.reset_batch(reset_key, episodes)

        def step_fn(carry, k):
            state, obs = carry
            action = policy_fn(obs, k)
            state, ts = bundle.step_batch(state, action)
            return (state, ts.obs), ts.reward

        keys = jax.random.split(policy_key, steps)
        _, rewards = jax.lax.scan(step_fn, (state, obs), keys)
        return rewards.sum(axis=0).mean()

    means = jax.jit(jax.vmap(one))(
        jnp.stack([jax.random.PRNGKey(s) for s in seeds]))
    return [float(m) for m in means]


def transfer_cells(
    checkpoint: tuple,
    scenario_names: list,
    node_counts: tuple = (8, 16),
    seeds: tuple = (0, 1, 2, 3, 4),
    episodes: int = 8,
    specialists: dict | None = None,
    trained_families: tuple = (),
    scenario_seed: int = 0,
    emit: Callable[[dict], None] | None = None,
) -> list[dict]:
    """One verdict-graded cell per (scenario × node count).

    ``checkpoint`` is ``(net, params, node_feat)`` — the generalist;
    ``specialists`` maps scenario name → the same tuple for a per-family
    specialist run; scenarios without one fall back to the strongest
    hand-coded baseline ON THE SAME PAIRED SEEDS. ``"csv"`` names the
    un-scenarioed replay row. Emits each cell through ``emit`` as it
    completes (the matrix CLI convention) and returns them all.
    """
    import logging

    from rl_scheduler_tpu.agent.evaluate import greedy_policy_fn
    from rl_scheduler_tpu.env.baselines import structured_baselines
    from rl_scheduler_tpu.scenarios import (
        baseline_columns,
        csv_reference_row,
        get_scenario,
        node_feat_for,
        scenario_bundle,
    )

    specialists = specialists or {}
    net, params, ckpt_feat = checkpoint
    gen_policy = greedy_policy_fn(net, params)
    cells = []
    for sname in scenario_names:
        if sname == "csv":
            # The shared csv-row definition (scenarios/spec.py): same
            # columns/width AND the same domain_random family mapping
            # the eval matrix keys its held-out flags on.
            csv_bundle_fn, columns, feat, csv_family = csv_reference_row()
            held_out = bool(trained_families) and \
                csv_family not in trained_families
            scn = None
        else:
            scn = get_scenario(sname, seed=scenario_seed)
            feat = node_feat_for(scn)
            columns = baseline_columns(scn)
            held_out = bool(trained_families) and \
                scn.family not in trained_families
        for nodes in node_counts:
            cell = {
                "schema_version": TRANSFER_GRID_SCHEMA_VERSION,
                "metric": "transfer_grid_cell",
                "scenario": sname,
                "num_nodes": nodes,
                "node_feat": feat,
                "held_out": held_out,
                "episodes": episodes,
                "seeds": len(seeds),
            }
            if feat != ckpt_feat:
                cell["incompatible"] = True
                cell.update(incompatible_reason(ckpt_feat, feat))
            else:
                if sname == "csv":
                    bundle = csv_bundle_fn(nodes)
                else:
                    bundle = scenario_bundle(scn, nodes)
                gen = _paired_means(bundle, gen_policy, episodes, seeds)
                spec = specialists.get(sname)
                if spec is not None and spec[2] != feat:
                    # An EXPLICITLY named specialist that cannot score
                    # this scenario must not silently become a baseline
                    # row — say so in the cell and in the log.
                    logging.getLogger(__name__).warning(
                        "transfer grid: --specialist %s trained "
                        "node_feat=%d but the scenario observes %d — "
                        "falling back to the baseline opponent",
                        sname, spec[2], feat)
                    cell["specialist_ignored"] = "obs_width"
                    spec = None
                if spec is not None:
                    opp_name = "specialist"
                    opp = _paired_means(
                        bundle, greedy_policy_fn(spec[0], spec[1]),
                        episodes, seeds)
                else:
                    # Strongest hand-coded opponent on the SAME paired
                    # seeds — picked by its mean over them, so the
                    # comparison is against the best honest alternative.
                    candidates = {
                        bname: _paired_means(bundle, fn, episodes, seeds)
                        for bname, fn in structured_baselines(
                            "cluster_set", columns=columns).items()
                    }
                    best = max(candidates,
                               key=lambda b: float(np.mean(candidates[b])))
                    opp_name = f"baseline:{best}"
                    opp = candidates[best]
                wins = sum(1 for g, o in zip(gen, opp) if g > o)
                losses = sum(1 for g, o in zip(gen, opp) if g < o)
                ties = len(seeds) - wins - losses
                opp_mean = float(np.mean(opp))
                margin = ((float(np.mean(gen)) - opp_mean)
                          / abs(opp_mean) * 100.0 if opp_mean else 0.0)
                cell.update({
                    "opponent": opp_name,
                    "generalist_reward_mean": round(float(np.mean(gen)), 3),
                    "opponent_reward_mean": round(opp_mean, 3),
                    "margin_pct": round(margin, 2),
                })
                cell.update(cell_verdict(wins, losses, ties))
            cells.append(cell)
            if emit is not None:
                emit(cell)
    return cells


def transfer_grid_summary(cells: list, run: str = "",
                          mixture: str | None = None,
                          trained_families: tuple = ()) -> dict:
    """The ONE ``schema_version``-tagged driver line for a grid run
    (bench.py convention): the cells plus the aggregate the acceptance
    bar reads — how many held-out cells the generalist wins or holds
    within the margin, and the worst held-out verdict."""
    order = ("confirmed_below", "point_below", "tied", "point_above",
             "confirmed_above")
    held = [c for c in cells if c.get("held_out")
            and not c.get("incompatible")]
    worst = min((order.index(c["verdict"]) for c in held), default=None)
    return {
        "schema_version": TRANSFER_GRID_SCHEMA_VERSION,
        "metric": "transfer_grid",
        "run": run,
        "mixture": mixture,
        "trained_families": list(trained_families),
        "scenarios": list(dict.fromkeys(c["scenario"] for c in cells)),
        "node_counts": sorted({c["num_nodes"] for c in cells}),
        "cells": cells,
        "held_out_cells": len(held),
        "held_out_not_below": sum(
            1 for c in held if c["verdict"] != "confirmed_below"),
        "worst_held_out_verdict": order[worst] if worst is not None
        else None,
        "incompatible_cells": sum(1 for c in cells
                                  if c.get("incompatible")),
    }


def render_transfer_grid(summary: dict) -> str:
    """The human grid: one row per scenario (held-out rows starred), one
    column per node count, each cell ``margin% verdict-glyph`` —
    ``++/+/=/-/--`` for confirmed/point above, tied, point/confirmed
    below — with the generalist-vs-opponent margin the acceptance
    criterion reads."""
    glyph = {"confirmed_above": "++", "point_above": "+ ", "tied": "= ",
             "point_below": "- ", "confirmed_below": "--"}
    nodes = summary["node_counts"]
    by = {(c["scenario"], c["num_nodes"]): c for c in summary["cells"]}
    width = 21
    lines = [
        "=" * (22 + width * len(nodes)),
        "ZERO-SHOT TRANSFER GRID (generalist margin vs opponent, "
        "paired seeds)",
        f"mixture: {summary.get('mixture')}   trained families: "
        f"{', '.join(summary.get('trained_families') or ()) or '-'}",
        "=" * (22 + width * len(nodes)),
        " " * 22 + "".join(f"{'N=' + str(n):>{width}}" for n in nodes),
    ]
    for s in summary["scenarios"]:
        cols = []
        for n in nodes:
            c = by.get((s, n))
            if c is None:
                cols.append(f"{'-':>{width}}")
            elif c.get("incompatible"):
                cols.append(f"{'incompat(' + c['reason'] + ')':>{width}}")
            else:
                cols.append(
                    f"{c['margin_pct']:+9.1f}% {glyph[c['verdict']]}"
                    f"{' vs spec' if c['opponent'] == 'specialist' else '':<6}"
                    .rjust(width))
        held = next((c.get("held_out") for c in summary["cells"]
                     if c["scenario"] == s), False)
        lines.append(f"{s + (' *' if held else ''):<22}" + "".join(cols))
    lines += [
        "-" * (22 + width * len(nodes)),
        "* = held-out family (zero-shot)   ++/+/=/-/-- = "
        "confirmed/point above, tied, point/confirmed below "
        "(Wilson95 + sign test vs 0.5)",
        f"held-out cells not confirmed_below: "
        f"{summary['held_out_not_below']}/{summary['held_out_cells']}"
        f"   worst held-out verdict: {summary['worst_held_out_verdict']}",
        "=" * (22 + width * len(nodes)),
    ]
    return "\n".join(lines)
