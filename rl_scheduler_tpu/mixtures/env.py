"""graftmix env layer: stacked per-family tables, per-episode family draw.

One :class:`MixtureSetParams` holds EVERY component's compiled
``cluster_set`` tables stacked on a leading family axis ``[K, ...]``.
At each episode boundary (reset and every vmapped auto-reset) the env
draws a family index from its own ``jax.random`` key — the per-episode
randomization substrate from the scenario layer — then steps the
UNCHANGED ``env/cluster_set.py`` pure functions over that family's
slice. Nothing here forks the env semantics: :func:`episode_params`
materializes a per-episode :class:`~rl_scheduler_tpu.env.cluster_set.
ClusterSetParams` by indexing the stacks, so every family's reward
terms, churn masks, and randomization draws are exactly the single-
family env's (the densification identities — all-ones avail mask,
degenerate randomization ranges — are the bitwise no-ops the scenario
suite already pins).

Densification: the stacked layout needs structural uniformity, so
components without a field get its identity value — ``pod_scale`` all
ones, ``avail_mask`` all ones (churn penalty then contributes exactly
0.0), missing randomization ranges become degenerate ``[x, x]`` ranges
around the component's static value. ``random_phase`` is a Python bool
on the single env (structural, untraceable per family), so the mixture
always resets with it ON and value-gates the drawn phase by the
component's flag — components without random phase land back on row 0
with the pod re-drawn at that row from a dedicated key (one extra,
unconditional draw per reset: the fixed-draw-order discipline).

The anneal schedule lives in the STATE: each env lane counts its own
episodes (``MixtureState.ep_count``, carried through the custom
auto-reset), and the draw weights interpolate start→final over the
first ``anneal_episodes`` episodes — resume-safe (the counter rides the
full-state checkpoint tree) and fully vmappable.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from rl_scheduler_tpu.env import cluster_set as cs
from rl_scheduler_tpu.env.bundle import EnvBundle
from rl_scheduler_tpu.mixtures.curriculum import MixtureSpec


class MixtureSetParams(NamedTuple):
    """Stacked per-family env tables (leading axis K = components) plus
    the draw schedule. Scalar env knobs that cannot differ between
    components (weights of the reward terms, the node→cloud map, episode
    length) stay unstacked."""

    # --- per-family stacks [K, ...] ---
    costs: jnp.ndarray           # [K, T, 2]
    latencies: jnp.ndarray       # [K, T, 2]
    pod_scale: jnp.ndarray       # [K, T] (ones = identity)
    avail_mask: jnp.ndarray      # [K, T, N] (ones = identity)
    churn_penalty: jnp.ndarray   # [K]
    node_jitter: jnp.ndarray     # [K]
    pod_cpu_low: jnp.ndarray     # [K]
    pod_cpu_high: jnp.ndarray    # [K]
    drain_rate: jnp.ndarray      # [K]
    overload_penalty: jnp.ndarray  # [K]
    jitter_range: jnp.ndarray    # [K, 2]
    drain_range: jnp.ndarray     # [K, 2]
    overload_range: jnp.ndarray  # [K, 2]
    random_phase_flag: jnp.ndarray  # [K] f32 0/1
    # --- shared ---
    cloud_of_node: jnp.ndarray   # [N]
    cost_weight: jnp.ndarray
    latency_weight: jnp.ndarray
    reward_scale: jnp.ndarray
    max_steps: jnp.ndarray
    # --- draw schedule ---
    weights: jnp.ndarray         # [K] final, sums to 1
    start_weights: jnp.ndarray   # [K] anneal start (== weights if none)
    anneal_episodes: jnp.ndarray  # scalar f32, 0 = static

    @property
    def num_components(self) -> int:
        return self.costs.shape[0]

    @property
    def num_nodes(self) -> int:
        return self.cloud_of_node.shape[0]


class MixtureState(NamedTuple):
    family: jnp.ndarray    # scalar int32: this episode's component
    ep_count: jnp.ndarray  # scalar int32: episodes completed by this lane
    inner: cs.ClusterSetState

    # The generic auto-reset helpers key on `.key`; route to the inner
    # env's carry so the mixture state satisfies the same contract.
    @property
    def key(self):
        return self.inner.key


def mixture_set_params(spec: MixtureSpec, num_nodes: int = 8,
                       seed: int = 0) -> MixtureSetParams:
    """Compile a :class:`MixtureSpec` into stacked env params.

    ``seed`` re-seeds every component's table compilation (the
    ``--scenario-seed`` composition: a reseeded training attempt keeps
    the same workload stack). All components must compile tables of one
    length — registry families share the 100-row convention; name-built
    trace components pin ``steps=`` in their name to match.
    """
    from rl_scheduler_tpu.scenarios import get_scenario, cluster_set_params

    per = [cluster_set_params(get_scenario(n, seed=seed), num_nodes)
           for n in spec.names()]
    rows = {p.costs.shape[0] for p in per}
    if len(rows) > 1:
        detail = ", ".join(f"{n}={p.costs.shape[0]}"
                           for n, p in zip(spec.names(), per))
        raise ValueError(
            f"mixture components compile tables of different lengths "
            f"({detail}); stacked replay needs one length — pin steps= "
            "on the name-built components")
    t = rows.pop()
    for field in ("cost_weight", "latency_weight", "reward_scale",
                  "max_steps"):
        vals = {float(getattr(p, field)) for p in per}
        if len(vals) > 1:
            raise ValueError(
                f"mixture components disagree on shared env knob "
                f"{field}: {sorted(vals)}")
    f32 = lambda x: jnp.asarray(x, jnp.float32)

    def dense(p: cs.ClusterSetParams) -> dict:
        ident_range = lambda rg, x: (np.asarray(rg, np.float32)
                                     if rg is not None
                                     else np.asarray([x, x], np.float32))
        return dict(
            costs=np.asarray(p.costs, np.float32),
            latencies=np.asarray(p.latencies, np.float32),
            pod_scale=(np.asarray(p.pod_scale, np.float32)
                       if p.pod_scale is not None
                       else np.ones(t, np.float32)),
            avail_mask=(np.asarray(p.avail_mask, np.float32)
                        if p.avail_mask is not None
                        else np.ones((t, num_nodes), np.float32)),
            churn_penalty=(float(p.churn_penalty)
                           if p.churn_penalty is not None else 0.0),
            node_jitter=float(p.node_jitter),
            pod_cpu_low=float(p.pod_cpu_low),
            pod_cpu_high=float(p.pod_cpu_high),
            drain_rate=float(p.drain_rate),
            overload_penalty=float(p.overload_penalty),
            jitter_range=ident_range(p.jitter_range, float(p.node_jitter)),
            drain_range=ident_range(p.drain_range, float(p.drain_rate)),
            overload_range=ident_range(p.overload_range,
                                       float(p.overload_penalty)),
            random_phase_flag=1.0 if p.random_phase else 0.0,
        )

    stacks = [dense(p) for p in per]
    stacked = {k: f32(np.stack([s[k] for s in stacks]))
               for k in stacks[0]}
    return MixtureSetParams(
        **stacked,
        cloud_of_node=per[0].cloud_of_node,
        cost_weight=per[0].cost_weight,
        latency_weight=per[0].latency_weight,
        reward_scale=per[0].reward_scale,
        max_steps=per[0].max_steps,
        weights=f32(spec.weights()),
        start_weights=f32(spec.start_weights()),
        anneal_episodes=f32(spec.anneal_episodes),
    )


def episode_params(params: MixtureSetParams,
                   family: jnp.ndarray) -> cs.ClusterSetParams:
    """The per-episode single-family view: every stacked leaf indexed at
    ``family`` (traced-safe), identity leaves included — the unchanged
    ``cluster_set`` reset/step consume it as-is. ``random_phase`` stays
    structurally True; :func:`reset` value-gates the drawn phase."""
    return cs.ClusterSetParams(
        costs=params.costs[family],
        latencies=params.latencies[family],
        cloud_of_node=params.cloud_of_node,
        cost_weight=params.cost_weight,
        latency_weight=params.latency_weight,
        reward_scale=params.reward_scale,
        overload_penalty=params.overload_penalty[family],
        node_jitter=params.node_jitter[family],
        pod_cpu_low=params.pod_cpu_low[family],
        pod_cpu_high=params.pod_cpu_high[family],
        drain_rate=params.drain_rate[family],
        max_steps=params.max_steps,
        pod_scale=params.pod_scale[family],
        avail_mask=params.avail_mask[family],
        churn_penalty=params.churn_penalty[family],
        jitter_range=params.jitter_range[family],
        drain_range=params.drain_range[family],
        overload_range=params.overload_range[family],
        random_phase=True,
    )


def weights_at(params: MixtureSetParams,
               ep_count: jnp.ndarray) -> jnp.ndarray:
    """Draw weights for a lane's ``ep_count``-th episode: linear
    start→final over ``anneal_episodes`` (already final when static —
    the compile sets start == final then, so the formula degenerates)."""
    frac = jnp.where(
        params.anneal_episodes > 0,
        jnp.clip(ep_count.astype(jnp.float32)
                 / jnp.maximum(params.anneal_episodes, 1.0), 0.0, 1.0),
        1.0)
    w = params.start_weights + frac * (params.weights
                                       - params.start_weights)
    return w / w.sum()


def draw_family(params: MixtureSetParams, key: jnp.ndarray,
                ep_count: jnp.ndarray) -> jnp.ndarray:
    """One seeded family index ~ Categorical(:func:`weights_at`)."""
    cum = jnp.cumsum(weights_at(params, ep_count))
    u = jax.random.uniform(key, (), jnp.float32)
    idx = jnp.searchsorted(cum, u, side="right")
    return jnp.clip(idx, 0, params.num_components - 1).astype(jnp.int32)


def reset(params: MixtureSetParams, key: jnp.ndarray,
          ep_count: jnp.ndarray | int = 0
          ) -> tuple[MixtureState, jnp.ndarray]:
    """Draw this episode's family, then the single-family reset.

    The inner reset runs with ``random_phase`` structurally on (the
    stacked params' one static shape); the drawn phase is then
    value-gated by the family's flag and the pending pod re-drawn at the
    gated row from a dedicated key — unconditional, so the split count
    and draw order are identical for every family (vmap-uniform)."""
    ep_count = jnp.asarray(ep_count, jnp.int32)
    fam_key, env_key, pod_key = jax.random.split(key, 3)
    family = draw_family(params, fam_key, ep_count)
    ep = episode_params(params, family)
    inner, _ = cs.reset(ep, env_key)
    flag = (params.random_phase_flag[family] > 0).astype(jnp.int32)
    inner = inner._replace(phase=inner.phase * flag)
    inner = inner._replace(
        pod_cpu=cs._draw_pod(ep, pod_key, cs._table_row(ep, inner)))
    state = MixtureState(family=family, ep_count=ep_count, inner=inner)
    return state, cs._observe(ep, inner)


def step(params: MixtureSetParams, state: MixtureState,
         action: jnp.ndarray) -> tuple[MixtureState, cs.TimeStep]:
    """Single step inside the episode's family (pure, jit/vmap-safe)."""
    ep = episode_params(params, state.family)
    inner, ts = cs.step(ep, state.inner, action)
    return state._replace(inner=inner), ts


def mixture_bundle(params: MixtureSetParams) -> EnvBundle:
    """The mixture env as an :class:`EnvBundle` — the same vmapped
    auto-reset fleet path every family trains through, with ONE
    difference from ``bundle_from_single``: the auto-reset threads the
    lane's episode counter into the replacement episode's draw (the
    anneal schedule's clock), incrementing exactly on ``done``."""

    def step_autoreset(state: MixtureState, action):
        new_state, ts = step(params, state, action)
        reset_key, carry_key = jax.random.split(new_state.inner.key)
        next_count = state.ep_count + 1
        r_state, r_obs = reset(params, reset_key, ep_count=next_count)
        r_state = r_state._replace(
            inner=r_state.inner._replace(key=carry_key))
        out_state = jax.tree.map(
            lambda r, n: jnp.where(ts.done, r, n), r_state, new_state)
        out_obs = jnp.where(ts.done, r_obs, ts.obs)
        return out_state, ts._replace(obs=out_obs)

    step_batch = jax.vmap(step_autoreset, in_axes=(0, 0))

    def reset_batch(key, num_envs):
        keys = jax.random.split(key, num_envs)
        return jax.vmap(lambda k: reset(params, k))(keys)

    return EnvBundle(
        reset_batch=reset_batch,
        step_batch=step_batch,
        obs_shape=(params.num_nodes, cs.NODE_FEAT),
        num_actions=params.num_nodes,
        name="cluster_set_mixture",
        episode_steps=int(params.max_steps),
    )
