"""graftmix part 2: seeded mixture curricula over the scenario universe.

A :class:`MixtureSpec` names a training DISTRIBUTION over scenario
families: weighted components (each a registered scenario preset or a
name-built ``trace_replay:``/``external_trace:`` spec), optionally with
an easy→adversarial anneal schedule. It compiles (``mixtures/env.py``)
into stacked per-family env tables with a per-episode family index drawn
from the vmapped reset key — the per-episode randomization substrate the
scenario layer already rides — so one jitted fleet program trains the
generalist across every component without a single host round-trip.

**The name IS the spec** (the ``trace_replay:`` convention): the
canonical form

    ``mixture:<name>*<w>+<name>*<w>[@anneal=E&from=<name>*<w>+...]``

round-trips through ``train_ppo --mixture``, checkpoint meta, the
``--resume`` guards, and the extender's serving-conformance demand.
Weights are relative (normalized at compile); ``anneal=E`` linearly
interpolates from the ``from=`` weights to the final weights over each
env lane's first ``E`` EPISODES (episodes, not iterations, because the
family draw happens at the vmapped auto-reset inside the jitted update —
``docs/scenarios.md`` gives the episodes↔iterations arithmetic:
``episodes ≈ iterations * rollout_steps / episode_steps``).

**Spec discipline** (graftstudy's): everything inert is refused at
construction — a weight-zero component (it would never train at steady
state), a single-component mixture (that is ``--scenario``), a
duplicate component, an anneal whose start equals its end, ``from=``
without ``anneal=``, and any component whose observation width differs
from the classic 6-feature layout (the heterogeneous family — stacked
tables need one obs shape; the transfer grid reports that cell
``incompatible`` with the obs-width reason instead).
"""

from __future__ import annotations

import dataclasses

MIXTURE_PREFIX = "mixture:"


def _fmt_components(components: tuple) -> str:
    return "+".join(f"{name}*{w:g}" for name, w in components)


@dataclasses.dataclass(frozen=True)
class MixtureSpec:
    """A frozen, validated mixture curriculum (module docstring).

    ``components``/``start`` are ``((scenario_name, weight), ...)``
    tuples; ``start`` is aligned to ``components`` by name and only
    present with a nonzero ``anneal_episodes``.
    """

    components: tuple
    anneal_episodes: int = 0
    start: tuple = ()

    def __post_init__(self):
        if len(self.components) < 2:
            raise ValueError(
                "a mixture needs >= 2 components — a single-family "
                "curriculum is --scenario, not --mixture")
        names = [n for n, _ in self.components]
        if len(set(names)) != len(names):
            raise ValueError(
                f"duplicate mixture components: {names} — merge the "
                "weights instead")
        for name, w in self.components:
            if not w > 0:
                raise ValueError(
                    f"component {name!r} has weight {w}: weight-zero "
                    "(or negative) components are inert — a family that "
                    "never draws never trains; drop it from the spec")
        if self.anneal_episodes < 0:
            raise ValueError(
                f"anneal={self.anneal_episodes}: the anneal horizon is "
                "an episode count >= 0 (0 = static weights)")
        if self.start and not self.anneal_episodes:
            raise ValueError(
                "from= start weights without anneal= are inert (the "
                "schedule never runs); pass both or neither")
        if self.anneal_episodes:
            if not self.start:
                raise ValueError(
                    "anneal= needs from= start weights (which easy "
                    "distribution the curriculum opens on)")
            extra = {n for n, _ in self.start} - set(names)
            if extra:
                raise ValueError(
                    f"from= names components not in the mixture: "
                    f"{sorted(extra)}")
            bad = [n for n, w in self.start if w < 0]
            if bad:
                raise ValueError(
                    f"from= weights must be >= 0 (start-at-zero is how a "
                    "family anneals IN): {bad}")
            if not sum(w for _, w in self.start) > 0:
                raise ValueError("from= weights must not all be zero")
            if self._normalized(self.start_weights()) == \
                    self._normalized([w for _, w in self.components]):
                raise ValueError(
                    "anneal from= equals the final weights — an inert "
                    "schedule; drop anneal=/from= for a static mixture")
        # Every component must parse/resolve NOW (the graftstudy
        # at-construction discipline: a typo'd family name must fail
        # before any training), and stacked tables need one obs width.
        from rl_scheduler_tpu.scenarios import get_scenario, node_feat_for
        from rl_scheduler_tpu.env.cluster_set import NODE_FEAT

        for name, _ in self.components:
            scn = get_scenario(name)
            feat = node_feat_for(scn)
            if feat != NODE_FEAT:
                raise ValueError(
                    f"component {name!r} (family {scn.family}) observes "
                    f"{feat} features; mixture tables stack the classic "
                    f"{NODE_FEAT}-feature layout — the heterogeneous "
                    "family trains alone and joins the transfer grid as "
                    "a held-out column")

    @staticmethod
    def _normalized(ws: list) -> tuple:
        total = sum(ws)
        return tuple(round(w / total, 9) for w in ws)

    def names(self) -> tuple:
        return tuple(n for n, _ in self.components)

    def families(self) -> tuple:
        """The component FAMILIES this mixture trains on — the transfer
        grid's held-out test reads this from checkpoint meta."""
        from rl_scheduler_tpu.scenarios import get_scenario

        return tuple(sorted({get_scenario(n).family for n, _ in
                             self.components}))

    def weights(self) -> tuple:
        """Final (steady-state) weights, normalized to sum 1."""
        return self._normalized([w for _, w in self.components])

    def start_weights(self) -> tuple:
        """Anneal start weights aligned to ``components`` order (final
        weights when no anneal), normalized to sum 1."""
        if not self.anneal_episodes:
            return self.weights()
        by_name = dict(self.start)
        raw = [by_name.get(n, 0.0) for n, _ in self.components]
        return self._normalized(raw)

    def canonical_name(self) -> str:
        """The one round-tripping string (module docstring):
        ``parse_mixture(spec.canonical_name()) == spec``."""
        name = MIXTURE_PREFIX + _fmt_components(self.components)
        if self.anneal_episodes:
            name += (f"@anneal={self.anneal_episodes}"
                     f"&from={_fmt_components(self.start)}")
        return name


def parse_mixture(name: str) -> MixtureSpec:
    """Parse the canonical ``mixture:...`` string (module docstring).

    Component weights split on the LAST ``*`` of each ``+``-separated
    term, so name-built components (``external_trace:<dir>?format=...``)
    carrying ``?``/``&`` in their own query parse unchanged; the
    mixture-level suffix splits on the last ``@anneal=``."""
    if not name.startswith(MIXTURE_PREFIX):
        raise ValueError(
            f"mixture spec {name!r} must start with {MIXTURE_PREFIX!r} "
            "(or name a registered preset; list_mixtures())")
    body = name[len(MIXTURE_PREFIX):]
    anneal_episodes, start = 0, ()
    if "@anneal=" in body:
        body, _, suffix = body.rpartition("@anneal=")
        anneal_part, _, from_part = suffix.partition("&from=")
        try:
            anneal_episodes = int(anneal_part)
        except ValueError:
            raise ValueError(
                f"mixture spec {name!r}: bad anneal episode count "
                f"{anneal_part!r}")
        if from_part:
            start = _parse_components(from_part, name)
    components = _parse_components(body, name)
    return MixtureSpec(components=components,
                       anneal_episodes=anneal_episodes, start=start)


def _parse_components(body: str, name: str) -> tuple:
    out = []
    for term in body.split("+"):
        comp, sep, w = term.rpartition("*")
        if not sep:
            raise ValueError(
                f"mixture spec {name!r}: component {term!r} needs "
                "<scenario>*<weight>")
        try:
            out.append((comp, float(w)))
        except ValueError:
            raise ValueError(
                f"mixture spec {name!r}: bad weight {w!r} for "
                f"component {comp!r}")
    return tuple(out)


# Registry presets: the one-command curricula. `generalist` is THE
# transfer-grid training distribution — every classic-width registry
# family, equal weight. `generalist_anneal` opens easy (the CSV-shaped
# domain_random workload) and anneals toward the adversarial families
# (churn + price spikes) over the first 200 episodes per lane.
MIXTURES = {
    "generalist": "mixture:bursty*1+churn*1+price_spike*1+randomized*1",
    "generalist_anneal": ("mixture:bursty*1+churn*1.5+price_spike*1.5"
                          "+randomized*1@anneal=200"
                          "&from=randomized*3+bursty*1"),
}


def list_mixtures() -> list:
    return sorted(MIXTURES)


def get_mixture(name: str) -> MixtureSpec:
    """Preset lookup or inline ``mixture:...`` parse — the one entry
    every CLI flag and meta rebuild goes through."""
    if name in MIXTURES:
        return parse_mixture(MIXTURES[name])
    if name.startswith(MIXTURE_PREFIX):
        return parse_mixture(name)
    raise ValueError(
        f"unknown mixture {name!r}; registered: {list_mixtures()} (or an "
        f"inline {MIXTURE_PREFIX}<scenario>*<w>+... spec)")


def mixture_meta(spec: MixtureSpec, scenario_seed: int = 0) -> dict:
    """The checkpoint-meta record (the ``scenario_meta`` counterpart):
    enough to rebuild the training distribution at eval time, pin the
    resume guards, and answer the serving-conformance demand."""
    from rl_scheduler_tpu.env.cluster_set import NODE_FEAT

    return {
        "scenario": None,
        "mixture": spec.canonical_name(),
        "mixture_families": list(spec.families()),
        "scenario_seed": scenario_seed,
        "node_feat": NODE_FEAT,
    }
