"""graftmix part 1: external cluster-trace importer.

Turns public cluster traces — Google ClusterData-style machine-event +
task-usage CSVs, Alibaba cluster-trace-v2018-style machine/container
tables — into the table space the envs already replay, **through the
existing data pipeline**: the per-cloud load series derived from the
trace drives a raw price/latency frame built on ``data/generate.py``'s
public on-demand anchors, and ``data/normalize.normalize`` MinMax-scales
it into the same ``[0, 1]`` columns the shipped CSV takes. The result is
a drop-in scenario family (``external_trace:<dir>?format=...``,
``scenarios/spec.py``), not a parallel format.

**What is reconstructed, and how.**

- *Load → cost/latency* ``[T, 2]``: machines are split into two "cloud"
  halves by sorted machine id (the ``cluster_set`` first-half-aws
  convention); per time bucket, each half's mean CPU utilization is the
  demand signal — cost follows it weakly (demand pricing), latency
  follows it hard, both through the normalize pipeline. Buckets a half
  never reports in carry the last observed level forward.
- *Pod sizes →* ``pod_scale [T]``: the mean requested CPU of
  tasks/containers arriving in each bucket, normalized to mean 1.0 — the
  arrival-intensity multiplier ``ClusterSetParams.pod_scale`` applies to
  the env's pod draw. An EMPTY usage table is a recorded outcome, not a
  crash: the import degrades to the env's default draw
  (``pod_scale=None``) and the report says so.
- *Machine lifecycle →* ``avail_mask [T, N]``: Google's ADD/REMOVE
  events (and Alibaba machines' observed usage lifespans) give each
  machine an up/down series; machines map onto the requested node count
  by a seeded assignment inside each cloud half, and a node is up when
  at least half its machines are (at least one node is kept up per row —
  the ``churn_mask`` discipline).

**Schema validation, counted.** Rows are validated positionally against
the format's column order; malformed rows (short, non-numeric where a
number is required, inverted time ranges) are COUNTED per reason in the
:class:`ImportReport` and skipped — a truncated download or a torn final
line must never kill a campaign. Only a trace with too few usable rows
to bucket refuses (:class:`TraceImportError`).

**Determinism.** Bitwise-identical tables per ``(trace digest, seed)``
(pinned by test): rows are sorted with stable tie-breaks after parse
(real traces arrive shard-ordered, not time-ordered — counted when
observed), and every random draw (latency jitter, machine→node
assignment) comes from one ``np.random.RandomState(seed)`` with a fixed
draw order. :func:`trace_digest` fingerprints the source bytes so "same
trace" is checkable, not assumed (the ``loopback/compile.py``
convention).
"""

from __future__ import annotations

import dataclasses
import hashlib
from pathlib import Path

import numpy as np

GOOGLE_FORMAT = "google"
ALIBABA_FORMAT = "alibaba"
FORMATS = (GOOGLE_FORMAT, ALIBABA_FORMAT)

# Positional column orders (headerless CSVs, matching the public
# releases' layouts; extra trailing columns are ignored so fuller
# real-trace exports parse unchanged).
GOOGLE_MACHINE_EVENT_COLUMNS = (
    "timestamp", "machine_id", "event_type", "platform_id", "cpus",
    "memory")
GOOGLE_TASK_USAGE_COLUMNS = (
    "start_time", "end_time", "job_id", "task_index", "machine_id",
    "cpu_rate", "memory_usage")
ALIBABA_MACHINE_USAGE_COLUMNS = (
    "machine_id", "time_stamp", "cpu_util_percent", "mem_util_percent")
ALIBABA_CONTAINER_META_COLUMNS = (
    "container_id", "machine_id", "time_stamp", "app_du", "status",
    "cpu_request", "cpu_limit", "mem_size")

# Google machine_events event_type values.
MACHINE_ADD, MACHINE_REMOVE, MACHINE_UPDATE = 0, 1, 2

_FORMAT_FILES = {
    GOOGLE_FORMAT: ("machine_events.csv", "task_usage.csv"),
    ALIBABA_FORMAT: ("machine_usage.csv", "container_meta.csv"),
}

# Pod-scale clipping: the compiled multiplier stays within the range the
# bursty family uses, so an outlier task cannot turn every pod draw into
# a guaranteed overload.
POD_SCALE_LOW, POD_SCALE_HIGH = 0.25, 4.0


class TraceImportError(ValueError):
    """The trace directory cannot compile — missing files or too few
    usable rows after counted rejection."""


@dataclasses.dataclass(frozen=True)
class ImportedTrace:
    """One import: env-ready tables plus the full accounting report."""

    costs: np.ndarray          # [T, 2] f32, normalized [0, 1]
    latencies: np.ndarray      # [T, 2] f32
    pod_scale: np.ndarray | None  # [T] f32 (None: empty usage table)
    machine_avail: np.ndarray  # [T, M] f32, 1 = up, machine-major
    machine_clouds: np.ndarray  # [M] int32, 0 = aws half, 1 = azure half
    report: "ImportReport"

    @property
    def steps(self) -> int:
        return int(self.costs.shape[0])


@dataclasses.dataclass
class ImportReport:
    """Counted-outcome accounting for one import (module docstring).

    Row invariant (pinned by test): ``rows_total == rows_used +
    rows_ignored + sum(rejected.values())`` — ``rejected`` counts
    malformed/invalid data (short rows, non-numeric fields, inverted
    intervals), ``rows_ignored`` counts well-formed rows the
    reconstruction deliberately skips (UPDATE events, duplicate
    add/remove transitions), and ``rows_used`` is what actually fed the
    compile. Non-row outcomes (an empty usage table) live in their own
    fields (``pod_from_trace``), not the row counters."""

    format: str
    digest: str
    seed: int
    steps: int
    files: dict = dataclasses.field(default_factory=dict)
    rows_total: int = 0
    rows_used: int = 0
    rows_ignored: int = 0
    rejected: dict = dataclasses.field(default_factory=dict)
    machines: int = 0
    usage_rows: int = 0
    pod_from_trace: bool = False
    out_of_order_rows: int = 0
    duplicate_machine_adds: int = 0

    def reject(self, reason: str, n: int = 1, parsed: bool = False) -> None:
        """Count a discarded row; ``parsed=True`` moves an
        already-parsed row out of ``rows_used`` (post-parse semantic
        rejection keeps the row invariant exact)."""
        self.rejected[reason] = self.rejected.get(reason, 0) + n
        if parsed:
            self.rows_used -= n

    def ignore(self, n: int = 1) -> None:
        """A well-formed row the reconstruction deliberately skips."""
        self.rows_ignored += n
        self.rows_used -= n

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


def trace_digest(trace_dir: str | Path, fmt: str) -> str:
    """Content digest over the format's source files (sorted, name +
    bytes) — the determinism key: same digest + same seed ⇒ bitwise the
    same compiled tables."""
    trace_dir = Path(trace_dir)
    h = hashlib.sha256()
    for name in sorted(_format_files(fmt)):
        path = trace_dir / name
        if path.is_file():
            h.update(name.encode())
            h.update(path.read_bytes())
    return h.hexdigest()[:16]


def _format_files(fmt: str) -> tuple:
    if fmt not in _FORMAT_FILES:
        raise TraceImportError(
            f"unknown external-trace format {fmt!r}; choose from "
            f"{list(FORMATS)}")
    return _FORMAT_FILES[fmt]


def _parse_rows(path: Path, schema: tuple, numeric: tuple,
                report: ImportReport, kind: str) -> list:
    """Positional CSV parse with counted rejection: one dict per valid
    row; short rows and non-numeric required fields are counted under
    ``<kind>_short_row`` / ``<kind>_bad_number`` and skipped. A torn
    final line (truncated download, mid-row writer crash) is just a
    short/bad row — counted like any other."""
    rows = []
    with path.open() as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            report.rows_total += 1
            fields = line.split(",")
            if len(fields) < len(schema):
                report.reject(f"{kind}_short_row")
                continue
            row = dict(zip(schema, fields))
            ok = True
            for col in numeric:
                try:
                    row[col] = float(row[col])
                except ValueError:
                    report.reject(f"{kind}_bad_number")
                    ok = False
                    break
            if not ok:
                continue
            rows.append(row)
            report.rows_used += 1
    return rows


def _sorted_counted(rows: list, key, report: ImportReport) -> list:
    """Stable sort by ``key``, counting how many rows arrived out of
    order (real traces are shard-ordered; the importer must not trust
    file order)."""
    keys = [key(r) for r in rows]
    report.out_of_order_rows += sum(
        1 for a, b in zip(keys, keys[1:]) if b < a)
    return [r for _, r in sorted(enumerate(rows),
                                 key=lambda ir: (key(rows[ir[0]]), ir[0]))]


def _load_google(trace_dir: Path, report: ImportReport):
    """``(machine_series, usage_points)`` from a Google-style dir:
    machine_series maps machine_id -> sorted [(time, up_bool)] from
    ADD/REMOVE events (duplicates counted, idempotent); usage_points is
    [(start_time, cpu_request)] per task."""
    events = _parse_rows(
        trace_dir / "machine_events.csv", GOOGLE_MACHINE_EVENT_COLUMNS,
        ("timestamp", "event_type"), report, "machine_events")
    usage = _parse_rows(
        trace_dir / "task_usage.csv", GOOGLE_TASK_USAGE_COLUMNS,
        ("start_time", "end_time", "cpu_rate"), report, "task_usage")
    events = _sorted_counted(events, lambda r: r["timestamp"], report)
    series: dict = {}
    up: dict = {}
    for ev in events:
        mid = ev["machine_id"]
        etype = int(ev["event_type"])
        if etype == MACHINE_UPDATE:
            report.ignore()      # valid, deliberately unused
            continue
        want_up = etype == MACHINE_ADD
        if up.get(mid) == want_up:
            # Redundant transition: idempotent, counted (report
            # invariant: ignored, not rejected — the row is well-formed).
            if want_up:
                report.duplicate_machine_adds += 1
            report.ignore()
            continue
        up[mid] = want_up
        series.setdefault(mid, []).append((ev["timestamp"], want_up))
    points = []
    for row in usage:
        if row["end_time"] < row["start_time"]:
            report.reject("task_usage_inverted_interval", parsed=True)
            continue
        points.append((row["start_time"], row["cpu_rate"],
                       row["machine_id"]))
    return series, points


def _load_alibaba(trace_dir: Path, report: ImportReport):
    """Same ``(machine_series, usage_points)`` shape from an
    Alibaba-v2018-style dir: a machine's lifespan is its first..last
    observed ``machine_usage`` timestamp (the table has no explicit
    add/remove events); per-machine utilization samples double as the
    load signal; container ``cpu_request`` arrives in 1/100 cores."""
    usage = _parse_rows(
        trace_dir / "machine_usage.csv", ALIBABA_MACHINE_USAGE_COLUMNS,
        ("time_stamp", "cpu_util_percent"), report, "machine_usage")
    meta = _parse_rows(
        trace_dir / "container_meta.csv", ALIBABA_CONTAINER_META_COLUMNS,
        ("time_stamp", "cpu_request"), report, "container_meta")
    usage = _sorted_counted(usage, lambda r: r["time_stamp"], report)
    spans: dict = {}
    samples: dict = {}
    for row in usage:
        mid = row["machine_id"]
        t = row["time_stamp"]
        lo, hi = spans.get(mid, (t, t))
        spans[mid] = (min(lo, t), max(hi, t))
        samples.setdefault(mid, []).append((t, row["cpu_util_percent"]
                                            / 100.0))
    series = {mid: [(lo, True), (hi, False)]
              for mid, (lo, hi) in spans.items()}
    points = [(row["time_stamp"], row["cpu_request"] / 100.0,
               row["machine_id"]) for row in meta]
    return series, points, samples


def _machine_clouds(machine_ids: list) -> np.ndarray:
    """First half of the SORTED machine ids is cloud 0 (aws), second
    half cloud 1 — the ``cluster_set`` node convention lifted to
    machines, so the mapping is a pure function of the trace."""
    n = len(machine_ids)
    return (np.arange(n) >= n // 2).astype(np.int32)


def _avail_matrix(series: dict, machine_ids: list,
                  edges: np.ndarray) -> np.ndarray:
    """``[T, M]`` machine availability: up at bucket b iff up at the
    bucket's left edge per the transition series."""
    t = len(edges) - 1
    out = np.zeros((t, len(machine_ids)), np.float32)
    for m, mid in enumerate(machine_ids):
        transitions = series.get(mid, ())
        state = False
        ti = 0
        for b in range(t):
            while ti < len(transitions) and transitions[ti][0] <= edges[b]:
                state = transitions[ti][1]
                ti += 1
            out[b, m] = 1.0 if state else 0.0
    return out


def _bucket_mean(times: np.ndarray, values: np.ndarray,
                 edges: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """``(mean_per_bucket [T], has_data [T])`` of ``values`` grouped by
    the bucket each time lands in."""
    t = len(edges) - 1
    idx = np.clip(np.searchsorted(edges, times, side="right") - 1, 0, t - 1)
    sums = np.bincount(idx, weights=values, minlength=t)
    counts = np.bincount(idx, minlength=t)
    has = counts > 0
    means = np.divide(sums, np.maximum(counts, 1))
    return means, has


def _forward_fill(values: np.ndarray, has: np.ndarray,
                  fallback: float) -> np.ndarray:
    """Carry the last observed level into empty buckets; buckets before
    the first observation take ``fallback``."""
    out = np.empty_like(values)
    last = fallback
    for i in range(len(values)):
        if has[i]:
            last = values[i]
        out[i] = last
    return out


def import_external_trace(
    trace_dir: str | Path,
    fmt: str,
    steps: int = 100,
    seed: int = 0,
) -> ImportedTrace:
    """Import one external trace directory (module docstring).

    Deterministic per (:func:`trace_digest`, ``seed``); raises
    :class:`TraceImportError` on missing files or too few usable rows.
    """
    from rl_scheduler_tpu.data.generate import (
        AWS_COST_BASE,
        AWS_LATENCY_BASE,
        AZURE_COST_BASE,
        AZURE_LATENCY_BASE,
    )
    from rl_scheduler_tpu.data.normalize import normalize

    trace_dir = Path(trace_dir)
    if steps < 2:
        raise TraceImportError(f"steps={steps}: a compiled table needs at "
                               "least 2 rows")
    for name in _format_files(fmt):
        if not (trace_dir / name).is_file():
            raise TraceImportError(
                f"{fmt} trace under {trace_dir} is missing {name} "
                f"(expected files: {', '.join(_format_files(fmt))}; "
                "mixtures/fixtures.py generates synthetic ones)")
    report = ImportReport(format=fmt, digest=trace_digest(trace_dir, fmt),
                          seed=seed, steps=steps)
    for name in _format_files(fmt):
        report.files[name] = (trace_dir / name).stat().st_size

    samples: dict = {}
    if fmt == GOOGLE_FORMAT:
        series, points = _load_google(trace_dir, report)
        # Google: the load signal is the tasks' cpu_rate at their start
        # times, attributed to the machine that ran them.
        for t, cpu, mid in points:
            samples.setdefault(mid, []).append((t, cpu))
    else:
        series, points, samples = _load_alibaba(trace_dir, report)

    machine_ids = sorted(series)
    report.machines = len(machine_ids)
    report.usage_rows = len(points)
    if len(machine_ids) < 2:
        raise TraceImportError(
            f"{fmt} trace under {trace_dir} describes "
            f"{len(machine_ids)} machines after counted rejection "
            f"({report.rejected or 'no rejects'}) — the two-cloud split "
            "needs at least 2")
    clouds = _machine_clouds(machine_ids)

    # Time base: the union span of machine transitions and usage points,
    # divided into `steps` equal buckets.
    all_times = [t for tr in series.values() for t, _ in tr]
    all_times += [t for t, _, _ in points]
    t_lo, t_hi = min(all_times), max(all_times)
    if t_hi <= t_lo:
        raise TraceImportError(
            f"trace under {trace_dir} spans zero time ({t_lo}..{t_hi}) — "
            "nothing to bucket")
    edges = np.linspace(t_lo, t_hi, steps + 1)

    # Per-cloud utilization series (the demand signal).
    rng = np.random.RandomState(seed)
    util = np.zeros((steps, 2), np.float64)
    for c in range(2):
        cloud_machines = {machine_ids[m] for m in range(len(machine_ids))
                          if clouds[m] == c}
        times, vals = [], []
        for mid in cloud_machines:
            for t, v in samples.get(mid, ()):
                times.append(t)
                vals.append(v)
        if times:
            means, has = _bucket_mean(np.asarray(times, np.float64),
                                      np.asarray(vals, np.float64), edges)
            fallback = float(np.asarray(vals).mean())
            util[:, c] = _forward_fill(means, has, fallback)
        # else: a cloud half with zero usage keeps util 0 (flat anchors).
    util = np.clip(util, 0.0, 1.5)

    # Raw $/ms frame on the shipped anchors, normalized through the
    # SHIPPED pipeline — demand pricing couples cost weakly and latency
    # hard to the trace's load, jitter drawn from this import's stream.
    import pandas as pd

    jitter = rng.uniform(-0.02, 0.02, (steps, 2))
    raw = pd.DataFrame({
        "step": range(steps),
        "cost_aws": AWS_COST_BASE * (1.0 + 0.5 * util[:, 0]
                                     + jitter[:, 0]),
        "cost_azure": AZURE_COST_BASE * (1.0 + 0.5 * util[:, 1]
                                         + jitter[:, 1]),
        "latency_aws": AWS_LATENCY_BASE * (1.0 + 1.5 * util[:, 0]),
        "latency_azure": AZURE_LATENCY_BASE * (1.0 + 1.5 * util[:, 1]),
    })
    table = normalize(raw)
    costs = table[["cost_aws", "cost_azure"]].to_numpy(np.float32)
    lats = table[["latency_aws", "latency_azure"]].to_numpy(np.float32)

    # Pod sizes: mean requested CPU per arrival bucket, normalized to
    # mean 1.0. An empty usage table degrades to the env's default draw.
    pod_scale = None
    if points:
        times = np.asarray([t for t, _, _ in points], np.float64)
        reqs = np.asarray([v for _, v, _ in points], np.float64)
        means, has = _bucket_mean(times, reqs, edges)
        filled = _forward_fill(means, has, float(reqs.mean()))
        overall = filled.mean()
        if overall > 0:
            pod_scale = np.clip(filled / overall, POD_SCALE_LOW,
                                POD_SCALE_HIGH).astype(np.float32)
    # An empty usage table is a non-ROW outcome: recorded on its own
    # field (the compile degrades to the env's default pod draw), kept
    # out of the per-row rejected counters so the row invariant holds.
    report.pod_from_trace = pod_scale is not None

    avail = _avail_matrix(series, machine_ids, edges)
    return ImportedTrace(costs=costs, latencies=lats, pod_scale=pod_scale,
                         machine_avail=avail, machine_clouds=clouds,
                         report=report)


def node_avail_mask(imported: ImportedTrace, num_nodes: int,
                    seed: int = 0) -> np.ndarray:
    """Map the trace's per-machine availability onto ``num_nodes`` env
    node slots: machines are dealt round-robin (in a seeded shuffle)
    onto the slots of their cloud half, a node is up when >= half of its
    machines are, and at least one node stays up per row (the
    ``churn_mask`` discipline — an all-dark cluster teaches nothing).
    Seeded independently of the table compile so the same draw order
    holds whatever ``num_nodes`` is."""
    t, m = imported.machine_avail.shape
    rng = np.random.RandomState(seed)
    order = rng.permutation(m)
    half = num_nodes // 2
    slots: list = [[] for _ in range(num_nodes)]
    next_slot = {0: 0, 1: 0}
    for mi in order:
        cloud = int(imported.machine_clouds[mi])
        base, width = (0, half) if cloud == 0 else (half, num_nodes - half)
        if width <= 0:           # degenerate tiny node counts
            base, width = 0, num_nodes
        slots[base + next_slot[cloud] % width].append(mi)
        next_slot[cloud] += 1
    mask = np.ones((t, num_nodes), np.float32)
    for n, members in enumerate(slots):
        if not members:
            continue             # an unbacked slot stays up (neutral)
        up_frac = imported.machine_avail[:, members].mean(axis=1)
        mask[:, n] = (up_frac >= 0.5).astype(np.float32)
    dark = mask.sum(axis=1) == 0
    mask[dark, 0] = 1.0
    return mask


def external_tables(trace_dir: str | Path, fmt: str, steps: int = 100,
                    seed: int = 0) -> dict:
    """The family-dispatch entry (``scenarios/families.
    external_trace_tables``): one import as the plain table dict every
    scenario family compiles into."""
    imported = import_external_trace(trace_dir, fmt, steps=steps, seed=seed)
    return {
        "costs": imported.costs,
        "latencies": imported.latencies,
        "pod_scale": imported.pod_scale,
        "report": imported.report.to_json(),
    }


