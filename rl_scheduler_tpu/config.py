"""Configuration layer: every constant the reference hardcodes, made explicit.

The reference scatters its knobs across files (reward weights and scale inline
at ``k8s_multi_cloud_env.py:122``, data path at ``:22-27``, baseline cost at
``final_evaluation.py:73``, run hyperparameters in each training script) and
accepts-but-ignores ``env_config`` (``:46``). Here a single dataclass layer
owns them; training presets live in ``agent/presets.py``.
"""

from __future__ import annotations

import dataclasses
from pathlib import Path


@dataclasses.dataclass(frozen=True)
class EnvConfig:
    """Multi-cloud simulator configuration.

    ``legacy_reward_sign`` reproduces the reference's reward exactly
    (``+scale*(w_c*cost + w_l*latency)`` — a *positive* function of
    normalized cost/latency, contradicting its own "negative weighted sum"
    docstring, see SURVEY.md §7.0.1). The corrected default negates it so
    reward-maximization prefers the cheaper/faster cloud.
    """

    data_path: str | None = None
    cost_weight: float = 0.6
    latency_weight: float = 0.4
    reward_scale: float = 100.0
    legacy_reward_sign: bool = False
    cpu_low: float = 0.1
    cpu_high: float = 0.8
    max_steps: int | None = None  # default: table rows - 1 (99)

    # Fault injection (SURVEY.md §5.3): probability per step that a cloud is
    # unavailable; drawn from the Locust failure data's spirit, off by default.
    fault_prob: float = 0.0
    fault_latency_penalty: float = 1.0  # normalized latency when faulted

    # (The scenario layer's per-episode random episode phases are a
    # BUNDLE-construction choice, not an env-params field:
    # env/bundle.multi_cloud_bundle(random_start=True) — a flag leaf in
    # the params pytree would trace under vmap/jit.)


@dataclasses.dataclass(frozen=True)
class SingleClusterConfig:
    """Single-cluster autoscaling simulator (BASELINE config 1)."""

    trace_path: str | None = None
    max_replicas: int = 10
    replica_cost_weight: float = 0.3
    latency_weight: float = 0.7
    overload_penalty: float = 2.0
    max_steps: int | None = None


@dataclasses.dataclass(frozen=True)
class RuntimeConfig:
    """Execution backend selection (BASELINE.json: --backend=jax with CPU
    fallback)."""

    backend: str = "jax"  # "jax" | "cpu"  ("cpu" = numpy fallback path)
    num_envs: int = 4096
    checkpoint_dir: str = str(Path.home() / "rl_scheduler_tpu_runs")


DEFAULT_ENV_CONFIG = EnvConfig()
LEGACY_ENV_CONFIG = EnvConfig(legacy_reward_sign=True)
