"""Utilities: checkpointing, metrics, profiling."""
