"""The one ``O_CREAT|O_EXCL`` pidfile single-writer lock.

graftstudy's runner lock established the discipline (take the lock via
exclusive create, record the holder's pid, clear stale locks from dead
pids and retry, refuse a LIVE holder by name); graftroll's promotion
lock needs exactly the same semantics. One implementation, so a fix to
the acquisition loop or the pid parse+liveness check can never diverge
between the two single-writer locks. Stdlib-only on purpose: the
graftserve supervisor (which takes the rollout lock) never imports
jax/orbax.
"""

from __future__ import annotations

import os
from pathlib import Path


def pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True
    return True


def read_live_pid(path: Path) -> int | None:
    """The pid recorded in a lock/pid file, IF that process is alive —
    the one parse+liveness implementation behind every pidfile lock and
    guard."""
    if not path.exists():
        return None
    try:
        pid = int(path.read_text().strip() or 0)
    except (ValueError, OSError):
        return None
    return pid if pid and pid_alive(pid) else None


def acquire_pidfile_lock(lock: Path, holder_msg: str) -> Path:
    """Take ``lock`` via exclusive create, recording this pid (stale
    locks from dead pids are cleared and retried). A LIVE holder raises
    ``RuntimeError`` with ``holder_msg`` formatted with ``{pid}`` and
    ``{lock}`` — the caller says what a second writer would break."""
    while True:
        try:
            fd = os.open(lock, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            os.write(fd, str(os.getpid()).encode())
            os.close(fd)
            return lock
        except FileExistsError:
            pid = read_live_pid(lock)
            if pid is not None:
                raise RuntimeError(holder_msg.format(pid=pid, lock=lock))
            # Stale (dead pid / unreadable): clear and retry the
            # exclusive create.
            lock.unlink(missing_ok=True)
