"""Orbax checkpointing, hardened (graftguard part 1).

Capability parity with the reference's Ray-delegated checkpointing
(SURVEY.md §5.4) — periodic save, keep-N, save-at-end, latest-run
auto-discovery, shared restore — plus the production failure modes the
reference never met (docs/robustness.md):

- **Async saves.** ``save`` dispatches the Orbax write and returns; the
  training step never blocks on storage. The PREVIOUS save is finalized
  (waited on + manifest written) at the next ``save``/``restore``/
  ``close`` — by then it has had a whole checkpoint interval to land, so
  the wait is ~0 in the steady state.
- **Integrity manifests.** Every finalized step gets a sidecar manifest
  (``checkpoint_manifests/<step>.json``): a tree-structure hash (leaf
  shapes/dtypes, container-agnostic) captured at save time plus sha256 +
  size of every file Orbax wrote. Restore verifies the files BEFORE
  deserializing and the tree hash after.
- **Quarantine + fallback.** A step that fails verification (truncated
  file, digest mismatch, missing file, restore exception) is moved to
  ``quarantine/`` — never deleted: it is evidence — and restore falls
  back to the newest step that DOES verify. A preempted VM that died
  mid-write costs one checkpoint interval, not the run.

Pre-graftguard checkpoints have no manifest; they restore with a logged
warning (legacy acceptance) so old runs stay loadable.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import logging
import shutil
import threading
import time
from pathlib import Path
from typing import Any

import orbax.checkpoint as ocp

logger = logging.getLogger(__name__)

MANIFEST_DIR = "checkpoint_manifests"
QUARANTINE_DIR = "quarantine"


class CheckpointCorrupt(RuntimeError):
    """An explicitly-requested step failed integrity verification (the
    auto-selection path falls back instead of raising this)."""


def tree_structure_hash(tree: Any) -> str:
    """Container-agnostic structure hash: sorted leaf ``shape:dtype``
    descriptors plus the leaf count.

    Deliberately ignores container TYPES (dict vs namedtuple vs list):
    Orbax restores without a target as nested dicts/lists while the
    save-time tree holds optax namedtuples, and both must hash equal —
    the integrity signal is "same tensors", byte integrity itself is the
    file digests' job.
    """
    import jax
    import numpy as np

    descs = []
    for leaf in jax.tree_util.tree_leaves(tree):
        # Read shape/dtype off the leaf's metadata: np.asarray on a
        # device array would pull the whole tree host-side (for DQN,
        # replay buffer included) inside save(), defeating the async
        # path. Only scalar Python leaves need materializing.
        shape, dtype = getattr(leaf, "shape", None), getattr(leaf, "dtype", None)
        if shape is None or dtype is None:
            arr = np.asarray(leaf)
            shape, dtype = arr.shape, arr.dtype
        descs.append(f"{tuple(shape)}:{dtype}")
    descs.sort()
    payload = ";".join(descs) + f";n={len(descs)}"
    return hashlib.sha256(payload.encode()).hexdigest()


def _digest_dir(step_dir: Path) -> dict:
    """``{relpath: {"sha256", "size"}}`` over every file under a step."""
    out = {}
    for p in sorted(step_dir.rglob("*")):
        if not p.is_file():
            continue
        h = hashlib.sha256()
        with p.open("rb") as fh:
            for chunk in iter(lambda: fh.read(1 << 20), b""):
                h.update(chunk)
        out[p.relative_to(step_dir).as_posix()] = {
            "sha256": h.hexdigest(), "size": p.stat().st_size,
        }
    return out


@dataclasses.dataclass
class _PendingSave:
    """A dispatched-but-not-finalized async save awaiting its manifest."""

    step: int
    tree_hash: str
    extras_keys: list


class CheckpointManager:
    """Thin wrapper over ``ocp.CheckpointManager`` for one training run."""

    def __init__(self, run_dir: str | Path, keep: int = 5,
                 async_save: bool = True, fault_plan: Any | None = None):
        self.run_dir = Path(run_dir)
        self.fault_plan = fault_plan
        options = ocp.CheckpointManagerOptions(
            max_to_keep=keep, create=True,
            enable_async_checkpointing=async_save,
        )
        self._mgr = ocp.CheckpointManager(
            (self.run_dir / "checkpoints").absolute(), options=options
        )
        self._pending: _PendingSave | None = None
        self._digest_thread: threading.Thread | None = None
        # Steps whose manifest already verified this process: resume paths
        # call latest_verified_step() then restore(step), and re-hashing
        # GBs of unchanged Orbax files on the second pass buys nothing.
        self._verified: set = set()

    # ------------------------------------------------------------- paths

    def _step_dir(self, step: int) -> Path:
        return self.run_dir / "checkpoints" / str(step)

    def _manifest_path(self, step: int) -> Path:
        return self.run_dir / MANIFEST_DIR / f"{step}.json"

    # -------------------------------------------------------------- save

    def save(self, step: int, tree: Any, extras: dict | None = None,
             wait: bool = False) -> None:
        """Dispatch an async save of ``(tree, extras)`` at ``step``.

        Finalizes the previous pending save first (waits for it — ~0 in
        the steady state — then hands its integrity manifest to a
        background digest thread), so at most one save is ever in flight.
        ``wait=True`` additionally finalizes THIS step — manifest on disk
        included — before returning (save-at-end semantics).
        """
        self._finalize_pending(wait_digest=False)
        if self.fault_plan is not None:
            # Simulated write failure (disk full / volume detached):
            # raised before the Orbax save dispatches, so the failed step
            # leaves nothing behind. Callers that must survive this wrap
            # save in try/except (make_periodic_checkpoint_fn does).
            self.fault_plan.check("checkpoint.save", OSError)
        self._mgr.save(
            step,
            args=ocp.args.Composite(
                state=ocp.args.StandardSave(tree),
                meta=ocp.args.JsonSave(extras or {}),
            ),
        )
        self._pending = _PendingSave(
            step=step,
            tree_hash=tree_structure_hash(tree),
            extras_keys=sorted(extras or {}),
        )
        if self.fault_plan is not None and self.fault_plan.fires(
                "checkpoint.partial"):
            # Torn write: the manifest is written from the intact files,
            # THEN a file is truncated — the artifact of a VM preempted
            # between the manifest fsync and the data fsync. Restore-time
            # verification must quarantine this step and fall back.
            from rl_scheduler_tpu.utils.faults import corrupt_checkpoint_step

            self._finalize_pending()
            corrupt_checkpoint_step(self._step_dir(step))
            return
        if wait:
            self._finalize_pending()

    def _finalize_pending(self, wait_digest: bool = True) -> None:
        """Wait for the in-flight save (if any) and hand its manifest
        digest to a background thread; prune manifests of steps Orbax's
        keep-N GC has deleted. With ``wait_digest`` (every caller except
        ``save``) the manifest is on disk before returning — readers
        treat a manifest-less step as unfinalized."""
        self._mgr.wait_until_finished()
        pending, self._pending = self._pending, None
        if pending is not None:
            if self._digest_thread is not None:
                self._digest_thread.join()
            # sha256 over the step's files OFF the training thread: a DQN
            # full-state step includes the replay buffer (GBs at
            # production size), and hashing it synchronously at the next
            # save() would re-insert the storage stall async saves exist
            # to remove.
            t = threading.Thread(target=self._write_manifest,
                                 args=(pending,), daemon=True)
            t.start()
            self._digest_thread = t
        if wait_digest and self._digest_thread is not None:
            self._digest_thread.join()
            self._digest_thread = None
        self._prune_manifests()

    def _write_manifest(self, pending: _PendingSave) -> None:
        try:
            step_dir = self._step_dir(pending.step)
            manifest = {
                "step": pending.step,
                "tree_hash": pending.tree_hash,
                "extras_keys": pending.extras_keys,
                "files": _digest_dir(step_dir),
                "created_at": time.time(),
            }
            mpath = self._manifest_path(pending.step)
            mpath.parent.mkdir(parents=True, exist_ok=True)
            tmp = mpath.with_suffix(".json.tmp")
            tmp.write_text(json.dumps(manifest, indent=1))
            tmp.replace(mpath)  # atomic: a manifest is whole or absent
        except Exception:  # noqa: BLE001 — a failed manifest leaves the
            # step restorable as unfinalized/legacy; never kill training
            logger.exception(
                "manifest write for checkpoint step %d failed; the step "
                "will restore unverified", pending.step)

    def _prune_manifests(self) -> None:
        mdir = self.run_dir / MANIFEST_DIR
        if not mdir.is_dir():
            return
        live = {str(s) for s in self._mgr.all_steps()}
        for p in mdir.glob("*.json"):
            if p.stem not in live:
                p.unlink(missing_ok=True)

    # ------------------------------------------------------ verification

    def verify_step(self, step: int) -> tuple[bool, str]:
        """``(ok, reason)`` for one step's on-disk integrity.

        ``ok`` with reason ``"legacy"`` means no manifest exists (pre-
        graftguard checkpoint): accepted, but the caller may want to log.
        """
        self._finalize_pending()
        if step in self._verified:
            return True, "verified"
        mpath = self._manifest_path(step)
        if not mpath.exists():
            return True, "legacy"
        try:
            manifest = json.loads(mpath.read_text())
        except (OSError, json.JSONDecodeError) as e:
            return False, f"unreadable manifest: {e}"
        step_dir = self._step_dir(step)
        on_disk = _digest_dir(step_dir) if step_dir.is_dir() else {}
        want = manifest.get("files", {})
        missing = sorted(set(want) - set(on_disk))
        if missing:
            return False, f"missing file(s): {', '.join(missing[:3])}"
        for rel, meta in want.items():
            got = on_disk[rel]
            if got["size"] != meta["size"]:
                return False, (f"{rel}: size {got['size']} != manifest "
                               f"{meta['size']} (truncated write)")
            if got["sha256"] != meta["sha256"]:
                return False, f"{rel}: sha256 mismatch (corrupt write)"
        self._verified.add(step)
        return True, "verified"

    def quarantine(self, step: int, reason: str) -> Path:
        """Move a failed step (and its manifest) to ``quarantine/`` —
        preserved as evidence, out of the restore path."""
        self._verified.discard(step)
        qdir = self.run_dir / QUARANTINE_DIR
        qdir.mkdir(parents=True, exist_ok=True)
        dest = qdir / str(step)
        n = 0
        while dest.exists():
            n += 1
            dest = qdir / f"{step}.{n}"
        # EAFP moves: a concurrent quarantine (two restore paths hitting
        # the same corrupt step) may have taken the evidence first —
        # "already moved" is success, not an error.
        step_dir = self._step_dir(step)
        try:
            shutil.move(str(step_dir), str(dest))
        except FileNotFoundError:
            pass
        mpath = self._manifest_path(step)
        try:
            shutil.move(str(mpath), str(dest) + ".manifest.json")
        except FileNotFoundError:
            pass
        logger.warning(
            "checkpoint step %d failed verification (%s); quarantined to %s",
            step, reason, dest)
        # Orbax caches its step list; make it re-read the directory so the
        # quarantined step stops being offered as latest.
        self._mgr.reload()
        return dest

    def latest_verified_step(self, exclude: frozenset | set = frozenset()) -> int | None:
        """Newest step that passes verification; corrupt steps met along
        the way are quarantined. ``None`` when nothing verifies.
        ``exclude`` skips steps the caller already tried (restore's
        fallback past unfinalized saves)."""
        self._finalize_pending()
        for step in sorted(self._mgr.all_steps(), reverse=True):
            if step in exclude:
                continue
            ok, reason = self.verify_step(step)
            if ok:
                if reason == "legacy":
                    logger.warning(
                        "checkpoint step %d has no integrity manifest "
                        "(pre-graftguard run); restoring unverified", step)
                return step
            self.quarantine(step, reason)
        return None

    # ----------------------------------------------------------- restore

    def latest_step(self) -> int | None:
        self._finalize_pending()
        return self._mgr.latest_step()

    def restore_meta(self, step: int | None = None) -> dict:
        """Restore only the extras dict (cheap; no state tree involved)."""
        if step is None:
            step = self.latest_verified_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {self.run_dir}")
        out = self._mgr.restore(
            step, args=ocp.args.Composite(meta=ocp.args.JsonRestore())
        )
        return dict(out["meta"] or {})

    def restore(self, step: int | None = None, target: Any | None = None):
        """Restore ``(tree, extras)`` from a VERIFIED step.

        ``step=None`` auto-selects: newest step whose manifest verifies,
        quarantining corrupt ones and falling back — so a torn final
        write costs one interval, not the run. An EXPLICIT corrupt step
        quarantines and raises :class:`CheckpointCorrupt` instead (the
        caller named it; silently restoring something else would lie).
        With ``target`` given, the tree is restored with the target's
        exact pytree structure (needed for opt_state); otherwise as
        nested dicts/lists (fine for params).
        """
        explicit = step is not None
        skipped: set = set()
        while True:
            if step is None:
                step = self.latest_verified_step(exclude=skipped)
                if step is None:
                    raise FileNotFoundError(
                        f"no verified checkpoints under {self.run_dir}")
            else:
                ok, reason = self.verify_step(step)
                if not ok:
                    self.quarantine(step, reason)
                    if explicit:
                        raise CheckpointCorrupt(
                            f"checkpoint step {step} under {self.run_dir} "
                            f"failed verification ({reason}); quarantined. "
                            "Pass step=None to fall back to the newest "
                            "verified step.")
                    step = None
                    continue
            try:
                return self._restore_verified(step, target)
            except (CheckpointCorrupt, FileNotFoundError):
                raise
            except Exception as e:  # noqa: BLE001 — see below: corrupt
                # step vs caller error, decided by the manifest
                if self._manifest_path(step).exists():
                    # The digests just verified these bytes, so a restore
                    # failure here means the TARGET is wrong (wrong net/
                    # algo/config — including the tree-hash mismatch),
                    # not the disk. Quarantining would relocate healthy
                    # checkpoints — in auto mode, the entire run, one
                    # fallback step at a time.
                    raise
                if (self.run_dir / MANIFEST_DIR).is_dir():
                    # No manifest for this step but the run HAS a manifest
                    # dir: a graftguard-era run, so this is almost
                    # certainly a not-yet-finalized async save by a live
                    # trainer. Quarantining would move the directory out
                    # from under the in-flight Orbax write — leave it in
                    # place and fall back to an older step.
                    logger.warning(
                        "checkpoint step %d has no manifest and failed to "
                        "restore (%s); treating as an unfinalized save — "
                        "left in place, falling back", step, e)
                    if explicit:
                        raise
                    skipped.add(step)
                    step = None
                    continue
                # Legacy step (no manifest, pre-graftguard run): nothing
                # vouched for the bytes, so a deserialization failure is
                # treated as corruption — same quarantine-or-raise as
                # verify_step.
                self.quarantine(step, f"restore failed: {e}")
                if explicit:
                    raise CheckpointCorrupt(
                        f"checkpoint step {step} under {self.run_dir} "
                        f"failed to restore ({e}); quarantined."
                    ) from e
                step = None

    def _restore_verified(self, step: int, target: Any | None):
        state_args = (
            ocp.args.StandardRestore(target) if target is not None else ocp.args.StandardRestore()
        )
        out = self._mgr.restore(
            step, args=ocp.args.Composite(state=state_args, meta=ocp.args.JsonRestore())
        )
        tree, extras = out["state"], dict(out["meta"] or {})
        mpath = self._manifest_path(step)
        if mpath.exists():
            want = json.loads(mpath.read_text()).get("tree_hash")
            got = tree_structure_hash(tree)
            if want is not None and got != want:
                raise ValueError(
                    f"restored tree structure hash {got[:12]} != manifest "
                    f"{str(want)[:12]} (wrong architecture or partial "
                    "restore)")
        return tree, extras

    # -------------------------------------------------------- lifecycle

    def clear(self) -> None:
        """Delete every checkpoint step in this run (used when an
        abandoned training attempt's checkpoints must not shadow its
        replacement — e.g. ``train_ppo --reseed-on-stall``)."""
        self._finalize_pending()
        for step in list(self._mgr.all_steps()):
            self._mgr.delete(step)
        self._mgr.wait_until_finished()
        self._verified.clear()
        self._prune_manifests()

    def delete_steps_after(self, step: int) -> None:
        """Delete every checkpoint step NEWER than ``step``.

        The ``--resume-best`` salvage semantics: training onward from the
        peak ABANDONS the degraded tail past it — and those step numbers
        must be free, or the continuation's periodic/final saves at them
        would be refused by Orbax (the same already-exists refusal the
        reseed path clears for) and silently swallowed as non-fatal save
        failures, leaving the continued run persisted nowhere."""
        self._finalize_pending()
        for s in list(self._mgr.all_steps()):
            if s > step:
                self._mgr.delete(s)
                self._verified.discard(s)
        self._mgr.wait_until_finished()
        self._prune_manifests()

    def close(self) -> None:
        """Finalize the in-flight save (manifest included) and release
        Orbax's resources. Always call this — an unfinalized final save
        has no integrity manifest and restores as 'legacy'."""
        self._finalize_pending()
        self._mgr.close()


def find_latest_run(root: str | Path, prefix: str = "") -> Path:
    """Latest run directory under ``root`` that contains checkpoints.

    Mirrors the reference's auto-discovery (newest checkpoint wins), keyed on
    checkpoint step number then mtime.
    """
    root = Path(root)
    if not root.exists():
        raise FileNotFoundError(f"run root {root} does not exist")
    candidates = []
    for run in sorted(root.iterdir()):
        if not run.is_dir() or not run.name.startswith(prefix):
            continue
        steps = [
            (int(d.name), d)
            for d in (run / "checkpoints").glob("*")
            if d.is_dir() and d.name.isdigit()
        ]
        if steps:
            step, step_dir = max(steps)
            # Newest checkpoint write wins (promotes resumed runs); step
            # number breaks ties.
            candidates.append((step_dir.stat().st_mtime, step, run))
    if not candidates:
        raise FileNotFoundError(
            f"No checkpoints found under {root}. Did training actually finish?"
        )
    return max(candidates)[2]


def load_policy_params(run_dir: str | Path, step: int | None = None):
    """Restore just the policy params (+meta) from a run directory."""
    mgr = CheckpointManager(run_dir)
    try:
        tree, meta = mgr.restore(step)
    finally:
        # A raised restore (corrupt step, wrong structure) must not leak
        # the manager's Orbax resources — serving retries this in a loop.
        mgr.close()
    return tree["params"], meta
