"""Orbax checkpointing with the reference's lifecycle semantics.

Capability parity with the reference's Ray-delegated checkpointing
(SURVEY.md §5.4): periodic save, keep-N, save-at-end (the caller's loop
decides when), latest-checkpoint auto-discovery across runs
(``final_evaluation.py:13-27`` does this with ``rglob`` + max numeric
suffix), and a ``from_checkpoint``-style restore shared by evaluation and
the scheduler-extender server.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Any

import orbax.checkpoint as ocp


class CheckpointManager:
    """Thin wrapper over ``ocp.CheckpointManager`` for one training run."""

    def __init__(self, run_dir: str | Path, keep: int = 5):
        self.run_dir = Path(run_dir)
        options = ocp.CheckpointManagerOptions(max_to_keep=keep, create=True)
        self._mgr = ocp.CheckpointManager(
            (self.run_dir / "checkpoints").absolute(), options=options
        )

    def save(self, step: int, tree: Any, extras: dict | None = None) -> None:
        self._mgr.save(
            step,
            args=ocp.args.Composite(
                state=ocp.args.StandardSave(tree),
                meta=ocp.args.JsonSave(extras or {}),
            ),
        )
        self._mgr.wait_until_finished()

    def latest_step(self) -> int | None:
        return self._mgr.latest_step()

    def restore_meta(self, step: int | None = None) -> dict:
        """Restore only the extras dict (cheap; no state tree involved)."""
        if step is None:
            step = self.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {self.run_dir}")
        out = self._mgr.restore(
            step, args=ocp.args.Composite(meta=ocp.args.JsonRestore())
        )
        return dict(out["meta"] or {})

    def restore(self, step: int | None = None, target: Any | None = None):
        """Restore ``(tree, extras)``. With ``target`` given, the tree is
        restored with the target's exact pytree structure (needed for
        opt_state); otherwise as nested dicts/lists (fine for params)."""
        if step is None:
            step = self.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {self.run_dir}")
        state_args = (
            ocp.args.StandardRestore(target) if target is not None else ocp.args.StandardRestore()
        )
        out = self._mgr.restore(
            step, args=ocp.args.Composite(state=state_args, meta=ocp.args.JsonRestore())
        )
        return out["state"], dict(out["meta"] or {})

    def clear(self) -> None:
        """Delete every checkpoint step in this run (used when an
        abandoned training attempt's checkpoints must not shadow its
        replacement — e.g. ``train_ppo --reseed-on-stall``)."""
        for step in list(self._mgr.all_steps()):
            self._mgr.delete(step)
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.close()


def find_latest_run(root: str | Path, prefix: str = "") -> Path:
    """Latest run directory under ``root`` that contains checkpoints.

    Mirrors the reference's auto-discovery (newest checkpoint wins), keyed on
    checkpoint step number then mtime.
    """
    root = Path(root)
    if not root.exists():
        raise FileNotFoundError(f"run root {root} does not exist")
    candidates = []
    for run in sorted(root.iterdir()):
        if not run.is_dir() or not run.name.startswith(prefix):
            continue
        steps = [
            (int(d.name), d)
            for d in (run / "checkpoints").glob("*")
            if d.is_dir() and d.name.isdigit()
        ]
        if steps:
            step, step_dir = max(steps)
            # Newest checkpoint write wins (promotes resumed runs); step
            # number breaks ties.
            candidates.append((step_dir.stat().st_mtime, step, run))
    if not candidates:
        raise FileNotFoundError(
            f"No checkpoints found under {root}. Did training actually finish?"
        )
    return max(candidates)[2]


def load_policy_params(run_dir: str | Path, step: int | None = None):
    """Restore just the policy params (+meta) from a run directory."""
    mgr = CheckpointManager(run_dir)
    tree, meta = mgr.restore(step)
    mgr.close()
    return tree["params"], meta
