"""Tracing / profiling harness (SURVEY.md §5.1 — the reference has none).

Two tools:

- :func:`trace_iterations` — a ``jax.profiler`` trace context writing a
  TensorBoard/Perfetto-compatible trace (XLA ops, fusion boundaries, HBM
  transfers) for everything run inside it. View with
  ``tensorboard --logdir <dir>`` (Profile tab) or upload the
  ``.trace.json.gz`` to ``ui.perfetto.dev``.
- :class:`StepTimer` — wall-clock timing of a jitted step function with
  proper device synchronization, giving p50/mean step latency and
  env-steps/sec/chip — the BASELINE.json metric. Synchronization is a
  ``jax.device_get`` of a jitted scalar reduction over EVERY state leaf,
  NOT ``jax.block_until_ready``: on tunneled backends the latter can
  return before execution finishes (observed on the round-3 bench chip —
  "timed" matmuls at physically impossible FLOP rates), silently turning
  timings into dispatch-overhead measurements. Only fetching a value
  that data-depends on the whole step provably waits (a single leaf is
  not enough — e.g. an iteration counter completes without the step's
  heavy compute).
"""

from __future__ import annotations

import contextlib
import dataclasses
import time
from pathlib import Path

import jax
import numpy as np


@contextlib.contextmanager
def trace_iterations(log_dir: str | Path):
    """Capture a ``jax.profiler`` trace of the enclosed block into ``log_dir``."""
    log_dir = str(log_dir)
    Path(log_dir).mkdir(parents=True, exist_ok=True)
    with jax.profiler.trace(log_dir):
        yield log_dir


@jax.jit
def _reduce_all_leaves(tree):
    import jax.numpy as jnp

    parts = [
        jnp.ravel(leaf)[0].astype(jnp.float32)
        for leaf in jax.tree.leaves(tree)
    ]
    return sum(parts, jnp.float32(0))


def fetch_sync(tree) -> float:
    """Force completion of everything ``tree`` depends on, by FETCHING.

    This is the one shared implementation of the repo's sync-by-fetching
    discipline (module docstring): ``jax.block_until_ready`` can return
    before execution finishes on tunneled backends, so the only trustworthy
    sync is a ``jax.device_get`` of a scalar that data-depends on every
    leaf of the state under test. Used by :class:`StepTimer` and by
    ``bench.py``'s measurement windows — the invariant lives here and
    nowhere else. Leaves must be non-empty arrays (the reduction reads one
    element of each). Returns the fetched scalar (callers usually ignore
    it)."""
    return float(jax.device_get(_reduce_all_leaves(tree)))


@dataclasses.dataclass
class StepReport:
    iters: int
    mean_s: float
    p50_s: float
    p90_s: float
    env_steps_per_sec: float

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


class StepTimer:
    """Time a jitted step over N iterations, excluding compile.

    ``fn`` must take and return the carried state: ``fn(state) -> state``
    by default, or ``fn(state) -> (state, aux)`` with ``returns_aux=True``
    (an explicit flag — a tuple-valued *state* would be indistinguishable
    from a ``(state, aux)`` pair by inspection). One warmup call triggers
    compilation before timing starts.
    """

    def __init__(self, fn, env_steps_per_iter: int = 1, returns_aux: bool = False):
        self._fn = fn
        self._steps_per_iter = env_steps_per_iter
        self._returns_aux = returns_aux

    def _step(self, state):
        out = self._fn(state)
        return out[0] if self._returns_aux else out

    def _sync(self, state) -> None:
        """Force completion via the shared :func:`fetch_sync` helper —
        a fetched scalar that data-depends on EVERY state leaf (module
        docstring: block_until_ready is not a reliable sync, and fetching
        a compute-independent leaf — e.g. an iteration counter — would
        not provably wait either)."""
        fetch_sync(state)

    def run(self, state, iters: int = 10) -> tuple:
        state = self._step(state)
        self._sync(state)

        samples = []
        for _ in range(iters):
            t0 = time.perf_counter()
            state = self._step(state)
            self._sync(state)
            samples.append(time.perf_counter() - t0)
        arr = np.asarray(samples)
        report = StepReport(
            iters=iters,
            mean_s=float(arr.mean()),
            p50_s=float(np.percentile(arr, 50)),
            p90_s=float(np.percentile(arr, 90)),
            env_steps_per_sec=float(self._steps_per_iter / arr.mean()),
        )
        return state, report
