"""Debug-mode numerical checks via ``jax.experimental.checkify``.

SURVEY.md §5.2: the reference has no sanitizers of any kind; failures in
jitted code normally surface as silent NaN propagation. In debug mode the
training update is checkified — every float op is instrumented via
``float_checks`` (NaN production and division by zero; note checkify has
no inf check, so overflow to inf only raises once it later produces a
NaN, e.g. via ``inf - inf`` or ``inf * 0``) plus ``index_checks`` for
out-of-bounds gathers/dynamic-slices — and the first violation raises a
host-side :class:`jax.experimental.checkify.JaxRuntimeError` naming the
failing op instead of corrupting the run.

(Historical note: ``index_checks`` used to fail at trace time on the
categorical log-prob path's fill-mode ``take_along_axis``; that gather was
replaced by a one-hot contraction — ``ops/indexing.py`` — so the checks
instrument cleanly now.)

Cost: instrumentation blocks some XLA fusions, so expect a slower update;
this is a debugging tool (``train_ppo --debug-checks``), not a production
mode.
"""

from __future__ import annotations

from typing import Callable

import jax
from jax.experimental import checkify

ALL_CHECKS = checkify.float_checks | checkify.index_checks


def checkified_update(update_fn: Callable, donate: bool = True) -> Callable:
    """Wrap ``update_fn(state) -> (state, out)`` with numerical checks.

    Returns a jitted callable with the same signature that raises
    ``JaxRuntimeError`` on the first NaN/zero-division/out-of-bounds
    index instead of propagating it (bare inf overflow is not
    instrumented; see module doc).
    """
    checked = checkify.checkify(update_fn, errors=ALL_CHECKS)
    jitted = jax.jit(checked, donate_argnums=0 if donate else ())

    def wrapped(state):
        err, out = jitted(state)
        checkify.check_error(err)
        return out

    return wrapped
