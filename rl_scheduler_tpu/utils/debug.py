"""Debug-mode numerical checks via ``jax.experimental.checkify``.

SURVEY.md §5.2: the reference has no sanitizers of any kind; failures in
jitted code normally surface as silent NaN propagation. In debug mode the
training update is checkified — every float op is instrumented via
``float_checks`` (NaN production and division by zero; note checkify has
no inf check, so overflow to inf only raises once it later produces a
NaN, e.g. via ``inf - inf`` or ``inf * 0``) — and the first violation
raises a host-side :class:`jax.experimental.checkify.JaxRuntimeError`
naming the failing op instead of corrupting the run.

``index_checks`` is deliberately excluded: in the installed JAX it fails
at trace time on ``take_along_axis``'s fill-mode gather (the categorical
log-prob path), raising an internal IndexError while instrumenting.
Bounds on the env's table gathers are enforced by construction
(``step_idx`` wraps at ``max_steps``).

Cost: instrumentation blocks some XLA fusions, so expect a slower update;
this is a debugging tool (``train_ppo --debug-checks``), not a production
mode.
"""

from __future__ import annotations

from typing import Callable

import jax
from jax.experimental import checkify

ALL_CHECKS = checkify.float_checks  # = {NaN, division-by-zero}; div_checks ⊂ this


def checkified_update(update_fn: Callable, donate: bool = True) -> Callable:
    """Wrap ``update_fn(state) -> (state, out)`` with numerical checks.

    Returns a jitted callable with the same signature that raises
    ``JaxRuntimeError`` on the first NaN/zero-division instead of
    propagating it (index bounds and bare inf overflow are not
    instrumented; see module doc).
    """
    checked = checkify.checkify(update_fn, errors=ALL_CHECKS)
    jitted = jax.jit(checked, donate_argnums=0 if donate else ())

    def wrapped(state):
        err, out = jitted(state)
        checkify.check_error(err)
        return out

    return wrapped
