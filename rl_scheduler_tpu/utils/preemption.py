"""graftguard part 2: preemption-safe training shutdown.

Production TPU pods get preempted: the VM receives SIGTERM and has a
grace window to get its state out. Before graftguard a SIGTERM mid-run
unwound the training loop wherever Python happened to be, losing every
iteration since the last periodic checkpoint. :class:`PreemptionGuard`
turns the signal into a cooperative stop:

- The handler only SETS A FLAG (signal-safe; no I/O, no locks). The
  training loop polls it at dispatch boundaries — the one place where
  the runner state is a consistent, checkpointable pytree — finishes the
  in-flight dispatch, flushes pending metrics, writes a final checkpoint
  plus a flight-recorder manifest, and returns cleanly
  (``agent/loop.run_train_loop``).
- A SECOND signal escalates: the original handler is restored and
  ``KeyboardInterrupt`` is raised, so a stuck shutdown can still be
  killed interactively.
- ``simulated`` is the chaos harness's seam: a zero-arg callable (e.g.
  ``lambda: plan.fires("preempt")``) consulted at each poll, so the
  chaos suite triggers byte-reproducible "preemptions" at exact dispatch
  indices without process signals. The CLIs arm it from the
  ``GRAFTGUARD_PREEMPT_AFTER`` env var (dispatch count) for end-to-end
  interrupt/resume tests.

Handlers install in ``__enter__`` and restore in ``__exit__``; signal
handling is process-wide and main-thread-only, so the guard refuses to
install off the main thread (it still works as a pure simulated guard
there).
"""

from __future__ import annotations

import logging
import signal
import threading
from typing import Callable

logger = logging.getLogger(__name__)


class PreemptionGuard:
    """Cooperative SIGTERM/SIGINT stop flag for training loops."""

    def __init__(self, signals: tuple = (signal.SIGTERM, signal.SIGINT),
                 simulated: Callable[[], bool] | None = None):
        self.signals = tuple(signals)
        self.simulated = simulated
        self.requested = False
        self.signum: int | None = None
        # Set by run_train_loop when it acts on the request: the last
        # completed iteration the final checkpoint covers.
        self.stopped_at: int | None = None
        self._old: dict = {}
        self._installed = False

    # ----------------------------------------------------- signal wiring

    def _handle(self, signum, frame) -> None:
        if self.requested:
            # Second signal: the operator (or the platform) is done
            # waiting — restore original disposition and escalate.
            self._uninstall()
            raise KeyboardInterrupt(
                f"second signal {signum} during preemption shutdown")
        self.requested = True
        self.signum = signum
        logger.warning(
            "signal %s received: finishing the in-flight dispatch, then "
            "checkpointing and exiting (send again to force)", signum)

    def __enter__(self) -> "PreemptionGuard":
        if threading.current_thread() is threading.main_thread():
            for s in self.signals:
                self._old[s] = signal.signal(s, self._handle)
            self._installed = True
        else:
            logger.warning(
                "PreemptionGuard off the main thread: OS signal handlers "
                "not installed (simulated trigger still active)")
        return self

    def _uninstall(self) -> None:
        if self._installed:
            for s, old in self._old.items():
                signal.signal(s, old)
            self._installed = False

    def __exit__(self, *exc) -> bool:
        self._uninstall()
        return False

    # ------------------------------------------------------------ polling

    def should_stop(self) -> bool:
        """Polled by the training loop at each dispatch boundary."""
        if not self.requested and self.simulated is not None and \
                self.simulated():
            self.requested = True
            logger.warning("simulated preemption fired (fault plan)")
        return self.requested


def guard_from_env(env_value: str | None) -> PreemptionGuard:
    """Build the CLIs' guard, optionally armed by
    ``GRAFTGUARD_PREEMPT_AFTER=<n>``: a deterministic simulated SIGTERM
    after ``n`` dispatch boundaries — the chaos suite's stand-in for a
    real preemption, identical downstream path (final checkpoint +
    flight-recorder manifest + clean exit)."""
    if not env_value:
        return PreemptionGuard()
    try:
        after = int(env_value)
    except ValueError:
        raise SystemExit(
            f"GRAFTGUARD_PREEMPT_AFTER={env_value!r}: pass a dispatch "
            "count (integer)")
    if after < 1:
        raise SystemExit(
            f"GRAFTGUARD_PREEMPT_AFTER={after}: must be >= 1")
    state = {"polls": 0}

    def fire() -> bool:
        state["polls"] += 1
        return state["polls"] > after

    return PreemptionGuard(simulated=fire)
