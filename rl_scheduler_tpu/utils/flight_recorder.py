"""graftscope part 2: the anomaly flight recorder (docs/observability.md).

Fleet seed failures today vanish: a NaN poisons the update, the greedy
eval collapses, and by the time a human looks, the metrics JSONL holds
only window-averaged scalars from AFTER the damage. The recorder keeps a
ring buffer of the last ``capacity`` iterations' per-step metrics ON
DEVICE — written by a tiny jitted scatter per dispatch, never fetched in
the steady state — and dumps it, together with a run manifest, to a JSONL
artifact the moment an anomaly is detected:

- **NaN/inf** in any watched metric of a fetched row;
- **grad-norm spike**: z-score over a host-side running Welford of the
  ``grad_norm`` stream exceeds ``zscore_threshold`` (after ``min_count``
  healthy observations);
- **greedy-eval collapse**: the ``--reseed-on-stall`` guard's checkpoint
  decision — its ``on_stall`` hook calls :meth:`FlightRecorder.dump`
  BEFORE the guard raises, so a reseeded attempt leaves its artifact.
  (``wrap_eval_log``'s own ``threshold`` path fires on EVERY
  below-threshold eval; early in-training evals are expected below the
  node baseline, so wiring it to the guard's bar would spend
  ``max_dumps`` on healthy warm-up — production CLIs pass
  ``threshold=None`` and let the guard decide.);
- **raised exceptions**: the CLIs call :meth:`FlightRecorder.dump` when a
  checkified run (``--debug-checks``) or any other failure unwinds.

The artifact is self-describing: line 1 is the manifest (config, jax
version, device kind, precision flags, git sha, reason), the rest are the
ring's rows in chronological order. Fleet seed failures (docs/scaling.md
§1b) become diagnosable post-hoc instead of unobservable.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import math
from pathlib import Path
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

# Same monkeypatch seam convention as utils/metrics.py: the recorder's
# only steady-state transfer is ZERO; dumps go through this.
_device_get = jax.device_get


def build_manifest(config: dict | None = None,
                   extra: dict | None = None) -> dict:
    """Run provenance for the dump header: everything needed to reproduce
    or triage without the run directory. Best-effort on every field —
    a recorder must never be the thing that crashes the run."""
    log = logging.getLogger(__name__)
    manifest: dict = {"config": config or {}}
    try:
        manifest["jax_version"] = jax.__version__
        dev = jax.devices()[0]
        manifest["backend"] = dev.platform
        manifest["device_kind"] = dev.device_kind
        manifest["device_count"] = jax.device_count()
    except Exception:  # noqa: BLE001 — provenance, not control flow
        log.debug("device provenance unavailable for manifest", exc_info=True)
        manifest.setdefault("jax_version", "unknown")
    try:
        manifest["precision"] = {
            "jax_enable_x64": bool(jax.config.jax_enable_x64),
            "jax_default_matmul_precision":
                getattr(jax.config, "jax_default_matmul_precision", None),
        }
    except Exception:  # noqa: BLE001
        log.debug("precision flags unavailable for manifest", exc_info=True)
        manifest["precision"] = {}
    try:
        import subprocess

        sha = subprocess.run(
            ["git", "-C", str(Path(__file__).resolve().parents[2]),
             "rev-parse", "HEAD"],
            capture_output=True, text=True, timeout=5,
        )
        manifest["git_sha"] = sha.stdout.strip() if sha.returncode == 0 else None
    except Exception:  # noqa: BLE001
        log.debug("git sha unavailable for manifest", exc_info=True)
        manifest["git_sha"] = None
    if extra:
        manifest.update(extra)
    return manifest


@jax.jit
def _ring_write(ring: dict, steps: jnp.ndarray, rows: dict) -> dict:
    """Scatter ``k`` rows at the ring head (one fused op per field)."""
    cap = ring["step"].shape[0]
    idx = (ring["pos"] + jnp.arange(steps.shape[0], dtype=jnp.int32)) % cap
    out = {"pos": ring["pos"] + steps.shape[0],
           "step": ring["step"].at[idx].set(steps)}
    for name, buf in ring.items():
        # graftlint: disable=GL003 -- name is a dict KEY (a host str from ring.items()), never a tracer; the branch resolves identically at every trace
        if name in ("pos", "step"):
            continue
        out[name] = buf.at[idx].set(rows[name])
    return out


@dataclasses.dataclass
class FlightRecorder:
    """Device-resident metrics ring + host-side anomaly triggers.

    ``record`` runs per dispatch (device ops only). ``check_row`` runs per
    FETCHED row at the loop's flush cadence — detection latency is the
    sync window, the ring's contents always run ahead of it (everything
    dispatched, not just everything logged). At most ``max_dumps``
    artifacts per run so a persistently-NaN run cannot fill a disk.
    """

    path: Path
    manifest: dict = dataclasses.field(default_factory=dict)
    capacity: int = 64
    zscore_threshold: float = 8.0
    zscore_keys: tuple = ("grad_norm",)
    min_count: int = 20
    max_dumps: int = 3

    def __post_init__(self):
        self.path = Path(self.path)
        self.dump_count = 0
        self._ring: dict | None = None
        self._keys: tuple = ()
        # Host-side running Welford per z-score key (plain floats — this
        # runs per logged row, device arrays would be syncs).
        self._welford: dict = {}

    # ------------------------------------------------------ device side

    def record(self, first_iteration: int, metrics: dict, k: int = 1) -> None:
        """Write this dispatch's ``k`` iterations into the device ring."""
        rows = {name: jnp.reshape(v, (-1,)).astype(jnp.float32)
                for name, v in metrics.items()
                if not isinstance(v, (dict, tuple))}
        if self._ring is None:
            # The ring must hold at least one full dispatch: k > capacity
            # would scatter duplicate indices in a single ``.at[].set``,
            # whose winning update XLA leaves undefined — a dump could
            # then mix stale and fresh steps while claiming chronological
            # order. Grow instead of truncating.
            cap = max(self.capacity, k)
            self._keys = tuple(sorted(rows))
            self._ring = {
                "pos": jnp.zeros((), jnp.int32),
                "step": jnp.full((cap,), -1, jnp.int32),
                **{name: jnp.full((cap,), jnp.nan, jnp.float32)
                   for name in self._keys},
            }
        steps = first_iteration + jnp.arange(k, dtype=jnp.int32)
        self._ring = _ring_write(self._ring, steps,
                                 {name: rows[name] for name in self._keys})

    def reset(self, **manifest_updates) -> None:
        """Clear the device ring and the host z-score baselines — called
        between ``--reseed-on-stall`` attempts. The replacement attempt
        re-uses the abandoned attempt's iteration numbers under a new
        seed, so stale ring rows would be indistinguishable from (and
        misattributed to) the new run in a later dump. ``manifest_updates``
        (e.g. ``attempt=``, ``seed=``) keep subsequent dumps attributable
        to the attempt that produced them."""
        self._ring = None
        self._keys = ()
        self._welford = {}
        self.manifest.update(manifest_updates)

    # -------------------------------------------------------- host side

    def check_row(self, iteration: int, row: dict) -> None:
        """Anomaly checks on one fetched metrics row (host floats)."""
        bad = [name for name, v in row.items()
               if isinstance(v, float) and not math.isfinite(v)]
        if bad:
            self.dump("nan_inf", iteration,
                      detail=f"non-finite metric(s): {', '.join(sorted(bad))}")
            return
        for name in self.zscore_keys:
            x = row.get(name)
            if x is None:
                continue
            count, mean, m2 = self._welford.get(name, (0, 0.0, 0.0))
            if count >= self.min_count:
                std = math.sqrt(m2 / count)
                if std > 0 and (x - mean) / std > self.zscore_threshold:
                    self.dump(
                        "zscore_spike", iteration,
                        detail=f"{name}={x:.6g} is "
                               f"{(x - mean) / std:.1f} sigma above its "
                               f"running mean {mean:.6g} (std {std:.3g}, "
                               f"n={count})")
                    # The spike itself stays OUT of the baseline stats:
                    # folding it in would mask an immediately-following
                    # second spike.
                    continue
            count += 1
            delta = x - mean
            mean += delta / count
            m2 += delta * (x - mean)
            self._welford[name] = (count, mean, m2)

    def wrap_eval_log(self, eval_log_fn, threshold: float | None):
        """Wrap an eval sink with eval-anomaly triggers: a non-finite
        eval reward, or one below ``threshold``, dumps BEFORE the inner
        sink runs — so an inner guard that raises ``EvalStall`` still
        leaves the artifact behind.

        ``threshold`` fires on EVERY below-threshold eval, which is only
        right for a bar the run should clear from the start. The train
        CLIs pass ``threshold=None`` (NaN check only) and route collapse
        detection through the stall guard's ``on_stall`` hook instead:
        pre-deadline evals are expected below the node baseline, and
        dumping each would exhaust ``max_dumps`` before a real anomaly."""

        def wrapped(i: int, metrics: dict) -> None:
            r = metrics.get("eval_episode_reward_mean")
            if r is not None and not math.isfinite(r):
                self.dump("eval_nan", i,
                          detail=f"eval_episode_reward_mean={r}")
            elif threshold is not None and r is not None and r < threshold:
                self.dump("eval_collapse", i,
                          detail=f"eval_episode_reward_mean={r:.3f} below "
                                 f"node-baseline threshold {threshold:.3f}")
            eval_log_fn(i, metrics)

        return wrapped

    def dump_exception(self, e: BaseException) -> bool:
        """CLI unwind hook: preserve the ring when a mid-run failure
        (e.g. a checkified ``--debug-checks`` NaN) unwinds; the caller
        re-raises unchanged. One place for the reason/detail format so
        the PPO and DQN CLIs' artifacts stay greppable the same way."""
        return self.dump(f"exception:{type(e).__name__}", -1,
                         detail=str(e)[:500])

    def dump(self, reason: str, iteration: int, detail: str = "") -> bool:
        """Fetch the ring once and append the artifact. Returns whether a
        dump was written (rate-limited by ``max_dumps``).

        NON-FATAL by contract (graftguard): an unwritable/full dump dir —
        or any other failure in here — logs and returns False; a
        diagnostic artifact must never be the thing that kills the run it
        is diagnosing. Failed attempts still count against ``max_dumps``
        (an unwritable dir fails every time; retry-spamming it per
        anomaly would flood the logs the operator needs).
        """
        if self.dump_count >= self.max_dumps:
            return False
        self.dump_count += 1
        try:
            lines = [json.dumps({
                "kind": "manifest", "reason": reason, "iteration": iteration,
                "detail": detail, **self.manifest,
            })]
            if self._ring is not None:
                host = _device_get(self._ring)
                pos = int(host["pos"])
                cap = self._ring["step"].shape[0]
                order = [(pos + j) % cap for j in range(cap)]
                for slot in order:
                    step = int(host["step"][slot])
                    if step < 0:
                        continue  # never written
                    row = {"kind": "ring", "step": step}
                    for name in self._keys:
                        v = float(host[name][slot])
                        row[name] = v if math.isfinite(v) else str(v)
                    lines.append(json.dumps(row))
            self.path.parent.mkdir(parents=True, exist_ok=True)
            with self.path.open("a") as fh:
                fh.write("\n".join(lines) + "\n")
        except Exception:  # noqa: BLE001 — see docstring: log and continue
            logging.getLogger(__name__).exception(
                "flight recorder dump (%s at iteration %d) failed; "
                "training continues", reason, iteration + 1)
            return False
        print(f"flight recorder: {reason} at iteration {iteration + 1} — "
              f"ring + manifest dumped to {self.path}", flush=True)
        return True
