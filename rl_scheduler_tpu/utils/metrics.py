"""graftscope part 1: device-resident training metrics (docs/observability.md).

The GL008 discipline as a library. Today's training loop fetches scalar
metrics per sync burst; anything richer — distributions of grad norms, PPO
ratios, advantages, per-cloud action counts — would naively mean per-step
host fetches, each a full network round-trip on a tunneled accelerator
(~100 ms, ``agent/loop.py``). Podracer-style architectures (Hessel et al.,
2021) solve this by keeping the metrics INSIDE the device program. Here:

- :class:`TensorStats`: a Welford accumulator (count/mean/M2 + min/max)
  as a tiny pytree of scalars. ``stats_observe`` summarizes one array;
  ``stats_merge`` combines two accumulators (Chan's parallel update);
  ``stats_reduce`` collapses a stacked ``[k]`` axis in closed form —
  all pure jnp, all jit-safe.
- Fixed-bucket histograms: ``hist_observe`` bucketizes an array against
  STATIC edges (one scatter-add, no host sync); categorical counts for
  integer streams (per-cloud/per-node action ids) via the same scatter.
- :class:`MetricsSpec` names what a trainer watches; ``scope_observe``
  builds one :data:`MetricsState` (a flat dict pytree) per update, which
  rides out of the jitted update in the metrics dict under the
  ``"graftscope"`` key.
- :class:`ScopeSession` accumulates those states ON DEVICE (jitted merge,
  no transfer) and flushes to host in exactly ONE batched
  ``jax.device_get`` per ``window`` iterations — the invariant
  ``tests/test_metrics.py`` pins and graftlint GL009 enforces on loops.
- :class:`TrainObserver` is the ``run_train_loop`` hook that carries a
  session plus (optionally) the flight recorder
  (``utils/flight_recorder.py``).

Everything here is version-portable jnp (no Pallas, no backend probes): it
behaves identically on the CPU container and the TPU driver.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp

# Monkeypatch seam for tests that count host fetches; the ONLY transfer
# this module ever performs goes through it.
_device_get = jax.device_get


class TensorStats(NamedTuple):
    """Welford accumulator over a scalar stream: 5 device scalars."""

    count: jnp.ndarray   # f32 scalar (f32 counts are exact to 2^24 obs)
    mean: jnp.ndarray
    m2: jnp.ndarray      # sum of squared deviations from the mean
    min: jnp.ndarray
    max: jnp.ndarray


def stats_observe(x: jnp.ndarray) -> TensorStats:
    """One-shot stats of an array (any shape; summarized as a flat stream)."""
    x = jnp.ravel(x).astype(jnp.float32)
    mean = jnp.mean(x)
    return TensorStats(
        count=jnp.float32(x.size),
        mean=mean,
        m2=jnp.sum(jnp.square(x - mean)),
        min=jnp.min(x),
        max=jnp.max(x),
    )


def stats_merge(a: TensorStats, b: TensorStats) -> TensorStats:
    """Chan's parallel Welford merge; exact for any split of the stream."""
    n = a.count + b.count
    safe_n = jnp.maximum(n, 1.0)
    delta = b.mean - a.mean
    mean = a.mean + delta * b.count / safe_n
    m2 = a.m2 + b.m2 + jnp.square(delta) * a.count * b.count / safe_n
    return TensorStats(
        count=n,
        mean=jnp.where(n > 0, mean, 0.0),
        m2=jnp.where(n > 0, m2, 0.0),
        min=jnp.minimum(a.min, b.min),
        max=jnp.maximum(a.max, b.max),
    )


def stats_reduce(s: TensorStats) -> TensorStats:
    """Collapse a stacked ``TensorStats`` (leaves ``[k]``) in closed form.

    The fused-dispatch path (``updates_per_dispatch=k``) stacks one
    accumulator per iteration; merging k groups at once is
    ``n = Σnᵢ; mean = Σnᵢmᵢ/n; M2 = ΣM2ᵢ + Σnᵢ(mᵢ - mean)²`` — the same
    algebra as pairwise merging, associativity folded into one reduction.
    """
    n = jnp.sum(s.count)
    safe_n = jnp.maximum(n, 1.0)
    mean = jnp.sum(s.count * s.mean) / safe_n
    m2 = jnp.sum(s.m2) + jnp.sum(s.count * jnp.square(s.mean - mean))
    return TensorStats(
        count=n,
        mean=jnp.where(n > 0, mean, 0.0),
        m2=jnp.where(n > 0, m2, 0.0),
        min=jnp.min(s.min),
        max=jnp.max(s.max),
    )


def hist_observe(x: jnp.ndarray, edges: tuple) -> jnp.ndarray:
    """Counts of ``x`` against static ``edges``: ``len(edges)+1`` buckets
    (bucket 0 is the underflow ``x < edges[0]``, the last is the overflow
    ``x >= edges[-1]``). One searchsorted + one scatter-add, no sync."""
    x = jnp.ravel(x).astype(jnp.float32)
    idx = jnp.searchsorted(jnp.asarray(edges, jnp.float32), x, side="right")
    return jnp.zeros(len(edges) + 1, jnp.int32).at[idx].add(1)


def categorical_observe(ids: jnp.ndarray, num_bins: int) -> jnp.ndarray:
    """Counts of integer ids in ``[0, num_bins)`` (action/cloud counters).
    Out-of-range ids are clipped into the end bins rather than dropped —
    a visible pile-up beats silent loss."""
    idx = jnp.clip(jnp.ravel(ids).astype(jnp.int32), 0, num_bins - 1)
    return jnp.zeros(num_bins, jnp.int32).at[idx].add(1)


@dataclasses.dataclass(frozen=True)
class HistSpec:
    """One histogram the scope tracks. ``edges`` (static float bounds) for
    value streams, or ``bins`` for categorical integer streams."""

    name: str
    edges: tuple | None = None
    bins: int | None = None

    def __post_init__(self):
        if (self.edges is None) == (self.bins is None):
            raise ValueError(
                f"HistSpec {self.name!r}: set exactly one of edges/bins"
            )


@dataclasses.dataclass(frozen=True)
class MetricsSpec:
    """What one trainer's scope watches. ``stats`` names get a
    :class:`TensorStats`; ``hists`` get fixed-bucket counts. Both index
    into the ``values`` dict the trainer hands ``scope_observe``."""

    stats: tuple = ()
    hists: tuple = ()       # tuple[HistSpec, ...]

    def hist(self, name: str) -> HistSpec:
        for h in self.hists:
            if h.name == name:
                return h
        raise KeyError(name)


# MetricsState: {name: TensorStats} ∪ {"hist/"+name: int32 counts} — a
# plain dict pytree, so it scans/stacks/jits like any other metrics leaf.
MetricsState = dict


def validate_spec(spec: MetricsSpec, values: tuple, counts: tuple = (),
                  context: str = "scope") -> None:
    """Reject a spec naming streams the trainer does not provide — at
    BUILD time, with the available names spelled out, instead of a bare
    ``KeyError`` from inside the first traced update. ``values`` are the
    raw-array streams the trainer feeds ``scope_observe``; ``counts`` the
    pre-bucketized in-scan streams (histogram-only, e.g. PPO's ratio)."""
    unknown = [n for n in spec.stats if n not in values]
    unknown += [h.name for h in spec.hists
                if h.name not in values and h.name not in counts]
    if unknown:
        raise ValueError(
            f"{context}: MetricsSpec names unknown stream(s) "
            f"{sorted(set(unknown))}. Available value streams: "
            f"{sorted(values)}; histogram-only in-scan streams: "
            f"{sorted(counts)}")
    # An in-scan stream is bucketized by the TRAINER against the spec's
    # static edges; a bins-typed spec has none, so the trainer would skip
    # bucketization and scope_observe would hit a KeyError from inside
    # the first traced update — the exact failure this guard exists for.
    bins_only = [h.name for h in spec.hists
                 if h.name in counts and h.name not in values
                 and h.edges is None]
    if bins_only:
        raise ValueError(
            f"{context}: histogram(s) {sorted(bins_only)} name in-scan "
            f"bucketized stream(s), which require static `edges` (the "
            f"trainer buckets against them inside its scan); `bins` "
            f"specs need a raw value stream")


def scope_observe(spec: MetricsSpec, values: dict,
                  counts: dict | None = None) -> MetricsState:
    """Build one MetricsState from this update's raw arrays.

    ``values[name]`` feeds both the stats and hist entries of that name;
    ``counts[name]`` supplies pre-bucketized histogram counts for streams
    the caller already reduced in place (e.g. the PPO ratio, bucketized
    inside the SGD scan so the per-sample array never stacks up).
    """
    counts = counts or {}
    state: MetricsState = {}
    for name in spec.stats:
        state[name] = stats_observe(values[name])
    for h in spec.hists:
        if h.name in counts:
            state["hist/" + h.name] = counts[h.name].astype(jnp.int32)
        elif h.bins is not None:
            state["hist/" + h.name] = categorical_observe(
                values[h.name], h.bins)
        else:
            state["hist/" + h.name] = hist_observe(values[h.name], h.edges)
    return state


def scope_merge(a: MetricsState, b: MetricsState) -> MetricsState:
    return {
        k: stats_merge(v, b[k]) if isinstance(v, TensorStats) else v + b[k]
        for k, v in a.items()
    }


def scope_reduce(stacked: MetricsState) -> MetricsState:
    """Collapse the leading ``[k]`` axis a fused dispatch stacks on."""
    return {
        k: stats_reduce(v) if isinstance(v, TensorStats)
        else jnp.sum(v, axis=0)
        for k, v in stacked.items()
    }


def scope_summary(host_state: dict, spec: MetricsSpec) -> dict:
    """Flatten a FETCHED state into the JSONL/TB-ready summary dict.

    Scalar keys (``<name>/mean`` etc.) are plain floats — the existing
    writers consume them unchanged; histogram keys hold
    ``{"edges"|"bins", "counts"}`` dicts (JSONL keeps them; the TB sink
    skips non-scalars)."""
    import math

    out: dict = {}
    for name in spec.stats:
        s = host_state[name]
        count = float(s.count)
        var = float(s.m2) / count if count > 0 else 0.0
        out[f"{name}/count"] = count
        out[f"{name}/mean"] = float(s.mean)
        out[f"{name}/std"] = math.sqrt(max(var, 0.0))
        out[f"{name}/min"] = float(s.min)
        out[f"{name}/max"] = float(s.max)
    for h in spec.hists:
        counts = [int(c) for c in host_state["hist/" + h.name]]
        entry: dict = {"counts": counts}
        if h.edges is not None:
            entry["edges"] = list(h.edges)
        else:
            entry["bins"] = h.bins
        out[f"hist/{h.name}"] = entry
    return out


class ScopeSession:
    """Host-side controller: device-merge per update, ONE fetch per window.

    ``accumulate(state, first_iteration, k)`` jit-merges the update's
    MetricsState into a device-resident accumulator (async, no transfer)
    and — when the window boundary ``(first_iteration + k) % window == 0``
    lands — flushes: one ``jax.device_get`` of the accumulator, summarize,
    ``emit(last_iteration, summary)``, reset. ``fetch_count`` counts the
    flushes so tests can assert the one-fetch-per-window contract.
    """

    def __init__(self, spec: MetricsSpec, window: int,
                 emit: Callable[[int, dict], None]):
        if window < 1:
            raise ValueError(f"metrics window must be >= 1, got {window}")
        self.spec = spec
        self.window = window
        self.emit = emit
        self.fetch_count = 0
        self._acc: MetricsState | None = None
        self._last_iteration = -1
        self._merge = jax.jit(scope_merge)
        self._reduce = jax.jit(scope_reduce)

    def accumulate(self, state: MetricsState, first_iteration: int,
                   k: int = 1) -> None:
        if k > 1:
            state = self._reduce(state)
        self._acc = (state if self._acc is None
                     else self._merge(self._acc, state))
        self._last_iteration = first_iteration + k - 1
        if (first_iteration + k) % self.window == 0:
            self.flush()

    def flush(self) -> None:
        """The window's single host fetch; no-op when nothing accumulated."""
        if self._acc is None:
            return
        host = _device_get(self._acc)
        self.fetch_count += 1
        self.emit(self._last_iteration, scope_summary(host, self.spec))
        self._acc = None


class TrainObserver:
    """``run_train_loop`` observer: scope session + optional flight recorder.

    - ``observe(i0, metrics, k)``: pops the ``"graftscope"`` state out of
      the update's metrics (device-side bookkeeping only — accumulate into
      the session, record the scalar leaves into the recorder's on-device
      ring) and returns the scalar-only metrics dict the loop logs.
    - ``after_log(i, row)``: host-side anomaly checks on each fetched row
      (delegated to the recorder).
    - ``close()``: final partial-window flush.
    """

    def __init__(self, session: ScopeSession | None = None,
                 recorder: Any | None = None):
        self.session = session
        self.recorder = recorder

    def observe(self, first_iteration: int, metrics: dict, k: int = 1) -> dict:
        metrics = dict(metrics)
        state = metrics.pop("graftscope", None)
        if self.session is not None and state is not None:
            self.session.accumulate(state, first_iteration, k)
        if self.recorder is not None:
            self.recorder.record(first_iteration, metrics, k)
        return metrics

    def after_log(self, iteration: int, row: dict) -> None:
        if self.recorder is not None:
            self.recorder.check_row(iteration, row)

    def close(self) -> None:
        if self.session is not None:
            self.session.flush()


# --------------------------------------------------------- default specs

# Edges chosen to bracket the measured regimes (docs/observability.md):
# grad norms are log-spaced decades around the healthy ~1e-2..1e1 band;
# PPO ratios cluster at 1 with the clip region (±0.3 at the default
# clip_eps) resolved; advantages/rewards get a symmetric pseudo-log grid.
GRAD_NORM_EDGES = (1e-4, 1e-3, 1e-2, 0.03, 0.1, 0.3, 1.0, 3.0, 10.0,
                   30.0, 100.0, 1e3)
RATIO_EDGES = (0.5, 0.7, 0.8, 0.9, 0.95, 0.99, 1.01, 1.05, 1.1, 1.2,
               1.3, 1.5, 2.0)
SYMLOG_EDGES = (-100.0, -30.0, -10.0, -3.0, -1.0, -0.3, -0.1, 0.0,
                0.1, 0.3, 1.0, 3.0, 10.0, 30.0, 100.0)


def ppo_scope_spec(num_actions: int) -> MetricsSpec:
    """What the PPO update watches: advantage/reward/value streams with
    stats+histograms, grad-norm per minibatch, the in-scan ratio
    histogram, and per-cloud (or per-node) action counts."""
    return MetricsSpec(
        stats=("advantage", "reward", "value", "grad_norm"),
        hists=(
            HistSpec("advantage", edges=SYMLOG_EDGES),
            HistSpec("grad_norm", edges=GRAD_NORM_EDGES),
            HistSpec("ratio", edges=RATIO_EDGES),
            HistSpec("action", bins=num_actions),
        ),
    )


def dqn_scope_spec(num_actions: int) -> MetricsSpec:
    """DQN watch set: replay-batch reward/td streams, grad norm, and the
    replayed action distribution. During buffer warm-up the learner is
    skipped and grad_norm observes 0 — visible as a spike at the underflow
    bucket, documented rather than masked."""
    return MetricsSpec(
        stats=("reward", "td_abs", "q_mean", "grad_norm"),
        hists=(
            HistSpec("reward", edges=SYMLOG_EDGES),
            HistSpec("grad_norm", edges=GRAD_NORM_EDGES),
            HistSpec("action", bins=num_actions),
        ),
    )
