"""Atomic filesystem idioms: the one place the write/replace discipline
lives.

Two primitives, each the canonical fix for a graftlint rule's defect
class (docs/static_analysis.md):

- :func:`atomic_write_json` (GL013): durable ``.json`` artifacts —
  ledgers, manifests, verdicts, caches — must never be observable
  half-written. Write a per-writer-unique ``.{name}.{pid}.tmp`` sibling
  and ``os.replace`` it in: a kill leaves either nothing or a complete
  file, and concurrent writers each rename their OWN complete file
  (last one wins) instead of racing on a shared tmp name.
- :func:`fresh_dir` (GL014): the ``if dest.exists(): rmtree(dest)``
  check-then-act pair loses to any process that creates or deletes
  ``dest`` inside the window. EAFP: delete unconditionally, swallow
  only "already gone", recreate.

Grew out of ``studies/runner.py`` (which re-exports
``atomic_write_json`` for its existing importers) when the discipline
went repo-wide with the GL013/GL014 rules.
"""

from __future__ import annotations

import json
import os
import shutil
from pathlib import Path


def atomic_write_json(path: str | Path, obj, indent: int | None = None) -> None:
    """tmp-then-rename JSON write — the one implementation of the
    graftguard atomicity discipline for durable artifacts (results,
    summaries, threshold caches, snapshot manifests); a kill leaves
    either nothing or a complete file. The tmp name is per-writer-unique
    (pid): concurrent writers of the same target (e.g. same-variant
    workers racing on the threshold cache) each rename their OWN
    complete file, last one wins — never a shared tmp renamed out from
    under a mid-write peer."""
    path = Path(path)
    tmp = path.parent / f".{path.name}.{os.getpid()}.tmp"
    tmp.write_text(json.dumps(obj, sort_keys=True, indent=indent))
    os.replace(tmp, path)


def fresh_dir(dest: str | Path) -> Path:
    """Recreate ``dest`` empty, without the exists()/rmtree TOCTOU pair:
    remove whatever is there (tolerating a concurrent delete), then
    mkdir. A concurrent CREATOR still surfaces as ``FileExistsError``
    from the mkdir — that conflict is real and must not be silenced."""
    dest = Path(dest)
    try:
        shutil.rmtree(dest)
    except FileNotFoundError:
        pass
    dest.mkdir(parents=True)
    return dest
