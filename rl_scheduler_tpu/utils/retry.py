"""graftguard part 3: the one retry/backoff/circuit-breaker policy.

Every host-I/O boundary in this repo talks to something that fails in
production — Prometheus scrapes time out, the kube API returns 5xx under
apiserver pressure, a policy backend can throw on a poisoned checkpoint.
Before graftguard each call site hand-rolled its own "try once, fall
back" shape, which meant no backoff (a dead Prometheus got re-probed at
full request rate), no deadline, and no way to see from /metrics that a
dependency was down. This module is the single policy all of them adopt
(``scheduler/telemetry.py``, ``scheduler/k8s_client.py``, the extender's
backend calls):

- :class:`RetryPolicy` — bounded attempts, exponential backoff with
  seeded-RNG jitter (deterministic under the chaos harness), and a total
  deadline so a retried call can never exceed its caller's latency
  budget. The sleep function is injectable so tests never actually wait.
- :class:`CircuitBreaker` — consecutive-failure trip, a cool-down after
  which ONE half-open probe is admitted, closing again only on probe
  success. State is exported as a dict snapshot; the extender mirrors it
  onto ``/stats`` and ``/metrics`` so "the breaker is open" is a scrape,
  not a log-dive.

Both are plain host-side Python (never inside jit) and thread-safe: the
extender serves requests concurrently and telemetry refreshes on a
background thread.
"""

from __future__ import annotations

import logging
import random
import threading
import time
from typing import Any, Callable

logger = logging.getLogger(__name__)


class RetryBudgetExceeded(RuntimeError):
    """All attempts (or the deadline) were exhausted; carries the last
    underlying exception as ``__cause__``."""


class CircuitOpenError(RuntimeError):
    """The breaker is open: the call was refused without being attempted."""


class RetryPolicy:
    """Bounded retries with exponential backoff, jitter, and a deadline.

    ``call(fn, *args, **kwargs)`` runs ``fn`` up to ``max_attempts``
    times. Between attempts it sleeps ``base_delay_s * 2**n``, capped at
    ``max_delay_s``, plus uniform jitter of up to ``jitter`` of the delay
    (seeded RNG — the chaos suite asserts exact schedules). A non-None
    ``deadline_s`` bounds the TOTAL time (attempt time + sleeps): once
    exceeded, no further attempt is made even if the attempt budget
    remains — a retried scrape must never outlive its caller's latency
    budget. Exceptions not listed in ``retry_on`` propagate immediately.
    """

    def __init__(
        self,
        max_attempts: int = 3,
        base_delay_s: float = 0.05,
        max_delay_s: float = 2.0,
        jitter: float = 0.1,
        deadline_s: float | None = None,
        retry_on: tuple = (Exception,),
        seed: int | None = None,
        sleep: Callable[[float], None] = time.sleep,
        clock: Callable[[], float] = time.monotonic,
    ):
        if max_attempts < 1:
            raise ValueError(f"max_attempts={max_attempts}: must be >= 1")
        if not 0.0 <= jitter <= 1.0:
            raise ValueError(f"jitter={jitter}: pass a fraction in [0, 1]")
        self.max_attempts = max_attempts
        self.base_delay_s = base_delay_s
        self.max_delay_s = max_delay_s
        self.jitter = jitter
        self.deadline_s = deadline_s
        self.retry_on = retry_on
        self._rng = random.Random(seed)
        self._sleep = sleep
        self._clock = clock

    def delays(self) -> list:
        """The backoff schedule this policy WOULD sleep (jitter included),
        one entry per retry gap. Fresh jitter draws each call; with a
        seeded policy the sequence is reproducible from construction."""
        out = []
        for n in range(self.max_attempts - 1):
            d = min(self.base_delay_s * (2 ** n), self.max_delay_s)
            out.append(d + self._rng.uniform(0.0, self.jitter * d))
        return out

    def call(self, fn: Callable, *args: Any, **kwargs: Any) -> Any:
        t0 = self._clock()
        last: BaseException | None = None
        for attempt in range(self.max_attempts):
            if self.deadline_s is not None and \
                    self._clock() - t0 >= self.deadline_s and attempt > 0:
                break
            try:
                return fn(*args, **kwargs)
            except self.retry_on as e:  # noqa: PERF203 - retry loop
                last = e
                logger.debug("retry %d/%d of %s failed: %s", attempt + 1,
                             self.max_attempts, getattr(fn, "__name__", fn), e)
                if attempt + 1 >= self.max_attempts:
                    break
                d = min(self.base_delay_s * (2 ** attempt), self.max_delay_s)
                d += self._rng.uniform(0.0, self.jitter * d)
                if self.deadline_s is not None:
                    remaining = self.deadline_s - (self._clock() - t0)
                    if remaining <= 0:
                        break
                    d = min(d, remaining)
                self._sleep(d)
        raise RetryBudgetExceeded(
            f"{getattr(fn, '__name__', fn)} failed after {self.max_attempts} "
            f"attempt(s): {last}"
        ) from last


class CircuitBreaker:
    """Consecutive-failure circuit breaker with half-open recovery probes.

    States: ``closed`` (calls flow; ``failure_threshold`` consecutive
    failures trip it) -> ``open`` (calls refused for ``reset_timeout_s``)
    -> ``half_open`` (ONE probe call admitted; ``probe_successes``
    consecutive probe successes close the breaker, any probe failure
    re-opens it and restarts the cool-down). The caller drives it either
    through :meth:`call` (raises :class:`CircuitOpenError` when refused)
    or through the ``allow``/``record_success``/``record_failure``
    primitives when it wants to substitute a fallback instead of raising
    — the fail-open serving paths do the latter.
    """

    CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"

    def __init__(
        self,
        name: str = "breaker",
        failure_threshold: int = 5,
        reset_timeout_s: float = 30.0,
        probe_successes: int = 1,
        clock: Callable[[], float] = time.monotonic,
    ):
        if failure_threshold < 1 or probe_successes < 1:
            raise ValueError(
                "failure_threshold and probe_successes must be >= 1"
            )
        self.name = name
        self.failure_threshold = failure_threshold
        self.reset_timeout_s = reset_timeout_s
        self.probe_successes = probe_successes
        self._clock = clock
        self._lock = threading.Lock()
        self._state = self.CLOSED
        self._consecutive_failures = 0
        self._probe_streak = 0
        self._opened_at = 0.0
        self._probe_in_flight = False
        self._probe_started = 0.0
        # Lifetime counters for /metrics (monotonic, Prometheus-safe).
        self._failures_total = 0
        self._refusals_total = 0
        self._opens_total = 0

    @property
    def state(self) -> str:
        with self._lock:
            return self._peek_state()

    def _peek_state(self) -> str:
        # Caller holds the lock. Promote open -> half_open lazily on read:
        # there is no timer thread, the next allow() after the cool-down
        # is the probe.
        if self._state == self.OPEN and \
                self._clock() - self._opened_at >= self.reset_timeout_s:
            self._state = self.HALF_OPEN
            self._probe_streak = 0
            self._probe_in_flight = False
        return self._state

    def allow(self) -> bool:
        """Whether the next call may proceed. In half-open, exactly one
        in-flight probe is admitted at a time (concurrent serving threads
        must not stampede a recovering dependency)."""
        with self._lock:
            state = self._peek_state()
            if state == self.CLOSED:
                return True
            if state == self.HALF_OPEN and (
                    not self._probe_in_flight or
                    self._clock() - self._probe_started >=
                    self.reset_timeout_s):
                # The in-flight check re-arms after a cool-down: a probe
                # that never reported back (wedged dependency, caller
                # thread died on a BaseException) must not block breaker
                # recovery for the rest of the process lifetime.
                self._probe_in_flight = True
                self._probe_started = self._clock()
                return True
            self._refusals_total += 1
            return False

    def record_success(self) -> None:
        with self._lock:
            state = self._peek_state()
            self._consecutive_failures = 0
            if state == self.HALF_OPEN:
                self._probe_in_flight = False
                self._probe_streak += 1
                if self._probe_streak >= self.probe_successes:
                    self._state = self.CLOSED
                    logger.info("breaker %s closed after %d probe "
                                "success(es)", self.name, self._probe_streak)

    def record_failure(self) -> None:
        with self._lock:
            state = self._peek_state()
            self._failures_total += 1
            if state == self.HALF_OPEN:
                # Failed probe: back to open, restart the cool-down.
                self._probe_in_flight = False
                self._trip("probe failed")
                return
            self._consecutive_failures += 1
            if state == self.CLOSED and \
                    self._consecutive_failures >= self.failure_threshold:
                self._trip(
                    f"{self._consecutive_failures} consecutive failures"
                )

    def _trip(self, why: str) -> None:
        # Caller holds the lock.
        self._state = self.OPEN
        self._opened_at = self._clock()
        self._opens_total += 1
        self._consecutive_failures = 0
        logger.warning("breaker %s opened (%s); cooling down %.1fs",
                       self.name, why, self.reset_timeout_s)

    def call(self, fn: Callable, *args: Any, **kwargs: Any) -> Any:
        if not self.allow():
            raise CircuitOpenError(f"breaker {self.name} is open")
        try:
            out = fn(*args, **kwargs)
        except Exception:
            self.record_failure()
            raise
        self.record_success()
        return out

    def snapshot(self) -> dict:
        """State + lifetime counters for /stats and /metrics export."""
        with self._lock:
            return {
                "state": self._peek_state(),
                "consecutive_failures": self._consecutive_failures,
                "failures_total": self._failures_total,
                "refusals_total": self._refusals_total,
                "opens_total": self._opens_total,
            }

    # Numeric encoding for the Prometheus gauge (docs/robustness.md).
    STATE_CODES = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}

    @classmethod
    def merge_snapshots(cls, snapshots: list) -> dict:
        """Pool-wide view of ONE breaker boundary across worker
        processes (graftserve, ``scheduler/pool.py``): the state is the
        MAX by :data:`STATE_CODES` — "this dependency is down anywhere
        in the pool" must surface as one gauge, and a single open
        breaker outranks any number of closed ones — while the lifetime
        counters sum (each worker's counters are independent monotonic
        streams, so their sum is the pool's monotonic stream) and
        ``consecutive_failures`` reports the worst worker. The returned
        dict has exactly :meth:`snapshot`'s shape, so every exporter
        that renders single-process snapshots renders merged ones
        unchanged."""
        if not snapshots:
            return {"state": cls.CLOSED, "consecutive_failures": 0,
                    "failures_total": 0, "refusals_total": 0,
                    "opens_total": 0}
        return {
            "state": max((s["state"] for s in snapshots),
                         key=cls.STATE_CODES.__getitem__),
            "consecutive_failures": max(
                s.get("consecutive_failures", 0) for s in snapshots),
            "failures_total": sum(
                s.get("failures_total", 0) for s in snapshots),
            "refusals_total": sum(
                s.get("refusals_total", 0) for s in snapshots),
            "opens_total": sum(
                s.get("opens_total", 0) for s in snapshots),
        }
