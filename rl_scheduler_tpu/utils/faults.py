"""graftguard part 4: the deterministic fault-injection harness.

The failure paths graftguard adds (hardened checkpoints, retry/breaker
adoption, preemption-safe shutdown) are worthless if they are never
executed — "fail-open everywhere" code that only runs in production IS
the untested path. A :class:`FaultPlan` makes every host-I/O boundary
attackable on purpose, deterministically:

- **Named sites.** Each injection point asks the plan by site name
  (:data:`SITES` lists the wired ones). Sites are consulted once per
  call, so a plan fully determines WHICH call of WHICH boundary fails.
- **Two trigger modes.** ``schedule={site: (call_indices...)}`` fires on
  exact 1-based call numbers (the chaos suite's mode — byte-reproducible
  runs); ``rates={site: p}`` fires each call with probability ``p`` from
  a per-site ``random.Random`` seeded from ``(seed, site)`` (the soak
  mode — still reproducible from the seed, but site streams are
  independent, so adding a new injection point never shifts another
  site's pattern).
- **Observability.** ``plan.calls``/``plan.fired`` count per site, so a
  test can assert a fault actually happened (a chaos test whose fault
  never fired is a green lie).

Production code never constructs a plan; every seam defaults to
``fault_plan=None`` (zero overhead, zero behavior change). The seams are
plumbed, not monkeypatched, so the chaos suite exercises the exact code
paths production runs.
"""

from __future__ import annotations

import logging
import random
import threading
from pathlib import Path

logger = logging.getLogger(__name__)

# The wired injection sites (see docs/robustness.md for the map):
#   checkpoint.save    raised before the Orbax save dispatches (write error)
#   checkpoint.partial step files truncated AFTER the manifest is written
#                      (torn write — restore-time verification must catch it)
#   telemetry.scrape   Prometheus HTTP query raises TimeoutError
#   k8s.place          kube pod-create raises a 503-style error
#   backend.decide     policy backend raises (wired by the chaos suite's
#                      backend stub; the extender's breaker absorbs it)
#   preempt            PreemptionGuard.should_stop() reports a simulated
#                      SIGTERM at the next dispatch boundary
#   scenario.churn     consulted per (node, step) by the scenario layer's
#                      node-pool churn generator (scenarios/families.py) to
#                      decide which nodes get preempted when — the same
#                      seeded per-site stream discipline, reused so a churn
#                      schedule is reproducible from (seed, rate) alone
#   tracelog.append    the trace log's segment write raises OSError (disk
#                      full mid-append) — the record is counted dropped,
#                      the hot decision path never sees the error
#                      (scheduler/tracelog.py)
#   rollout.spawn      a rollout-driven worker respawn fails before fork —
#                      the promotion gate must treat the slot as failed and
#                      roll already-promoted workers back
#                      (scheduler/rollout.py)
#   rollout.health     a respawned worker's health/warm-up gate reports
#                      failure — same rollback obligation as a real dead
#                      canary (scheduler/rollout.py)
#   fastpath.agree     the graftfwd promote gate's int8 agreement
#                      re-check fails — the rollout must refuse/roll
#                      back rather than serve a badly-quantizing (or
#                      unverifiable) candidate (scheduler/rollout.py,
#                      scheduler/fastpath.check_int8_agreement)
#   loopback.compile   graftloop's trace→Scenario compile raises mid-
#                      stage — the loop ledger must record the failure
#                      and a re-run must resume at the compile stage,
#                      never promote (rl_scheduler_tpu/loopback/)
#   loopback.promote   graftloop's promote stage fails before the POST —
#                      the loop must surface the refusal with the pool
#                      untouched on the incumbent generation
#                      (rl_scheduler_tpu/loopback/orchestrator.py)
#   fleet.scrape       a fleet controller's pool /stats scrape raises
#                      TimeoutError — the pool must show as down/degraded
#                      on fleet /healthz while the merge proceeds over
#                      the pools that answered (scheduler/fleet.py)
#   fleet.promote      a pool becomes unreachable mid fleet-roll (OSError
#                      before the POST dispatches) — the fleet promote
#                      must record `aborted` and revert every already-
#                      rolled pool to its incumbent (scheduler/fleet.py)
#   daemon.poll        graftpilot's /stats poll raises OSError — the
#                      daemon must record a `poll_error` decision (after
#                      its RetryPolicy budget) and keep polling; a flaky
#                      control plane never kills the controller
#                      (rl_scheduler_tpu/loopback/daemon.py)
#   daemon.trigger     raised between the trigger verdict and arming the
#                      iteration — the crash window where drift was seen
#                      but nothing is recorded yet; a resume must re-poll
#                      and re-arm from live evidence, never double-arm
#   daemon.shadow_gate raised inside the live shadow gate (arm/collect/
#                      grade) — the gate must leave the pool disarmed on
#                      the incumbent generation and the iteration must
#                      resume at the shadow_gate stage, never promote on
#                      a half-collected verdict
SITES = ("checkpoint.save", "checkpoint.partial", "telemetry.scrape",
         "k8s.place", "backend.decide", "preempt", "scenario.churn",
         "tracelog.append", "rollout.spawn", "rollout.health",
         "fastpath.agree", "loopback.compile", "loopback.promote",
         "fleet.scrape", "fleet.promote", "daemon.poll",
         "daemon.trigger", "daemon.shadow_gate")


class FaultInjected(RuntimeError):
    """The base exception a fired site raises (sites that simulate a
    specific error family raise that family instead — the seam decides)."""

    def __init__(self, site: str, call_index: int):
        self.site = site
        self.call_index = call_index
        super().__init__(f"injected fault at {site} (call #{call_index})")


class FaultPlan:
    """Seeded, deterministic per-site fault triggers. Thread-safe: the
    telemetry/extender seams are consulted from serving threads."""

    def __init__(self, seed: int = 0,
                 schedule: dict | None = None,
                 rates: dict | None = None):
        self.seed = seed
        self.schedule = {k: frozenset(v) for k, v in (schedule or {}).items()}
        self.rates = dict(rates or {})
        bad = [s for s in list(self.schedule) + list(self.rates)
               if s not in SITES]
        if bad:
            raise ValueError(
                f"unknown fault site(s) {sorted(bad)}; wired sites: "
                f"{list(SITES)}"
            )
        self.calls: dict = {}   # site -> consult count
        self.fired: dict = {}   # site -> fire count
        self._lock = threading.Lock()
        # Independent stream per site: (seed, site) keys the RNG, so a new
        # injection point cannot shift an existing site's pattern.
        self._rngs = {s: random.Random(f"{seed}:{s}") for s in self.rates}

    def fires(self, site: str) -> bool:
        """Consult the plan for one call at ``site`` (advances the site's
        call counter either way)."""
        with self._lock:
            n = self.calls.get(site, 0) + 1
            self.calls[site] = n
            hit = n in self.schedule.get(site, ())
            if not hit and site in self._rngs:
                hit = self._rngs[site].random() < self.rates[site]
            if hit:
                self.fired[site] = self.fired.get(site, 0) + 1
                logger.info("fault plan: firing %s (call #%d)", site, n)
            return hit

    def check(self, site: str, exc: type = FaultInjected) -> None:
        """Raise when the plan fires this call. ``exc`` is the error
        family the real dependency would raise (TimeoutError for a
        scrape, OSError for a write, ...); :class:`FaultInjected` itself
        is raised when the family's constructor does not take our
        message."""
        if not self.fires(site):
            return
        n = self.calls[site]
        if exc is FaultInjected:
            raise FaultInjected(site, n)
        raise exc(f"injected fault at {site} (call #{n})")


def corrupt_checkpoint_step(step_dir: str | Path, mode: str = "truncate") -> list:
    """Simulate a torn/corrupt checkpoint write on a FINALIZED step dir.

    ``truncate`` halves the largest file (a write cut off mid-flush —
    the classic disk-full/preempted-VM artifact); ``garbage`` overwrites
    its head with junk bytes (bit rot / torn sector). Returns the
    relative paths touched so tests can assert exactly what was damaged.
    The hardened restore path must detect either against the step's
    integrity manifest and fall back to the previous verified step.
    """
    step_dir = Path(step_dir)
    files = sorted(
        (p for p in step_dir.rglob("*") if p.is_file()),
        key=lambda p: p.stat().st_size, reverse=True,
    )
    if not files:
        raise FileNotFoundError(f"no files to corrupt under {step_dir}")
    target = files[0]
    size = target.stat().st_size
    if mode == "truncate":
        with target.open("rb+") as fh:
            fh.truncate(max(size // 2, 0))
    elif mode == "garbage":
        with target.open("rb+") as fh:
            fh.write(b"\xde\xad\xbe\xef" * max(1, min(size, 256) // 4))
    else:
        raise ValueError(f"unknown corruption mode {mode!r}; "
                         "choose truncate|garbage")
    logger.info("corrupted checkpoint file %s (%s, was %d bytes)",
                target, mode, size)
    return [str(target.relative_to(step_dir))]
