"""graftfront wire: the compact candidate-list codec for the data plane.

The extender protocol's JSON bodies are the serving plane's residual
parse cost once graftfwd cached/quantized the forward: a 1024-node
/filter request is ~40 KB of JSON that ``json.loads`` re-materializes
into a Python list of node strings (or worse, node OBJECTS) on every
request, only for the policy to immediately reduce it to a per-candidate
cloud list. This module extends the trace log's ``clouds_token``
one-char-per-candidate encoding (``tracelog.py``) into a full
request/response codec so a front can hand a request to the policy
without ever building that list:

request body (``Content-Type: application/x-graft-wire``)::

    1;<pod_millicores>;<clouds_token>[;<name,name,...>]

    1;500;azaz?          # 5 candidates, pod requests 0.5 cores
    1;250;az;web-0,web-1 # explicit display names (optional)

- one char per candidate: ``a``=aws, ``z``=azure, ``?``=unknown — the
  EXACT alphabet ``tracelog.clouds_token`` writes, so a trace replayer
  can turn records back into wire bodies with zero translation;
- display names are optional: when absent they synthesize lazily
  (``aws-0``, ``azure-3``, ``node-7`` — the same names ``extender_bench
  --replay-trace`` fabricates) and only the chosen one is ever built;
- the decoder is STRICT where the trace reader is lenient: an unknown
  cloud char, a malformed millicore field or a name-count mismatch
  raises :class:`WireError`, which both fronts answer with HTTP 400 —
  a refusal, never a dropped connection.

responses::

    /filter      1;0,3,7     kept candidate indices (csv)
                 1;*         keep ALL (fail-open / empty request)
    /prioritize  1;100,42,7  one 0-100 score per candidate (csv)

Wire v1 carries the pod's cpu request only (millicores); deployments
whose checkpoints consume full heterogeneous resource vectors keep the
JSON path — the two content types share one port and one policy.
"""

from __future__ import annotations

import time
from collections.abc import Sequence

from rl_scheduler_tpu.scheduler.tracelog import _CLOUD_CHARS

WIRE_CONTENT_TYPE = "application/x-graft-wire"
WIRE_VERSION = 1

# Strict inverses of the trace alphabet: the trace READER tolerates junk
# chars (an old record must replay), the wire DECODER refuses them (a
# malformed request must 400, not silently score "unknown cloud").
_CHAR_TO_CLOUD = {ch: cloud for cloud, ch in _CLOUD_CHARS.items()}
_CLOUD_TO_CHAR = dict(_CLOUD_CHARS)
# Delimiters the name field cannot carry (no escaping in v1 — k8s node
# names are DNS-1123 labels, which exclude all three anyway).
_NAME_FORBIDDEN = (";", ",", "\n", "\r")
_SENTINEL = object()


class WireError(ValueError):
    """Malformed wire body — the fronts answer 400 with this message."""


class SynthNames(Sequence):
    """Lazy display names for a names-less wire request: ``{cloud}-{i}``
    (``node-{i}`` for unknown clouds), matching what ``extender_bench
    --replay-trace`` synthesizes from trace records. Indexing builds ONE
    string; the policy only ever needs the chosen candidate's name."""

    __slots__ = ("_clouds",)

    def __init__(self, clouds: Sequence) -> None:
        self._clouds = clouds

    def __len__(self) -> int:
        return len(self._clouds)

    def __getitem__(self, i):
        if isinstance(i, slice):
            return [self[j] for j in range(*i.indices(len(self)))]
        cloud = self._clouds[i]
        return f"{cloud or 'node'}-{i if i >= 0 else i % len(self._clouds)}"


class WireRequest:
    """One decoded wire request: the candidate cloud list (the only
    per-candidate structure the decide path consumes), the pod's cpu
    request in millicores, and a display-name sequence that is lazy
    unless the client sent explicit names."""

    __slots__ = ("clouds", "pod_millicores", "names")

    def __init__(self, clouds: list, pod_millicores: int,
                 names: Sequence | None = None) -> None:
        self.clouds = clouds
        self.pod_millicores = pod_millicores
        self.names = names if names is not None else SynthNames(clouds)

    def __len__(self) -> int:
        return len(self.clouds)

    def pod_cpu_fraction(self, node_capacity_cores: float) -> float:
        """The set policy's [0,1] pod_cpu feature, same normalization as
        ``pod_cpu_fraction`` on the JSON path."""
        return self.pod_millicores / 1e3 / node_capacity_cores


def encode_request(clouds: Sequence, pod_millicores: int,
                   names: Sequence | None = None) -> bytes:
    """Candidate list -> wire body (the client/bench side)."""
    if pod_millicores < 0 or int(pod_millicores) != pod_millicores:
        raise WireError(f"pod_millicores {pod_millicores!r}: pass a "
                        "non-negative integer")
    try:
        token = "".join(_CLOUD_TO_CHAR[c] for c in clouds)
    except KeyError as exc:
        raise WireError(f"unknown cloud {exc.args[0]!r} (wire v1 encodes "
                        f"{sorted(c for c in _CLOUD_TO_CHAR if c)})")
    parts = [str(WIRE_VERSION), str(int(pod_millicores)), token]
    if names is not None:
        names = list(names)
        if len(names) != len(clouds):
            raise WireError(f"{len(names)} names for {len(clouds)} "
                            "candidates")
        for name in names:
            if not isinstance(name, str) or not name:
                raise WireError(f"bad candidate name {name!r}")
            if any(ch in name for ch in _NAME_FORBIDDEN):
                raise WireError(f"name {name!r} contains a wire delimiter "
                                "(;,\\n\\r) — send JSON for such names")
        parts.append(",".join(names))
    return ";".join(parts).encode("utf-8")


def decode_request(body: bytes) -> WireRequest:
    """Wire body -> :class:`WireRequest`; :class:`WireError` on any
    malformation (the fronts' 400 path)."""
    try:
        text = body.decode("utf-8")
    except UnicodeDecodeError as exc:
        raise WireError(f"body is not utf-8: {exc}")
    parts = text.split(";")
    if len(parts) not in (3, 4):
        raise WireError(f"expected 3 or 4 ';'-fields, got {len(parts)}")
    if parts[0] != str(WIRE_VERSION):
        raise WireError(f"unsupported wire version {parts[0]!r} "
                        f"(this server speaks {WIRE_VERSION})")
    try:
        millis = int(parts[1])
    except ValueError:
        raise WireError(f"bad pod_millicores field {parts[1]!r}")
    if millis < 0:
        raise WireError(f"negative pod_millicores {millis}")
    clouds = []
    for ch in parts[2]:
        cloud = _CHAR_TO_CLOUD.get(ch, _SENTINEL)
        if cloud is _SENTINEL:
            raise WireError(f"unknown cloud char {ch!r} in token "
                            f"(alphabet: {sorted(_CHAR_TO_CLOUD)})")
        clouds.append(cloud)
    names = None
    if len(parts) == 4:
        names = parts[3].split(",") if parts[3] else []
        if len(names) != len(clouds):
            raise WireError(f"{len(names)} names for {len(clouds)} "
                            "candidates")
        if any(not n for n in names):
            raise WireError("empty candidate name")
    return WireRequest(clouds, millis, names)


def encode_filter_response(kept: Sequence | None) -> bytes:
    """Kept-indices -> wire body; ``None`` is the fail-open/passthrough
    answer (``1;*`` — keep every candidate)."""
    if kept is None:
        return f"{WIRE_VERSION};*".encode()
    return (f"{WIRE_VERSION};"
            + ",".join(str(int(i)) for i in kept)).encode()


def decode_filter_response(body: bytes, n: int) -> list | None:
    """Wire filter body -> kept indices (``None`` = keep all); strict —
    an out-of-range index is a server bug the client must see."""
    text = body.decode("utf-8")
    parts = text.split(";")
    if len(parts) != 2 or parts[0] != str(WIRE_VERSION):
        raise WireError(f"bad filter response {text!r}")
    if parts[1] == "*":
        return None
    if not parts[1]:
        return []
    try:
        kept = [int(f) for f in parts[1].split(",")]
    except ValueError:
        raise WireError(f"bad filter response {text!r}")
    if any(i < 0 or i >= n for i in kept):
        raise WireError(f"filter response index out of range 0..{n - 1}")
    return kept


def encode_prioritize_response(scores: Sequence) -> bytes:
    """Per-candidate 0-100 scores -> wire body."""
    return (f"{WIRE_VERSION};"
            + ",".join(str(int(s)) for s in scores)).encode()


def decode_prioritize_response(body: bytes) -> list:
    text = body.decode("utf-8")
    parts = text.split(";")
    if len(parts) != 2 or parts[0] != str(WIRE_VERSION):
        raise WireError(f"bad prioritize response {text!r}")
    if not parts[1]:
        return []
    try:
        return [int(f) for f in parts[1].split(",")]
    except ValueError:
        raise WireError(f"bad prioritize response {text!r}")


def serve_wire(policy, path: str, body: bytes) -> bytes:
    """One wire request against the policy: decode (the request's
    ``parse`` phase — charged to the span exactly like the JSON path's
    node extraction), dispatch to the policy's wire entry points, encode
    the answer. Raises :class:`WireError` on a malformed body (callers
    answer 400) and ``ValueError`` on an unknown path (404)."""
    t_parse = time.perf_counter()
    req = decode_request(body)
    parse_s = time.perf_counter() - t_parse
    if path == "/filter":
        return encode_filter_response(policy.filter_wire(req, parse_s))
    if path == "/prioritize":
        return encode_prioritize_response(
            policy.prioritize_wire(req, parse_s))
    raise ValueError(f"unknown wire path {path}")
