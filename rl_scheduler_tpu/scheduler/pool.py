"""graftserve: the multi-worker serving plane for the scheduler extender.

The extender's entire serving plane was one Python process: a trained
N=1024 fleet policy answers at 16 ms p50 single-stream but queues to
~160 ms at 8-way because the set-transformer numpy forward holds the GIL
(docs/serving.md). Every piece below it was already built for a pool —
the backends are stateless, ``--price-replay wallclock`` gives
cross-replica agreement, and ``LatencyStats.merged_histogram`` pins how
multi-worker scrapes sum — but nothing could run more than one serving
core. This module is the missing plane:

- :class:`ServingPool` forks ``N`` worker processes that each run the
  EXISTING ``ThreadingHTTPServer`` + backend stack unchanged, sharing one
  data port via ``SO_REUSEPORT`` (each worker binds its own listener; the
  kernel load-balances connections). Where the option is unavailable the
  pool falls back to binding once in the supervisor and letting the
  forked workers ``accept()`` on the inherited socket — classic pre-fork
  sharing, same semantics, no kernel hashing.
- A lightweight **supervisor** restarts dead workers on the
  ``utils/retry.RetryPolicy`` backoff schedule (deaths within the
  stability window walk the exponential schedule; a worker that stays up
  resets it; a slot that exhausts the schedule is marked failed so a
  crash-looping misconfiguration cannot flap forever) and serves the
  pool-wide control plane on its own port:

  - ``GET /stats``      — decision counts summed, latency percentiles
    derived from ``LatencyStats.merged_histogram`` (bucket sums are the
    union stream's buckets; exact per-worker ring percentiles ride in the
    ``workers`` array), shed/reroute fractions request-weighted.
  - ``GET /metrics``    — ONE Prometheus histogram for the pool, summed
    decision/opens counters, breaker state per boundary as the MAX across
    workers (``CircuitBreaker.merge_snapshots``: "this dependency is down
    anywhere" is one gauge), plus per-worker ``_pool_worker_*`` series
    where per-worker identity matters (liveness, decision share).
  - ``POST /stats/reset`` — fanned out to every worker (each clears its
    percentile ring; lifetime histograms — and every graftroll counter:
    trace records/drops/segments, promotions, rollbacks — stay
    monotonic, as Prometheus requires).
  - ``GET /healthz``    — live worker count vs configured, restart total,
    and ``rolling: true`` (still 200) while a promote/rollback is in
    flight — a rollout must not trip k8s liveness.
  - ``POST /promote``   — graftroll (``scheduler/rollout.py``): verify a
    candidate checkpoint against its integrity manifests, then execute a
    canary-gated rolling worker restart onto it, rolling back
    automatically on any gate failure. ``GET /rollout`` reports the
    state machine, per-worker generations, and lifetime counters.

- Workers publish snapshots to the supervisor over a **local control
  socket** (AF_UNIX where available, else loopback TCP; newline-delimited
  JSON both ways — stdlib only, matching the repo's zero-dependency
  serving stack). The supervisor is the client: one ``snapshot``/``reset``
  command per worker per scrape, so a wedged worker costs one timeout,
  never the scrape.
- :class:`SharedCounter` (``multiprocessing.Value``) backs the graph
  family's ``--price-replay counter`` row position and the telemetry
  table replay, so all workers of ONE pool walk the same trajectory a
  single process would (cross-replica deployments keep the documented
  ``wallclock`` answer — separate pools never share memory).

The pool requires the ``fork`` start method (Linux): workers must inherit
the policy factory, the shared counters, and (in fallback mode) the bound
listener without pickling. Aggregation itself is pure functions over
worker snapshot dicts (:func:`aggregate_stats`,
:func:`aggregate_metrics`) so the semantics are unit-testable without
processes.
"""

from __future__ import annotations

import inspect
import json
import logging
import multiprocessing
import os
import signal
import socket
import tempfile
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from rl_scheduler_tpu.scheduler.extender import (
    LatencyStats,
    fastpath_metric_lines,
    make_server,
    phase_metric_lines,
    slo_metric_lines,
)
from rl_scheduler_tpu.scheduler.rollout import (
    STATE_CODES,
    RolloutController,
    WorkerSpec,
)
from rl_scheduler_tpu.scheduler import drift as drift_mod
from rl_scheduler_tpu.scheduler import slo as slo_mod
from rl_scheduler_tpu.utils.retry import CircuitBreaker, RetryPolicy

logger = logging.getLogger(__name__)

METRIC_PREFIX = "rl_scheduler_extender"
SNAPSHOT_SCHEMA = 1
_LISTEN_BACKLOG = 128


class SharedCounter:
    """Monotonic cross-process counter (``multiprocessing.Value``).

    Duck-typed for ``RawPriceReplay(counter=...)`` and
    ``TableTelemetry(counter=...)``: one ``next_index()`` per request,
    under the Value's own cross-process lock. Stores the RAW monotonic
    count — consumers apply their own ``% len(table)``, so one counter
    can back tables of different lengths.
    """

    def __init__(self, ctx=None):
        ctx = ctx or multiprocessing.get_context("fork")
        self._val = ctx.Value("Q", 0)  # uint64: never wraps in practice

    def next_index(self) -> int:
        with self._val.get_lock():
            idx = self._val.value
            self._val.value = idx + 1
            return idx

    @property
    def value(self) -> int:
        with self._val.get_lock():
            return int(self._val.value)


class PoolShared:
    """The cross-process state one pool's workers share: the graph
    family's raw-price replay position and the telemetry table replay
    position. Created by the supervisor BEFORE forking; each worker's
    ``build_policy`` threads them into ``RawPriceReplay`` and
    ``TableTelemetry`` so the pool walks one trajectory."""

    def __init__(self, ctx=None):
        ctx = ctx or multiprocessing.get_context("fork")
        self.price_counter = SharedCounter(ctx)
        self.table_counter = SharedCounter(ctx)


# --------------------------------------------------------------- snapshots


def worker_snapshot(policy, worker_id: int | None = None) -> dict:
    """One worker's control-plane snapshot: the existing ``/stats`` body
    (decision counts, ring percentiles, breakers, shed/reroute) plus the
    raw lifetime histogram — the one piece ``/stats`` doesn't carry and
    the only one that merges exactly across workers — plus the worker's
    policy generation (graftroll: a rolling promote is observable per
    worker) and trace-writer counters when a trace log is attached."""
    cumulative, total_sum, count = policy.stats.histogram()
    trace = getattr(policy, "trace", None)
    # graftlens: raw per-phase lifetime histograms (the one shape that
    # merges exactly across workers) and the SLO snapshot (window counts
    # merge via slo.merge_snapshots). Both None on pre-graftlens or
    # spans-off policies — aggregation tolerates the gap.
    phases = None
    if getattr(policy, "spans_enabled", False):
        phases = {}
        for phase, stats in policy.phase_stats.items():
            p_cum, p_sum, p_count = stats.histogram()
            phases[phase] = {"cumulative": p_cum, "sum": p_sum,
                             "count": p_count}
    tracker = getattr(policy, "slo", None)
    return {
        "schema": SNAPSHOT_SCHEMA,
        "worker_id": worker_id,
        "pid": os.getpid(),
        "generation": getattr(policy, "generation", 0),
        "stats": policy.statistics(),
        "trace": trace.snapshot() if trace is not None else None,
        "histogram": {
            "cumulative": cumulative,
            "sum": total_sum,
            "count": count,
        },
        "phases": phases,
        "slo": tracker.snapshot() if tracker is not None else None,
    }


class _HistogramView:
    """Adapts a snapshot's histogram dict to the ``.histogram()`` shape
    ``LatencyStats.merged_histogram`` consumes, so the pool aggregation
    literally reuses the method that pinned the multi-worker scrape
    story (extender.py)."""

    def __init__(self, hist: dict):
        self._hist = hist

    def histogram(self):
        return (
            list(self._hist["cumulative"]),
            float(self._hist["sum"]),
            int(self._hist["count"]),
        )


def quantiles_from_histogram(cumulative: list, qs=(0.5, 0.9, 0.99)) -> dict:
    """Prometheus ``histogram_quantile``-style estimates from cumulative
    bucket counts over ``LatencyStats.BUCKETS``.

    Linear interpolation inside the winning bucket; the first bucket
    interpolates from 0, and a quantile landing in the +Inf bucket
    reports the highest finite bound (exactly histogram_quantile's
    behavior — the histogram carries no information above it). Ring
    percentiles do not merge across workers; these do, because bucket
    sums are the union stream's buckets (``merged_histogram``).
    Returns ``{"p50_ms": ..., ...}`` keyed like ``percentiles_ms``.
    """
    bounds = LatencyStats.BUCKETS
    count = cumulative[-1] if cumulative else 0
    if count <= 0:
        return {"count": 0}
    out = {"count": int(count)}
    for q in qs:
        rank = q * count
        idx = next(i for i, c in enumerate(cumulative) if c >= rank)
        if idx >= len(bounds):  # +Inf bucket: no upper bound to lerp to
            value = bounds[-1]
        else:
            lo = bounds[idx - 1] if idx > 0 else 0.0
            hi = bounds[idx]
            prev = cumulative[idx - 1] if idx > 0 else 0
            span = cumulative[idx] - prev
            frac = (rank - prev) / span if span > 0 else 1.0
            value = lo + (hi - lo) * frac
        out[f"p{int(q * 100)}_ms"] = round(value * 1e3, 4)
    return out


def _weighted_fraction(snapshots: list, key: str) -> float | None:
    """Request-weighted pool fraction of a per-worker fraction gauge
    (shed/reroute): each worker's fraction is over ITS lifetime
    decisions, so the pool value weights by decision count. ``None``
    when no worker reports the gauge (backend doesn't track it)."""
    num = den = 0.0
    seen = False
    for snap in snapshots:
        frac = snap["stats"].get(key)
        if frac is None:
            continue
        seen = True
        weight = sum(snap["stats"].get("decisions", {}).values())
        num += frac * weight
        den += weight
    if not seen:
        return None
    return round(num / den, 4) if den else 0.0


def _merged_breakers(snapshots: list) -> dict:
    by_name: dict = {}
    for snap in snapshots:
        for name, breaker_snap in snap["stats"].get("breakers", {}).items():
            by_name.setdefault(name, []).append(breaker_snap)
    return {
        name: CircuitBreaker.merge_snapshots(snaps)
        for name, snaps in sorted(by_name.items())
    }


def _consensus(snapshots: list, key: str) -> str:
    """One value when all workers agree; a sorted '/'-join when they
    drifted (e.g. a respawned worker fell back to greedy on a corrupt
    checkpoint) — divergence must be VISIBLE on the pool scrape, not
    averaged away."""
    values = sorted({str(s["stats"].get(key)) for s in snapshots})
    return values[0] if len(values) == 1 else "/".join(values)


def merge_worker_histograms(snapshots: list) -> tuple[list, float, int]:
    """``LatencyStats.merged_histogram`` over snapshot dicts — the ONE
    place the pool's union histogram is computed (``/stats`` and
    ``/metrics`` must never drift)."""
    return LatencyStats.merged_histogram(
        [_HistogramView(s["histogram"]) for s in snapshots]
    )


def merge_phase_histograms(snapshots: list) -> dict:
    """graftlens: the pool's per-phase union histograms —
    ``{phase: (cumulative, sum, count)}`` via the SAME
    ``merged_histogram`` machinery as the end-to-end latency (bucket
    sums of per-worker cumulative counts ARE the union stream's
    buckets). Workers without spans (pre-graftlens, ``--no-spans``)
    simply contribute nothing; empty result when no worker spans."""
    by_phase: dict = {}
    for snap in snapshots:
        for phase, hist in (snap.get("phases") or {}).items():
            by_phase.setdefault(phase, []).append(_HistogramView(hist))
    return {
        phase: LatencyStats.merged_histogram(views)
        for phase, views in sorted(by_phase.items())
    }


def merge_worker_slo(snapshots: list) -> dict | None:
    """Pool-wide SLO snapshot (``slo.merge_snapshots``): window counts
    and lifetime counters sum, burn rates recompute from the sums.
    ``None`` when no worker tracks SLOs."""
    return slo_mod.merge_snapshots(
        [s.get("slo") for s in snapshots if s.get("slo")]
    )


def merge_worker_drift(snapshots: list) -> dict | None:
    """Pool-wide drift snapshot (``drift.merge_snapshots``): bucket
    counts sum, Welford moments merge, PSI/KS distances RECOMPUTE from
    the merged counts — the ``merged_histogram`` discipline, never an
    average of per-worker distances. Workers without a ``drift``
    section (version skew, ``--drift`` off) contribute nothing;
    ``None`` when no worker tracks drift."""
    return drift_mod.merge_snapshots(
        [s.get("stats", {}).get("drift") for s in snapshots]
    )


def sum_worker_shadow(snapshots: list) -> dict | None:
    """Pool-wide shadow-scoring section (``drift.sum_shadow``):
    lifetime counters and delta-histogram buckets sum; agreement rate
    recomputes from the sums. ``None`` when no worker runs a shadow
    checkpoint."""
    return drift_mod.sum_shadow(
        [s.get("stats", {}).get("shadow") for s in snapshots
         if s.get("stats", {}).get("shadow")]
    )


def aggregate_stats(snapshots: list, pool: dict, merged=None,
                    phase_hists=None) -> dict:
    """The pool-wide ``GET /stats`` body from per-worker snapshots.

    Decision counts sum; latency percentiles come from
    ``LatencyStats.merged_histogram`` (lifetime — the only cross-worker
    merge that is exact; each worker's reset-scoped ring percentiles ride
    in ``workers[]``); shed/reroute fractions are request-weighted;
    breakers merge per boundary via ``CircuitBreaker.merge_snapshots``.
    ``merged``/``phase_hists`` let a caller that already merged the
    (end-to-end / per-phase) histograms — the ``/metrics`` exposition —
    share the computation.
    """
    merged_cum, merged_sum, merged_count = (
        merged if merged is not None else merge_worker_histograms(snapshots)
    )
    decisions: dict = {}
    for snap in snapshots:
        for cloud, n in snap["stats"].get("decisions", {}).items():
            decisions[cloud] = decisions.get(cloud, 0) + n
    total = sum(decisions.values())
    latency = quantiles_from_histogram(merged_cum)
    latency["source"] = "merged_histogram"
    latency["sum_seconds"] = round(merged_sum, 6)
    # Same lifetime keys as the single-process /stats body, so
    # tools/decisionview reads one shape from either plane.
    latency["lifetime_mean_ms"] = (round(merged_sum / merged_count * 1e3, 4)
                                   if merged_count else None)
    latency["lifetime_count"] = merged_count
    out = {
        "pool": dict(pool),
        "backend": _consensus(snapshots, "backend") if snapshots else None,
        "family": _consensus(snapshots, "family") if snapshots else None,
        "decisions": decisions,
        "choice_fractions": {
            c: (n / total if total else 0.0) for c, n in decisions.items()
        },
        "latency": latency,
        "breakers": _merged_breakers(snapshots),
        "workers": [
            {
                "worker_id": s.get("worker_id"),
                "pid": s.get("pid"),
                "generation": s.get("generation", 0),
                "decisions_total": sum(
                    s["stats"].get("decisions", {}).values()
                ),
                "latency": s["stats"].get("latency", {}),
            }
            for s in snapshots
        ],
    }
    for key in ("shed_fraction", "reroute_fraction"):
        frac = _weighted_fraction(snapshots, key)
        if frac is not None:
            out[key] = frac
    dropped = [s["stats"]["placements_dropped"] for s in snapshots
               if "placements_dropped" in s["stats"]]
    if dropped:
        out["placements_dropped"] = sum(dropped)
    fail_open = [s["stats"]["fail_open_total"] for s in snapshots
                 if "fail_open_total" in s["stats"]]
    if fail_open:
        out["fail_open_total"] = sum(fail_open)
    # graftlens: per-phase pool quantiles + lifetime means from the
    # merged phase histograms (exact across workers), and the merged
    # SLO snapshot.
    if phase_hists is None:
        phase_hists = merge_phase_histograms(snapshots)
    if phase_hists:
        phases = {}
        for phase, (cum, p_sum, p_count) in phase_hists.items():
            entry = quantiles_from_histogram(cum)
            entry["source"] = "merged_histogram"
            entry["lifetime_mean_ms"] = (round(p_sum / p_count * 1e3, 4)
                                         if p_count else None)
            entry["lifetime_count"] = p_count
            phases[phase] = entry
        out["phases"] = phases
    # graftfleet: the raw merged buckets ride on the body so a fleet
    # controller can re-merge pool scrapes with the SAME machinery the
    # pool applies to workers — quantiles do not merge, bucket counts
    # do. Additive; version-skewed scrapers simply ignore the key, and
    # a version-skewed pool missing it contributes an empty histogram
    # (the optional-phase rule, one level up).
    out["raw"] = {
        "histogram": {
            "cumulative": [int(c) for c in merged_cum],
            "sum": merged_sum,
            "count": int(merged_count),
        },
        "phases": {
            phase: {
                "cumulative": [int(c) for c in cum],
                "sum": p_sum,
                "count": int(p_count),
            }
            for phase, (cum, p_sum, p_count) in (phase_hists or {}).items()
        },
    }
    merged_slo = merge_worker_slo(snapshots)
    if merged_slo is not None:
        out["slo"] = merged_slo
    # graftdrift: merged drift sketches (counts sum, distances
    # recompute) and summed shadow-scoring counters ride the pool body
    # under the same keys as the single-process /stats, so driftview
    # reads one shape from either plane.
    merged_drift = merge_worker_drift(snapshots)
    if merged_drift is not None:
        out["drift"] = merged_drift
    shadow = sum_worker_shadow(snapshots)
    if shadow is not None:
        out["shadow"] = shadow
    fastpath = sum_fastpath(snapshots)
    if fastpath is not None:
        out["fastpath"] = fastpath
    trace = _summed_trace(snapshots)
    if trace is not None:
        out["trace"] = trace
    return out


def sum_fastpath(snapshots: list) -> dict | None:
    """Pool-wide graftfwd section: lifetime counters sum exactly across
    workers (each worker owns its cache/batcher); the cache hit rate and
    batch occupancy recompute from the sums (rates are not linear — the
    ``merged_histogram`` discipline). The int8 agreement reports the
    MINIMUM across workers: the gate bar must hold for every worker, so
    the pool gauge shows the worst one. ``None`` when no worker runs a
    fast-path lever."""
    sections = [s["stats"]["fastpath"] for s in snapshots
                if s.get("stats", {}).get("fastpath")]
    if not sections:
        return None
    out: dict = {}
    caches = [sec["cache"] for sec in sections if "cache" in sec]
    if caches:
        cache = {key: sum(c.get(key, 0) for c in caches)
                 for key in ("hits_total", "misses_total",
                             "invalidations_total", "entries")}
        requests = cache["hits_total"] + cache["misses_total"]
        cache["hit_rate"] = (round(cache["hits_total"] / requests, 4)
                             if requests else None)
        out["cache"] = cache
    batches = [sec["batch"] for sec in sections if "batch" in sec]
    if batches:
        batch = {key: sum(b.get(key, 0) for b in batches)
                 for key in ("requests_total", "batches_total",
                             "coalesced_total")}
        batch["max_occupancy"] = max(b.get("max_occupancy", 0)
                                     for b in batches)
        occupancy_sum = sum(
            (b.get("mean_occupancy") or 0) * b.get("batches_total", 0)
            for b in batches)
        batch["mean_occupancy"] = (
            round(occupancy_sum / batch["batches_total"], 3)
            if batch["batches_total"] else None)
        out["batch"] = batch
    int8 = [sec["int8"] for sec in sections if "int8" in sec]
    if int8:
        out["int8"] = {
            "agreement": min(entry["agreement"] for entry in int8),
            "scales_recorded": max(entry.get("scales_recorded", 0)
                                   for entry in int8),
        }
    return out


def _summed_trace(snapshots: list) -> dict | None:
    """Pool-wide trace-writer counters: per-worker monotonic counts sum
    exactly (each worker owns its own segment stream). ``None`` when no
    worker carries a trace log."""
    traced = [s["trace"] for s in snapshots if s.get("trace")]
    if not traced:
        return None
    keys = ("records_total", "written_total", "dropped_total",
            "write_errors_total", "segments_total",
            "segments_pruned_total")
    return {k: sum(t.get(k, 0) for t in traced) for k in keys}


def aggregate_metrics(snapshots: list, pool: dict) -> str:
    """Pool-wide Prometheus exposition: the SAME metric names the
    single-process plane exports (one scrape config serves both), with
    counters summed, ONE merged histogram, breaker state as the
    per-boundary max, and ``_pool_*`` series carrying the per-worker
    labels that matter (liveness, decision share, restarts)."""
    p = METRIC_PREFIX
    merged_cum, merged_sum, merged_count = merge_worker_histograms(snapshots)
    phase_hists = merge_phase_histograms(snapshots)
    stats = aggregate_stats(snapshots, pool,
                            merged=(merged_cum, merged_sum, merged_count),
                            phase_hists=phase_hists)
    lines = [
        f"# HELP {p}_decisions_total Placement decisions by cloud "
        "(summed across pool workers).",
        f"# TYPE {p}_decisions_total counter",
    ]
    for cloud, n in sorted(stats["decisions"].items()):
        lines.append(f'{p}_decisions_total{{cloud="{cloud}"}} {n}')
    lines += [
        f"# HELP {p}_decision_latency_seconds Server-side decision "
        "latency (merged across pool workers; lifetime histogram).",
        f"# TYPE {p}_decision_latency_seconds histogram",
    ]
    bounds = [f"{b:g}" for b in LatencyStats.BUCKETS] + ["+Inf"]
    for bound, c in zip(bounds, merged_cum or [0] * len(bounds)):
        lines.append(
            f'{p}_decision_latency_seconds_bucket{{le="{bound}"}} {c}'
        )
    lines.append(f"{p}_decision_latency_seconds_sum {merged_sum:.9g}")
    lines.append(f"{p}_decision_latency_seconds_count {merged_count}")
    # graftlens: one merged histogram per phase and the merged SLO
    # gauges — the SAME exposition helpers as the single-process plane
    # (extender.phase_metric_lines/slo_metric_lines), so the two planes
    # cannot drift.
    if phase_hists:
        lines += phase_metric_lines(p, phase_hists)
    if "slo" in stats:
        lines += slo_metric_lines(p, stats["slo"])
    if "drift" in stats:
        # graftdrift: the SAME exposition helpers as the single-process
        # plane, fed the merged drift section — distances were already
        # recomputed from the summed buckets in aggregate_stats.
        lines += drift_mod.drift_metric_lines(p, stats["drift"])
    if "shadow" in stats:
        lines += drift_mod.shadow_metric_lines(p, stats["shadow"])
    if "fastpath" in stats:
        # graftfwd: the SAME exposition helper as the single-process
        # plane, fed the pool-summed section (one scrape config).
        lines += fastpath_metric_lines(p, stats["fastpath"])
    for key, help_text in (
        ("shed_fraction", "Pool request-weighted fraction served off the "
                          "primary path by the load-aware backends."),
        ("reroute_fraction", "Pool request-weighted fraction of "
                             "latency-router decisions served host-side."),
    ):
        if key in stats:
            lines += [
                f"# HELP {p}_{key} {help_text}",
                f"# TYPE {p}_{key} gauge",
                f"{p}_{key} {stats[key]:.9g}",
            ]
    if "placements_dropped" in stats:
        lines += [
            f"# HELP {p}_placements_dropped_total Dry-run placements "
            "dropped by the bounded async queues (pool total).",
            f"# TYPE {p}_placements_dropped_total counter",
            f"{p}_placements_dropped_total {stats['placements_dropped']}",
        ]
    if "fail_open_total" in stats:
        lines += [
            f"# HELP {p}_fail_open_total Requests answered by a fail-open "
            "path (open breaker or backend raise), summed across workers.",
            f"# TYPE {p}_fail_open_total counter",
            f"{p}_fail_open_total {stats['fail_open_total']}",
        ]
    if "trace" in stats:
        trace = stats["trace"]
        for key, help_text in (
            ("records_total", "Decision records appended to the durable "
                              "trace log (pool lifetime; /stats/reset "
                              "never clears it)."),
            ("dropped_total", "Trace records dropped by the bounded "
                              "queues' drop-oldest backpressure."),
            ("write_errors_total", "Trace segment writes that failed "
                                   "(records dropped, serving unaffected)."),
            ("segments_total", "Trace segments sealed (fsync + rename), "
                               "pool total."),
            ("segments_pruned_total", "Sealed segments dropped by the "
                                      "--trace-max-segments retention "
                                      "cap, pool total."),
        ):
            lines += [
                f"# HELP {p}_trace_{key} {help_text}",
                f"# TYPE {p}_trace_{key} counter",
                f"{p}_trace_{key} {trace[key]}",
            ]
    breakers = stats["breakers"]
    lines += [
        f"# HELP {p}_circuit_state Circuit breaker state per host-I/O "
        "boundary, MAX across workers (0=closed, 1=half_open, 2=open): "
        "a dependency down anywhere in the pool shows here.",
        f"# TYPE {p}_circuit_state gauge",
    ]
    for name, snap in breakers.items():
        code = CircuitBreaker.STATE_CODES[snap["state"]]
        lines.append(f'{p}_circuit_state{{breaker="{name}"}} {code}')
    lines += [
        f"# HELP {p}_circuit_opens_total Times each breaker tripped open "
        "(summed across workers, lifetime).",
        f"# TYPE {p}_circuit_opens_total counter",
    ]
    for name, snap in breakers.items():
        lines.append(
            f'{p}_circuit_opens_total{{breaker="{name}"}} '
            f'{snap["opens_total"]}')
    # Per-worker series: identity matters for liveness and load balance,
    # nowhere else — everything above stays pool-scoped so dashboards
    # built against the single-process plane keep working.
    lines += [
        f"# HELP {p}_pool_workers Configured worker count.",
        f"# TYPE {p}_pool_workers gauge",
        f"{p}_pool_workers {pool.get('workers', len(snapshots))}",
        f"# HELP {p}_pool_workers_alive Workers that answered this scrape.",
        f"# TYPE {p}_pool_workers_alive gauge",
        f"{p}_pool_workers_alive {pool.get('alive', len(snapshots))}",
        f"# HELP {p}_pool_restarts_total Dead workers restarted by the "
        "supervisor (lifetime).",
        f"# TYPE {p}_pool_restarts_total counter",
        f"{p}_pool_restarts_total {pool.get('restarts_total', 0)}",
    ]
    # graftroll: the rollout generation labels the drill reads off one
    # scrape — pool generation, per-worker generation, the promote/
    # rollback lifetime counters (monotonic: /stats/reset never touches
    # them), and whether a rollout is in flight (docs/serving.md drill).
    rollout = pool.get("rollout", {})
    lines += [
        f"# HELP {p}_pool_generation Policy generation the pool serves "
        "(bumped per successful promote).",
        f"# TYPE {p}_pool_generation gauge",
        f"{p}_pool_generation {pool.get('generation', 0)}",
        f"# HELP {p}_pool_promotions_total Successful checkpoint "
        "promotions (lifetime).",
        f"# TYPE {p}_pool_promotions_total counter",
        f"{p}_pool_promotions_total {rollout.get('promotions_total', 0)}",
        f"# HELP {p}_pool_rollbacks_total Rollouts rolled back by a "
        "failed canary/health gate (lifetime).",
        f"# TYPE {p}_pool_rollbacks_total counter",
        f"{p}_pool_rollbacks_total {rollout.get('rollbacks_total', 0)}",
        f"# HELP {p}_pool_promote_refusals_total Promotions refused "
        "before any worker was touched (corrupt/unverifiable candidate).",
        f"# TYPE {p}_pool_promote_refusals_total counter",
        f"{p}_pool_promote_refusals_total "
        f"{rollout.get('refusals_total', 0)}",
        f"# HELP {p}_pool_rollout_state Rollout state machine "
        "(0=idle, 1=promoting, 2=rolling_back).",
        f"# TYPE {p}_pool_rollout_state gauge",
        f"{p}_pool_rollout_state "
        f"{STATE_CODES.get(rollout.get('state'), 0)}",
        f"# HELP {p}_pool_worker_generation Per-worker policy generation "
        "(diverges from pool generation only mid-rollout).",
        f"# TYPE {p}_pool_worker_generation gauge",
    ]
    for snap in snapshots:
        lines.append(
            f'{p}_pool_worker_generation{{worker="{snap.get("worker_id")}"}} '
            f'{snap.get("generation", 0)}')
    lines += [
        f"# HELP {p}_pool_worker_up Per-worker liveness (answered this "
        "scrape).",
        f"# TYPE {p}_pool_worker_up gauge",
    ]
    answered = {s.get("worker_id") for s in snapshots}
    for worker_id in range(pool.get("workers", len(snapshots))):
        lines.append(
            f'{p}_pool_worker_up{{worker="{worker_id}"}} '
            f"{1 if worker_id in answered else 0}")
    lines += [
        f"# HELP {p}_pool_worker_decisions_total Per-worker decision "
        "share (kernel connection balancing is visible here).",
        f"# TYPE {p}_pool_worker_decisions_total counter",
    ]
    for snap in snapshots:
        n = sum(snap["stats"].get("decisions", {}).values())
        lines.append(
            f'{p}_pool_worker_decisions_total{{worker="{snap.get("worker_id")}"}} {n}')
    lines += [
        f"# HELP {p}_info Serving backend and decision family.",
        f"# TYPE {p}_info gauge",
        f'{p}_info{{backend="{stats["backend"]}",family="{stats["family"]}",'
        f'workers="{pool.get("workers", len(snapshots))}"}} 1',
    ]
    return "\n".join(lines) + "\n"


# ----------------------------------------------------------- control plane


def _control_listener() -> tuple[socket.socket, str]:
    """``(listener, address_spec)`` for the supervisor's control socket.

    AF_UNIX under a private tempdir where the platform has it (one file,
    no port exhaustion, filesystem permissions); loopback TCP otherwise.
    The spec string (``unix:<path>`` / ``tcp:<host>:<port>``) is what
    workers get — it survives fork trivially.
    """
    if hasattr(socket, "AF_UNIX"):
        path = os.path.join(
            tempfile.mkdtemp(prefix="graftserve-"), "control.sock"
        )
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        sock.bind(path)
        sock.listen(_LISTEN_BACKLOG)
        return sock, f"unix:{path}"
    sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    sock.bind(("127.0.0.1", 0))
    sock.listen(_LISTEN_BACKLOG)
    host, port = sock.getsockname()
    return sock, f"tcp:{host}:{port}"


def _control_connect(spec: str) -> socket.socket:
    kind, _, rest = spec.partition(":")
    if kind == "unix":
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        sock.connect(rest)
        return sock
    host, _, port = rest.rpartition(":")
    sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    sock.connect((host, int(port)))
    return sock


def _send_line(sock: socket.socket, payload: dict) -> None:
    sock.sendall(json.dumps(payload).encode() + b"\n")


def _worker_control_loop(policy, server, sock, worker_id: int) -> None:
    """Answer supervisor commands over the control connection; treat EOF
    (or any socket error) as 'the supervisor is gone' and shut the
    worker down — the supervisor owns the pool's lifecycle, and orphan
    workers would hold the data port forever."""
    try:
        reader = sock.makefile("rb")
        for line in reader:
            try:
                msg = json.loads(line)
                cmd = msg.get("cmd")
            except (json.JSONDecodeError, AttributeError):
                _send_line(sock, {"error": "bad command"})
                continue
            if cmd == "snapshot":
                _send_line(sock, worker_snapshot(policy, worker_id))
            elif cmd == "reset":
                _send_line(sock, {"ok": True, **policy.reset_stats()})
            elif cmd == "ping":
                _send_line(sock, {"ok": True})
            elif cmd == "probe":
                # graftroll warm-up gate: one REAL decision through the
                # exact decide path (rollout.py targets a specific
                # worker here — the data port is kernel-balanced and
                # cannot). warmup_probe never submits a placement and
                # tags its trace record, so synthetic gate traffic
                # cannot contaminate the kube API or the trace.
                _send_line(sock, {"ok": True, **policy.warmup_probe()})
            elif cmd == "fastpath":
                # graftfwd promote gate: flush this worker's score
                # cache and re-run the int8 agreement check (rollout.py
                # calls it on every respawned worker BEFORE the canary
                # serves; ok=False fails the gate -> rollback). Policy
                # stand-ins without the method have no levers to
                # verify — vacuously ok, like spans-less snapshots.
                verify = getattr(policy, "fastpath_verify", None)
                ack = verify() if verify is not None else {"ok": True}
                ack.setdefault("ok", False)
                _send_line(sock, ack)
            elif cmd == "flip_tables":
                # graftdrift regime flip: swap this worker's price-replay
                # table in place (same loader contract as --telemetry-data;
                # the shared replay counter keeps walking, so all workers
                # of one pool flip onto the same trajectory).
                try:
                    _send_line(sock, {"ok": True,
                                      **policy.flip_tables(msg.get("path"))})
                except Exception as exc:  # noqa: BLE001 - report, don't die
                    logger.warning("worker %d refused flip_tables: %s",
                                   worker_id, exc)
                    _send_line(sock, {"ok": False, "error": str(exc)})
            elif cmd == "drift_ref":
                # Load a frozen drift reference (drift.save_reference
                # output) into this worker's tracker; fingerprint-verified
                # by load_reference, so a truncated file is refused.
                try:
                    _send_line(sock, {
                        "ok": True,
                        **policy.set_drift_reference(msg.get("path")),
                    })
                except Exception as exc:  # noqa: BLE001 - report, don't die
                    logger.warning("worker %d refused drift_ref: %s",
                                   worker_id, exc)
                    _send_line(sock, {"ok": False, "error": str(exc)})
            elif cmd == "shadow":
                # graftpilot promote gate: arm (path = candidate run dir)
                # or disarm (path = null) runtime shadow scoring on this
                # worker. Arming swaps in a FRESH scorer — zeroed
                # counters, so the pool-summed paired verdict covers
                # exactly the gated window.
                try:
                    _send_line(sock, {
                        "ok": True,
                        **policy.set_shadow(msg.get("path")),
                    })
                except Exception as exc:  # noqa: BLE001 - report, don't die
                    logger.warning("worker %d refused shadow: %s",
                                   worker_id, exc)
                    _send_line(sock, {"ok": False, "error": str(exc)})
            else:
                _send_line(sock, {"error": f"unknown cmd {cmd!r}"})
    except OSError:
        pass  # connection torn down mid-command: same as EOF below
    logger.info("worker %d lost its control connection; shutting down",
                worker_id)
    threading.Thread(target=server.shutdown, daemon=True).start()


def _limit_blas_threads(n: int, worker_id: int):
    """Clamp the worker's BLAS intra-op thread pools to ``n``.

    With a worker pool, PROCESSES are the parallelism: the default
    OpenBLAS pool (one thread per core, per worker) oversubscribes the
    host N-fold and measurably LOSES even single-stream (2-thread
    OpenBLAS: 124 ms/decide at N=1024 on this 2-core container vs 71 ms
    pinned to 1 — pthread handoff costs more than the second core
    brings; docs/serving.md). numpy is already loaded when the worker
    forks, so the env vars are too late — threadpoolctl talks to the
    loaded libraries' own set_num_threads APIs. Best-effort: without
    threadpoolctl the worker logs and serves with library defaults.
    Returns the controller (kept alive by the caller) or None.
    """
    try:
        from threadpoolctl import threadpool_limits

        limiter = threadpool_limits(limits=n)
        logger.info("worker %d: BLAS pools limited to %d thread(s)",
                    worker_id, n)
        return limiter
    except Exception:  # noqa: BLE001 - optional dependency / odd BLAS
        logger.warning(
            "worker %d: threadpoolctl unavailable; BLAS thread pools "
            "keep library defaults — set OPENBLAS_NUM_THREADS/"
            "OMP_NUM_THREADS before starting the pool to avoid "
            "oversubscription", worker_id)
        return None


def _worker_main(worker_id: int, n_workers: int, policy_factory, shared,
                 host: str, port: int, listener, reuse_port: bool,
                 control_spec: str, blas_threads: int = 0,
                 spec: WorkerSpec | None = None,
                 takes_spec: bool = False, front: str = "threading") -> None:
    """The forked worker body: build the policy, serve the data port
    (own SO_REUSEPORT listener, or the inherited pre-fork socket), and
    answer the supervisor's control commands. Any startup failure exits
    nonzero — the supervisor sees the death and applies its backoff.
    ``spec`` (graftroll) names the generation/checkpoint this worker
    serves; spec-aware factories get it as a third argument."""
    spec = spec or WorkerSpec()
    # The supervisor's signal handlers were inherited across fork —
    # running THEM here would make a terminated child call the
    # supervisor's pool.shutdown() (SIGTERM-ing siblings, unlinking the
    # control socket), so drop to defaults FIRST. The graceful drain
    # handler replaces SIG_DFL below, once there is a server to drain:
    # a terminate landing before that (slow checkpoint restore) kills a
    # worker that was serving nothing, which loses nothing.
    signal.signal(signal.SIGTERM, signal.SIG_DFL)
    signal.signal(signal.SIGINT, signal.SIG_IGN)  # ^C goes to supervisor
    limiter = _limit_blas_threads(blas_threads, worker_id) \
        if blas_threads > 0 else None
    try:
        policy = (policy_factory(worker_id, shared, spec) if takes_spec
                  else policy_factory(worker_id, shared))
        policy.pool_info = {"workers": n_workers, "worker_id": worker_id,
                            "generation": spec.generation}
        policy.generation = spec.generation
        if reuse_port:
            server = make_server(policy, host, port, reuse_port=True,
                                 front=front)
            if listener is not None:
                listener.close()  # the supervisor's startup placeholder
        else:
            server = make_server(policy, host, port,
                                 inherited_socket=listener, front=front)
        # Drainable handlers: ThreadingHTTPServer's daemon handler
        # threads are NOT tracked by socketserver's _Threads, so
        # server_close() would join nothing and an in-flight request
        # could race the trace log's close (answered but never
        # recorded). Non-daemon threads make the shutdown drain real;
        # a truly wedged handler is bounded by the supervisor's
        # terminate→join(10 s)→kill escalation.
        server.daemon_threads = False
        def _graceful_stop(signum, frame):  # noqa: ARG001 (signal API)
            threading.Thread(target=server.shutdown, daemon=True).start()

        # Graceful drain from here on (and installed BEFORE the
        # control-plane hello: the rollout controller may terminate this
        # worker the moment it appears): a deliberate SIGTERM unwinds
        # serve_forever so the finally below drains in-flight requests
        # and seals the trace log — a SIG_DFL kill would strand both.
        signal.signal(signal.SIGTERM, _graceful_stop)
        control = _control_connect(control_spec)
        _send_line(control, {
            "hello": True, "worker_id": worker_id, "pid": os.getpid(),
            "port": server.server_address[1],
        })
    except Exception:
        logger.exception("worker %d failed to start", worker_id)
        raise SystemExit(1)
    threading.Thread(
        target=_worker_control_loop, args=(policy, server, control, worker_id),
        daemon=True,
    ).start()
    try:
        server.serve_forever()
    finally:
        # Drain before dying: server_close() drops the listener out of
        # the SO_REUSEPORT balancing group and JOINS in-flight handler
        # threads (ThreadingHTTPServer.block_on_close), so a request a
        # dying worker already accepted is answered, not reset — the
        # rolling-restart zero-failed-requests bar depends on it.
        try:
            server.server_close()
        except OSError:
            pass
        control.close()
        trace = getattr(policy, "trace", None)
        if trace is not None:
            trace.close()  # drain + seal: sealed segments replay fully
        del limiter  # the BLAS clamp lives exactly as long as serving


# -------------------------------------------------------------- supervisor


def _accepts_spec(factory) -> bool:
    """True when a policy factory NAMES a third positional parameter —
    the graftroll :class:`WorkerSpec` (generation + checkpoint). Legacy
    ``(worker_id, shared)`` factories are detected and served the old
    call shape, so every existing embedder keeps working unchanged.
    Deliberately conservative: ``*args`` and unresolvable signatures
    stay legacy too — a pre-graftroll ``*args`` factory could TAKE a
    third argument but was never written to expect one, and a wrong
    guess here kills every worker at startup."""
    try:
        sig = inspect.signature(factory)
    except (TypeError, ValueError):  # builtins/C callables: stay legacy
        return False
    positional = [
        p for p in sig.parameters.values()
        if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)
    ]
    return len(positional) >= 3


class _WorkerSlot:
    """Supervisor-side state for one worker index."""

    def __init__(self, worker_id: int, backoff: list,
                 spec: WorkerSpec | None = None):
        self.worker_id = worker_id
        self.process = None
        self.conn: socket.socket | None = None
        self.conn_lock = threading.Lock()
        self.deaths = 0
        self.last_spawn = 0.0
        self.failed = False
        self.backoff = backoff  # RetryPolicy.delays() schedule
        # graftroll: what this slot serves (generation + checkpoint). The
        # monitor respawns a crashed worker onto ITS spec — mid-rollout a
        # dead canary resumes on the candidate generation until the gate
        # decides; `hold` marks a slot the rollout controller is
        # deliberately operating on, so the monitor never races it.
        self.spec = spec or WorkerSpec()
        self.hold = False

    @property
    def alive(self) -> bool:
        return self.process is not None and self.process.is_alive()


class ServingPool:
    """Supervisor for a pool of extender worker processes (module doc).

    ``policy_factory(worker_id, shared) -> ExtenderPolicy`` runs INSIDE
    each forked worker (fork start method: no pickling), so checkpoint
    restore and backend compiles happen per worker, off the supervisor.
    ``mode``: ``"auto"`` picks SO_REUSEPORT when the platform has it,
    ``"reuseport"``/``"inherit"`` force one (inherit is the fallback and
    stays testable everywhere).
    """

    def __init__(self, policy_factory, workers: int, host: str = "0.0.0.0",
                 port: int = 8787, control_host: str = "127.0.0.1",
                 control_port: int | None = None, mode: str = "auto",
                 restart_policy: RetryPolicy | None = None,
                 stable_after_s: float = 30.0, poll_interval_s: float = 0.2,
                 blas_threads: int | None = None,
                 initial_checkpoint: str | None = None,
                 fault_plan=None, rollout_opts: dict | None = None,
                 slo_enabled: bool = False, front: str = "threading"):
        if workers < 1:
            raise ValueError(f"workers={workers}: pass at least 1")
        if front not in ("threading", "asyncio"):
            raise ValueError(f"unknown front {front!r} (choose "
                             "'threading' or 'asyncio')")
        if blas_threads is not None and blas_threads < 0:
            raise ValueError(f"blas_threads={blas_threads}: pass a positive "
                             "count, 0 to leave library defaults, or None "
                             "for the cores//workers heuristic")
        if mode not in ("auto", "reuseport", "inherit"):
            raise ValueError(f"unknown pool mode {mode!r}")
        ctx = multiprocessing.get_context("fork")
        self._ctx = ctx
        self.workers = workers
        self.host, self.port = host, port
        self.control_host = control_host
        self.control_port = control_port
        have_reuseport = hasattr(socket, "SO_REUSEPORT")
        if mode == "reuseport" and not have_reuseport:
            raise ValueError("SO_REUSEPORT unavailable on this platform "
                             "(mode='auto' falls back to socket inheritance)")
        self.reuse_port = (mode == "reuseport"
                          or (mode == "auto" and have_reuseport))
        # graftfront: per-worker data-plane transport. The supervisor's
        # control plane stays ThreadingHTTPServer either way — it is a
        # scrape/promote plane, not the 10k-connection path.
        self.front = front
        self._factory = policy_factory
        # graftroll: spec-aware factories take (worker_id, shared, spec)
        # and can build a policy for ANY checkpoint generation; legacy
        # 2-arg factories keep working (they serve whatever they were
        # built to serve — a promote still bumps their generation label).
        self._factory_takes_spec = _accepts_spec(policy_factory)
        # The generation the POOL serves: bumped only after the last
        # worker of a rollout promotes, so crash-restarts always respawn
        # onto a generation every gate approved.
        self.generation = 0
        self.checkpoint = initial_checkpoint
        self.shared = PoolShared(ctx)
        # One backoff schedule per slot, straight off RetryPolicy — the
        # repo's single backoff implementation. Seeded per slot so the
        # jitter is deterministic under test yet decorrelated across
        # slots (simultaneous deaths don't respawn in lockstep).
        restart_policy = restart_policy or RetryPolicy(
            max_attempts=8, base_delay_s=0.5, max_delay_s=30.0, jitter=0.1,
        )
        self._slots = [
            _WorkerSlot(i, RetryPolicy(
                max_attempts=restart_policy.max_attempts,
                base_delay_s=restart_policy.base_delay_s,
                max_delay_s=restart_policy.max_delay_s,
                jitter=restart_policy.jitter, seed=i,
            ).delays(), spec=WorkerSpec(0, initial_checkpoint))
            for i in range(workers)
        ]
        # graftroll: the promotion/rollout controller (POST /promote on
        # the control plane; scheduler/rollout.py). `fault_plan` is the
        # chaos seam for the rollout.spawn/rollout.health sites.
        self.rollout = RolloutController(self, fault_plan=fault_plan,
                                         **(rollout_opts or {}))
        # graftlens: when the workers run an SLO tracker, the pool's
        # /healthz folds their merged burn state in (503 while degraded
        # — the control plane is the READINESS probe, so a burning pool
        # drains from endpoints instead of being liveness-killed).
        self.slo_enabled = slo_enabled
        self.stable_after_s = stable_after_s
        self.poll_interval_s = poll_interval_s
        # Worker processes ARE the pool's parallelism: the default gives
        # each worker its fair share of cores for intra-op BLAS (min 1)
        # instead of every worker spawning one thread per core and
        # oversubscribing the host workers-fold (_limit_blas_threads).
        if blas_threads is None:
            blas_threads = max(1, (os.cpu_count() or 1) // workers)
        self.blas_threads = blas_threads
        self.restarts_total = 0
        self._lock = threading.Lock()
        self._shutdown = threading.Event()
        self._listener: socket.socket | None = None
        self._control_sock: socket.socket | None = None
        self._control_spec = ""
        self._http: ThreadingHTTPServer | None = None
        self._threads: list = []

    # ------------------------------------------------------------ lifecycle

    def start(self, ready_timeout_s: float = 60.0) -> None:
        """Bind, fork all workers, wait until every worker has bound its
        listener and connected to the control plane, then (in reuseport
        mode) drop the supervisor's startup placeholder socket so the
        kernel only balances across sockets a worker actually accepts
        on. A failed start tears the partial pool down before raising —
        orphaned non-daemon workers would otherwise hold the data port
        and deadlock the supervisor's interpreter exit (multiprocessing
        joins non-daemon children at atexit, while the workers only exit
        on control EOF, i.e. after the supervisor is gone)."""
        try:
            self._start(ready_timeout_s)
        except BaseException:
            self.shutdown()
            raise

    def _start(self, ready_timeout_s: float = 60.0) -> None:
        # Always bind in the supervisor first: it resolves port 0 once
        # (every worker must share the SAME port) and holds the port so
        # nothing steals it between worker spawns. In reuseport mode the
        # placeholder never accepts and closes once the pool is ready.
        self._listener = _make_data_listener(self.host, self.port,
                                             self.reuse_port)
        self.port = self._listener.getsockname()[1]
        self._control_sock, self._control_spec = _control_listener()
        accept_thread = threading.Thread(target=self._accept_control,
                                         daemon=True)
        accept_thread.start()
        self._threads.append(accept_thread)
        for slot in self._slots:
            self._spawn(slot)
        deadline = time.monotonic() + ready_timeout_s
        connected = 0
        while time.monotonic() < deadline:
            with self._lock:
                connected = sum(1 for s in self._slots if s.conn is not None)
            if connected == self.workers:
                break
            if all(not s.alive for s in self._slots):
                raise RuntimeError(
                    "every pool worker died during startup — see worker "
                    "logs (a build_policy refusal, e.g. a wrong-family "
                    "checkpoint, kills all workers identically)"
                )
            time.sleep(0.02)
        else:
            raise RuntimeError(
                f"pool not ready after {ready_timeout_s:.0f}s: "
                f"{connected}/{self.workers} workers connected"
            )
        if self.reuse_port:
            self._listener.close()
            self._listener = None
        monitor = threading.Thread(target=self._monitor, daemon=True)
        monitor.start()
        self._threads.append(monitor)
        self._http = _make_control_server(
            self, self.control_host,
            self.port + 1 if self.control_port is None else self.control_port,
        )
        # The control plane serves on its own thread from the moment
        # start() returns — embedders (tests, notebooks) must not need
        # to dedicate a thread to serve_forever() just to be scrapeable.
        http_thread = threading.Thread(target=self._http.serve_forever,
                                       daemon=True)
        http_thread.start()
        self._threads.append(http_thread)

    def serve_forever(self) -> None:
        """Block until :meth:`shutdown` (the CLI's foreground loop)."""
        self._shutdown.wait()

    def shutdown(self) -> None:
        self._shutdown.set()
        if self._http is not None:
            threading.Thread(target=self._http.shutdown,
                             daemon=True).start()
        for slot in self._slots:
            proc = slot.process
            if proc is not None and proc.is_alive():
                proc.terminate()
        for slot in self._slots:
            proc = slot.process
            if proc is not None:
                proc.join(timeout=5.0)
                if proc.is_alive():
                    proc.kill()
                    proc.join(timeout=5.0)
            with slot.conn_lock:
                if slot.conn is not None:
                    slot.conn.close()
                    slot.conn = None
        for sock in (self._control_sock, self._listener):
            if sock is not None:
                sock.close()
        if self._control_spec.startswith("unix:"):
            path = self._control_spec[len("unix:"):]
            for target in (path, os.path.dirname(path)):
                try:
                    os.remove(target) if target == path else os.rmdir(target)
                except OSError:
                    pass

    @property
    def control_address(self) -> tuple[str, int]:
        return self._http.server_address[:2]

    # ------------------------------------------------------------- workers

    def _spawn(self, slot: _WorkerSlot) -> None:
        slot.last_spawn = time.monotonic()
        slot.process = self._ctx.Process(
            target=_worker_main,
            args=(slot.worker_id, self.workers, self._factory, self.shared,
                  self.host, self.port, self._listener, self.reuse_port,
                  self._control_spec, self.blas_threads, slot.spec,
                  self._factory_takes_spec, self.front),
            daemon=False,
            name=f"graftserve-worker-{slot.worker_id}",
        )
        slot.process.start()

    def _accept_control(self) -> None:
        while not self._shutdown.is_set():
            try:
                conn, _ = self._control_sock.accept()
            except OSError:
                return  # listener closed during shutdown
            try:
                conn.settimeout(5.0)
                hello = json.loads(conn.makefile("rb").readline())
                worker_id = int(hello["worker_id"])
                if not 0 <= worker_id < len(self._slots):
                    # Range check BEFORE indexing: on the loopback-TCP
                    # fallback any local process can reach this listener,
                    # and an IndexError here would kill the accept thread
                    # for the pool's lifetime (restarted workers could
                    # never rejoin); a negative id would silently alias
                    # an existing slot.
                    raise ValueError(f"worker_id {worker_id} out of range")
                conn.settimeout(None)
            except (OSError, ValueError, KeyError, TypeError):
                logger.warning("dropping control connection with bad hello")
                conn.close()
                continue
            with self._lock:
                slot = self._slots[worker_id]
                with slot.conn_lock:
                    if slot.conn is not None:
                        slot.conn.close()
                    slot.conn = conn
            logger.info("worker %d (pid %s) joined the control plane",
                        worker_id, hello.get("pid"))

    def _monitor(self) -> None:
        """Restart dead workers on the slot's RetryPolicy backoff
        schedule. A death after ``stable_after_s`` of uptime resets the
        slot's position in the schedule (the crash was not a loop); a
        slot that exhausts the schedule is marked failed and left down —
        a crash-looping worker must not flap forever, and /healthz makes
        the degradation visible. All slots failed ends the pool."""
        while not self._shutdown.is_set():
            time.sleep(self.poll_interval_s)
            for slot in self._slots:
                if (slot.failed or slot.hold or slot.alive
                        or self._shutdown.is_set()):
                    # `hold`: the rollout controller is deliberately
                    # replacing this worker — a "death" here is surgery,
                    # not a crash, and a concurrent monitor respawn would
                    # double-spawn the slot.
                    continue
                uptime = time.monotonic() - slot.last_spawn
                exitcode = (slot.process.exitcode
                            if slot.process is not None else None)
                with slot.conn_lock:
                    if slot.conn is not None:
                        slot.conn.close()
                        slot.conn = None
                if uptime >= self.stable_after_s:
                    slot.deaths = 0
                slot.deaths += 1
                if slot.deaths > len(slot.backoff):
                    slot.failed = True
                    logger.error(
                        "worker %d died %d times (last exitcode %s); "
                        "restart schedule exhausted — slot marked failed",
                        slot.worker_id, slot.deaths, exitcode)
                    if all(s.failed for s in self._slots):
                        logger.error("all pool workers failed; shutting "
                                     "down the pool")
                        threading.Thread(target=self.shutdown,
                                         daemon=True).start()
                        return
                    continue
                delay = slot.backoff[min(slot.deaths - 1,
                                         len(slot.backoff) - 1)]
                logger.warning(
                    "worker %d died (exitcode %s, uptime %.1fs); "
                    "restarting in %.2fs (death %d/%d)",
                    slot.worker_id, exitcode, uptime, delay, slot.deaths,
                    len(slot.backoff))
                if self._shutdown.wait(delay):
                    return
                if slot.hold or slot.alive:
                    # The rollout controller took the slot over during
                    # the backoff wait; its replacement supersedes ours.
                    continue
                with self._lock:
                    self.restarts_total += 1
                self._spawn(slot)

    # -------------------------------------------------------- control plane

    def _command(self, slot: _WorkerSlot, cmd: str,
                 timeout_s: float, args: dict | None = None) -> dict | None:
        with slot.conn_lock:
            conn = slot.conn
            if conn is None:
                return None
            try:
                conn.settimeout(timeout_s)
                _send_line(conn, {"cmd": cmd, **(args or {})})
                reader = conn.makefile("rb")
                line = reader.readline()
                conn.settimeout(None)
                if not line:
                    raise OSError("control EOF")
                return json.loads(line)
            except (OSError, ValueError):
                logger.warning("worker %d control %s failed; dropping its "
                               "connection", slot.worker_id, cmd)
                conn.close()
                slot.conn = None
                return None

    def _fanout(self, cmd: str, timeout_s: float,
                args: dict | None = None) -> list:
        """Issue ``cmd`` to every worker CONCURRENTLY (one thread per
        slot): a wedged worker costs max one timeout, not one timeout
        per wedged worker serially — a degraded pool is exactly when the
        scrape must still fit inside Prometheus' scrape_timeout."""
        results: list = [None] * len(self._slots)

        def ask(i: int, slot: _WorkerSlot) -> None:
            results[i] = self._command(slot, cmd, timeout_s, args)

        threads = [threading.Thread(target=ask, args=(i, slot), daemon=True)
                   for i, slot in enumerate(self._slots)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=timeout_s + 1.0)
        return results

    def scrape(self, timeout_s: float = 2.0) -> list:
        """Per-worker snapshots from every worker that answers — the
        ground truth the aggregated endpoints are computed from (and the
        same per-worker records the pool tests sum independently)."""
        return [snap for snap in self._fanout("snapshot", timeout_s)
                if snap is not None and "error" not in snap]

    def reset_stats(self, timeout_s: float = 2.0) -> dict:
        """Fan ``/stats/reset`` out to every worker; each clears its
        percentile ring (decision counters and lifetime histograms stay,
        exactly like the single-process endpoint)."""
        acked = sum(1 for ack in self._fanout("reset", timeout_s)
                    if (ack or {}).get("ok"))
        return {"status": "reset", "workers": acked}

    def flip_tables(self, path: str, timeout_s: float = 5.0) -> dict:
        """graftdrift: fan a price-replay table swap out to every worker
        (the drift drill's mid-soak regime flip). Per-worker acks ride
        back so a worker that refused the table (shape mismatch, missing
        file) is visible, not averaged away."""
        acks = self._fanout("flip_tables", timeout_s, {"path": path})
        flipped = sum(1 for ack in acks if (ack or {}).get("ok"))
        out = {"status": "flipped" if flipped == len(self._slots)
               else "partial", "workers": flipped, "path": path}
        errors = sorted({ack["error"] for ack in acks
                         if ack and not ack.get("ok") and "error" in ack})
        if errors:
            out["errors"] = errors
        return out

    def set_drift_reference(self, path: str,
                            timeout_s: float = 5.0) -> dict:
        """Load a frozen drift reference into every worker's tracker.
        Same fan-out/ack contract as :meth:`flip_tables`."""
        acks = self._fanout("drift_ref", timeout_s, {"path": path})
        loaded = sum(1 for ack in acks if (ack or {}).get("ok"))
        out = {"status": "loaded" if loaded == len(self._slots)
               else "partial", "workers": loaded, "path": path}
        errors = sorted({ack["error"] for ack in acks
                         if ack and not ack.get("ok") and "error" in ack})
        if errors:
            out["errors"] = errors
        return out

    def set_shadow(self, path: str | None,
                   timeout_s: float = 30.0) -> dict:
        """graftpilot promote gate: arm (``path`` = candidate run dir)
        or disarm (``path`` = None) runtime shadow scoring on every
        worker. Same fan-out/ack contract as :meth:`flip_tables`; the
        longer timeout covers each worker's candidate checkpoint restore
        + compile. Arming swaps in FRESH per-worker scorers, so the
        summed ``/stats`` shadow section counts exactly the traffic
        paired while the gate is up."""
        acks = self._fanout("shadow", timeout_s, {"path": path})
        acked = sum(1 for ack in acks if (ack or {}).get("ok"))
        full = acked == len(self._slots)
        if path is None:
            status = "disarmed" if full else "partial"
        else:
            status = "armed" if full else "partial"
        out = {"status": status, "workers": acked, "path": path}
        errors = sorted({ack["error"] for ack in acks
                         if ack and not ack.get("ok") and "error" in ack})
        if errors:
            out["errors"] = errors
        return out

    def status(self) -> dict:
        alive = sum(1 for s in self._slots if s.alive)
        with self._lock:
            restarts = self.restarts_total
        return {
            "workers": self.workers,
            "alive": alive,
            "failed": sum(1 for s in self._slots if s.failed),
            "restarts_total": restarts,
            "mode": "reuseport" if self.reuse_port else "inherit",
            "port": self.port,
            "generation": self.generation,
            "rollout": self.rollout.counters(),
        }

    def health(self) -> dict:
        """Pool liveness body. ``rolling: true`` while a promote/rollback
        is in flight: a pool that is briefly below strength because IT is
        replacing a worker is healthy-by-design, and k8s liveness must
        not kill the pod mid-rollout (the handler answers 200 for
        ``rolling`` exactly as for ``ok``)."""
        status = self.status()
        rolling = self.rollout.active
        status["rolling"] = rolling
        if status["alive"] == status["workers"]:
            status["status"] = "ok"
        else:
            status["status"] = "rolling" if rolling else "degraded"
        if self.slo_enabled:
            merged = merge_worker_slo(self.scrape(timeout_s=1.0))
            if merged is not None:
                status["slo"] = {
                    "degraded": merged["degraded"],
                    "burning": sorted(
                        name for name, o in merged["objectives"].items()
                        if o["burning"]),
                }
                if merged["degraded"] and status["status"] == "ok":
                    # SLO burn degrades a structurally-healthy pool; a
                    # mid-rollout pool keeps "rolling" (the rollout's
                    # own gate holds the canary to the SLO).
                    status["status"] = "degraded"
        return status


def _make_data_listener(host: str, port: int,
                        reuse_port: bool) -> socket.socket:
    sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    if reuse_port:
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
    sock.bind((host, port))
    sock.listen(_LISTEN_BACKLOG)
    return sock


class _PoolHandler(BaseHTTPRequestHandler):
    pool: ServingPool  # bound by _make_control_server

    def _send(self, code: int, payload, content_type="application/json"):
        body = (payload if isinstance(payload, bytes)
                else json.dumps(payload).encode())
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802 (stdlib API)
        if self.path == "/healthz":
            health = self.pool.health()
            ok = health["status"] in ("ok", "rolling")
            self._send(200 if ok else 503, health)
        elif self.path == "/rollout":
            self._send(200, self.pool.rollout.status())
        elif self.path == "/stats":
            pool = self.pool.status()
            snapshots = self.pool.scrape()
            pool["responding"] = len(snapshots)
            self._send(200, aggregate_stats(snapshots, pool))
        elif self.path == "/metrics":
            pool = self.pool.status()
            snapshots = self.pool.scrape()
            pool["alive"] = len(snapshots)
            self._send(200, aggregate_metrics(snapshots, pool).encode(),
                       content_type="text/plain; version=0.0.4; "
                                    "charset=utf-8")
        else:
            self._send(404, {"error": f"unknown path {self.path}"})

    def do_POST(self):  # noqa: N802
        length = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(length)
        if self.path == "/stats/reset":
            # Fans the ring-clear out; every lifetime counter — the
            # merged histograms, trace records/drops/segments, and the
            # promotion/rollback totals — stays monotonic (pinned by
            # test; Prometheus rate() must never see a rewind).
            self._send(200, self.pool.reset_stats())
        elif self.path == "/promote":
            try:
                payload = json.loads(body or b"{}")
            except json.JSONDecodeError as exc:
                self._send(400, {"error": f"bad json: {exc}"})
                return
            if not isinstance(payload, dict):
                # Valid JSON that is not an object ('"abc"', '5') must
                # get the same 400 contract, not an AttributeError that
                # drops the connection responseless.
                self._send(400, {"error": "pass a JSON object: "
                                          '{"checkpoint": "<run_dir>"}'})
                return
            code, out = self.pool.rollout.request_promote(
                payload.get("checkpoint"))
            self._send(code, out)
        elif self.path in ("/telemetry/flip", "/drift/reference"):
            # graftdrift control plane: both take {"path": "<file>"} and
            # fan out to every worker (table swap / reference load). The
            # bench's --flip-tables drives the first; `drift snapshot` +
            # this route close the reference lifecycle for the second.
            try:
                payload = json.loads(body or b"{}")
            except json.JSONDecodeError as exc:
                self._send(400, {"error": f"bad json: {exc}"})
                return
            if not isinstance(payload, dict) or not payload.get("path"):
                self._send(400, {"error": "pass a JSON object: "
                                          '{"path": "<file>"}'})
                return
            if self.path == "/telemetry/flip":
                out = self.pool.flip_tables(payload["path"])
            else:
                out = self.pool.set_drift_reference(payload["path"])
            self._send(200 if not out.get("errors") else 409, out)
        elif self.path == "/shadow":
            # graftpilot promote gate: {"path": "<run_dir>"} arms
            # runtime shadow scoring pool-wide, {"path": null} disarms.
            # Unlike the graftdrift routes above, a null path is a valid
            # request here — so the route validates separately.
            try:
                payload = json.loads(body or b"{}")
            except json.JSONDecodeError as exc:
                self._send(400, {"error": f"bad json: {exc}"})
                return
            if not isinstance(payload, dict) or "path" not in payload:
                self._send(400, {"error": "pass a JSON object: "
                                          '{"path": "<run_dir>"|null}'})
                return
            out = self.pool.set_shadow(payload["path"])
            self._send(200 if not out.get("errors") else 409, out)
        else:
            self._send(404, {"error": f"unknown path {self.path}"})

    def log_message(self, fmt, *log_args):  # quiet, like the data plane
        logger.debug("%s " + fmt, self.address_string(), *log_args)


def _make_control_server(pool: ServingPool, host: str,
                         port: int) -> ThreadingHTTPServer:
    handler = type("BoundPoolHandler", (_PoolHandler,), {"pool": pool})
    return ThreadingHTTPServer((host, port), handler)


# --------------------------------------------------------------- CLI glue


def run_pool(build_kwargs: dict, workers: int, host: str, port: int,
             control_port: int | None, control_host: str | None = None,
             blas_threads: int | None = None,
             front: str = "threading") -> None:
    """The ``--workers N`` entry point behind the extender CLI: wrap
    ``build_policy`` into a per-worker factory (each worker restores the
    checkpoint and compiles its own backend AFTER the fork — the
    supervisor never imports jax), start the pool, serve until
    SIGTERM/SIGINT. The factory is spec-aware (graftroll): a promoted
    generation's workers build from the PROMOTED checkpoint, everything
    else in the serve config unchanged, and each worker's decision trace
    (``--trace-dir``) writes its own ``w<id>-`` stream."""

    def factory(worker_id, shared, spec):
        from rl_scheduler_tpu.scheduler.extender import (
            build_policy,
            check_warm_nodes_served,
        )

        kwargs = dict(build_kwargs)
        if spec.checkpoint is not None:
            kwargs["run"] = spec.checkpoint
        if kwargs.get("trace_dir") is not None:
            kwargs["trace_prefix"] = f"w{worker_id}-"
        policy = build_policy(
            **kwargs,
            price_counter=shared.price_counter,
            table_counter=shared.table_counter,
        )
        check_warm_nodes_served(policy, build_kwargs.get("warm_nodes"))
        return policy

    # graftlens: an armed SLO threads three ways — each worker's tracker
    # (build_policy), the pool /healthz degrade, and the rollout's
    # principled canary gate (the canary must not burn the budget the
    # incumbents are keeping).
    slo_cfg = None
    if (build_kwargs.get("slo_p99_ms") is not None
            or build_kwargs.get("slo_avail") is not None):
        slo_cfg = slo_mod.SloConfig(
            p99_ms=build_kwargs.get("slo_p99_ms"),
            availability=build_kwargs.get("slo_avail"))
    # The control plane follows the data plane's bind address by default:
    # k8s probes and Prometheus reach both through the pod IP
    # (k8s_manifests/extender-deployment.yaml) — a loopback-only control
    # plane would leave the Deployment permanently unready.
    pool = ServingPool(factory, workers=workers, host=host, port=port,
                       control_host=control_host if control_host is not None
                       else host,
                       control_port=control_port, blas_threads=blas_threads,
                       initial_checkpoint=build_kwargs.get("run"),
                       slo_enabled=slo_cfg is not None,
                       rollout_opts={"slo": slo_cfg} if slo_cfg else None,
                       front=front)
    pool.start()

    def _stop(signum, frame):  # noqa: ARG001 (signal API)
        threading.Thread(target=pool.shutdown, daemon=True).start()

    signal.signal(signal.SIGTERM, _stop)
    signal.signal(signal.SIGINT, _stop)
    status = pool.status()
    print(
        f"graftserve pool: {workers} worker(s) on {host}:{pool.port} "
        f"({status['mode']}, front={front}), control plane on "
        f"{pool.control_address[0]}:{pool.control_address[1]}",
        flush=True,
    )
    try:
        pool.serve_forever()
    finally:
        pool.shutdown()
