"""Host-side Kubernetes cluster hooks (never inside jit).

Completes the reference's "slow mode" (``k8s_multi_cloud_env.py:69-82,
125-137``) with two of its bugs fixed:

- The reference hardcodes kubeconfig contexts ``kind-aws``/``kind-azure``,
  but ``kind create cluster --config aws-cluster-config.yaml`` registers the
  context as ``kind-kind-aws`` (kind prefixes cluster names with ``kind-``).
  The lookup always failed and the bare ``except: pass`` hid it. We try both
  spellings and log what we find.
- Failures are logged (once per failure kind) instead of silently swallowed,
  and ``place`` reports success, so callers can fall back.
"""

from __future__ import annotations

import logging
import time

logger = logging.getLogger(__name__)

# Candidate kubeconfig context names per simulated cloud.
CLOUD_CONTEXTS = {
    "aws": ("kind-kind-aws", "kind-aws"),
    "azure": ("kind-kind-azure", "kind-azure"),
}


class DryRunPodPlacer:
    """Dry-run pod creation against per-cloud kind clusters."""

    def __init__(
        self,
        namespace: str = "default",
        image: str = "nginx:alpine",
        request_timeout: float = 10.0,
    ):
        self.namespace = namespace
        self.image = image
        # Bounded (connect, read) timeout: without it one stalled kube API
        # connection wedges AsyncPlacer's single drain thread forever.
        self.request_timeout = request_timeout
        self._clients: dict[str, object] = {}
        self._warned: set[str] = set()
        self._load_clients()

    def _load_clients(self) -> None:
        try:
            from kubernetes import client, config
        except ImportError:
            logger.warning("kubernetes client not installed; slow mode is a no-op")
            return
        for cloud, contexts in CLOUD_CONTEXTS.items():
            for ctx in contexts:
                try:
                    api_client = config.new_client_from_config(context=ctx)
                    self._clients[cloud] = client.CoreV1Api(api_client=api_client)
                    logger.info("loaded kube context %s for cloud %s", ctx, cloud)
                    break
                except Exception as e:  # noqa: BLE001 - any config error means "not available"
                    logger.debug("kube context %s unavailable: %s", ctx, e)
        missing = set(CLOUD_CONTEXTS) - set(self._clients)
        if missing:
            logger.warning("no kube context found for clouds: %s", sorted(missing))

    def place(self, cloud: str, dry_run: bool = True) -> bool:
        """Dry-run create an nginx pod on the chosen cloud. Returns success."""
        v1 = self._clients.get(cloud)
        if v1 is None:
            self._warn_once(f"no-client-{cloud}", f"no kube client for cloud {cloud}")
            return False
        from kubernetes import client

        pod = client.V1Pod(
            metadata=client.V1ObjectMeta(name=f"rl-pod-{int(time.time() * 1000)}"),
            spec=client.V1PodSpec(
                containers=[client.V1Container(name="nginx", image=self.image)]
            ),
        )
        try:
            v1.create_namespaced_pod(
                namespace=self.namespace,
                body=pod,
                dry_run="All" if dry_run else None,
                _request_timeout=(5.0, self.request_timeout),
            )
            return True
        except Exception as e:  # noqa: BLE001 - surface, don't crash the env loop
            self._warn_once(f"place-{cloud}", f"pod placement on {cloud} failed: {e}")
            return False

    def _warn_once(self, key: str, msg: str) -> None:
        if key not in self._warned:
            self._warned.add(key)
            logger.warning(msg)
