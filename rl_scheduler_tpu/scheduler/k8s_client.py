"""Host-side Kubernetes cluster hooks (never inside jit).

Completes the reference's "slow mode" (``k8s_multi_cloud_env.py:69-82,
125-137``) with two of its bugs fixed:

- The reference hardcodes kubeconfig contexts ``kind-aws``/``kind-azure``,
  but ``kind create cluster --config aws-cluster-config.yaml`` registers the
  context as ``kind-kind-aws`` (kind prefixes cluster names with ``kind-``).
  The lookup always failed and the bare ``except: pass`` hid it. We try both
  spellings and log what we find.
- Failures are logged (once per failure kind) instead of silently swallowed,
  and ``place`` reports success, so callers can fall back.
"""

from __future__ import annotations

import logging
import time

logger = logging.getLogger(__name__)

# Candidate kubeconfig context names per simulated cloud.
CLOUD_CONTEXTS = {
    "aws": ("kind-kind-aws", "kind-aws"),
    "azure": ("kind-kind-azure", "kind-azure"),
}


class DryRunPodPlacer:
    """Dry-run pod creation against per-cloud kind clusters.

    graftguard (docs/robustness.md): kube API calls run under the unified
    ``utils/retry.py`` policy — bounded retries with backoff for the
    transient 5xx an apiserver throws under pressure, behind a circuit
    breaker PER cloud so a down cluster is probed at recovery cadence
    instead of per decision — without its failure streak being reset by
    the healthy cloud, and without refusing the healthy cloud when open.
    Breaker state rides the extender's ``/stats`` and ``/metrics``
    (``breakers["k8s_aws"]``/``["k8s_azure"]``). ``fault_plan`` is the
    chaos seam (site ``k8s.place``).
    """

    def __init__(
        self,
        namespace: str = "default",
        image: str = "nginx:alpine",
        request_timeout: float = 10.0,
        retry=None,
        breakers=None,
        fault_plan=None,
    ):
        from rl_scheduler_tpu.utils.retry import CircuitBreaker, RetryPolicy

        self.namespace = namespace
        self.image = image
        # Bounded (connect, read) timeout: without it one stalled kube API
        # connection wedges AsyncPlacer's single drain thread forever.
        self.request_timeout = request_timeout
        self.fault_plan = fault_plan
        # Deadline = one request_timeout: retries are for FAST transient
        # 5xx, and the deadline gates whether another attempt may START —
        # so a timeout-dominated failure (connect 5 s + read
        # request_timeout) never re-runs, keeping the worst case one
        # stalled connection can hold AsyncPlacer's single drain thread
        # at ~one attempt, not attempts x timeout.
        self.retry = retry if retry is not None else RetryPolicy(
            max_attempts=3, base_delay_s=0.1, max_delay_s=1.0,
            deadline_s=request_timeout, seed=0,
        )
        # One breaker PER cloud (mirrors telemetry's per-endpoint split):
        # a dead aws cluster must not have its failure streak reset by
        # healthy azure placements, nor an open aws breaker refuse azure.
        self.breakers = {
            cloud: CircuitBreaker(name=f"k8s_{cloud}",
                                  failure_threshold=5, reset_timeout_s=30.0)
            for cloud in CLOUD_CONTEXTS
        }
        self.breakers.update(breakers or {})
        self._clients: dict[str, object] = {}
        self._warned: set[str] = set()
        self._load_clients()

    def _load_clients(self) -> None:
        try:
            from kubernetes import client, config
        except ImportError:
            logger.warning("kubernetes client not installed; slow mode is a no-op")
            return
        for cloud, contexts in CLOUD_CONTEXTS.items():
            for ctx in contexts:
                try:
                    api_client = config.new_client_from_config(context=ctx)
                    self._clients[cloud] = client.CoreV1Api(api_client=api_client)
                    logger.info("loaded kube context %s for cloud %s", ctx, cloud)
                    break
                except Exception as e:  # noqa: BLE001 - any config error means "not available"
                    logger.debug("kube context %s unavailable: %s", ctx, e)
        missing = set(CLOUD_CONTEXTS) - set(self._clients)
        if missing:
            logger.warning("no kube context found for clouds: %s", sorted(missing))

    def _create_pod(self, v1, cloud: str, dry_run: bool) -> None:
        """One kube API attempt (the unit the retry policy re-runs)."""
        if self.fault_plan is not None:
            # Simulated apiserver 5xx — the transient family the retry
            # policy exists for.
            self.fault_plan.check("k8s.place", ConnectionError)
        from kubernetes import client

        pod = client.V1Pod(
            metadata=client.V1ObjectMeta(name=f"rl-pod-{int(time.time() * 1000)}"),
            spec=client.V1PodSpec(
                containers=[client.V1Container(name="nginx", image=self.image)]
            ),
        )
        v1.create_namespaced_pod(
            namespace=self.namespace,
            body=pod,
            dry_run="All" if dry_run else None,
            _request_timeout=(5.0, self.request_timeout),
        )

    def place(self, cloud: str, dry_run: bool = True) -> bool:
        """Dry-run create an nginx pod on the chosen cloud. Returns success."""
        v1 = self._clients.get(cloud)
        if v1 is None:
            # Unconditional: with a fault plan armed but no client, the
            # non-firing calls would reach create_namespaced_pod on None
            # and trip the breaker on harness artifacts, not faults.
            self._warn_once(f"no-client-{cloud}", f"no kube client for cloud {cloud}")
            return False
        breaker = self.breakers[cloud]
        if not breaker.allow():
            # Keyed on opens_total: one warning per OPEN WINDOW, not per
            # process lifetime — a breaker that re-trips hours later must
            # not drop placements invisibly (the GL010 principle).
            self._warn_once(
                f"breaker-{cloud}-{breaker.snapshot()['opens_total']}",
                f"kube breaker {breaker.name} open; dropping placements "
                f"until a recovery probe succeeds (state exported on "
                f"/stats and /metrics)")
            return False
        try:
            self.retry.call(self._create_pod, v1, cloud, dry_run)
            breaker.record_success()
            return True
        # graftlint: disable=GL010 -- logs through the rate-limited _warn_once helper (one logger.warning per failure kind); the rule's AST walk cannot see one level of indirection
        except Exception as e:  # noqa: BLE001 - surface, don't crash the env loop
            breaker.record_failure()
            self._warn_once(f"place-{cloud}", f"pod placement on {cloud} failed: {e}")
            return False

    def _warn_once(self, key: str, msg: str) -> None:
        if key not in self._warned:
            self._warned.add(key)
            logger.warning(msg)
