"""Serving backend for the GNN policy over cluster topology (config 5).

Round 4 closed the "trains but can't serve" hole for ``cluster_set``
(``set_backend.py``); this module closes it for ``cluster_graph``: the
GNN's pointer head also emits one logit per candidate node — the exact
``/prioritize`` shape — and its GCN weights are node-count-independent
(``models/gnn.py``: per-feature ``w_self``/``w_nbr`` matrices + a
degree-normalized adjacency), so one trained checkpoint scores ANY
candidate node set once a topology is supplied.

Serving-time topology: the same two-cloud gateway construction the
training env builds (``env/cluster_graph.py::build_topology``),
generalized to the request's actual cloud assignment — per cloud group a
ring + chords to the group's gateway (its first node in request order),
gateways chained across groups; unknown-cloud nodes form their own
group. For the canonical first-half-aws ordering this reproduces the
training topology bit-for-bit (tested). Real cluster topologies can be
injected by replacing :func:`topology_for_clouds`.

Affinity: the training env scores placement relative to the node the
pod's service runs on. At serving time the pod names it with the
``rl-scheduler.io/affinity-node`` annotation (documented contract); when
absent, the hops-to-affinity feature falls back to each node's MEAN hop
distance — the marginal expectation under the env's uniform-random
affinity draw, i.e. the neutral in-distribution value.

Prices: the graph env replays RAW dollar prices (``real_prices.csv``),
not the normalized table, so this module carries its own replay counter
(:class:`RawPriceReplay`) alongside the shared CPU source.

Only a numpy forward is provided (``cpu`` semantics): the GCN is three
BLAS matmuls per layer — microseconds at serving sizes — and, unlike the
set family, the adjacency varies per request, which would defeat a
shape-specialized AOT cache. Every ``--backend`` flag maps here with a
log line. A C++ GCN core (the graph analogue of
``native/set_infer.cpp``) was built and measured in round 4 and
DELETED: it lost to this numpy forward at every size and concurrency
(N=8: 0.12 vs 0.16 ms; N=100: 0.44 vs 1.44 ms; 8-way: 9,400 vs
6,300 req/s) because the GCN forward is BLAS-dominated and numpy's BLAS
calls release the GIL — there is no GIL-serialization to fix here,
unlike the set transformer whose numpy forward holds the GIL across
many small non-BLAS ops.
"""

from __future__ import annotations

import functools
import logging
import threading
import time

import numpy as np

logger = logging.getLogger(__name__)

GNN_DIM = 64    # GNNPolicy defaults (models/gnn.py, train CLI)
GNN_DEPTH = 3
AFFINITY_ANNOTATION = "rl-scheduler.io/affinity-node"
# Feature-scale constants mirrored from env/cluster_graph.py::_observe.
PRICE_FEATURE_SCALE = 30.0


def _params_subtree(tree: dict) -> dict:
    return tree["params"] if "params" in tree else tree


def _np_tree(tree):
    if isinstance(tree, dict):
        return {k: _np_tree(v) for k, v in tree.items()}
    return np.asarray(tree, np.float32)


@functools.lru_cache(maxsize=256)
def _topology_cached(clouds: tuple) -> tuple[np.ndarray, np.ndarray]:
    n = len(clouds)
    adj = np.zeros((n, n), np.float32)
    groups = [
        [i for i, c in enumerate(clouds) if c == key]
        for key in ("aws", "azure", None)
    ]
    groups = [g for g in groups if g]
    for members in groups:
        gateway = members[0]
        for i, u in enumerate(members):
            v = members[(i + 1) % len(members)]  # ring
            if u != v:
                adj[u, v] = adj[v, u] = 1.0
            if u != gateway:                      # chord to gateway
                adj[u, gateway] = adj[gateway, u] = 1.0
    for a, b in zip(groups[:-1], groups[1:]):     # gateway <-> gateway
        adj[a[0], b[0]] = adj[b[0], a[0]] = 1.0
    # All-pairs hop counts via matrix BFS: one boolean matmul per hop
    # level (the graph's diameter is small by construction), BLAS-bound
    # instead of a Python frontier loop per source node.
    hops = np.where(np.eye(n, dtype=bool), 0.0, np.inf).astype(np.float32)
    reach = np.eye(n, dtype=bool)
    d = 0
    while True:
        d += 1
        new_reach = reach | ((reach.astype(np.float32) @ adj) > 0)
        fresh = new_reach & ~reach
        if not fresh.any():
            break
        hops[fresh] = d
        reach = new_reach
    return adj, hops


def topology_for_clouds(clouds: list) -> tuple[np.ndarray, np.ndarray]:
    """``(adjacency, hops)`` for a candidate node list's cloud assignment.

    Mirrors the training env's construction (ring + gateway chords per
    cloud, gateways chained) on the request's actual clouds. Groups are
    ordered aws, azure, unknown; each group's gateway is its first node
    in request order. Single-node groups contribute no intra-group edges;
    a single-group request is just that group's ring. Results are
    LRU-cached on the cloud signature (a cluster's candidate lists
    repeat), so steady-state requests pay a dict lookup, not a BFS —
    treat the returned arrays as read-only (they are shared).
    """
    return _topology_cached(tuple(clouds))


class RawPriceReplay:
    """Replays the raw dollar pricing table (the graph env's price source,
    ``env/cluster_graph.py::make_params``) — the serving-side analogue of
    the env's ``step_idx``. Two modes:

    - ``"counter"`` (default): advances one row per request, mirroring
      the env's per-step ``step_idx`` exactly. PROCESS-LOCAL by design: a
      restart starts over at row 0, and two extender replicas walk
      independent trajectories for identical request streams (each
      replica sees a valid in-distribution price path — the rows are the
      same table — but their score trajectories differ). Pinned by
      ``tests/test_extender.py``; right for single-replica deployments
      and training parity. A graftserve pool (``scheduler/pool.py``)
      passes ``counter=`` — a cross-process ``SharedCounter`` — so every
      worker of ONE pool advances the same position and the pool as a
      whole walks exactly the trajectory a single process would
      (cross-replica deployments keep the ``wallclock`` answer: separate
      pools never share memory).
    - ``"wallclock"``: the row derives from wall time
      (``int(now / period_s) % T``), so restarts and ALL replicas agree
      on the current row with zero coordination. ``period_s`` is the
      real-world cadence one table row represents (default 300 s — the
      5-minute cloud-pricing update interval the reference's collector
      scripts poll at). The extender exposes this as
      ``--price-replay wallclock``.
    """

    def __init__(self, prices: np.ndarray | None = None,
                 mode: str = "counter", period_s: float = 300.0,
                 now_fn=None, counter=None):
        if mode not in ("counter", "wallclock"):
            raise ValueError(f"unknown price replay mode {mode!r}")
        if counter is not None and mode != "counter":
            # Wallclock already agrees across processes with zero
            # coordination; accepting a counter there would imply it
            # drives the position when it never would.
            raise ValueError(
                f"price replay counter= only backs mode='counter' "
                f"(got mode={mode!r})"
            )
        if period_s <= 0:
            # Validate at construction for EVERY entry point: wallclock
            # divides by the period per request (0 -> ZeroDivisionError
            # at request time; negative -> silent backwards replay).
            raise ValueError(
                f"price replay period_s={period_s}: must be a positive "
                "number of seconds"
            )
        if prices is None:
            from rl_scheduler_tpu.data.loader import load_raw_prices

            prices = np.asarray(load_raw_prices(), np.float32)
        self.prices = np.asarray(prices, np.float32)  # [T, 2]
        self.mode = mode
        self._period = float(period_s)
        self._now = now_fn if now_fn is not None else time.time
        self._counter = counter
        self._step = 0
        self._lock = threading.Lock()

    def next_row(self) -> tuple[np.ndarray, float]:
        """``(row [2], step_frac)`` at the current replay position."""
        if self.mode == "wallclock":
            idx = int(self._now() / self._period) % len(self.prices)
        elif self._counter is not None:
            # Pool-shared position: the counter's own cross-process lock
            # makes the fetch-and-increment atomic across workers.
            idx = self._counter.next_index() % len(self.prices)
        else:
            with self._lock:
                idx = self._step % len(self.prices)
                self._step += 1
        return self.prices[idx], idx / max(len(self.prices) - 1, 1)


def build_graph_obs(clouds: list, price_row: np.ndarray, cpus: np.ndarray,
                    hops: np.ndarray, adj: np.ndarray,
                    affinity: int | None, pod_cpu: float,
                    step_frac: float) -> np.ndarray:
    """``[N, 7]`` node features matching training column order
    (``env/cluster_graph.py::_observe``): price*30, cpu_used, cloud_id,
    hops_to_affinity/max_hops, degree/n, pod_cpu, step_frac. Unknown-cloud
    nodes take the cross-cloud mean price/cpu and ``cloud_id = 0.5``;
    ``affinity=None`` uses each node's mean hop distance (the marginal of
    the env's uniform affinity draw)."""
    n = len(clouds)
    cloud_idx = np.fromiter(
        ({"aws": 0, "azure": 1}.get(c, -1) for c in clouds),
        np.int64, count=n,
    )
    known = cloud_idx >= 0
    safe = np.where(known, cloud_idx, 0)
    price = np.where(known, price_row[safe], price_row.mean())
    cpu = np.where(known, cpus[safe], cpus.mean())
    if affinity is None:
        # E[hops[i, aff]] under the env's uniform draw, which INCLUDES
        # self (randint(0, num_nodes), env/cluster_graph.py:184): sum/n.
        hops_to_aff = hops.sum(axis=1) / n
    else:
        hops_to_aff = hops[:, affinity]
    obs = np.empty((n, 7), np.float32)
    obs[:, 0] = price * PRICE_FEATURE_SCALE
    obs[:, 1] = cpu
    obs[:, 2] = np.where(known, cloud_idx, 0.5)
    obs[:, 3] = hops_to_aff / max(hops.max(), 1.0)
    obs[:, 4] = adj.sum(axis=1) / n
    obs[:, 5] = pod_cpu
    obs[:, 6] = step_frac
    return obs


class NumpyGNNBackend:
    """GCN pointer forward in plain numpy: ``decide_nodes(obs, adj)``.

    Matches ``models/gnn.py::GNNPolicy`` (relu embed, depth x GCN layers
    ``relu(h W_self + Â h W_nbr)``, pointer score head) — flax-apply
    agreement tested to 1e-5 in ``tests/test_extender.py``. The degree
    normalization ``Â = D^-1 A`` lives HERE (one definition mirroring
    ``GNNPolicy.__call__``), so callers pass the raw 0/1 adjacency.
    """

    name = "cpu"
    family = "graph"

    def __init__(self, params_tree: dict, depth: int = GNN_DEPTH):
        p = _np_tree(_params_subtree(params_tree))
        self._embed = p["embed"]
        self._convs = [p[f"conv_{i}"] for i in range(depth)]
        self._score = p["head"]["score_head"]

    def decide_nodes(self, node_obs: np.ndarray,
                     adj: np.ndarray) -> tuple[int, np.ndarray]:
        # D^-1 A, exactly as GNNPolicy.__call__ (models/gnn.py:73-74).
        norm_adj = adj / np.maximum(adj.sum(axis=1, keepdims=True), 1.0)
        h = np.maximum(
            node_obs.astype(np.float32) @ self._embed["kernel"]
            + self._embed["bias"], 0.0,
        )
        for conv in self._convs:
            self_msg = h @ conv["w_self"]["kernel"] + conv["w_self"]["bias"]
            nbr = norm_adj @ h
            nbr_msg = nbr @ conv["w_nbr"]["kernel"] + conv["w_nbr"]["bias"]
            h = np.maximum(self_msg + nbr_msg, 0.0)
        logits = h @ self._score["kernel"][:, 0] + self._score["bias"][0]
        return int(np.argmax(logits)), logits


def make_graph_backend(backend: str, params_tree: dict):
    """Build the graph-family backend for the ``--backend`` flag. All
    flags serve the numpy forward (see module docstring for why there is
    no AOT variant); non-``cpu`` flags log the mapping. Returns
    ``(backend_obj, fallback_used)`` like ``make_backend``."""
    if backend != "cpu":
        logger.info(
            "backend %r maps to the numpy GCN forward for cluster_graph "
            "checkpoints (per-request topology defeats shape-specialized "
            "AOT; the forward is BLAS-bound microseconds)", backend,
        )
    try:
        return NumpyGNNBackend(params_tree), False
    except Exception:
        from rl_scheduler_tpu.scheduler.policy_backend import GreedyBackend

        logger.exception(
            "graph backend failed to initialize; falling back to greedy"
        )
        return GreedyBackend(), True
