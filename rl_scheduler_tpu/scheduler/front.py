"""graftfront: the asyncio data-plane front for the scheduler extender.

graftfwd left the serving plane transport-bound: with the score cache
armed the POLICY answers a cache hit in ~0.055 ms, yet clients measured
p50 ~26 ms at 8-way concurrency (BENCH_serving.jsonl) — the residual is
``ThreadingHTTPServer``'s one-GIL-bound-thread-per-connection accept
path plus a fresh TCP connection per request. This module replaces the
transport and ONLY the transport:

- :class:`AsyncFrontServer` is facade-compatible with the
  ``ThreadingHTTPServer`` the pool workers drive (``server_address``
  readable after construction, blocking ``serve_forever()``,
  thread-safe ``shutdown()`` that drains in-flight requests, idempotent
  ``server_close()``, a writable ``daemon_threads`` attribute) — so
  ``pool.py``'s supervisor, SIGTERM drain, and rolling promote/canary
  gates run unchanged on asyncio workers.
- One event loop accepts 10k+ concurrent keep-alive connections
  (``loops=N`` runs N accept loops over ``SO_REUSEPORT`` sockets — the
  same port-sharing the pool's listener machinery uses across worker
  PROCESSES, here across loops of one worker).
- Every policy call — JSON decode included — runs in a bounded
  ``ThreadPoolExecutor`` via ``run_in_executor``: the loop never blocks
  on numpy/backend work, and each request occupies exactly one executor
  thread for its whole policy call, which is what keeps the policy's
  ``threading.local`` span/synthetic machinery (graftlens) working
  bit-for-bit: phase counts stay uniform, fail-open drops partial
  spans, probes stay excluded, ``/stats/reset`` never rewinds
  lifetimes. The agreement suites run identically against both fronts.
- ``/filter``/``/prioritize`` bodies with the compact wire content type
  (``wire.py``) skip JSON entirely; a malformed wire token answers 400
  and KEEPS the connection — a refusal is not a reset.

What this front does NOT change: routes, payloads, status codes, the
fail-open backstops, trace records, SLO accounting. ``--front asyncio``
selects it; threading stays the default (docs/serving.md).
"""

from __future__ import annotations

import asyncio
import json
import logging
import socket
import threading
from concurrent.futures import ThreadPoolExecutor

from rl_scheduler_tpu.scheduler.wire import (
    WIRE_CONTENT_TYPE,
    WireError,
    serve_wire,
)

logger = logging.getLogger(__name__)

# Header-section cap (stdlib http.server reads 64 KiB lines; same bar).
_MAX_HEADER_BYTES = 65536
# Listen backlog: sized for connection storms, clamped by somaxconn.
_BACKLOG = 1024
# How long shutdown waits for in-flight requests before cancelling the
# stragglers (the pool supervisor's terminate->join(10 s)->kill
# escalation is the outer bound).
_DRAIN_TIMEOUT_S = 10.0

_REASONS = {200: "OK", 400: "Bad Request", 404: "Not Found",
            431: "Request Header Fields Too Large",
            500: "Internal Server Error"}


class AsyncFrontServer:
    """The event-loop HTTP front (module doc). Dispatch semantics are
    defined by ``extender._Handler`` — this class reimplements the
    transport beneath them, not the routes."""

    def __init__(self, policy, host: str = "0.0.0.0", port: int = 8787,
                 reuse_port: bool = False, inherited_socket=None,
                 loops: int = 1, executor_workers: int | None = None):
        if loops < 1:
            raise ValueError(f"loops={loops}: pass at least 1")
        if loops > 1 and inherited_socket is not None:
            raise ValueError("loops>1 needs per-loop SO_REUSEPORT "
                             "listeners; an inherited socket is one "
                             "shared listener (use loops=1)")
        self.policy = policy
        # Binding happens AT CONSTRUCTION, exactly like HTTPServer's
        # __init__: the pool worker sends its hello (with
        # server_address[1]) before serve_forever starts.
        if inherited_socket is not None:
            self._socks = [inherited_socket]
            self._owns_socks = False
        else:
            want_reuseport = reuse_port or loops > 1
            if want_reuseport and not hasattr(socket, "SO_REUSEPORT"):
                raise ValueError(
                    "SO_REUSEPORT unavailable on this platform (the "
                    "pool's inherit mode is the fallback)")
            self._socks = []
            try:
                for _ in range(loops):
                    self._socks.append(
                        self._bind(host, port, want_reuseport))
                    # Subsequent loops join the first socket's port.
                    port = self._socks[0].getsockname()[1]
            except OSError:
                for s in self._socks:
                    s.close()
                raise
            self._owns_socks = True
        self.server_address = self._socks[0].getsockname()
        # Facade compatibility: pool.py sets this on both fronts. The
        # drain behaviour it selects on ThreadingHTTPServer (join
        # handlers on close) is this front's only behaviour.
        self.daemon_threads = False
        self._loops_n = loops
        self._executor = ThreadPoolExecutor(
            max_workers=executor_workers or 32,
            thread_name_prefix="graftfront")
        self._loop_ctx: list = [None] * loops  # (loop, stop_event) pairs
        self._serving = threading.Event()
        self._is_shut_down = threading.Event()
        self._is_shut_down.set()  # matches socketserver: set while idle
        self._shutdown_requested = False
        self._closed = False
        self._lock = threading.Lock()

    @staticmethod
    def _bind(host: str, port: int, reuseport: bool) -> socket.socket:
        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        try:
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            if reuseport:
                sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
            sock.bind((host, port))
            sock.listen(_BACKLOG)
            sock.setblocking(False)
        except OSError:
            sock.close()
            raise
        return sock

    # ------------------------------------------------------------ facade

    def serve_forever(self) -> None:
        """Run the accept loop(s) until :meth:`shutdown`. Loop 0 runs in
        the calling thread (the worker's main thread, where the SIGTERM
        drain handler lives); extra loops run in daemon threads."""
        with self._lock:
            if self._shutdown_requested or self._closed:
                return  # shutdown() won the race before serving started
            self._is_shut_down.clear()
            self._serving.set()
        threads = [
            threading.Thread(target=self._run_loop, args=(i,),
                             name=f"graftfront-loop-{i}", daemon=True)
            for i in range(1, self._loops_n)
        ]
        for t in threads:
            t.start()
        try:
            self._run_loop(0)
        finally:
            for t in threads:
                t.join()
            self._serving.clear()
            self._is_shut_down.set()

    def shutdown(self) -> None:
        """Thread-safe stop: close the listeners, finish in-flight
        requests, close idle keep-alive connections, then return once
        serve_forever has unwound (ThreadingHTTPServer.shutdown's
        blocking contract — the pool's SIGTERM drain depends on it)."""
        with self._lock:
            self._shutdown_requested = True
            if not self._serving.is_set():
                return
            for ctx in self._loop_ctx:
                if ctx is None:
                    continue
                loop, stop = ctx
                try:
                    loop.call_soon_threadsafe(stop.set)
                except RuntimeError:
                    pass  # loop already closed: nothing left to stop
        self._is_shut_down.wait()

    def server_close(self) -> None:
        """Release the sockets and join the executor (idempotent)."""
        if self._closed:
            return
        self._closed = True
        if self._owns_socks:
            for sock in self._socks:
                try:
                    sock.close()
                except OSError:
                    pass
        self._executor.shutdown(wait=True)

    # ------------------------------------------------------- event loops

    def _run_loop(self, idx: int) -> None:
        loop = asyncio.new_event_loop()
        try:
            loop.run_until_complete(self._serve(loop, idx))
        finally:
            loop.close()

    async def _serve(self, loop, idx: int) -> None:
        stop = asyncio.Event()
        conns: dict = {}  # task -> mutable {"inflight": bool}
        with self._lock:
            if self._shutdown_requested:
                return
            self._loop_ctx[idx] = (loop, stop)
        stopping = {"flag": False}

        async def handle(reader, writer):
            task = asyncio.current_task()
            state = {"inflight": False}
            conns[task] = state
            try:
                await self._handle_conn(reader, writer, state, stopping)
            except asyncio.CancelledError:
                pass  # idle keep-alive connection closed by the drain
            except (ConnectionResetError, BrokenPipeError, EOFError,
                    TimeoutError, OSError):
                pass  # client went away mid-request: nothing to answer
            finally:
                conns.pop(task, None)
                writer.close()

        server = await asyncio.start_server(
            handle, sock=self._socks[idx], limit=_MAX_HEADER_BYTES,
            backlog=_BACKLOG)
        await stop.wait()
        # Drain: stop accepting, let in-flight requests answer, close
        # idle connections — a request an exiting worker already read
        # is answered, not reset (the rolling-restart zero-failures bar,
        # same contract as the threading front's server_close join).
        server.close()
        # close() closed our listening socket too; mark it released so
        # server_close does not double-close an fd someone else may own.
        await server.wait_closed()
        stopping["flag"] = True
        for task, state in list(conns.items()):
            if not state["inflight"]:
                task.cancel()
        if conns:
            await asyncio.wait(list(conns), timeout=_DRAIN_TIMEOUT_S)
        for task in list(conns):
            task.cancel()
        if conns:
            await asyncio.gather(*list(conns), return_exceptions=True)

    async def _handle_conn(self, reader, writer, state: dict,
                           stopping: dict) -> None:
        loop = asyncio.get_running_loop()
        while True:
            try:
                head = await reader.readuntil(b"\r\n\r\n")
            except (asyncio.IncompleteReadError, ConnectionResetError):
                return  # clean EOF between requests (or torn request)
            except asyncio.LimitOverrunError:
                await self._respond(writer, 431,
                                    b'{"error": "headers too large"}',
                                    "application/json", close=True)
                return
            parsed = self._parse_head(head)
            if parsed is None:
                await self._respond(writer, 400,
                                    b'{"error": "malformed request"}',
                                    "application/json", close=True)
                return
            method, path, version, headers = parsed
            try:
                length = int(headers.get("content-length", 0))
            except ValueError:
                length = -1
            if length < 0 or length > 64 * 1024 * 1024:
                await self._respond(writer, 400,
                                    b'{"error": "bad content-length"}',
                                    "application/json", close=True)
                return
            body = await reader.readexactly(length) if length else b""
            conn_hdr = headers.get("connection", "").lower()
            keep = (version == "HTTP/1.1" and conn_hdr != "close") \
                or conn_hdr == "keep-alive"
            state["inflight"] = True
            try:
                # The whole request — JSON/wire decode AND the policy
                # call — on ONE executor thread: the policy's
                # threading.local request state needs exactly that.
                status, ctype, payload = await loop.run_in_executor(
                    self._executor, _dispatch, self.policy, method, path,
                    headers, body)
            finally:
                state["inflight"] = False
            close = not keep or stopping["flag"]
            await self._respond(writer, status, payload, ctype,
                                close=close)
            if close:
                return

    @staticmethod
    def _parse_head(head: bytes):
        """Request line + headers; None on malformation (a 400, never a
        reset)."""
        try:
            lines = head[:-4].decode("latin-1").split("\r\n")
            method, path, version = lines[0].split(" ")
        except ValueError:
            return None
        headers = {}
        for line in lines[1:]:
            name, sep, value = line.partition(":")
            if not sep:
                return None
            headers[name.strip().lower()] = value.strip()
        return method, path, version, headers

    @staticmethod
    async def _respond(writer, status: int, payload: bytes, ctype: str,
                       close: bool = False) -> None:
        reason = _REASONS.get(status, "Unknown")
        conn = "close" if close else "keep-alive"
        writer.write(
            (f"HTTP/1.1 {status} {reason}\r\n"
             f"Content-Type: {ctype}\r\n"
             f"Content-Length: {len(payload)}\r\n"
             f"Connection: {conn}\r\n\r\n").encode("latin-1") + payload)
        await writer.drain()


def _dispatch(policy, method: str, path: str, headers: dict,
              body: bytes) -> tuple:
    """One request against the policy: ``(status, content_type, bytes)``.
    Runs on an executor thread. Routes, payloads, and every fail-open
    backstop mirror ``extender._Handler`` line for line — that handler
    is the semantics spec; this function is its transport-free twin."""
    from rl_scheduler_tpu.scheduler.extender import ExtenderPolicy

    def js(code, obj):
        return code, "application/json", json.dumps(obj).encode()

    if method == "GET":
        if path == "/healthz":
            return js(200, policy.health())
        if path == "/stats":
            return js(200, policy.statistics())
        if path == "/metrics":
            return (200, "text/plain; version=0.0.4; charset=utf-8",
                    policy.metrics_text().encode())
        return js(404, {"error": f"unknown path {path}"})
    if method != "POST":
        return js(404, {"error": f"unknown path {path}"})
    ctype = (headers.get("content-type") or "").split(";")[0].strip()
    if ctype == WIRE_CONTENT_TYPE:
        try:
            answer = serve_wire(policy, path, body)
        except WireError as exc:
            # A refusal, never a dropped connection (codec contract).
            return js(400, {"error": f"bad wire: {exc}"})
        except ValueError:
            return js(404, {"error": f"unknown path {path}"})
        return 200, WIRE_CONTENT_TYPE, answer
    try:
        args = json.loads(body or b"{}")
    except json.JSONDecodeError as exc:
        return js(400, {"error": f"bad json: {exc}"})
    args = {k.lower(): v for k, v in args.items()}
    if path == "/filter":
        try:
            result = policy.filter(args)
        except Exception:  # noqa: BLE001 — last-line fail-open backstop
            logger.exception("filter failed on malformed request; "
                             "passing nodes through")
            result = ExtenderPolicy._passthrough(args)
        return js(200, result)
    if path == "/prioritize":
        try:
            result = policy.prioritize(args)
        except Exception:  # noqa: BLE001 — last-line fail-open backstop
            logger.exception("prioritize failed on malformed request; "
                             "empty priority list")
            result = []
        return js(200, result)
    if path == "/stats/reset":
        return js(200, policy.reset_stats())
    return js(404, {"error": f"unknown path {path}"})
