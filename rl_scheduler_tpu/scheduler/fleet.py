"""graftfleet: the multi-host fleet control plane.

Every production primitive below this module is pool-local: ``POST
/promote`` lands on ONE supervisor, ``/stats``/``/metrics`` merge one
pool's workers, trace dirs live on one host. This module generalizes
each of those exactly one level up — pools-in-a-fleet reuse the same
machinery as workers-in-a-pool:

* **Discovery** — a resolver seam turns a topology source into
  ``PoolRef``s: ``StaticResolver`` for a ``--pools host:port,...``
  list, ``EndpointsResolver`` for a kubernetes Endpoints document
  (the Service in ``k8s_manifests/extender-deployment.yaml``), read
  from a file so it is fixture-testable off-network.

* **Fleet promote** — one designated canary POOL promotes through its
  own ``/promote`` + ``/rollout`` gates (which already canary one
  WORKER internally) and holds; the remaining pools roll one at a
  time only after the canary pool lands. Any pool-level rollback or a
  pool dying mid-roll aborts the fleet promote and reverts every
  already-rolled pool to its incumbent checkpoint. The fleet
  generation advances only after the last pool. All of it is recorded
  in a graftstudy-discipline ``fleet_ledger.jsonl`` (atomic whole-file
  rewrites, spec-fingerprint header, SIGKILL-anywhere resumable) with
  graftloop's promote-stage semantics: a pool 422 is a *refusal*
  outcome, a 5xx/timeout is transient (nothing recorded — a re-run
  resumes and retries), a connection-level failure mid-roll is an
  *abort*.

* **Fleet observability** — ``GET /stats`` and ``/metrics`` merge pool
  scrapes with the SAME pure functions the pool applies to worker
  snapshots (``aggregate_stats`` over pseudo-snapshots built from each
  pool's additive ``raw`` histogram section): bucket sums for
  latency/phases, ``slo.merge_snapshots``, breaker max-by-severity,
  fastpath counter sums / agreement min. Merged == union of per-pool
  scrapes, pinned by test. Fleet-only series (``_fleet_generation``,
  ``_fleet_pool_up{pool=}``, promote/rollback/abort totals) ride on
  top; ``/healthz`` separates *degraded* pools (scrape answered,
  below strength or burning SLO) from *down* pools (scrape failed).
  Scrape EITHER the pools OR the fleet — scraping both double-counts.

* **Trace harvest** — ``fleet_snapshot`` fans graftloop's
  ``snapshot_trace`` out across every pool's trace dir into ONE
  snapshot root with per-pool file prefixes and a union manifest, so a
  single graftloop iteration retrains on fleet-wide traffic.

Stdlib-only: the controller never imports jax (or the loopback retrain
stack — snapshot helpers import lazily), so it runs on any box that
can reach the pools' control planes.
"""

from __future__ import annotations

import argparse
import dataclasses
import hashlib
import json
import logging
import os
import shutil
import signal
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path

from rl_scheduler_tpu.scheduler.drift import (
    drift_metric_lines,
    shadow_metric_lines,
)
from rl_scheduler_tpu.scheduler.extender import (
    LatencyStats,
    fastpath_metric_lines,
    phase_metric_lines,
    slo_metric_lines,
)
from rl_scheduler_tpu.scheduler.pool import (
    METRIC_PREFIX,
    aggregate_stats,
    merge_phase_histograms,
    merge_worker_histograms,
)
from rl_scheduler_tpu.utils.pidlock import acquire_pidfile_lock
from rl_scheduler_tpu.utils.retry import CircuitBreaker

logger = logging.getLogger(__name__)

FLEET_SCHEMA_VERSION = 1
FLEET_LEDGER_NAME = "fleet_ledger.jsonl"
FLEET_LOCK_NAME = "fleet_promote.lock"


# ------------------------------------------------------------ discovery


@dataclasses.dataclass(frozen=True)
class PoolRef:
    """One pool's control plane. ``name`` is the stable identity the
    ledger and the ``pool=`` metric label use; ``host:port`` is where
    the scrapes and promotes go."""

    name: str
    host: str
    port: int

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"


def parse_pools(spec: str) -> list:
    """``host:port,host:port,...`` -> ``[PoolRef]`` (names are the
    ``host:port`` strings — unambiguous and stable across restarts)."""
    refs = []
    for entry in spec.split(","):
        entry = entry.strip()
        if not entry:
            continue
        host, sep, port = entry.rpartition(":")
        if not sep or not host:
            raise ValueError(
                f"--pools entry {entry!r}: expected host:port")
        try:
            refs.append(PoolRef(name=entry, host=host, port=int(port)))
        except ValueError:
            raise ValueError(f"--pools entry {entry!r}: port must be an "
                             "integer")
    if not refs:
        raise ValueError("--pools: at least one host:port entry")
    return refs


class StaticResolver:
    """The ``--pools`` list, resolved once at construction. The seam
    every other topology source implements: ``resolve() -> [PoolRef]``,
    called per refresh so dynamic sources can churn."""

    def __init__(self, pools):
        self._pools = (parse_pools(pools) if isinstance(pools, str)
                       else list(pools))

    def resolve(self) -> list:
        return list(self._pools)


class EndpointsResolver:
    """Pool discovery from a kubernetes Endpoints document (JSON), read
    from a file on every ``resolve()`` so endpoint churn is picked up.
    Off-network by design: point it at ``kubectl get endpoints
    rl-scheduler-extender -o json`` output, a downward-API mount, or a
    test fixture. Addresses come from every subset; the port is the
    subset port named ``port_name`` (the control port in
    ``k8s_manifests/extender-deployment.yaml``), falling back to the
    first listed port when nothing matches by name."""

    def __init__(self, source: str | Path, port_name: str = "control"):
        self.source = Path(source)
        self.port_name = port_name

    def resolve(self) -> list:
        doc = json.loads(self.source.read_text())
        refs = []
        for subset in doc.get("subsets") or []:
            ports = subset.get("ports") or []
            port = next((p["port"] for p in ports
                         if p.get("name") == self.port_name),
                        ports[0]["port"] if ports else None)
            if port is None:
                continue
            for addr in subset.get("addresses") or []:
                ip = addr.get("ip")
                if ip:
                    refs.append(PoolRef(name=f"{ip}:{port}",
                                        host=ip, port=int(port)))
        if not refs:
            raise ValueError(
                f"{self.source}: no ready addresses in the Endpoints "
                "document (is the Deployment ready?)")
        return refs


# ----------------------------------------------------------- the merge


_EMPTY_HIST = {"cumulative": [], "sum": 0.0, "count": 0}


def pool_stats_snapshot(name: str, body: dict) -> dict:
    """Adapt one pool's ``/stats`` body into the pseudo-worker-snapshot
    shape ``pool.aggregate_stats`` consumes, so the fleet merge is
    LITERALLY the pool merge one level up. Raw bucket counts come from
    the body's additive ``raw`` section; a version-skewed pool without
    it contributes an empty histogram (the optional-phase rule), so its
    counters still sum while its latency simply adds no buckets."""
    raw = body.get("raw") or {}
    stats = {
        "backend": body.get("backend"),
        "family": body.get("family"),
        "decisions": body.get("decisions") or {},
        "breakers": body.get("breakers") or {},
        "latency": body.get("latency") or {},
    }
    for key in ("shed_fraction", "reroute_fraction", "placements_dropped",
                "fail_open_total", "fastpath", "drift", "shadow"):
        # graftdrift: the drift section is closed under merge (bucket
        # counts sum, distances recompute), so the pool-merged section
        # re-merges at fleet level with the SAME drift.merge_snapshots
        # the pool used — a pool without it contributes nothing, never
        # a zero-filled distance.
        if key in body:
            stats[key] = body[key]
    snap = {
        "worker_id": name,
        "pid": None,
        "generation": (body.get("pool") or {}).get("generation", 0),
        "stats": stats,
        "histogram": raw.get("histogram") or dict(_EMPTY_HIST),
        "phases": raw.get("phases") or {},
    }
    if body.get("slo"):
        snap["slo"] = body["slo"]
    if body.get("trace"):
        snap["trace"] = body["trace"]
    return snap


def aggregate_fleet_stats(scrapes: dict, fleet: dict) -> dict:
    """The fleet ``GET /stats`` body: ``pool.aggregate_stats`` over the
    pool pseudo-snapshots (down pools — ``None`` bodies — contribute
    nothing; they are visible in ``fleet.down``, never silently
    averaged in). The body keeps the pool-body keys decisionview reads
    (``latency``/``phases``/``slo``/``fastpath``) and its own additive
    ``raw`` section, so a fleet-of-fleets merges the same way."""
    snaps = [pool_stats_snapshot(name, body)
             for name, body in sorted(scrapes.items()) if body]
    out = aggregate_stats(snaps, pool={})
    del out["pool"]
    rows = out.pop("workers")
    for row in rows:
        row["pool"] = row.pop("worker_id")
        row.pop("pid", None)
    out["pools"] = rows
    out["fleet"] = dict(fleet)
    return out


def aggregate_fleet_metrics(scrapes: dict, fleet: dict) -> str:
    """The fleet Prometheus exposition: the SAME metric names and the
    same shared exposition helpers as the pool plane (one scrape config
    serves worker, pool, and fleet), counters summed across pools, ONE
    merged histogram, plus the ``_fleet_*`` series. Point Prometheus at
    EITHER the pools or the fleet — both double-counts."""
    p = METRIC_PREFIX
    snaps = [pool_stats_snapshot(name, body)
             for name, body in sorted(scrapes.items()) if body]
    merged_cum, merged_sum, merged_count = merge_worker_histograms(snaps)
    phase_hists = merge_phase_histograms(snaps)
    stats = aggregate_fleet_stats(scrapes, fleet)
    lines = [
        f"# HELP {p}_decisions_total Placement decisions by cloud "
        "(summed across fleet pools).",
        f"# TYPE {p}_decisions_total counter",
    ]
    for cloud, n in sorted(stats["decisions"].items()):
        lines.append(f'{p}_decisions_total{{cloud="{cloud}"}} {n}')
    lines += [
        f"# HELP {p}_decision_latency_seconds Server-side decision "
        "latency (merged across fleet pools; lifetime histogram).",
        f"# TYPE {p}_decision_latency_seconds histogram",
    ]
    bounds = [f"{b:g}" for b in LatencyStats.BUCKETS] + ["+Inf"]
    for bound, c in zip(bounds, merged_cum or [0] * len(bounds)):
        lines.append(
            f'{p}_decision_latency_seconds_bucket{{le="{bound}"}} {c}')
    lines.append(f"{p}_decision_latency_seconds_sum {merged_sum:.9g}")
    lines.append(f"{p}_decision_latency_seconds_count {merged_count}")
    if phase_hists:
        lines += phase_metric_lines(p, phase_hists)
    if "slo" in stats:
        lines += slo_metric_lines(p, stats["slo"])
    if "drift" in stats:
        lines += drift_metric_lines(p, stats["drift"])
    if "shadow" in stats:
        lines += shadow_metric_lines(p, stats["shadow"])
    if "fastpath" in stats:
        lines += fastpath_metric_lines(p, stats["fastpath"])
    for key, help_text in (
        ("fail_open_total", "Requests answered by a fail-open path, "
                            "summed across fleet pools."),
        ("placements_dropped", "Dry-run placements dropped by the "
                               "bounded async queues, fleet total."),
    ):
        if key in stats:
            suffix = "_total" if not key.endswith("_total") else ""
            lines += [
                f"# HELP {p}_{key}{suffix} {help_text}",
                f"# TYPE {p}_{key}{suffix} counter",
                f"{p}_{key}{suffix} {stats[key]}",
            ]
    breakers = stats["breakers"]
    if breakers:
        lines += [
            f"# HELP {p}_circuit_state Circuit breaker state per "
            "host-I/O boundary, MAX across fleet pools (0=closed, "
            "1=half_open, 2=open).",
            f"# TYPE {p}_circuit_state gauge",
        ]
        for name, snap in breakers.items():
            code = CircuitBreaker.STATE_CODES[snap["state"]]
            lines.append(f'{p}_circuit_state{{breaker="{name}"}} {code}')
    # The fleet-only series: topology liveness and the ledger-derived
    # promote lifecycle (monotonic — /stats/reset fan-out never touches
    # the ledger, pinned by test).
    up = [name for name, body in sorted(scrapes.items()) if body]
    lines += [
        f"# HELP {p}_fleet_pools Pools in the fleet topology.",
        f"# TYPE {p}_fleet_pools gauge",
        f"{p}_fleet_pools {len(scrapes)}",
        f"# HELP {p}_fleet_pools_up Pools that answered this scrape.",
        f"# TYPE {p}_fleet_pools_up gauge",
        f"{p}_fleet_pools_up {len(up)}",
        f"# HELP {p}_fleet_pool_up Per-pool scrape liveness "
        "(1=answered, 0=down).",
        f"# TYPE {p}_fleet_pool_up gauge",
    ]
    for name in sorted(scrapes):
        lines.append(
            f'{p}_fleet_pool_up{{pool="{name}"}} '
            f'{1 if scrapes[name] else 0}')
    lines += [
        f"# HELP {p}_fleet_pool_generation Policy generation each pool "
        "serves (divergence mid-roll is visible, never averaged).",
        f"# TYPE {p}_fleet_pool_generation gauge",
    ]
    for name in sorted(scrapes):
        body = scrapes[name]
        if body:
            gen = (body.get("pool") or {}).get("generation", 0)
            lines.append(
                f'{p}_fleet_pool_generation{{pool="{name}"}} {gen}')
    lines += [
        f"# HELP {p}_fleet_generation Fleet policy generation (advances "
        "only after the LAST pool of a fleet promote lands).",
        f"# TYPE {p}_fleet_generation gauge",
        f"{p}_fleet_generation {fleet.get('generation', 0)}",
    ]
    for key, help_text in (
        ("promotions_total", "Fleet promotes that landed on every pool "
                             "(lifetime)."),
        ("rollbacks_total", "Pool-level rollbacks observed during fleet "
                            "promotes (lifetime)."),
        ("aborts_total", "Fleet promotes aborted and reverted "
                         "(lifetime)."),
        ("refusals_total", "Fleet promotes refused by the canary pool "
                           "with nothing rolled (lifetime)."),
    ):
        lines += [
            f"# HELP {p}_fleet_{key} {help_text}",
            f"# TYPE {p}_fleet_{key} counter",
            f"{p}_fleet_{key} {fleet.get(key, 0)}",
        ]
    return "\n".join(lines) + "\n"


# ------------------------------------------------------------ the ledger


@dataclasses.dataclass(frozen=True)
class FleetSpec:
    """The fleet promote topology, frozen: which pools, which one
    canaries. The fingerprint binds the ledger — a changed topology
    refuses to resume into the same fleet dir (the graftstudy rule:
    two protocols must not interleave records)."""

    pools: tuple
    canary: str

    def __post_init__(self):
        if not self.pools:
            raise ValueError("pools: a fleet has at least one pool")
        if self.canary not in self.pools:
            raise ValueError(
                f"canary {self.canary!r} is not one of the fleet's pools "
                f"{list(self.pools)}")

    def to_json(self) -> dict:
        return {"pools": list(self.pools), "canary": self.canary}

    def fingerprint(self) -> str:
        blob = json.dumps(self.to_json(), sort_keys=True,
                          separators=(",", ":"))
        return hashlib.sha256(blob.encode()).hexdigest()[:16]


class FleetLedgerMismatch(RuntimeError):
    """The fleet dir's ledger was written under a different topology."""


class FleetLedger:
    """The fleet's promote journal: the graftstudy ledger discipline
    (whole-file tmp-then-rename appends, sorted-key records, header
    bound to the spec fingerprint) applied to fleet promotes. A SIGKILL
    leaves a complete ledger — prior bytes survive verbatim, so a
    resumed run's ledger is a byte-prefix extension of the killed one.

    Record kinds after the header: ``begin`` (promote id, candidate
    checkpoint, per-pool incumbents), ``stage`` (one pool × role —
    canary/roll/revert — with graftloop's outcome vocabulary:
    ok/refused/rolled_back/aborted), ``end`` (ok/refused/aborted). The
    fleet lifecycle counters DERIVE from the ledger, which is why
    ``/stats/reset`` can never rewind them."""

    def __init__(self, fleet_dir: str | Path, spec: FleetSpec):
        self.path = Path(fleet_dir) / FLEET_LEDGER_NAME
        self.spec = spec
        self.path.parent.mkdir(parents=True, exist_ok=True)
        if self.path.exists() and self.path.stat().st_size:
            header = json.loads(self.path.read_text().splitlines()[0])
            if header.get("spec_sha") != spec.fingerprint():
                raise FleetLedgerMismatch(
                    f"{self.path} was written for topology "
                    f"{header.get('spec_sha')}; this run's topology is "
                    f"{spec.fingerprint()} — a changed fleet cannot "
                    "resume into the same ledger (use a new fleet dir)")
        else:
            self._rewrite([self._dumps({
                "kind": "header",
                "schema_version": FLEET_SCHEMA_VERSION,
                "spec_sha": spec.fingerprint(),
                "spec": spec.to_json(),
            })])

    @staticmethod
    def _dumps(record: dict) -> str:
        return json.dumps(record, sort_keys=True, separators=(", ", ": "))

    def _rewrite(self, lines: list) -> None:
        tmp = self.path.with_suffix(f".tmp.{os.getpid()}")
        data = "".join(line + "\n" for line in lines)
        with open(tmp, "w") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)

    def append(self, record: dict) -> None:
        record = dict(record)
        record.setdefault("ts", round(time.time(), 3))
        lines = (self.path.read_text().splitlines()
                 if self.path.exists() else [])
        self._rewrite(lines + [self._dumps(record)])

    def records(self) -> list:
        if not self.path.exists():
            return []
        return [json.loads(line)
                for line in self.path.read_text().splitlines()[1:]]

    def begun_total(self) -> int:
        return sum(1 for r in self.records() if r.get("kind") == "begin")

    def open_promote(self) -> dict | None:
        """The latest ``begin`` record with no matching ``end`` —
        the promote a resumed run must finish before anything else."""
        open_by_id: dict = {}
        for record in self.records():
            if record.get("kind") == "begin":
                open_by_id[record["promote"]] = record
            elif record.get("kind") == "end":
                open_by_id.pop(record["promote"], None)
        if not open_by_id:
            return None
        return list(open_by_id.values())[-1]

    def promote_stages(self, promote_id: str) -> dict:
        """``{(pool, role): record}`` for one promote's recorded
        stages (newest wins)."""
        out = {}
        for record in self.records():
            if (record.get("kind") == "stage"
                    and record.get("promote") == promote_id):
                out[(record["pool"], record["role"])] = record
        return out

    def counters(self) -> dict:
        """The fleet lifecycle counters, derived by scanning the ledger
        — durable across controller restarts and immune to
        ``/stats/reset`` by construction."""
        out = {"generation": 0, "promotions_total": 0,
               "rollbacks_total": 0, "aborts_total": 0,
               "refusals_total": 0}
        for record in self.records():
            kind = record.get("kind")
            if kind == "end":
                status = record.get("status")
                if status == "ok":
                    out["promotions_total"] += 1
                elif status == "aborted":
                    out["aborts_total"] += 1
                elif status == "refused":
                    out["refusals_total"] += 1
            elif (kind == "stage"
                    and record.get("status") == "rolled_back"):
                out["rollbacks_total"] += 1
        out["generation"] = out["promotions_total"]
        return out


# -------------------------------------------------------- the controller


class FleetController:
    """Scrape, merge, health-classify, and promote across a fleet of
    pool control planes. Stdlib HTTP only; every network failure is
    classified, never swallowed silently."""

    def __init__(self, resolver, fleet_dir: str | Path,
                 canary: str | None = None, scrape_timeout_s: float = 2.0,
                 rollout_timeout_s: float = 120.0,
                 canary_hold_s: float = 0.0, fault_plan=None):
        self.resolver = resolver
        self.fleet_dir = Path(fleet_dir)
        self.scrape_timeout_s = scrape_timeout_s
        self.rollout_timeout_s = rollout_timeout_s
        self.canary_hold_s = canary_hold_s
        self.fault_plan = fault_plan
        self.pools = list(resolver.resolve())
        if not self.pools:
            raise ValueError("resolver returned no pools")
        names = [ref.name for ref in self.pools]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate pool names in topology: {names}")
        self.canary = canary if canary is not None else names[0]
        self.spec = FleetSpec(pools=tuple(names), canary=self.canary)
        self.fleet_dir.mkdir(parents=True, exist_ok=True)
        self.ledger = FleetLedger(self.fleet_dir, self.spec)
        self._by_name = {ref.name: ref for ref in self.pools}

    def refresh(self) -> list:
        """Re-resolve the topology (Endpoints churn). Scrapes follow the
        new pool set immediately; the promote topology stays bound to
        the ledger spec — a changed pool SET needs a new fleet dir."""
        self.pools = list(self.resolver.resolve())
        self._by_name = {ref.name: ref for ref in self.pools}
        return self.pools

    # ------------------------------------------------------- scraping

    def scrape_pool(self, ref: PoolRef) -> dict | None:
        """One pool's ``/stats`` body, ``None`` when the pool is down
        or times out — the merge proceeds over the pools that answered
        (the fault site ``fleet.scrape`` fires here)."""
        try:
            if self.fault_plan is not None:
                self.fault_plan.check("fleet.scrape", TimeoutError)
            with urllib.request.urlopen(
                    ref.url + "/stats",
                    timeout=self.scrape_timeout_s) as resp:
                body = json.load(resp)
            return body if isinstance(body, dict) else None
        except Exception as exc:  # noqa: BLE001 — classified as down
            logger.warning("fleet: scrape of %s failed: %s", ref.name, exc)
            return None

    def scrape(self) -> dict:
        # Serial, in topology order: deterministic fault-plan indices
        # matter more than shaving a timeout off a 3-pool scrape.
        return {ref.name: self.scrape_pool(ref) for ref in self.pools}

    def fleet_info(self, scrapes: dict) -> dict:
        counters = self.ledger.counters()
        down = sorted(n for n, body in scrapes.items() if body is None)
        return {
            "pools": [ref.name for ref in self.pools],
            "canary": self.canary,
            "up": len(scrapes) - len(down),
            "down": down,
            **counters,
        }

    def stats(self) -> dict:
        scrapes = self.scrape()
        return aggregate_fleet_stats(scrapes, self.fleet_info(scrapes))

    def metrics(self) -> str:
        scrapes = self.scrape()
        return aggregate_fleet_metrics(scrapes, self.fleet_info(scrapes))

    def health(self) -> dict:
        """Degraded-vs-down classification from ONE scrape pass: a pool
        whose scrape failed is *down*; a pool that answered but is below
        worker strength (outside a rollout) or burning its SLO budget is
        *degraded*. The fleet is ``down`` only when every pool is."""
        scrapes = self.scrape()
        pools: dict = {}
        for ref in self.pools:
            body = scrapes.get(ref.name)
            if body is None:
                pools[ref.name] = {"status": "down"}
                continue
            status = body.get("pool") or {}
            rolling = bool((status.get("rollout") or {}).get("active"))
            workers = status.get("workers", 0)
            alive = status.get("alive", status.get("responding", 0))
            state = "ok"
            if alive < workers:
                state = "rolling" if rolling else "degraded"
            if (body.get("slo") or {}).get("degraded") and state == "ok":
                state = "degraded"
            pools[ref.name] = {
                "status": state,
                "workers": workers,
                "alive": alive,
                "generation": status.get("generation", 0),
            }
        down = sorted(n for n, p in pools.items() if p["status"] == "down")
        degraded = sorted(n for n, p in pools.items()
                          if p["status"] == "degraded")
        if len(down) == len(pools):
            fleet_state = "down"
        elif down or degraded:
            fleet_state = "degraded"
        else:
            fleet_state = "ok"
        counters = self.ledger.counters()
        return {
            "status": fleet_state,
            "pools": pools,
            "up": len(pools) - len(down),
            "down": down,
            "degraded": degraded,
            "workers": sum(p.get("alive", 0) for p in pools.values()),
            "generation": counters["generation"],
        }

    def reset_stats(self) -> dict:
        """Fan ``/stats/reset`` out to every pool. The fleet lifecycle
        counters derive from the ledger and every pool-side lifetime
        counter is reset-proof already, so nothing monotonic rewinds."""
        acked = {}
        for ref in self.pools:
            try:
                req = urllib.request.Request(ref.url + "/stats/reset",
                                             data=b"", method="POST")
                with urllib.request.urlopen(
                        req, timeout=self.scrape_timeout_s) as resp:
                    acked[ref.name] = resp.status == 200
            except Exception as exc:  # noqa: BLE001 — down pool: not acked
                logger.warning("fleet: /stats/reset to %s failed: %s",
                               ref.name, exc)
                acked[ref.name] = False
        return {"status": "reset", "pools": acked}

    # ------------------------------------------------------- promoting

    def promote(self, checkpoint: str) -> dict:
        """Run (or resume) one fleet promote of ``checkpoint``. Single
        writer per fleet dir (pidfile lock); every outcome lands in the
        ledger before this returns."""
        checkpoint = str(checkpoint)
        lock = acquire_pidfile_lock(
            self.fleet_dir / FLEET_LOCK_NAME,
            "fleet promote already running as pid {pid} (lock {lock})")
        try:
            return self._promote_locked(checkpoint)
        finally:
            lock.unlink(missing_ok=True)

    def _promote_locked(self, checkpoint: str) -> dict:
        order = [self.canary] + [n for n in self.spec.pools
                                 if n != self.canary]
        begin = self.ledger.open_promote()
        if begin is not None and begin.get("checkpoint") != checkpoint:
            raise RuntimeError(
                f"fleet promote of {begin.get('checkpoint')!r} is "
                f"mid-flight in {self.ledger.path}; resume that "
                "checkpoint first (re-run with it) — two promotes must "
                "not interleave")
        if begin is None:
            # Gather incumbents BEFORE anything rolls: this is the
            # revert target set. A pool unreachable here is transient
            # (nothing recorded) — fix the pool and re-run.
            incumbents = {}
            for name in order:
                status = self._rollout_status(self._by_name[name])
                if status.get("active"):
                    raise RuntimeError(
                        f"pool {name} has a rollout in flight — wait "
                        "for it before a fleet promote")
                incumbents[name] = {
                    "generation": status.get("generation", 0),
                    "checkpoint": status.get("checkpoint"),
                }
            promote_id = f"fp{self.ledger.begun_total() + 1:04d}"
            self.ledger.append({"kind": "begin", "promote": promote_id,
                                "checkpoint": checkpoint,
                                "incumbents": incumbents})
        else:
            promote_id = begin["promote"]
            incumbents = begin["incumbents"]
        stages = self.ledger.promote_stages(promote_id)
        rolled = []
        failure = None
        for name in order:
            role = "canary" if name == self.canary else "roll"
            if (name, role) in stages:
                record = stages[(name, role)]
                if record["status"] == "ok":
                    rolled.append(name)
                    continue
                failure = {"pool": name, "role": role,
                           "status": record["status"],
                           "out": record.get("out", {})}
                break
            if failure is None:
                status, out = self._promote_pool(
                    self._by_name[name], checkpoint, role)
                self.ledger.append({"kind": "stage", "promote": promote_id,
                                    "pool": name, "role": role,
                                    "status": status, "out": out})
                if status != "ok":
                    failure = {"pool": name, "role": role,
                               "status": status, "out": out}
                    break
                rolled.append(name)
                if role == "canary" and self.canary_hold_s > 0:
                    # The fleet-level canary HOLD: the canary pool bakes
                    # on live traffic before the rest of the fleet rolls.
                    time.sleep(self.canary_hold_s)
        if failure is None:
            counters = self.ledger.counters()
            generation = counters["generation"] + 1
            self.ledger.append({"kind": "end", "promote": promote_id,
                                "status": "ok", "checkpoint": checkpoint,
                                "generation": generation})
            return {"promote": promote_id, "status": "ok",
                    "generation": generation, "pools": order,
                    "checkpoint": checkpoint}
        if failure["status"] == "refused" and not rolled:
            # graftloop's rule, one level up: a refusal with NOTHING
            # rolled is an outcome, not an abort — the fleet never left
            # the incumbent generation.
            self.ledger.append({"kind": "end", "promote": promote_id,
                                "status": "refused",
                                "reason": failure["out"].get("reason"),
                                "pool": failure["pool"]})
            return {"promote": promote_id, "status": "refused",
                    "pool": failure["pool"],
                    "reason": failure["out"].get("reason")}
        reverted = {}
        for name in reversed(rolled):
            if (name, "revert") in stages:
                reverted[name] = stages[(name, "revert")]["status"]
                continue
            status, out = self._promote_pool(
                self._by_name[name], incumbents[name].get("checkpoint"),
                "revert")
            self.ledger.append({"kind": "stage", "promote": promote_id,
                                "pool": name, "role": "revert",
                                "status": status, "out": out})
            reverted[name] = status
        self.ledger.append({"kind": "end", "promote": promote_id,
                            "status": "aborted", "pool": failure["pool"],
                            "reason": failure["out"].get("reason"),
                            "reverted": reverted})
        return {"promote": promote_id, "status": "aborted",
                "pool": failure["pool"],
                "reason": failure["out"].get("reason"),
                "reverted": reverted}

    def _promote_pool(self, ref: PoolRef, checkpoint, role: str):
        """One pool × role step: ``(status, out)`` with graftloop's
        promote-stage vocabulary. ``ok`` — the pool serves the
        checkpoint; ``refused`` — the pool said no (4xx) and stayed on
        its incumbent; ``rolled_back`` — the pool's own canary/health
        gate rolled it back; ``aborted`` — the pool became unreachable
        mid-roll. Transient conditions (5xx, poll deadline) RAISE with
        nothing recorded, so a re-run resumes and retries the step."""
        try:
            if checkpoint is None:
                return "refused", {"reason": f"pool {ref.name} has no "
                                   "incumbent checkpoint to revert to"}
            # Idempotent resume: a killed run's POST may have landed.
            status = self._rollout_status(ref)
            if status.get("active"):
                status = self._poll_rollout(ref)
            if status.get("checkpoint") == checkpoint:
                return "ok", {"generation": status.get("generation", 0),
                              "already_serving": True}
            if self.fault_plan is not None:
                self.fault_plan.check("fleet.promote", ConnectionError)
            req = urllib.request.Request(
                ref.url + "/promote",
                data=json.dumps({"checkpoint": checkpoint}).encode(),
                headers={"Content-Type": "application/json"})
            target = None
            try:
                with urllib.request.urlopen(req, timeout=30) as resp:
                    body = json.load(resp)
                target = body.get("target_generation")
            except urllib.error.HTTPError as exc:
                detail = exc.read().decode(errors="replace")[:200]
                if exc.code == 409:
                    # A rollout raced in (our own killed POST, or an
                    # operator's) — judge by where the pool lands.
                    pass
                elif exc.code >= 500:
                    raise RuntimeError(
                        f"pool {ref.name} answered {exc.code} on "
                        f"/promote ({detail}) — transient, re-run to "
                        "resume this step")
                else:
                    return "refused", {
                        "code": exc.code,
                        "reason": f"pool {ref.name} refused the promote "
                                  f"({exc.code}): {detail}"}
            status = self._poll_rollout(ref)
            if status.get("checkpoint") == checkpoint and (
                    target is None
                    or status.get("generation") == target):
                return "ok", {"generation": status.get("generation", 0)}
            return "rolled_back", {
                "generation": status.get("generation", 0),
                "reason": status.get("last_error")
                or f"pool {ref.name} stayed on its incumbent"}
        except (TimeoutError, RuntimeError):
            raise
        except (urllib.error.URLError, OSError) as exc:
            return "aborted", {
                "reason": f"pool {ref.name} unreachable mid-{role}: "
                          f"{exc}"}

    def _rollout_status(self, ref: PoolRef, attempts: int = 3) -> dict:
        """``GET /rollout`` with a couple of quick retries so one
        dropped packet does not read as a dead pool."""
        for attempt in range(attempts):
            try:
                with urllib.request.urlopen(ref.url + "/rollout",
                                            timeout=10) as resp:
                    return json.load(resp)
            except (urllib.error.URLError, OSError):
                if attempt == attempts - 1:
                    raise
                time.sleep(0.2)
        raise AssertionError("unreachable")

    def _poll_rollout(self, ref: PoolRef) -> dict:
        deadline = time.monotonic() + self.rollout_timeout_s
        while time.monotonic() < deadline:
            status = self._rollout_status(ref)
            if not status.get("active"):
                return status
            time.sleep(0.2)
        raise TimeoutError(
            f"pool {ref.name} rollout still in flight after "
            f"{self.rollout_timeout_s:.0f}s — transient, re-run to "
            "resume")


# -------------------------------------------------------- trace harvest


def fleet_snapshot(trace_dirs, dest: str | Path, fault_plan=None) -> dict:
    """Fan graftloop's ``snapshot_trace`` across every pool's trace dir
    into ONE snapshot root. Each pool's segments land under a ``p<i>-``
    prefix (still ``_SEG_RE``-parseable, so the union root IS a valid
    trace dir for ``iter_trace`` — and therefore for a graftloop
    iteration's own snapshot stage), with a union manifest recording
    per-pool provenance, the merged record count, and the content
    digest. ``trace_dirs`` is ``{pool_name: dir}`` (sorted for a
    deterministic prefix assignment) or an ordered ``[(name, dir)]``."""
    from rl_scheduler_tpu.loopback.compile import (
        SNAPSHOT_META,
        snapshot_digest,
        snapshot_trace,
    )
    from rl_scheduler_tpu.scheduler.tracelog import iter_trace
    from rl_scheduler_tpu.utils.fsio import atomic_write_json, fresh_dir

    items = (sorted(trace_dirs.items()) if isinstance(trace_dirs, dict)
             else list(trace_dirs))
    if not items:
        raise ValueError("fleet_snapshot: at least one (name, trace_dir)")
    dest = fresh_dir(dest)
    pools_meta = {}
    files = {}
    for i, (name, trace_dir) in enumerate(items):
        staging = dest / f".pool-{i}.tmp"
        meta = snapshot_trace(trace_dir, staging, fault_plan=fault_plan)
        prefix = f"p{i}-"
        for fname in sorted(meta["files"]):
            os.replace(staging / fname, dest / (prefix + fname))
            files[prefix + fname] = meta["files"][fname]
        shutil.rmtree(staging)
        pools_meta[name] = {"source": meta["source"],
                            "records": meta["records"],
                            "segments": len(meta["files"]),
                            "prefix": prefix}
    records = sum(1 for _ in iter_trace(dest))
    union = {
        "source": "fleet",
        "pools": pools_meta,
        "files": files,
        "records": records,
        "digest": snapshot_digest(dest),
    }
    atomic_write_json(dest / SNAPSHOT_META, union, indent=2)
    return union


# ------------------------------------------------------------ HTTP plane


class _FleetHandler(BaseHTTPRequestHandler):
    controller: FleetController  # bound by _make_fleet_server

    def _send(self, code: int, payload, content_type="application/json"):
        body = (payload if isinstance(payload, bytes)
                else json.dumps(payload).encode())
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802 (stdlib API)
        if self.path == "/healthz":
            health = self.controller.health()
            self._send(200 if health["status"] != "down" else 503, health)
        elif self.path == "/stats":
            self._send(200, self.controller.stats())
        elif self.path == "/metrics":
            self._send(200, self.controller.metrics().encode(),
                       content_type="text/plain; version=0.0.4; "
                                    "charset=utf-8")
        else:
            self._send(404, {"error": f"unknown path {self.path}"})

    def do_POST(self):  # noqa: N802
        length = int(self.headers.get("Content-Length", 0))
        self.rfile.read(length)
        if self.path == "/stats/reset":
            self._send(200, self.controller.reset_stats())
        else:
            # Fleet promotes run through the CLI (single writer, ledger
            # lock) — the HTTP plane stays read-mostly by design.
            self._send(404, {"error": f"unknown path {self.path}"})

    def log_message(self, fmt, *log_args):  # quiet, like the pool plane
        logger.debug("%s " + fmt, self.address_string(), *log_args)


def _make_fleet_server(controller: FleetController, host: str,
                       port: int) -> ThreadingHTTPServer:
    handler = type("BoundFleetHandler", (_FleetHandler,),
                   {"controller": controller})
    server = ThreadingHTTPServer((host, port), handler)
    # Non-daemon handler threads: server_close() joins them, so the
    # finally-block drain in run_fleet actually waits for in-flight
    # requests instead of letting interpreter exit kill them mid-reply
    # (same contract as the pool's serving plane, scheduler/pool.py).
    server.daemon_threads = False
    return server


def run_fleet(controller: FleetController, host: str, port: int) -> None:
    """Serve the fleet control plane until SIGTERM/SIGINT."""
    server = _make_fleet_server(controller, host, port)

    def _stop(signum, frame):  # noqa: ARG001 (signal API)
        threading.Thread(target=server.shutdown, daemon=True).start()

    signal.signal(signal.SIGTERM, _stop)
    signal.signal(signal.SIGINT, _stop)
    bound = server.socket.getsockname()
    print(
        f"graftfleet: {len(controller.pools)} pool(s) "
        f"({', '.join(r.name for r in controller.pools)}), canary "
        f"{controller.canary}, control plane on {bound[0]}:{bound[1]}",
        flush=True,
    )
    try:
        server.serve_forever()
    finally:
        server.server_close()


# --------------------------------------------------------------- CLI glue


def fault_plan_from_env(value: str | None):
    """Parse ``GRAFTFLEET_FAULTS`` into a deterministic FaultPlan
    schedule: ``site:idx[,idx...]`` entries joined by ``;`` — e.g.
    ``fleet.promote:3`` fires the third pool-promote attempt,
    ``fleet.scrape:1`` the first pool scrape. ``None``/empty disarms
    (the production default — the plan is plumbed, never ambient)."""
    if not value:
        return None
    from rl_scheduler_tpu.utils.faults import FaultPlan

    schedule: dict = {}
    for entry in value.split(";"):
        entry = entry.strip()
        if not entry:
            continue
        site, _, idxs = entry.partition(":")
        if not idxs:
            raise ValueError(
                f"GRAFTFLEET_FAULTS entry {entry!r}: expected "
                "site:call_index[,call_index...]")
        try:
            schedule[site.strip()] = tuple(
                int(i) for i in idxs.split(","))
        except ValueError:
            raise ValueError(
                f"GRAFTFLEET_FAULTS entry {entry!r}: call indices must "
                "be integers")
    return FaultPlan(schedule=schedule)


def _build_resolver(args):
    if args.endpoints:
        return EndpointsResolver(args.endpoints,
                                 port_name=args.endpoints_port)
    if args.pools:
        return StaticResolver(args.pools)
    raise SystemExit("pass --pools host:port,... or --endpoints FILE")


def _build_controller(args, fault_plan=None) -> FleetController:
    return FleetController(
        _build_resolver(args), fleet_dir=args.fleet_dir,
        canary=args.canary, scrape_timeout_s=args.scrape_timeout,
        rollout_timeout_s=getattr(args, "rollout_timeout", 120.0),
        canary_hold_s=getattr(args, "canary_hold", 0.0),
        fault_plan=fault_plan)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m rl_scheduler_tpu.scheduler.fleet",
        description="graftfleet: discovery, cross-pool canary promote, "
                    "fleet-merged observability, fleet-wide trace "
                    "harvest (docs/serving.md#graftfleet).")
    topo = argparse.ArgumentParser(add_help=False)
    topo.add_argument("--pools", default=None,
                      help="static topology: host:port,host:port,...")
    topo.add_argument("--endpoints", default=None,
                      help="k8s Endpoints JSON file (kubectl get "
                           "endpoints ... -o json); re-read per refresh")
    topo.add_argument("--endpoints-port", default="control",
                      help="named port to pick from the Endpoints "
                           "document (default: control)")
    topo.add_argument("--canary", default=None,
                      help="pool name that canaries a fleet promote "
                           "(default: first pool)")
    topo.add_argument("--fleet-dir", default="fleet",
                      help="ledger + lock directory (default: ./fleet)")
    topo.add_argument("--scrape-timeout", type=float, default=2.0)
    sub = p.add_subparsers(dest="cmd", required=True)
    serve = sub.add_parser("serve", parents=[topo],
                           help="serve the fleet control plane")
    serve.add_argument("--host", default="0.0.0.0")
    serve.add_argument("--port", type=int, default=8790)
    promote = sub.add_parser("promote", parents=[topo],
                             help="run (or resume) one fleet promote")
    promote.add_argument("--checkpoint", required=True,
                         help="candidate run dir (every pool must see "
                              "this path)")
    promote.add_argument("--rollout-timeout", type=float, default=120.0)
    promote.add_argument("--canary-hold", type=float, default=0.0,
                         help="seconds the canary pool bakes before the "
                              "rest of the fleet rolls")
    status = sub.add_parser("status", parents=[topo],
                            help="print the fleet health body")
    del status  # parsed via args.cmd
    snap = sub.add_parser("snapshot",
                          help="union-snapshot every pool's trace dir")
    snap.add_argument("--trace-dirs", required=True,
                      help="comma-separated pool trace directories")
    snap.add_argument("--names", default=None,
                      help="comma-separated pool names (default: "
                           "pool0,pool1,...)")
    snap.add_argument("--out", required=True,
                      help="union snapshot destination directory")
    args = p.parse_args(argv)

    fault_plan = fault_plan_from_env(os.environ.get("GRAFTFLEET_FAULTS"))
    if args.cmd == "snapshot":
        dirs = [d.strip() for d in args.trace_dirs.split(",") if d.strip()]
        names = ([n.strip() for n in args.names.split(",")]
                 if args.names else [f"pool{i}" for i in range(len(dirs))])
        if len(names) != len(dirs):
            p.error("--names must match --trace-dirs one to one")
        union = fleet_snapshot(list(zip(names, dirs)), args.out,
                               fault_plan=fault_plan)
        print(json.dumps({"metric": "fleet_snapshot",
                          "schema_version": FLEET_SCHEMA_VERSION,
                          "out": str(args.out),
                          "records": union["records"],
                          "segments": len(union["files"]),
                          "pools": {n: m["records"]
                                    for n, m in union["pools"].items()},
                          "digest": union["digest"]}))
        return 0
    controller = _build_controller(args, fault_plan=fault_plan)
    if args.cmd == "serve":
        run_fleet(controller, args.host, args.port)
        return 0
    if args.cmd == "status":
        health = controller.health()
        print(json.dumps(health, indent=2, sort_keys=True))
        return 0 if health["status"] != "down" else 1
    # promote
    summary = controller.promote(args.checkpoint)
    summary = {"metric": "fleet_promote",
               "schema_version": FLEET_SCHEMA_VERSION, **summary}
    print(json.dumps(summary, sort_keys=True))
    return {"ok": 0, "refused": 2}.get(summary["status"], 3)


if __name__ == "__main__":  # pragma: no cover - CLI entry
    raise SystemExit(main())
