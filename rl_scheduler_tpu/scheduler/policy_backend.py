"""Policy serving backends for the scheduler extender.

The reference planned (but never built) a scheduler plugin serving placement
decisions from a trained checkpoint (``rl_scheduler/scheduler/extender.py``,
0 bytes). The serving target is <1 ms p50 per decision, which rules out
naive ``jit`` dispatch-per-request on an accelerator round-trip; the
backends here are:

- ``jax``: single-observation apply AOT-compiled via
  ``jax.jit(...).lower().compile()`` with buffers kept warm on device.
- ``cpu``: the MLP forward extracted into plain numpy matmuls — zero
  framework dispatch overhead, microseconds per decision (the required
  CPU fallback).
- ``native``: the same forward in the C++ core
  (``native/mlp_infer.cpp``), one ctypes hop per decision — the fastest
  host path under concurrent serving load; degrades to ``cpu`` when the
  toolchain/library is unavailable.
- ``torch``: the same parameters mirrored into a torch CPU module (the
  reference stack's framework, kept as a serving fallback for users
  migrating from the RLlib/torch checkpoint world).
- ``greedy``: the cost-greedy baseline — the guaranteed-available fallback
  when no checkpoint loads (SURVEY.md §5.3 failure-handling plan).

All backends share one contract: ``decide(obs) -> (action, scores)`` where
``obs`` is a ``[OBS_DIM]`` float32 numpy array and ``scores`` are
per-action logits (greedy returns pseudo-logits from the cost gap).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable

import numpy as np

from rl_scheduler_tpu.env import core as env_core

logger = logging.getLogger(__name__)


# Per-algo network layout: (torso subtree, head subtree, hidden activation).
# ppo = flax ActorCritic (tanh torso, named submodules); dqn = QNetwork
# (relu torso, flax auto-names). Greedy argmax over the head output is the
# serving decision either way.
ALGO_LAYOUTS = {
    "ppo": ("actor_torso", "actor_head", "tanh"),
    "dqn": ("MLPTorso_0", "Dense_0", "relu"),
}


def _flatten_mlp(tree: dict, torso: str, head: str) -> list[tuple[np.ndarray, np.ndarray]]:
    """Extract ``[(kernel, bias), ...]`` for a torso+head stack from a flax
    MLP param tree (nested dicts, as restored by orbax)."""
    params = tree["params"] if "params" in tree else tree
    layers = []
    torso_tree = params[torso]
    for name in sorted(torso_tree, key=lambda n: int(n.split("_")[-1])):
        leaf = torso_tree[name]
        layers.append((np.asarray(leaf["kernel"]), np.asarray(leaf["bias"])))
    head_leaf = params[head]
    layers.append((np.asarray(head_leaf["kernel"]), np.asarray(head_leaf["bias"])))
    return layers


def _layout(algo: str) -> tuple[str, str, str]:
    if algo not in ALGO_LAYOUTS:
        raise ValueError(f"unknown algo {algo!r}; choose from {sorted(ALGO_LAYOUTS)}")
    return ALGO_LAYOUTS[algo]


class NumpyMLPBackend:
    """Policy forward pass in plain numpy (MLP -> action scores)."""

    name = "cpu"

    def __init__(self, params_tree: dict, algo: str = "ppo"):
        torso, head, act = _layout(algo)
        self._layers = _flatten_mlp(params_tree, torso, head)
        self._act = np.tanh if act == "tanh" else lambda x: np.maximum(x, 0.0)

    def decide(self, obs: np.ndarray) -> tuple[int, np.ndarray]:
        x = obs.astype(np.float32)
        for kernel, bias in self._layers[:-1]:
            x = self._act(x @ kernel + bias)
        kernel, bias = self._layers[-1]
        logits = x @ kernel + bias
        return int(np.argmax(logits)), logits


class NativeMLPBackend:
    """Policy forward in the C++ core (one ctypes call per decision)."""

    name = "native"

    def __init__(self, params_tree: dict, algo: str = "ppo"):
        from rl_scheduler_tpu.native import NativeMLP

        torso, head, act = _layout(algo)
        self._mlp = NativeMLP(_flatten_mlp(params_tree, torso, head), activation=act)

    def decide(self, obs: np.ndarray) -> tuple[int, np.ndarray]:
        return self._mlp.decide(obs)


class TorchMLPBackend:
    """Same policy forward mirrored into torch CPU tensors."""

    name = "torch"

    def __init__(self, params_tree: dict, algo: str = "ppo"):
        import torch

        self._torch = torch
        torso, head, act = _layout(algo)
        self._act = torch.tanh if act == "tanh" else torch.relu
        self._layers = [
            (torch.from_numpy(np.array(k)), torch.from_numpy(np.array(b)))
            for k, b in _flatten_mlp(params_tree, torso, head)
        ]

    def decide(self, obs: np.ndarray) -> tuple[int, np.ndarray]:
        torch = self._torch
        with torch.no_grad():
            x = torch.from_numpy(obs.astype(np.float32))
            for kernel, bias in self._layers[:-1]:
                x = self._act(x @ kernel + bias)
            kernel, bias = self._layers[-1]
            logits = (x @ kernel + bias).numpy()
        return int(np.argmax(logits)), logits


class JaxAOTBackend:
    """AOT-compiled single-obs apply; params live on device across requests.

    ``device="cpu"`` (default) compiles the apply for the host's XLA CPU
    backend: a single 6-dim decision is dispatch-bound, and serving from a
    remote/tunneled accelerator would pay a host<->device round-trip per
    request (measured ~70 ms p50 over a tunnel vs <0.1 ms on host). Pass
    ``device="tpu"`` to pin serving to a co-located accelerator.
    """

    name = "jax"

    def __init__(self, params_tree: dict, hidden: tuple = (256, 256),
                 device: str = "cpu", algo: str = "ppo"):
        import jax
        import jax.numpy as jnp

        from rl_scheduler_tpu.models import build_flat_policy_net

        _layout(algo)  # validate algo up front
        net = build_flat_policy_net(algo, env_core.NUM_ACTIONS, hidden)
        try:
            dev = jax.devices(device)[0]
        except RuntimeError:
            dev = jax.devices()[0]
        self._params = jax.device_put(params_tree, dev)

        def apply(params, obs):
            out = net.apply(params, obs)
            return out[0] if isinstance(out, tuple) else out

        obs_spec = jax.ShapeDtypeStruct((env_core.OBS_DIM,), jnp.float32)
        params_spec = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), self._params
        )
        with jax.default_device(dev):
            self._compiled = jax.jit(apply).lower(params_spec, obs_spec).compile()
        # Warm the dispatch path once so first request isn't a cold start.
        np.asarray(self._compiled(self._params, np.zeros(env_core.OBS_DIM, np.float32)))

    def decide(self, obs: np.ndarray) -> tuple[int, np.ndarray]:
        # NOTE on concurrency: a jax dispatch releases and re-acquires the
        # GIL while the XLA CPU executable runs, so under heavy multi-thread
        # serving load each call pays a thread-wakeup penalty that pure-C
        # numpy matmuls (which never release the GIL at these sizes) do not
        # (a queue/wakeup executor and finer GIL switch intervals were both
        # tried and measured no better). The ``jax`` serving flag therefore
        # maps to LoadAwareJaxBackend, which routes overflow concurrency
        # past this dispatcher; use this class directly only for
        # single-stream callers.
        logits = np.asarray(self._compiled(self._params, obs.astype(np.float32)))
        return int(np.argmax(logits)), logits


class ConcurrencyTracker:
    """In-flight request tracking shared by the load-aware families (one
    implementation, like :class:`ShedGate` / :class:`AdaptiveLatencyRouter`):
    who was concurrent at entry, and whether a timing window stayed
    single-stream — a mid-call join inflates wall times with GIL-wakeup
    penalties, so such samples must not feed the latency EWMAs."""

    def __init__(self):
        self._lock = threading.Lock()
        self._active = 0
        self._last_concurrent = float("-inf")   # monotonic seconds

    def enter(self) -> bool:
        """Register an in-flight request; True when others are already
        in flight (concurrency observed — also stamps the clock)."""
        with self._lock:
            self._active += 1
            if self._active > 1:
                self._last_concurrent = time.monotonic()
                return True
            return False

    def exit(self) -> None:
        with self._lock:
            self._active -= 1

    def clean_since(self, t0_monotonic: float) -> bool:
        """True when no concurrency has been observed since ``t0`` — the
        whole window was single-stream, so its timing is a clean sample."""
        with self._lock:
            return self._last_concurrent < t0_monotonic

    @property
    def last_concurrent(self) -> float:
        with self._lock:
            return self._last_concurrent

    def force_quiet(self) -> None:
        """Reset the concurrency clock (tests: deterministically end a
        cooldown window)."""
        with self._lock:
            self._last_concurrent = float("-inf")


class AdaptiveLatencyRouter:
    """Latency-aware AOT-vs-host routing state, shared by the MLP and
    set serving families (same rationale as :class:`ShedGate`: one
    implementation so the accounting cannot diverge).

    The AOT dispatch rides a backend whose round-trip is pool-dependent
    — measured sub-ms in quiet windows and 100+ ms when the tunnel/pool
    degrades — while the host forwards are deterministic. This tracks a
    latency EWMA per ``key`` (the set family keys on node count; the
    MLP family's obs shape is fixed, one key) for each path and demotes
    the AOT path once its EWMA exceeds ``margin`` x the host path's,
    with 1-in-``probe_every`` recovery probes so a recovered pool
    promotes it back without operator action.

    Callers must feed ``observe()`` only single-stream samples
    (contended wall times would corrupt both baselines) and only for
    calls the attributed path actually served. Thread-safe.

    Latency-based rerouting is accounted separately from overload
    shedding: ``reroute_fraction`` is the fraction of routing decisions
    that chose the host path — in a steady state where the host forward
    simply IS faster (a legitimate live condition), the overload
    ``shed_fraction`` metric must stay meaningful, not saturate at 1.
    """

    # The tuning constants, defined ONCE for both serving families (the
    # set family re-exports them as its ADAPTIVE_* attributes).
    ALPHA = 0.2
    MARGIN = 1.5
    PROBE_EVERY = 32
    MIN_SAMPLES = 8
    MAX_TRACKED = 64

    def __init__(self, label: str = "AOT dispatch",
                 alpha: float | None = None, margin: float | None = None,
                 probe_every: int | None = None,
                 min_samples: int | None = None,
                 max_tracked: int | None = None):
        self._label = label
        self._alpha = self.ALPHA if alpha is None else alpha
        self._margin = self.MARGIN if margin is None else margin
        self._probe_every = (self.PROBE_EVERY if probe_every is None
                             else probe_every)
        self._min_samples = (self.MIN_SAMPLES if min_samples is None
                             else min_samples)
        self._max_tracked = (self.MAX_TRACKED if max_tracked is None
                             else max_tracked)
        self._lock = threading.Lock()
        self.lat = {"aot": {}, "host": {}}     # key -> (ewma_ms, samples)
        self._probe_countdown = {}             # key -> requests to probe
        self._demotion_logged = set()          # keys already warned
        self._decisions = 0                    # route_aot() calls
        self._rerouted = 0                     # ... that chose host

    @property
    def min_samples(self) -> int:
        return self._min_samples

    @property
    def reroute_fraction(self) -> float:
        with self._lock:
            return self._rerouted / self._decisions if self._decisions else 0.0

    def observe(self, path: str, key, ms: float) -> None:
        with self._lock:
            table = self.lat[path]
            prev = table.get(key)
            if prev is None:
                # Bounded per-key state (a kube-scheduler's candidate
                # list size varies per pod): oldest-tracked evicts.
                while len(table) >= self._max_tracked:
                    evicted = next(iter(table))
                    del table[evicted]
                    self._probe_countdown.pop(evicted, None)
                    self._demotion_logged.discard(evicted)
                table[key] = (ms, 1)
            else:
                ewma, count = prev
                table[key] = (ewma + self._alpha * (ms - ewma), count + 1)

    def host_known(self, key) -> bool:
        with self._lock:
            return self.lat["host"].get(key) is not None

    def route_aot(self, key) -> tuple[bool, bool]:
        """``(route_aot, is_probe)`` for single-stream traffic at this
        key: AOT while healthy/unmeasured/probing, host once demoted."""
        with self._lock:
            self._decisions += 1
            aot = self.lat["aot"].get(key)
            host = self.lat["host"].get(key)
            if (aot is None or host is None
                    or aot[1] < self._min_samples
                    or aot[0] <= self._margin * host[0]):
                self._demotion_logged.discard(key)
                return True, False
            if key not in self._demotion_logged:
                self._demotion_logged.add(key)
                logger.warning(
                    "%s demoted at key=%s: EWMA %.2f ms vs host %.2f ms — "
                    "serving host-side, probing every %d requests",
                    self._label, key, aot[0], host[0], self._probe_every)
            left = self._probe_countdown.get(key, self._probe_every)
            if left <= 1:
                self._probe_countdown[key] = self._probe_every
                return True, True
            self._probe_countdown[key] = left - 1
            self._rerouted += 1
            return False, False

    def refund_probe(self, key) -> None:
        """A probe that produced no usable AOT sample (gate-shed, or the
        fallback served) must not count as taken, or sustained
        concurrency would starve recovery."""
        with self._lock:
            if key in self._probe_countdown:
                self._probe_countdown[key] = 1


class ShedGate:
    """Thread-safe admission control for load-aware routing, shared by the
    MLP (``LoadAwareJaxBackend``) and set (``LoadAwareSetBackend``)
    families so the accounting/logging mechanics cannot diverge.

    At most ``max_inflight`` callers run the primary path concurrently;
    the rest are shed (the caller routes them to its overflow forward).
    ``admit()`` returns ``(take_primary, log_line_or_None)`` — the log
    line is rate-limited to one per 5 s; ``release()`` must be called
    after a primary-path call finishes (use try/finally).
    """

    def __init__(self, max_inflight: float, primary: str = "jax dispatcher",
                 overflow: str = "overflow"):
        import time as _time

        self._max = max_inflight
        self._primary = primary
        self._overflow = overflow
        self._lock = threading.Lock()
        self._inflight = 0
        self._shed = 0
        self._total = 0
        self._time = _time
        self._last_log = 0.0

    @property
    def shed_fraction(self) -> float:
        with self._lock:
            return self._shed / self._total if self._total else 0.0

    def admit(self) -> tuple[bool, str | None]:
        with self._lock:
            self._total += 1
            if self._inflight < self._max:
                self._inflight += 1
                return True, None
            self._shed += 1
            now = self._time.monotonic()
            if now - self._last_log > 5.0:
                self._last_log = now
                return False, (
                    f"{self._primary} saturated ({self._inflight} in "
                    f"flight): routing overflow to {self._overflow} "
                    f"({self._shed}/{self._total} requests shed so far)"
                )
            return False, None

    def release(self) -> None:
        with self._lock:
            self._inflight -= 1

    def record_shed(self, reason: str | None = None) -> str | None:
        """Account a request the CALLER routed off the primary without
        consulting admission (e.g. the set family's concurrent large-N
        reroute) so ``shed_fraction`` and the saturation log cover every
        request served off the primary path. Returns a rate-limited log
        line or None."""
        with self._lock:
            self._total += 1
            self._shed += 1
            now = self._time.monotonic()
            if now - self._last_log > 5.0:
                self._last_log = now
                return (
                    f"{self._primary}: routing {reason or 'request'} to "
                    f"{self._overflow} ({self._shed}/{self._total} requests "
                    "shed so far)"
                )
            return None


class LoadAwareJaxBackend:
    """``jax`` flag backend that holds its latency contract at saturation.

    The AOT path is the fastest single-stream policy forward, but a jax
    dispatch releases/re-acquires the GIL while the XLA CPU executable
    runs, so when MANY server threads dispatch concurrently each call
    pays a thread-wakeup penalty — measured p50 degrading from ~0.25 ms
    at 1-2 way to 1-6 ms at 8-way saturation (docs/status.md, round 2;
    a serialized-executor design and finer GIL switch intervals were
    tried and measured no better). Since every backend family computes
    the same argmax decision from the same checkpoint (decision agreement
    tested across thousands of random observations in
    ``tests/test_extender.py``; logits agree to ~1e-4 — XLA-CPU's
    vectorized/FMA reduction order is not formally guaranteed bit-equal
    to the naive numpy/C++ loops, so an adversarially exact logit tie
    could in principle argmax-flip between paths), the load-aware fix is
    routing, not math: requests that arrive while ``max_concurrent_jax``
    calls are already inside the jax dispatcher run the native C++ (or
    numpy) forward instead — whose GIL-holding matmuls stay flat
    (~0.09 ms p50) from 1-way to 8-way. Transitions are counted and
    logged (rate-limited) so operators can see when load is being shed.

    The AOT path is also LATENCY-AWARE (round 5, same router as the set
    family): its dispatch round-trip is pool-dependent, so both paths
    are calibrated at startup and single-stream samples feed a latency
    EWMA; once the AOT dispatch runs ``margin`` x worse than the host
    forward it is demoted, with periodic recovery probes — see
    :class:`AdaptiveLatencyRouter`. Demoted traffic is exported as
    ``reroute_fraction``, deliberately NOT ``shed_fraction``: shedding
    keeps meaning overload, so a host-path-is-faster steady state
    cannot masquerade as saturation.
    """

    name = "jax"
    _KEY = "mlp"    # the flat obs shape is fixed: one router key

    def __init__(self, params_tree: dict, hidden: tuple = (256, 256),
                 device: str = "cpu", algo: str = "ppo",
                 max_concurrent_jax: int = 2):
        self._jax = JaxAOTBackend(params_tree, hidden, device, algo)
        self._adaptive = None
        self._tracker = ConcurrencyTracker()
        if device != "cpu":
            # Shedding only keeps decisions consistent when the AOT path
            # runs on the host's XLA-CPU (f32 matmuls matching numpy/C++
            # to ~1e-4; decision agreement tested). An accelerator AOT
            # path diverges much further from the host overflow forward
            # and could argmax-flip near-ties, so decisions would depend
            # on arrival timing — disable shedding (and skip building the
            # dead overflow backend) rather than serve inconsistently.
            logger.info(
                "load-aware shedding disabled for serve device %r (the host "
                "overflow forward diverges too far from it for tested "
                "decision agreement)", device
            )
            max_concurrent_jax = float("inf")
            self._overflow = None
        else:
            try:
                self._overflow = NativeMLPBackend(params_tree, algo)
            except Exception as e:  # noqa: BLE001 - missing toolchain/.so
                logger.info("native overflow path unavailable (%s); numpy", e)
                self._overflow = NumpyMLPBackend(params_tree, algo)
            # Both paths are built and warm: calibrate the latency EWMAs
            # with min_samples timed single-stream calls each (one extra
            # untimed overflow warmup first — lazy init must not bias
            # the baseline). Full calibration matters: with fewer than
            # min_samples the router could not demote until live traffic
            # topped the count up, so a server started against an
            # already-degraded pool would pay the slow dispatch for its
            # first requests. ~1 ms at startup on a healthy pool.
            self._adaptive = AdaptiveLatencyRouter(label="AOT MLP dispatch")
            zeros = np.zeros(env_core.OBS_DIM, np.float32)
            self._overflow.decide(zeros)
            for _ in range(self._adaptive.min_samples):
                t0 = time.perf_counter()
                self._overflow.decide(zeros)
                self._adaptive.observe("host", self._KEY,
                                       (time.perf_counter() - t0) * 1e3)
            for _ in range(self._adaptive.min_samples):
                t0 = time.perf_counter()
                self._jax.decide(zeros)
                self._adaptive.observe("aot", self._KEY,
                                       (time.perf_counter() - t0) * 1e3)
        # Only JAX-PATH calls count against the concurrency cap: a shed
        # request running the overflow forward must not keep later
        # arrivals away from an idle jax dispatcher.
        self._gate = ShedGate(
            max_concurrent_jax,
            overflow=self._overflow.name if self._overflow else "-",
        )

    @property
    def shed_fraction(self) -> float:
        return self._gate.shed_fraction

    @property
    def reroute_fraction(self) -> float:
        """Fraction of routing decisions the latency router sent host-
        side — separate from ``shed_fraction`` (overload), which must
        stay meaningful when rerouting is the healthy steady state."""
        return (self._adaptive.reroute_fraction
                if self._adaptive is not None else 0.0)

    def decide(self, obs: np.ndarray) -> tuple[int, np.ndarray]:
        if self._overflow is None:
            # Accelerator serve device: no host paths, no routing.
            return self._jax.decide(obs)
        concurrent = self._tracker.enter()
        try:
            route_aot, is_probe = self._adaptive.route_aot(self._KEY)
            if not route_aot:
                # Latency-routed to the host path (router-counted as a
                # reroute, NOT overload shed — see reroute_fraction).
                t0m = time.monotonic()
                t0 = time.perf_counter()
                out = self._overflow.decide(obs)
                if not concurrent and self._tracker.clean_since(t0m):
                    self._adaptive.observe("host", self._KEY,
                                           (time.perf_counter() - t0) * 1e3)
                return out
            take_jax, log_line = self._gate.admit()
            if not take_jax:
                if log_line:
                    logger.info("%s", log_line)
                if is_probe:
                    # The probe never reached the AOT path (cheap to
                    # retry). A probe that RAN the dispatch but whose
                    # sample was contaminated is NOT refunded — it paid
                    # the degraded latency, and refunding would make
                    # sustained concurrency probe near-continuously.
                    self._adaptive.refund_probe(self._KEY)
                return self._overflow.decide(obs)
            try:
                t0m = time.monotonic()
                t0 = time.perf_counter()
                out = self._jax.decide(obs)
                if not concurrent and self._tracker.clean_since(t0m):
                    self._adaptive.observe("aot", self._KEY,
                                           (time.perf_counter() - t0) * 1e3)
                return out
            finally:
                self._gate.release()
        finally:
            self._tracker.exit()


class GreedyBackend:
    """Cost-greedy fallback (reference ``normal_scheduler_step``); always
    available, used when checkpoint loading or a policy backend fails."""

    name = "greedy"

    def decide(self, obs: np.ndarray) -> tuple[int, np.ndarray]:
        # Pseudo-logits: negative cost, so argmax picks the cheaper cloud
        # (tie -> AWS, matching obs[0] <= obs[1] in the reference).
        logits = np.array([-obs[0], -obs[1] - 1e-9], np.float32)
        return int(np.argmax(logits)), logits


BACKENDS: dict[str, Callable] = {
    "jax": LoadAwareJaxBackend,
    "cpu": NumpyMLPBackend,
    "native": NativeMLPBackend,
    "torch": TorchMLPBackend,
    "greedy": GreedyBackend,
}


def backend_info(backend) -> dict:
    """Provenance dict for one serving backend — the fields the trace
    log stamps on every decision record and the rollout canary gate
    reads off worker snapshots (scheduler/tracelog.py,
    scheduler/rollout.py). Every backend family answers: ``family``
    defaults to the flat cloud decision, and the load-aware gauges are
    included only when the backend tracks them."""
    out = {
        "name": getattr(backend, "name", backend.__class__.__name__),
        "family": getattr(backend, "family", "cloud"),
    }
    for key in ("shed_fraction", "reroute_fraction"):
        value = getattr(backend, key, None)
        if value is not None:
            out[key] = round(float(value), 4)
    return out


def make_backend(
    backend: str = "jax",
    params_tree: dict | None = None,
    hidden: tuple = (256, 256),
    device: str = "cpu",
    algo: str = "ppo",
):
    """Build a serving backend; degrade to ``greedy`` if construction fails.

    ``algo`` selects the checkpoint's network family (``ppo`` actor-critic
    or ``dqn`` Q-network — the eval/serving decision is greedy argmax either
    way). Returns ``(backend_obj, fallback_used: bool)``.
    """
    if backend not in BACKENDS:
        raise ValueError(f"unknown backend {backend!r}; choose from {sorted(BACKENDS)}")
    _layout(algo)
    if backend == "greedy" or params_tree is None:
        if backend != "greedy":
            logger.warning("no checkpoint params; serving cost-greedy fallback")
        return GreedyBackend(), backend != "greedy"
    if backend == "native":
        # Native degrades to the numerically-identical numpy path first
        # (missing compiler / .so), and only then to greedy.
        try:
            return NativeMLPBackend(params_tree, algo), False
        except Exception as e:  # noqa: BLE001 - any build/load failure
            logger.warning("native backend unavailable (%s); using cpu", e)
            backend = "cpu"
    try:
        if backend == "jax":
            return LoadAwareJaxBackend(params_tree, hidden, device, algo), False
        if backend == "cpu":
            return NumpyMLPBackend(params_tree, algo), False
        return TorchMLPBackend(params_tree, algo), False
    except Exception:  # any init failure (bad param tree, device error, ...)
        logger.exception("backend %r failed to initialize; falling back to greedy", backend)
        return GreedyBackend(), True
