"""Kubernetes scheduler-extender HTTP server serving the trained policy.

Completes the reference's planned-but-empty L4 layer
(``rl_scheduler/scheduler/extender.py`` — 0 bytes, ``scheduler-config.yaml``
— 0 bytes): an HTTP webhook the default kube-scheduler calls per pod via
the extender protocol, answering

- ``POST /filter``     — ``ExtenderArgs`` -> ``ExtenderFilterResult``:
  keeps only nodes on the cloud the policy picked (greedy argmax, the
  reference's ``explore=False`` serving intent). For ``cluster_set``
  checkpoints (pointer-over-nodes set transformer, ``set_backend.py``)
  the policy scores each candidate node directly and the filter keeps
  the argmax node.
- ``POST /prioritize`` — ``ExtenderArgs`` -> ``HostPriorityList``: scores
  every candidate node 0-100 from the policy's softmax probabilities, so
  the extender also works in soft (prioritize-only) deployments. Set
  checkpoints score per node (the pointer head's logits ARE per-node
  scores).
- ``GET /healthz``     — liveness + backend name.
- ``GET /stats``       — decision count, per-cloud split, latency
  p50/p90/p99 in ms (the <1 ms p50 target is measured here).
- ``GET /metrics``     — the same signals in Prometheus text format
  (decision counters, lifetime latency histogram, shed fraction), so
  the serving path is scrapeable by the stack the framework already
  reads telemetry from (``telemetry.PrometheusCpu``).

graftlens (docs/observability.md): the decision hot path is additionally
instrumented with cheap monotonic per-phase spans — request-parse,
telemetry-observe, backend-forward, priority-marshal, trace-append —
feeding one :class:`LatencyStats` per phase (``/stats`` percentiles,
``/metrics`` lifetime histograms, span breakdown on every trace record),
plus an optional SLO engine (``scheduler/slo.py``: ``--slo-p99-ms`` /
``--slo-avail`` burn-rate gauges, ``/healthz`` degradation). graftdrift
(``scheduler/drift.py``, ``--drift``/``--shadow-run``) adds
distribution-shift sketches on the same hot path and an optional
candidate checkpoint scoring live requests in shadow. Synthetic traffic
(``endpoint in tracelog.SYNTHETIC_ENDPOINTS``: warmup probes, shadow
scores) is excluded from every client-facing histogram, SLO counter and
drift sketch at record time.

Node -> cloud mapping uses the ``cloud: aws|azure`` node labels that the
kind cluster configs apply (reference ``aws-cluster-config.yaml:12-14``),
falling back to substring matching on node names. Unknown-cloud nodes pass
the filter untouched (fail-open: the extender must never wedge scheduling
— SURVEY.md §5.3).

The heavy lifting happens once at startup (checkpoint restore + AOT
compile); per-request work is one telemetry read + one ``decide`` on a
warm backend, so p50 stays well under 1 ms even for the ``jax`` backend.
"""

from __future__ import annotations

import argparse
import bisect
import json
import logging
import queue
import random
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from rl_scheduler_tpu.scheduler.drift import (
    drift_metric_lines,
    shadow_metric_lines,
)
from rl_scheduler_tpu.scheduler.policy_backend import make_backend
from rl_scheduler_tpu.scheduler.tracelog import decision_record, obs_digest
from rl_scheduler_tpu.scheduler.wire import (
    WIRE_CONTENT_TYPE,
    WireError,
    serve_wire,
)
from rl_scheduler_tpu.utils.retry import CircuitOpenError
from rl_scheduler_tpu.scheduler.telemetry import (
    PrometheusCpu,
    RandomCpu,
    TableTelemetry,
)

logger = logging.getLogger(__name__)

CLOUDS = ("aws", "azure")
MAX_EXTENDER_SCORE = 100
# graftlens decision-path phases, in hot-path order (docs/observability.md):
#   parse      — request-parse: node/pod extraction + the candidate cap draw
#   observe    — telemetry-observe/obs-build: table replay + cpu sample into
#                the finished observation array (graph: topology + raw-price
#                row + graph obs build); on a graftfwd score-cache HIT this
#                phase carries the (much cheaper) cache lookup instead
#   batch_wait — graftfwd micro-batching: time a request spent in the
#                admission window before its batch's shared forward ran
#                (0 with batching off, and 0 for cache hits — recorded
#                unconditionally so every phase keeps exactly one sample
#                per served decision, the count-uniformity invariant)
#   forward    — backend-forward: the policy forward through the breaker
#                (for a coalesced request: the batch's SHARED forward time;
#                0 on a cache hit)
#   marshal    — priority-marshal: softmax/score mapping + response body
#   trace      — trace-append: obs digest + replay position + record build
# Each phase feeds its own LatencyStats; sums reconcile against the
# end-to-end decide histogram (pinned by test, read by tools/decisionview).
PHASES = ("parse", "observe", "batch_wait", "forward", "marshal", "trace")
# Serving-time default for the arriving pod's cpu request as a fraction of
# node capacity: the midpoint of the training distribution
# (env/cluster_set.py pod_cpu ~ U[0.1, 0.4]) when the request carries no
# parseable resources.requests.cpu.
DEFAULT_POD_CPU = 0.25
DEFAULT_NODE_CAPACITY_CORES = 4.0
# Heterogeneous-scenario serving defaults (scenarios/het_env.py): node
# memory and accelerator capacity for normalizing a pod's requests into
# [0, 1] fractions, mirroring the cpu-cores default above.
DEFAULT_NODE_MEMORY_BYTES = 16 * 1024 ** 3
DEFAULT_NODE_GPUS = 1.0
# Training draws the mem/acc midpoints when the pod carries no request
# (env req ranges: mem U[0.05, 0.3]; acc gated, often 0).
DEFAULT_POD_MEM = 0.15
DEFAULT_POD_ACC = 0.0

_CPU_QTY = re.compile(r"^\s*(\d+(?:\.\d+)?)(m?)\s*$")
_MEM_QTY = re.compile(r"^\s*(\d+(?:\.\d+)?)\s*(Ki|Mi|Gi|Ti|K|M|G|T|k)?\s*$")
_MEM_MULT = {None: 1.0, "k": 1e3, "K": 1e3, "M": 1e6, "G": 1e9, "T": 1e12,
             "Ki": 2 ** 10, "Mi": 2 ** 20, "Gi": 2 ** 30, "Ti": 2 ** 40}
_GPU_KEYS = ("nvidia.com/gpu", "amd.com/gpu", "google.com/tpu")


def pod_cpu_fraction(pod: dict | None,
                     capacity_cores: float = DEFAULT_NODE_CAPACITY_CORES) -> float:
    """The pod's total cpu request as a fraction of node capacity.

    Sums ``spec.containers[].resources.requests.cpu`` k8s quantities
    (``"250m"`` = 0.25 cores, ``"2"`` = 2 cores); clips to [0, 1] of
    ``capacity_cores``. Falls back to :data:`DEFAULT_POD_CPU` when the pod
    carries no parseable request — serving must never wedge on a weird
    manifest (fail-open, SURVEY.md §5.3).
    """
    try:
        containers = ((pod or {}).get("spec") or {}).get("containers") or []
        total = 0.0
        seen = False
        for c in containers:
            qty = (((c.get("resources") or {}).get("requests") or {})
                   .get("cpu"))
            if qty is None:
                continue
            m = _CPU_QTY.match(str(qty))
            if m is None:
                continue
            cores = float(m.group(1)) * (1e-3 if m.group(2) else 1.0)
            total += cores
            seen = True
        if not seen:
            return DEFAULT_POD_CPU
        return min(max(total / capacity_cores, 0.0), 1.0)
    except Exception:  # noqa: BLE001 - malformed manifest: fail open
        logger.debug("unparseable pod cpu request; using default", exc_info=True)
        return DEFAULT_POD_CPU


def pod_resource_fractions(
    pod: dict | None,
    capacity_cores: float = DEFAULT_NODE_CAPACITY_CORES,
    capacity_bytes: float = DEFAULT_NODE_MEMORY_BYTES,
    capacity_gpus: float = DEFAULT_NODE_GPUS,
) -> list:
    """``[cpu, mem, acc]`` request fractions for heterogeneous-scenario
    serving (``scenarios/het_env.py`` feature order).

    cpu reuses :func:`pod_cpu_fraction`; memory sums
    ``resources.requests.memory`` k8s quantities (``128Mi``/``1Gi``/
    decimal suffixes); accelerator sums the extended-resource GPU/TPU
    keys (``nvidia.com/gpu`` etc., integer counts). Unparseable/missing
    requests fall back to the training distribution's defaults — serving
    must never wedge on a weird manifest (same fail-open contract as the
    cpu path).
    """
    cpu = pod_cpu_fraction(pod, capacity_cores)
    mem = acc = None
    try:
        containers = ((pod or {}).get("spec") or {}).get("containers") or []
        mem_total = acc_total = 0.0
        mem_seen = acc_seen = False
        for c in containers:
            requests = ((c.get("resources") or {}).get("requests") or {})
            q = requests.get("memory")
            if q is not None:
                m = _MEM_QTY.match(str(q))
                if m is not None:
                    mem_total += float(m.group(1)) * _MEM_MULT[m.group(2)]
                    mem_seen = True
            for key in _GPU_KEYS:
                q = requests.get(key)
                if q is None:
                    continue
                try:
                    acc_total += float(q)
                    acc_seen = True
                except (TypeError, ValueError):
                    pass
        if mem_seen:
            mem = min(max(mem_total / capacity_bytes, 0.0), 1.0)
        if acc_seen:
            acc = min(max(acc_total / capacity_gpus, 0.0), 1.0)
    except Exception:  # noqa: BLE001 - malformed manifest: fail open
        logger.debug("unparseable pod resource requests; using defaults",
                     exc_info=True)
    return [cpu,
            DEFAULT_POD_MEM if mem is None else mem,
            DEFAULT_POD_ACC if acc is None else acc]


def node_cloud(node: dict | str) -> str | None:
    """Cloud of a node from its ``cloud`` label, else name tokens.

    The name fallback matches whole '-'/'.'-separated tokens only, so a
    node named ``gateways-1`` is NOT classified as aws — unknown-cloud
    nodes must pass the filter untouched.
    """
    if isinstance(node, dict):
        labels = (node.get("metadata") or {}).get("labels") or {}
        cloud = labels.get("cloud")
        if cloud in CLOUDS:
            return cloud
        name = (node.get("metadata") or {}).get("name", "")
    else:
        name = node
    tokens = re.split(r"[-._]", name.lower())
    for cloud in CLOUDS:
        if cloud in tokens:
            return cloud
    return None


class LatencyStats:
    """Thread-safe ring buffer of per-decision latencies, plus a
    cumulative Prometheus-style histogram.

    The ring feeds ``/stats`` percentiles (reset-scoped measurement
    windows); the histogram counters are LIFETIME-monotonic — they
    survive ``/stats/reset`` because Prometheus counters must never go
    backwards (``rate()``/``histogram_quantile()`` treat decreases as
    counter resets). Bucket bounds bracket the measured serving regimes:
    sub-ms native/numpy decisions through the multi-ms saturated tail.
    """

    # seconds; +Inf is implicit
    BUCKETS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005,
               0.01, 0.025, 0.05, 0.1, 0.25, 1.0)

    def __init__(self, capacity: int = 4096):
        self._lat = np.zeros(capacity, np.float64)
        self._n = 0
        self._capacity = capacity
        self._lock = threading.Lock()
        self._bucket_counts = [0] * (len(self.BUCKETS) + 1)
        self._sum = 0.0
        self._count = 0

    def record(self, seconds: float) -> None:
        with self._lock:
            self._lat[self._n % self._capacity] = seconds
            self._n += 1
            i = bisect.bisect_left(self.BUCKETS, seconds)
            self._bucket_counts[i] += 1
            self._sum += seconds
            self._count += 1

    def reset(self) -> None:
        with self._lock:
            self._n = 0

    def histogram(self) -> tuple[list, float, int]:
        """``(cumulative_bucket_counts, sum_seconds, count)`` — counts are
        cumulative per Prometheus histogram semantics (each le-bucket
        includes everything below it; the last entry is +Inf = count)."""
        with self._lock:
            counts = list(self._bucket_counts)
            total_sum, count = self._sum, self._count
        cumulative = []
        running = 0
        for c in counts:
            running += c
            cumulative.append(running)
        return cumulative, total_sum, count

    def percentiles_ms(self) -> dict:
        with self._lock:
            total = self._n  # snapshot under the lock: a concurrent
            # reset() must not yield {count: 0, p50: <stale value>}
            n = min(total, self._capacity)
            data = self._lat[:n].copy()
        if n == 0:
            return {"count": 0}
        p50, p90, p99 = np.percentile(data, [50, 90, 99]) * 1e3
        return {
            "count": int(total),
            "p50_ms": round(float(p50), 4),
            "p90_ms": round(float(p90), 4),
            "p99_ms": round(float(p99), 4),
        }

    @classmethod
    def merged_histogram(cls, stats) -> tuple[list, float, int]:
        """Aggregate several workers' histograms for ONE shared scrape.

        Multi-worker serving runs one ``LatencyStats`` per process; a
        fronting scrape can sum them because cumulative bucket counts are
        LINEAR: every worker shares ``cls.BUCKETS``, so bucket-wise sums
        of per-worker cumulative counts are exactly the cumulative counts
        of the union stream (same for ``sum``/``count``). Percentiles do
        NOT merge this way — ``histogram_quantile()`` over the merged
        buckets is the aggregate story, per-worker ``/stats`` stays the
        exact one (docs/serving.md).
        """
        totals = [0] * (len(cls.BUCKETS) + 1)
        total_sum, total_count = 0.0, 0
        for s in stats:
            cumulative, ssum, count = s.histogram()
            for i, c in enumerate(cumulative):
                totals[i] += c
            total_sum += ssum
            total_count += count
        return totals, total_sum, total_count


def phase_metric_lines(prefix: str, histograms: dict) -> list:
    """Prometheus exposition for the graftlens per-phase latency
    histograms. ``histograms`` maps phase name to the
    ``LatencyStats.histogram()`` tuple — the single-process plane passes
    its own stats, the pool passes per-phase merged histograms, so both
    planes export the identical metric shape (one scrape config)."""
    lines = [
        f"# HELP {prefix}_phase_latency_seconds Decision-path time per "
        "graftlens phase (parse/observe/forward/marshal/trace; lifetime "
        "histogram, /stats/reset does not clear it).",
        f"# TYPE {prefix}_phase_latency_seconds histogram",
    ]
    bounds = [f"{b:g}" for b in LatencyStats.BUCKETS] + ["+Inf"]
    for phase in sorted(histograms):
        cumulative, total_sum, count = histograms[phase]
        for bound, c in zip(bounds, cumulative):
            lines.append(
                f'{prefix}_phase_latency_seconds_bucket'
                f'{{phase="{phase}",le="{bound}"}} {c}')
        lines.append(f'{prefix}_phase_latency_seconds_sum'
                     f'{{phase="{phase}"}} {total_sum:.9g}')
        lines.append(f'{prefix}_phase_latency_seconds_count'
                     f'{{phase="{phase}"}} {count}')
    return lines


def slo_metric_lines(prefix: str, snapshot: dict) -> list:
    """Prometheus exposition for an SLO snapshot (scheduler/slo.py) —
    shared by the single-process plane and the pool's merged snapshot."""
    lines = [
        f"# HELP {prefix}_slo_burn_rate Error-budget burn rate per "
        "objective and window (1.0 = burning exactly the budget).",
        f"# TYPE {prefix}_slo_burn_rate gauge",
    ]
    for name, objective in sorted(snapshot["objectives"].items()):
        for wname, window in sorted(objective["windows"].items()):
            lines.append(
                f'{prefix}_slo_burn_rate{{objective="{name}",'
                f'window="{wname}"}} {window["burn_rate"]:.9g}')
    lines += [
        f"# HELP {prefix}_slo_burning Objective is burning (both "
        "windows over threshold).",
        f"# TYPE {prefix}_slo_burning gauge",
    ]
    for name, objective in sorted(snapshot["objectives"].items()):
        lines.append(f'{prefix}_slo_burning{{objective="{name}"}} '
                     f'{1 if objective["burning"] else 0}')
    lifetime = snapshot.get("lifetime", {})
    lines += [
        f"# HELP {prefix}_slo_degraded Any objective burning (the "
        "/healthz degradation signal).",
        f"# TYPE {prefix}_slo_degraded gauge",
        f"{prefix}_slo_degraded {1 if snapshot['degraded'] else 0}",
        f"# HELP {prefix}_slo_requests_total Requests observed by the "
        "SLO tracker (probe traffic excluded), lifetime.",
        f"# TYPE {prefix}_slo_requests_total counter",
        f"{prefix}_slo_requests_total "
        f"{lifetime.get('requests_total', 0)}",
        f"# HELP {prefix}_slo_latency_bad_total Decided requests over "
        "the latency threshold, lifetime.",
        f"# TYPE {prefix}_slo_latency_bad_total counter",
        f"{prefix}_slo_latency_bad_total "
        f"{lifetime.get('latency_bad_total', 0)}",
    ]
    return lines


def fastpath_metric_lines(prefix: str, fastpath: dict) -> list:
    """Prometheus exposition for the graftfwd fast-path counters —
    shared by the single-process plane and the pool's summed section
    (``pool.sum_fastpath``), so both export one metric shape. Empty
    input -> no lines (levers off = byte-identical scrape)."""
    lines: list = []
    cache = fastpath.get("cache")
    if cache:
        lines += [
            f"# HELP {prefix}_score_cache_hits_total Telemetry-epoch "
            "score-cache hits (observe+forward skipped), lifetime.",
            f"# TYPE {prefix}_score_cache_hits_total counter",
            f"{prefix}_score_cache_hits_total {cache['hits_total']}",
            f"# HELP {prefix}_score_cache_misses_total Score-cache "
            "misses (full decide path ran), lifetime.",
            f"# TYPE {prefix}_score_cache_misses_total counter",
            f"{prefix}_score_cache_misses_total {cache['misses_total']}",
            f"# HELP {prefix}_score_cache_invalidations_total Epoch "
            "rollovers and explicit flushes (promote!) that dropped the "
            "cache, lifetime.",
            f"# TYPE {prefix}_score_cache_invalidations_total counter",
            f"{prefix}_score_cache_invalidations_total "
            f"{cache['invalidations_total']}",
            f"# HELP {prefix}_score_cache_entries Live cache entries.",
            f"# TYPE {prefix}_score_cache_entries gauge",
            f"{prefix}_score_cache_entries {cache['entries']}",
        ]
    batch = fastpath.get("batch")
    if batch:
        lines += [
            f"# HELP {prefix}_batch_requests_total Requests that went "
            "through the micro-batch admission window, lifetime.",
            f"# TYPE {prefix}_batch_requests_total counter",
            f"{prefix}_batch_requests_total {batch['requests_total']}",
            f"# HELP {prefix}_batch_forwards_total Coalesced [k, N, F] "
            "forwards executed, lifetime.",
            f"# TYPE {prefix}_batch_forwards_total counter",
            f"{prefix}_batch_forwards_total {batch['batches_total']}",
            f"# HELP {prefix}_batch_coalesced_total Requests served by "
            "a k>=2 shared forward, lifetime.",
            f"# TYPE {prefix}_batch_coalesced_total counter",
            f"{prefix}_batch_coalesced_total {batch['coalesced_total']}",
            f"# HELP {prefix}_batch_occupancy_mean Mean requests per "
            "executed batch window.",
            f"# TYPE {prefix}_batch_occupancy_mean gauge",
            f"{prefix}_batch_occupancy_mean "
            f"{batch['mean_occupancy'] if batch['mean_occupancy'] is not None else 0}",
        ]
    int8 = fastpath.get("int8")
    if int8:
        lines += [
            f"# HELP {prefix}_int8_agreement Measured top-1 agreement of "
            "the int8 native forward vs fp32 on the seeded corpus "
            "(startup/promote gate; serving refuses below 0.995).",
            f"# TYPE {prefix}_int8_agreement gauge",
            f"{prefix}_int8_agreement {int8['agreement']:.9g}",
        ]
    return lines


class AsyncPlacer:
    """Bounded async wrapper around a pod placer.

    One worker thread drains a bounded queue, so a hung kube API (the client
    has an unbounded read timeout) never blocks a scheduling response and a
    scheduling burst cannot accumulate threads without limit — the oldest
    queued placement drops on overflow instead.
    """

    def __init__(self, placer, maxsize: int = 64):
        self._placer = placer
        self._queue: queue.Queue = queue.Queue(maxsize=maxsize)
        self._dropped = 0
        self._lock = threading.Lock()
        threading.Thread(target=self._drain, daemon=True).start()

    @property
    def dropped(self) -> int:
        with self._lock:
            return self._dropped

    def submit(self, cloud: str) -> None:
        while True:
            try:
                self._queue.put_nowait(cloud)
                return
            except queue.Full:
                try:
                    self._queue.get_nowait()
                    with self._lock:
                        self._dropped += 1
                except queue.Empty:
                    pass

    def _drain(self) -> None:
        while True:
            cloud = self._queue.get()
            try:
                self._placer.place(cloud)
            except Exception:
                logger.exception("pod placement on %s failed", cloud)


class ExtenderPolicy:
    """Pure decision logic, independent of HTTP (unit-testable directly).

    Three decision families, selected by the backend's ``family``
    attribute:

    - ``cloud`` (flat multi-cloud MLP/DQN checkpoints): one cloud-level
      decision per request; ``/filter`` keeps the chosen cloud's nodes,
      ``/prioritize`` scores each node by its cloud's probability.
    - ``set`` (``cluster_set`` pointer-over-nodes checkpoints,
      ``set_backend.py``): the policy scores *each candidate node
      directly* — the pointer head's shape IS the extender protocol's
      shape. ``/filter`` keeps the argmax node, ``/prioritize`` maps the
      per-node softmax onto 0-100 scores.
    - ``graph`` (``cluster_graph`` GNN checkpoints,
      ``graph_backend.py``): per-node pointer decision like ``set``, with
      the message-passing topology built per request from the candidate
      clouds and the affinity node read from the pod's
      ``rl-scheduler.io/affinity-node`` annotation.
    """

    STRUCTURED = ("set", "graph")

    def __init__(self, backend, telemetry: TableTelemetry, placer=None,
                 node_capacity_cores: float = DEFAULT_NODE_CAPACITY_CORES,
                 price_replay: str = "counter",
                 price_replay_period_s: float = 300.0,
                 max_score_nodes: int = 0,
                 price_counter=None,
                 num_resources: int = 0,
                 scenario: str | None = None,
                 spans: bool = True,
                 slo=None):
        self.backend = backend
        self.family = getattr(backend, "family", "cloud")
        self.telemetry = telemetry
        self.node_capacity_cores = node_capacity_cores
        # Heterogeneous-scenario serving (scenarios/het_env.py): R > 0
        # switches the set family's observation to the widened
        # multi-resource layout (observe_nodes_het) and parses the pod's
        # full request vector. `scenario` is provenance from checkpoint
        # meta, surfaced on /healthz and matched against the serve
        # config's --scenario (build_policy refuses a disagreement).
        self.num_resources = int(num_resources)
        self.scenario = scenario
        # graftserve (scheduler/pool.py) sets this on pool workers so
        # /healthz reports pool membership; None keeps the single-process
        # health body byte-identical.
        self.pool_info: dict | None = None
        # graftroll (scheduler/rollout.py): the policy generation this
        # process serves — bumped per successful pool promote; the trace
        # log stamps it on every record and /stats reports it so a
        # rolling restart is observable per worker.
        self.generation = 0
        # graftroll (scheduler/tracelog.py): the durable decision trace.
        # None (the default) keeps the hot path untouched; build_policy
        # attaches a TraceLog when --trace-dir is configured.
        self.trace = None
        # graftfwd (scheduler/fastpath.py): the serving fast path's two
        # policy-level levers, both None by default (hot path untouched);
        # build_policy attaches them from --score-cache-epoch-s /
        # --batch-window-ms. The third lever (the int8 native forward)
        # lives in the backend (--backend native-int8).
        self.score_cache = None
        self.batcher = None
        # graftdrift (scheduler/drift.py): the distribution-shift sketches
        # and the optional shadow scorer, both None by default (hot path
        # untouched); build_policy attaches them from --drift /
        # --shadow-run. The drift tracker records in _record_trace (so
        # probes/shadow/fail-opens are excluded in ONE place); the shadow
        # scorer is fed at the decide sites where (obs, action, score)
        # are all in scope.
        self.drift = None
        self.shadow = None
        # graftpilot (loopback/daemon.py): the backend request this
        # policy was assembled under, stashed by build_policy so
        # set_shadow can rebuild a candidate backend at RUNTIME with the
        # same restore path the incumbent used. None on hand-constructed
        # policies — runtime shadow arming refuses there.
        self._shadow_build: dict | None = None
        # Candidate-list cap for the structured families — the same idea
        # as kube-scheduler's percentageOfNodesToScore: scoring cost per
        # request is O(cap) no matter how large the fleet's node list
        # grows, and every large request hits ONE AOT executable size.
        # 0 = score every candidate. Unsampled nodes score 0 (they just
        # can't win this pod — the next request samples independently).
        if max_score_nodes < 0 or max_score_nodes == 1:
            # Same refuse-before-traffic rule as the CLI: a negative cap
            # would make random.sample raise inside the fail-open
            # handlers (every request silently passthrough), and a
            # 1-node sample is a coin flip, not a policy decision.
            raise ValueError(
                f"max_score_nodes={max_score_nodes}: pass a cap >= 2 "
                "(0 disables the cap)"
            )
        self.max_score_nodes = max_score_nodes
        # OS-entropy seed: replicas must sample DIFFERENT subsets (a
        # constant seed would make every replica's n-th request score
        # the identical nodes, so a retried pod re-hits the same
        # unsampled set).
        self._cap_rng = random.Random()
        self._cap_lock = threading.Lock()
        if self.family == "graph":
            from rl_scheduler_tpu.scheduler.graph_backend import RawPriceReplay

            # The graph env replays RAW dollar prices, not the normalized
            # table. "counter" mirrors the env's per-step counter
            # (process-local — unless a pool supervisor supplies a shared
            # cross-process counter so all workers of one pool walk one
            # trajectory); "wallclock" derives the row from wall time so
            # replicas/restarts agree — see RawPriceReplay.
            self._price_replay = RawPriceReplay(
                mode=price_replay, period_s=price_replay_period_s,
                # The pool supplies the shared counter unconditionally;
                # wallclock derives its position from time and needs no
                # coordination, so the seam only engages in counter mode.
                counter=price_counter if price_replay == "counter" else None,
            )
        # Optional DryRunPodPlacer (slow-mode parity), wrapped so kube API
        # stalls can neither block responses nor exhaust threads.
        self._placer_impl = placer
        self.placer = AsyncPlacer(placer) if placer is not None else None
        from rl_scheduler_tpu.utils.retry import CircuitBreaker

        # graftguard: repeated backend failures trip this breaker and the
        # decision paths degrade to their documented fail-open answers
        # WITHOUT invoking the backend — a poisoned checkpoint cannot tax
        # every scheduling request with a raise/catch round trip. State is
        # exported on /stats and /metrics with the telemetry and kube
        # breakers (docs/robustness.md).
        self.backend_breaker = CircuitBreaker(
            name="backend", failure_threshold=5, reset_timeout_s=10.0,
        )
        self.stats = LatencyStats()
        # graftlens: one LatencyStats per decision-path phase (PHASES).
        # `spans` off skips all recording (the A/B knob, --no-spans);
        # the stats objects exist either way so readers never branch.
        self.spans_enabled = bool(spans)
        self.phase_stats = {phase: LatencyStats() for phase in PHASES}
        # graftlens: optional SLO tracker (scheduler/slo.py). None keeps
        # every path byte-identical; build_policy arms it from
        # --slo-p99-ms / --slo-avail.
        self.slo = slo
        # Per-REQUEST span accumulator + the synthetic-traffic flag, both
        # thread-local (ThreadingHTTPServer serves one request per
        # thread; the pool's control loop runs probes on its own thread).
        self._req_local = threading.local()
        # Structured-family decisions can land on an unknown-cloud node
        # (scored from neutral features); give those their own bucket.
        keys = CLOUDS + (("unknown",) if self.family in self.STRUCTURED else ())
        self._decisions = {c: 0 for c in keys}
        # Lifetime count of requests answered by a fail-open path (open
        # breaker or backend raise): the rollout canary gate compares
        # deltas of this — a canary that "serves" by passing everything
        # through is not a promotable policy.
        self._fail_open_total = 0
        self._lock = threading.Lock()

    def _backend_call(self, fn, *args):
        """Run one backend decision through the circuit breaker: an open
        breaker refuses WITHOUT calling the backend (CircuitOpenError —
        absorbed by the same fail-open handlers that catch backend
        raises), successes/failures drive its state."""
        return self.backend_breaker.call(fn, *args)

    # ------------------------------------------------------ graftlens spans

    def _span_begin(self) -> None:
        """Open a fresh per-request span accumulator on this thread
        (request entry: filter/prioritize/warmup_probe). Replaces any
        stale dict a direct decide() call may have left behind."""
        self._req_local.spans = {} if self.spans_enabled else None

    def _span_add(self, phase: str, seconds: float) -> None:
        """Charge ``seconds`` to ``phase`` for the current request; a
        no-op outside a request context or with spans disabled. Multiple
        charges to one phase accumulate (e.g. ``parse`` spans both the
        node extraction and the pod parse)."""
        spans = getattr(self._req_local, "spans", None)
        if spans is not None:
            spans[phase] = spans.get(phase, 0.0) + seconds

    def _span_finish(self, drop: bool = False) -> dict | None:
        """Close the request's span accumulator: record each phase into
        its lifetime LatencyStats (unless ``drop`` — synthetic probes
        and fail-open requests must not land in client-facing
        histograms) and return the span dict in milliseconds for the
        trace record."""
        spans = getattr(self._req_local, "spans", None)
        self._req_local.spans = None
        if spans is None:
            return None
        if not drop and not self._synthetic:
            for phase, seconds in spans.items():
                self.phase_stats[phase].record(seconds)
        return {phase: round(seconds * 1e3, 4)
                for phase, seconds in spans.items()}

    @property
    def _synthetic(self) -> bool:
        """True while this thread serves a warmup_probe: synthetic
        traffic is excluded from the latency/phase histograms and SLO
        counters the canary gates and dashboards read (tagged
        ``endpoint=probe`` in the trace instead)."""
        return getattr(self._req_local, "synthetic", False)

    def _record_latency(self, seconds: float) -> None:
        """One successful decision's end-to-end latency: the lifetime
        histogram + ring, and the SLO latency objective — both skipped
        for synthetic probe traffic (pinned by test)."""
        if self._synthetic:
            return
        self.stats.record(seconds)
        if self.slo is not None:
            self.slo.observe(seconds)

    def _drift_features(self, obs) -> tuple:
        """The drift tracker's input-telemetry features for one served
        observation: the mean of its cost and latency columns. The flat
        layout is ``[cost_aws, cost_azure, lat_aws, lat_azure, ...]``;
        both structured table layouts put cost/latency in columns 0/1
        (``observe_nodes`` / ``observe_nodes_het``). The graph family's
        raw-dollar prices are not on the normalized [0, 1] scale, so its
        feature streams record nothing (never garbage buckets) — its
        score/action streams still track."""
        if obs is None or self.family == "graph":
            return None, None
        try:
            arr = np.asarray(obs)
            if arr.ndim == 1 and arr.size >= 4:
                return float(arr[0:2].mean()), float(arr[2:4].mean())
            if arr.ndim == 2 and arr.shape[1] >= 2:
                return float(arr[:, 0].mean()), float(arr[:, 1].mean())
        except Exception:  # noqa: BLE001 — sketches must never hurt serving
            logger.debug("drift feature extraction failed", exc_info=True)
        return None, None

    def _record_trace(self, endpoint: str, *, candidates: int,
                      chosen: str | None, score: float | None, obs,
                      t0: float, fail_open: bool = False,
                      clouds: list | None = None) -> None:
        """Append one decision record to the durable trace (tracelog.py),
        count fail-opens, and close out the request's graftlens spans.
        Hot-path cost: one obs digest (computed at the source ON PURPOSE
        — it must fingerprint what was actually served, not a queue-held
        array a later request could alias) plus one bounded-queue put
        that never blocks; with no trace configured the fail-open/SLO
        counters and the span close-out are the only work.

        ``clouds`` (the candidate cloud list, success paths only) and the
        request's parsed pod_cpu (stashed thread-locally by
        ``_structured_decide``) are graftloop's schema-2 replay fields —
        what the trace→Scenario compiler and ``extender_bench
        --replay-trace`` rebuild workloads from."""
        pod_cpu = getattr(self._req_local, "pod_cpu", None)
        self._req_local.pod_cpu = None
        if self.drift is not None and not fail_open \
                and not self._synthetic and score is not None:
            # graftdrift sketches, exactly one observation per stream per
            # SERVED decision — recorded here so the exclusions (probes,
            # shadow, fail-opens) mirror the histograms' in one place.
            cloud = (chosen if chosen in CLOUDS
                     else node_cloud(chosen) if chosen else None)
            cost, lat = self._drift_features(obs)
            self.drift.observe_decision(cloud or "unknown", score,
                                        cost, lat)
        if fail_open:
            with self._lock:
                self._fail_open_total += 1
            if self.slo is not None and not self._synthetic:
                self.slo.observe_failure()
        if self.trace is None:
            # Still close the span accumulator: phase stats are recorded
            # with or without a trace log attached (fail-open requests
            # drop their partial spans, like the end-to-end histogram).
            # The trace phase charges its true cost — zero — so every
            # phase histogram carries one sample per served decision
            # (the count-uniformity invariant decisionview relies on).
            self._span_add("trace", 0.0)
            self._span_finish(drop=fail_open)
            return
        t_trace = time.perf_counter()
        try:
            telemetry_pos = self.telemetry.last_replay_position()
        except AttributeError:  # policy stand-ins with bare telemetry
            telemetry_pos = None
        digest = obs_digest(obs)
        # The digest + provenance lookup are the measurable trace-append
        # cost; the remaining bounded-queue put never blocks.
        self._span_add("trace", time.perf_counter() - t_trace)
        spans_ms = self._span_finish(drop=fail_open)
        self.trace.append(decision_record(
            endpoint=endpoint, family=self.family,
            backend=getattr(self.backend, "name",
                            self.backend.__class__.__name__),
            candidates=candidates, chosen=chosen, score=score,
            latency_ms=(time.perf_counter() - t0) * 1e3, obs_sha=digest,
            telemetry_pos=telemetry_pos,
            worker_id=(self.pool_info or {}).get("worker_id"),
            generation=self.generation, fail_open=fail_open,
            breaker_state=self.backend_breaker.state, spans=spans_ms,
            clouds=clouds, pod_cpu=pod_cpu,
        ))

    def decide(self) -> tuple[int, np.ndarray, np.ndarray]:
        """One placement decision: ``(action, probs, obs)``; timed."""
        t0 = time.perf_counter()
        obs = self.telemetry.observe()
        t_obs = time.perf_counter()
        action, logits = self._backend_call(self.backend.decide, obs)
        t_fwd = time.perf_counter()
        self._record_latency(t_fwd - t0)
        self._span_add("observe", t_obs - t0)
        self._span_add("batch_wait", 0.0)  # count-uniformity (graftfwd)
        self._span_add("forward", t_fwd - t_obs)
        z = logits - logits.max()
        probs = np.exp(z) / np.exp(z).sum()
        with self._lock:
            self._decisions[CLOUDS[action]] += 1
        self._span_add("marshal", time.perf_counter() - t_fwd)
        if self.shadow is not None and not self._synthetic:
            # graftdrift shadow scoring: one non-blocking enqueue AFTER
            # the marshal span closed — the served answer, its latency
            # samples and its phase counts are bitwise those of a
            # shadow-off run (pinned by test).
            self.shadow.submit(obs, action, float(probs[action]))
        return action, probs, obs

    def _fastpath_forward(self, obs):
        """The set family's forward seam: through the micro-batcher when
        one is armed, else the direct backend call. Returns ``(action,
        logits, forward_s)`` — ``forward_s`` is the batch's SHARED
        forward duration (None unbatched), so the caller can split its
        blocked time into ``batch_wait`` + ``forward``. Runs INSIDE the
        circuit breaker: a poisoned batch fans its exception out to
        every member, and each member's breaker/fail-open accounting
        sees its own failure."""
        if self.batcher is not None:
            return self.batcher.submit(obs, self.generation)
        action, logits = self.backend.decide_nodes(obs)
        return action, logits, None

    def _cached_decide_set(self, entry, clouds: list,
                           t0: float) -> tuple[int, np.ndarray, np.ndarray]:
        """Serve one decide from a score-cache hit: the stored decision
        bitwise-unchanged, the stored observation/replay position as
        provenance, observe/forward skipped (the lookup IS the observe
        phase's cost; batch_wait/forward charge their true zero so
        every phase still carries one sample per decision)."""
        action, logits, obs, replay_pos = entry
        t_hit = time.perf_counter()
        self._record_latency(t_hit - t0)
        self._span_add("observe", t_hit - t0)
        self._span_add("batch_wait", 0.0)
        self._span_add("forward", 0.0)
        if replay_pos is not None:
            try:
                # Trace provenance: the record must name the telemetry
                # row the cached score actually observed, not whatever
                # this thread last replayed.
                self.telemetry.note_replay_position(replay_pos)
            except AttributeError:  # bare-telemetry policy stand-ins
                pass
        z = logits - logits.max()
        probs = np.exp(z) / np.exp(z).sum()
        with self._lock:
            self._decisions[clouds[action] or "unknown"] += 1
        self._span_add("marshal", time.perf_counter() - t_hit)
        if self.shadow is not None and not self._synthetic:
            # Cache hits shadow-score too: the candidate grades against
            # the live request mix, not just the cache-miss slice.
            self.shadow.submit(obs, action, float(probs[action]))
        return action, probs, obs

    def decide_set(self, clouds: list, pod_cpu: float,
                   pod_reqs: list | None = None) -> tuple[int, np.ndarray, np.ndarray]:
        """One set-family pointer decision over the request's nodes; timed
        like :meth:`decide`. ``clouds`` has one aws/azure/None per node;
        ``pod_reqs`` is the parsed ``[R]`` request vector when this
        policy serves a heterogeneous-scenario checkpoint.

        graftfwd: with a score cache armed, an identical (generation,
        node-set, pod-request) key inside the current telemetry epoch
        answers from cache — skipping observe AND forward; with a
        micro-batcher armed, the forward may be one row of a coalesced
        ``[k, N, F]`` batch (``batch_wait`` carries the window time).
        Synthetic probes bypass the cache both ways: a rollout gate
        probe must exercise the real decide path, and must not seed the
        cache with probe-shaped entries."""
        t0 = time.perf_counter()
        cache = self.score_cache if not self._synthetic else None
        cache_key = None
        if cache is not None:
            cache_key = cache.make_key(self.generation, clouds, pod_cpu,
                                       pod_reqs)
            entry = cache.get(cache_key)
            if entry is not None:
                return self._cached_decide_set(entry, clouds, t0)
        if self.num_resources:
            reqs = (pod_reqs if pod_reqs is not None
                    else [pod_cpu, DEFAULT_POD_MEM, DEFAULT_POD_ACC])
            obs = self.telemetry.observe_nodes_het(clouds, reqs,
                                                   self.num_resources)
        else:
            obs = self.telemetry.observe_nodes(clouds, pod_cpu)
        t_obs = time.perf_counter()
        action, logits, forward_s = self._backend_call(
            self._fastpath_forward, obs)
        t_fwd = time.perf_counter()
        self._record_latency(t_fwd - t0)
        self._span_add("observe", t_obs - t0)
        if forward_s is None:
            self._span_add("batch_wait", 0.0)
            self._span_add("forward", t_fwd - t_obs)
        else:
            # Coalesced: the shared batch forward is this request's
            # forward cost; the rest of its blocked time was the window.
            shared = min(forward_s, t_fwd - t_obs)
            self._span_add("batch_wait", (t_fwd - t_obs) - shared)
            self._span_add("forward", shared)
        if cache_key is not None:
            try:
                replay_pos = self.telemetry.last_replay_position()
            except AttributeError:
                replay_pos = None
            cache.put(cache_key, action, logits, obs, replay_pos)
        z = logits - logits.max()
        probs = np.exp(z) / np.exp(z).sum()
        with self._lock:
            self._decisions[clouds[action] or "unknown"] += 1
        self._span_add("marshal", time.perf_counter() - t_fwd)
        if self.shadow is not None and not self._synthetic:
            self.shadow.submit(obs, action, float(probs[action]))
        return action, probs, obs

    def decide_graph(self, clouds: list, display: list,
                     pod: dict | None, pod_cpu: float) -> tuple[int, np.ndarray, np.ndarray]:
        """One graph-family pointer decision: per-request topology from the
        candidate clouds, affinity from the pod annotation (mean-hops
        neutral fallback), raw-price replay row; timed like
        :meth:`decide`."""
        from rl_scheduler_tpu.scheduler.graph_backend import (
            AFFINITY_ANNOTATION,
            build_graph_obs,
            topology_for_clouds,
        )

        t0 = time.perf_counter()
        adj, hops = topology_for_clouds(clouds)
        price_row, step_frac = self._price_replay.next_row()
        cpus = np.asarray(self.telemetry.cpu.sample(), np.float32)
        affinity = None
        annotations = (((pod or {}).get("metadata") or {})
                       .get("annotations") or {})
        aff_name = annotations.get(AFFINITY_ANNOTATION)
        if aff_name is not None and aff_name in display:
            affinity = display.index(aff_name)
        obs = build_graph_obs(clouds, price_row, cpus, hops, adj,
                              affinity, pod_cpu, step_frac)
        t_obs = time.perf_counter()
        action, logits = self._backend_call(self.backend.decide_nodes, obs, adj)
        t_fwd = time.perf_counter()
        self._record_latency(t_fwd - t0)
        self._span_add("observe", t_obs - t0)
        self._span_add("batch_wait", 0.0)  # count-uniformity (graftfwd)
        self._span_add("forward", t_fwd - t_obs)
        z = logits - logits.max()
        probs = np.exp(z) / np.exp(z).sum()
        with self._lock:
            self._decisions[clouds[action] or "unknown"] += 1
        self._span_add("marshal", time.perf_counter() - t_fwd)
        return action, probs, obs

    def _structured_decide(self, args: dict, display: list,
                           clouds: list) -> tuple[int, np.ndarray, np.ndarray]:
        t_parse = time.perf_counter()
        pod = args.get("pod")
        pod_cpu = pod_cpu_fraction(pod, self.node_capacity_cores)
        pod_reqs = (pod_resource_fractions(pod, self.node_capacity_cores)
                    if self.family == "set" and self.num_resources else None)
        self._span_add("parse", time.perf_counter() - t_parse)
        return self._decide_candidates(display, clouds, pod, pod_cpu,
                                       pod_reqs)

    def _decide_candidates(self, display, clouds: list, pod: dict | None,
                           pod_cpu: float, pod_reqs: list | None
                           ) -> tuple[int, np.ndarray, np.ndarray]:
        """The family dispatch both request encodings share: cap-sample,
        decide, re-expand. ``display`` may be any sequence (the wire
        path's lazy name view — only indexed names materialize). The
        JSON path arrives via :meth:`_structured_decide`; graftfront's
        compact wire path calls this directly with its pre-parsed
        fields."""
        t_parse = time.perf_counter()
        # Stashed for the trace record (graftloop replay field): the
        # record site closes out the request after marshal, where the
        # parsed pod is long out of scope.
        self._req_local.pod_cpu = pod_cpu
        cap = self.max_score_nodes
        idx = None
        if cap and len(clouds) > cap:
            # Uniform subset per request (seeded process RNG: which nodes
            # get scored varies by request, so no node is systematically
            # unscoreable; replicas sample independently — fail-open
            # semantics, an unsampled node just can't win this pod). An
            # affinity-annotated node outside the sample falls back to
            # the graph family's documented mean-hops neutral handling.
            with self._cap_lock:
                idx = sorted(self._cap_rng.sample(range(len(clouds)), cap))
            sub_clouds = [clouds[i] for i in idx]
            sub_display = [display[i] for i in idx]
        else:
            sub_clouds, sub_display = clouds, display
        self._span_add("parse", time.perf_counter() - t_parse)
        if self.family == "set":
            action, probs, obs = self.decide_set(sub_clouds, pod_cpu, pod_reqs)
        else:
            action, probs, obs = self.decide_graph(sub_clouds, sub_display,
                                                   pod, pod_cpu)
        if idx is not None:
            t_m = time.perf_counter()
            full = np.zeros(len(clouds), probs.dtype)
            full[idx] = probs
            action, probs = idx[action], full
            self._span_add("marshal", time.perf_counter() - t_m)
        return action, probs, obs

    @staticmethod
    def _request_nodes(args: dict) -> tuple[bool, list, list, list]:
        """``(use_names, sources, display_names, clouds)`` for a request:
        the extender protocol carries either full node objects or bare
        names (``nodecachecapable``). Structurally malformed payloads
        (non-list ``nodenames``, non-dict ``nodes``, junk items) coerce
        to empty/unknown instead of raising — a scheduling webhook must
        answer every request (the HTTP layer additionally backstops with
        a passthrough)."""
        names = args.get("nodenames")
        raw_nodes = args.get("nodes")
        nodes = raw_nodes.get("items") if isinstance(raw_nodes, dict) else []
        if not isinstance(nodes, list):
            nodes = []
        use_names = isinstance(names, list)
        if use_names:
            # Junk entries are DROPPED, not scored: a non-string "name"
            # (or non-dict node below) is not a schedulable candidate, and
            # letting it win the pointer argmax would reject every real
            # node. An entirely junk request yields empty sources, which
            # filter() answers with a passthrough.
            sources = [s for s in names if isinstance(s, str)]
            display = list(sources)
        else:
            sources = [n for n in nodes if isinstance(n, dict)]
            display = [(n.get("metadata") or {}).get("name", "?")
                       for n in sources]
        return use_names, sources, display, [node_cloud(s) for s in sources]

    def _filter_structured(self, args: dict) -> dict:
        """Structured-family (set/graph) ExtenderFilterResult: keep the
        argmax node; fail open."""
        self._span_begin()
        t_parse = time.perf_counter()
        use_names, sources, display, clouds = self._request_nodes(args)
        self._span_add("parse", time.perf_counter() - t_parse)
        if not sources:
            return self._passthrough(args)
        t0 = time.perf_counter()
        try:
            action, probs, obs = self._structured_decide(args, display,
                                                         clouds)
        except CircuitOpenError:
            # Expected for the whole open window — the breaker logged its
            # trip; a traceback per refused request would flood the hot
            # serving path.
            logger.debug("backend breaker open; passing all nodes")
            self._record_trace("filter", candidates=len(sources),
                               chosen=None, score=None, obs=None, t0=t0,
                               fail_open=True)
            return self._passthrough(args)
        except Exception:  # never wedge scheduling: pass all nodes through.
            logger.exception("%s policy decision failed; passing all nodes",
                             self.family)
            self._record_trace("filter", candidates=len(sources),
                               chosen=None, score=None, obs=None, t0=t0,
                               fail_open=True)
            return self._passthrough(args)
        t_marshal = time.perf_counter()
        failed = {
            name: f"{self.family} policy ranked {display[action]} first"
            for i, name in enumerate(display) if i != action
        }
        if use_names:
            result = {"nodenames": [sources[action]], "failedNodes": failed,
                      "error": ""}
        else:
            result = {"nodes": {"items": [sources[action]]},
                      "failedNodes": failed, "error": ""}
        self._span_add("marshal", time.perf_counter() - t_marshal)
        if self.placer is not None and clouds[action] is not None:
            self.placer.submit(clouds[action])
        # Trace record LAST (the trace-append phase closes the span
        # breakdown): its latency_ms now covers marshaling too — the
        # record describes the whole answered request.
        self._record_trace("filter", candidates=len(sources),
                           chosen=display[action],
                           score=float(probs[action]), obs=obs, t0=t0,
                           clouds=clouds)
        return result

    def _prioritize_structured(self, args: dict) -> list[dict]:
        """Structured-family HostPriorityList: per-node softmax -> 0-100
        scores (rank-preserving; the argmax node always scores 100)."""
        self._span_begin()
        t_parse = time.perf_counter()
        _, sources, display, clouds = self._request_nodes(args)
        self._span_add("parse", time.perf_counter() - t_parse)
        if not sources:
            return []
        t0 = time.perf_counter()
        try:
            action, probs, obs = self._structured_decide(args, display,
                                                         clouds)
        except CircuitOpenError:
            logger.debug("backend breaker open; uniform priorities")
            self._record_trace("prioritize", candidates=len(sources),
                               chosen=None, score=None, obs=None, t0=t0,
                               fail_open=True)
            return self._uniform_priorities(display)
        except Exception:
            logger.exception("%s policy decision failed; uniform priorities",
                             self.family)
            self._record_trace("prioritize", candidates=len(sources),
                               chosen=None, score=None, obs=None, t0=t0,
                               fail_open=True)
            return self._uniform_priorities(display)
        t_marshal = time.perf_counter()
        scores = np.round(probs / probs.max() * MAX_EXTENDER_SCORE)
        result = [{"host": name, "score": int(s)}
                  for name, s in zip(display, scores)]
        self._span_add("marshal", time.perf_counter() - t_marshal)
        # Success record OUTSIDE the try (like the filter paths): a
        # trace-layer raise must never downgrade a computed answer to
        # uniform scores, nor count a spurious fail-open the rollout
        # canary gate would read as a regression. Recorded after the
        # marshal so the span breakdown (and latency_ms) covers the
        # whole answered request.
        self._record_trace("prioritize", candidates=len(sources),
                           chosen=display[action],
                           score=float(probs[action]), obs=obs, t0=t0,
                           clouds=clouds)
        return result

    @staticmethod
    def _uniform_priorities(display: list) -> list[dict]:
        return [{"host": name, "score": MAX_EXTENDER_SCORE // 2}
                for name in display]

    def warmup_probe(self) -> dict:
        """One synthetic decision through the real decide path — the
        rollout gate's warm-up probe (scheduler/rollout.py). Unlike a
        request through :meth:`filter` it never submits a placement (no
        kube API call per probe) and its trace record is tagged
        ``endpoint="probe"`` so a trace consumer can exclude synthetic
        traffic. ``decided`` False means the decision failed open — a
        canary that only passes through is not promotable."""
        sources = ["aws-probe-0", "azure-probe-1"]
        clouds = [node_cloud(s) for s in sources]
        # Synthetic-traffic flag for the whole probe: the decide path
        # must not land this in the latency/phase histograms or SLO
        # counters client-facing scrapes and canary gates read (the
        # trace record's endpoint=probe tag is the replay-side filter).
        self._req_local.synthetic = True
        self._span_begin()
        t0 = time.perf_counter()
        try:
            try:
                if self.family in self.STRUCTURED:
                    action, probs, obs = self._structured_decide(
                        {"pod": {}}, sources, clouds)
                    chosen = sources[action]
                else:
                    action, probs, obs = self.decide()
                    chosen = CLOUDS[action]
            except Exception:  # noqa: BLE001 — CircuitOpenError included:
                # a fail-open probe IS the gate's signal, not an error
                logger.debug("warm-up probe failed open", exc_info=True)
                self._record_trace("probe", candidates=len(sources),
                                   chosen=None, score=None, obs=None, t0=t0,
                                   fail_open=True)
                return {"decided": False,
                        "latency_ms": round((time.perf_counter() - t0) * 1e3,
                                            3)}
            self._record_trace("probe", candidates=len(sources),
                               chosen=chosen,
                               score=float(probs[action]), obs=obs, t0=t0)
            return {"decided": True,
                    "latency_ms": round((time.perf_counter() - t0) * 1e3, 3)}
        finally:
            self._req_local.synthetic = False

    def fastpath_verify(self) -> dict:
        """graftfwd flush-on-promote: drop every score-cache entry and,
        when the int8 native forward is armed, RE-RUN the seeded-corpus
        agreement check against the fp32 reference. The rollout gate
        calls this per respawned worker (pool ``fastpath`` command)
        before the canary serves: a stale-generation cache hit after a
        rollout is a correctness bug, and a candidate checkpoint that
        quantizes badly must fail the gate, not silently serve. ``ok``
        False is a gate failure (chaos-tested via ``fastpath.agree``)."""
        out: dict = {"ok": True}
        if self.score_cache is not None:
            out["cache_flushed"] = self.score_cache.flush(
                "promote gate: generation boundary")
        backend = self.backend
        if getattr(backend, "name", "") == "native-int8" \
                and getattr(backend, "reference", None) is not None:
            from rl_scheduler_tpu.scheduler.fastpath import (
                check_int8_agreement,
            )

            try:
                agreement, ok = check_int8_agreement(
                    backend, backend.reference, backend.node_feat,
                    node_counts=getattr(backend, "agreement_node_counts",
                                        (8, 64)))
            except Exception as e:  # noqa: BLE001 — a check that cannot
                # run must refuse the promote, never pass by default
                logger.exception("int8 agreement re-check failed to run")
                return {"ok": False, "error": str(e)}
            backend.agreement = agreement
            out["agreement"] = round(agreement, 4)
            out["ok"] = bool(ok)
        return out

    def flip_tables(self, data_path: str) -> dict:
        """graftdrift regime flip: swap the replayed price table in
        place (``POST /telemetry/flip`` on the pool control plane;
        ``extender_bench --flip-tables`` drives it mid-soak). The new
        table goes through the same ``load_table`` validation the
        startup path uses — a bad flip refuses, it never serves
        half-validated prices."""
        from rl_scheduler_tpu.data.loader import load_table

        table = load_table(data_path)
        self.telemetry.swap_table(np.asarray(table.costs),
                                  np.asarray(table.latencies))
        logger.info("telemetry table flipped to %s (%d rows, swap #%d)",
                    data_path, len(np.asarray(table.costs)),
                    self.telemetry.swaps_total)
        return {"swapped": True, "rows": int(len(np.asarray(table.costs))),
                "swaps_total": self.telemetry.swaps_total}

    def set_drift_reference(self, path: str) -> dict:
        """Load a frozen reference (``drift snapshot`` output) into the
        drift tracker — fingerprint-verified by ``load_reference``, so a
        hand-edited file refuses here instead of silently grading
        against a tampered distribution."""
        if self.drift is None:
            raise ValueError(
                "drift tracking is not armed on this policy (start the "
                "server with --drift)")
        from rl_scheduler_tpu.scheduler.drift import load_reference

        ref = load_reference(path)
        self.drift.set_reference(ref)
        logger.info("drift reference loaded from %s (generation %d, "
                    "fingerprint %s)", path, ref["generation"],
                    ref["fingerprint"][:12])
        return {"loaded": True, "generation": ref["generation"],
                "fingerprint": ref["fingerprint"]}

    def set_shadow(self, shadow_run: str | None) -> dict:
        """graftpilot (loopback/daemon.py): arm or disarm shadow scoring
        at RUNTIME (``POST /shadow`` on the pool control plane). Arming
        rebuilds the candidate backend through the same
        refuse-before-grading checks as ``--shadow-run`` at startup and
        swaps in a FRESH :class:`~..drift.ShadowScorer` — zeroed
        counters, so the paired promote gate grades exactly the window
        it armed, never stale startup-shadow traffic. ``None`` disarms.
        The previous scorer (startup or runtime) is closed either way;
        a failed arm leaves it serving untouched."""
        if shadow_run is not None and self._shadow_build is None:
            raise ValueError(
                "set_shadow: this policy was not assembled by "
                "build_policy (no recorded backend request), so the "
                "candidate backend cannot be rebuilt — arm shadow at "
                "startup via shadow_run instead")
        scorer = None
        if shadow_run is not None:
            scorer = build_shadow_scorer(self, str(shadow_run),
                                         **self._shadow_build)
        old, self.shadow = self.shadow, scorer
        if old is not None:
            old.close()
        if scorer is None:
            logger.info("shadow scoring disarmed")
            return {"shadow": "disarmed"}
        logger.info("shadow scoring armed on %s (fresh counters)",
                    shadow_run)
        return {"shadow": "armed", "run": str(shadow_run)}

    def filter(self, args: dict) -> dict:
        """ExtenderFilterResult: keep nodes on the chosen cloud; fail open."""
        if self.family in self.STRUCTURED:
            return self._filter_structured(args)
        self._span_begin()
        t_parse = time.perf_counter()
        use_names, sources, display, clouds = self._request_nodes(args)
        self._span_add("parse", time.perf_counter() - t_parse)
        if not sources:
            # Nothing parseable to score (empty request, or every field/
            # item was junk): echo the request through rather than answer
            # "zero feasible nodes" — same guard as the structured path.
            return self._passthrough(args)
        t0 = time.perf_counter()
        try:
            action, probs, obs = self.decide()
        except CircuitOpenError:
            logger.debug("backend breaker open; passing all nodes")
            self._record_trace("filter", candidates=len(sources),
                               chosen=None, score=None, obs=None, t0=t0,
                               fail_open=True)
            return self._passthrough(args)
        except Exception:  # never wedge scheduling: pass all nodes through.
            # error stays "" — kube-scheduler treats a non-empty Error as a
            # hard extender failure unless ignorable=true is configured.
            logger.exception("policy decision failed; passing all nodes")
            self._record_trace("filter", candidates=len(sources),
                               chosen=None, score=None, obs=None, t0=t0,
                               fail_open=True)
            return self._passthrough(args)
        chosen = CLOUDS[action]
        if self.placer is not None:
            self.placer.submit(chosen)

        t_marshal = time.perf_counter()
        kept, failed = [], {}
        for src, name, cloud in zip(sources, display, clouds):
            if cloud is None or cloud == chosen:
                kept.append(src)  # unknown-cloud nodes pass (fail-open)
            else:
                failed[name] = f"policy selected {chosen}"
        if use_names:
            result = {"nodenames": kept, "failedNodes": failed, "error": ""}
        else:
            result = {"nodes": {"items": kept}, "failedNodes": failed,
                      "error": ""}
        self._span_add("marshal", time.perf_counter() - t_marshal)
        self._record_trace("filter", candidates=len(sources), chosen=chosen,
                           score=float(probs[action]), obs=obs, t0=t0,
                           clouds=clouds)
        return result

    def prioritize(self, args: dict) -> list[dict]:
        """HostPriorityList: score = policy probability of the node's cloud."""
        if self.family in self.STRUCTURED:
            return self._prioritize_structured(args)
        self._span_begin()
        t_parse = time.perf_counter()
        _, _, display, clouds = self._request_nodes(args)
        self._span_add("parse", time.perf_counter() - t_parse)
        t0 = time.perf_counter()
        action = obs = None
        try:
            action, probs, obs = self.decide()
        except CircuitOpenError:
            logger.debug("backend breaker open; uniform priorities")
            probs = np.full(len(CLOUDS), 1.0 / len(CLOUDS))
        except Exception:
            logger.exception("policy decision failed; uniform priorities")
            probs = np.full(len(CLOUDS), 1.0 / len(CLOUDS))
        t_marshal = time.perf_counter()
        out = []
        for name, cloud in zip(display, clouds):
            if cloud is None:
                score = MAX_EXTENDER_SCORE // 2
            else:
                score = int(round(float(probs[CLOUDS.index(cloud)]) * MAX_EXTENDER_SCORE))
            out.append({"host": name, "score": score})
        self._span_add("marshal", time.perf_counter() - t_marshal)
        if action is not None:
            # Success record outside the try — see _prioritize_structured.
            self._record_trace("prioritize", candidates=len(display),
                               chosen=CLOUDS[action],
                               score=float(probs[action]), obs=obs, t0=t0,
                               clouds=clouds)
        else:
            self._record_trace("prioritize", candidates=len(display),
                               chosen=None, score=None, obs=None, t0=t0,
                               fail_open=True)
        return out

    # --------------------------------------------------- graftfront wire

    def filter_wire(self, req, parse_s: float = 0.0) -> list | None:
        """Compact-wire ExtenderFilterResult: answer with kept candidate
        INDICES — ``None`` means keep all (the fail-open/passthrough
        answer). ``req`` is a decoded ``wire.WireRequest``; ``parse_s``
        is the codec's decode time, charged to the request's ``parse``
        span so the phase decomposition covers the wire path end to end.
        Span/trace/SLO semantics mirror :meth:`filter` exactly — the
        graftlens agreement suites run against both entry points."""
        self._span_begin()
        self._span_add("parse", parse_s)
        clouds = req.clouds
        n = len(clouds)
        if not n:
            return None
        t0 = time.perf_counter()
        try:
            if self.family in self.STRUCTURED:
                action, probs, obs = self._decide_candidates(
                    req.names, clouds, None,
                    req.pod_cpu_fraction(self.node_capacity_cores), None)
            else:
                action, probs, obs = self.decide()
        except CircuitOpenError:
            logger.debug("backend breaker open; passing all nodes")
            self._record_trace("filter", candidates=n, chosen=None,
                               score=None, obs=None, t0=t0, fail_open=True)
            return None
        except Exception:  # never wedge scheduling: keep every candidate.
            logger.exception("%s policy decision failed; passing all nodes",
                             self.family)
            self._record_trace("filter", candidates=n, chosen=None,
                               score=None, obs=None, t0=t0, fail_open=True)
            return None
        t_marshal = time.perf_counter()
        if self.family in self.STRUCTURED:
            kept = [action]
            chosen = req.names[action]
            if self.placer is not None and clouds[action] is not None:
                self.placer.submit(clouds[action])
        else:
            chosen = CLOUDS[action]
            if self.placer is not None:
                self.placer.submit(chosen)
            kept = [i for i, c in enumerate(clouds)
                    if c is None or c == chosen]
        self._span_add("marshal", time.perf_counter() - t_marshal)
        self._record_trace("filter", candidates=n, chosen=chosen,
                           score=float(probs[action]), obs=obs, t0=t0,
                           clouds=clouds)
        return kept

    def prioritize_wire(self, req, parse_s: float = 0.0) -> list:
        """Compact-wire HostPriorityList: one 0-100 score per candidate
        (positional — the wire response carries no names). Fail-open
        answers uniform midpoint scores, mirroring the JSON paths."""
        self._span_begin()
        self._span_add("parse", parse_s)
        clouds = req.clouds
        n = len(clouds)
        if not n:
            return []
        t0 = time.perf_counter()
        if self.family in self.STRUCTURED:
            try:
                action, probs, obs = self._decide_candidates(
                    req.names, clouds, None,
                    req.pod_cpu_fraction(self.node_capacity_cores), None)
            except CircuitOpenError:
                logger.debug("backend breaker open; uniform priorities")
                self._record_trace("prioritize", candidates=n, chosen=None,
                                   score=None, obs=None, t0=t0,
                                   fail_open=True)
                return [MAX_EXTENDER_SCORE // 2] * n
            except Exception:
                logger.exception("%s policy decision failed; uniform "
                                 "priorities", self.family)
                self._record_trace("prioritize", candidates=n, chosen=None,
                                   score=None, obs=None, t0=t0,
                                   fail_open=True)
                return [MAX_EXTENDER_SCORE // 2] * n
            t_marshal = time.perf_counter()
            scores = np.round(probs / probs.max() * MAX_EXTENDER_SCORE)
            out = [int(s) for s in scores]
            self._span_add("marshal", time.perf_counter() - t_marshal)
            # Success record outside the try — see _prioritize_structured.
            self._record_trace("prioritize", candidates=n,
                               chosen=req.names[action],
                               score=float(probs[action]), obs=obs, t0=t0,
                               clouds=clouds)
            return out
        action = obs = None
        try:
            action, probs, obs = self.decide()
        except CircuitOpenError:
            logger.debug("backend breaker open; uniform priorities")
            probs = np.full(len(CLOUDS), 1.0 / len(CLOUDS))
        except Exception:
            logger.exception("policy decision failed; uniform priorities")
            probs = np.full(len(CLOUDS), 1.0 / len(CLOUDS))
        t_marshal = time.perf_counter()
        out = [MAX_EXTENDER_SCORE // 2 if c is None
               else int(round(float(probs[CLOUDS.index(c)])
                              * MAX_EXTENDER_SCORE))
               for c in clouds]
        self._span_add("marshal", time.perf_counter() - t_marshal)
        if action is not None:
            self._record_trace("prioritize", candidates=n,
                               chosen=CLOUDS[action],
                               score=float(probs[action]), obs=obs, t0=t0,
                               clouds=clouds)
        else:
            self._record_trace("prioritize", candidates=n, chosen=None,
                               score=None, obs=None, t0=t0, fail_open=True)
        return out

    @staticmethod
    def _passthrough(args: dict) -> dict:
        if args.get("nodenames") is not None:
            return {"nodenames": args["nodenames"], "failedNodes": {}, "error": ""}
        return {
            "nodes": args.get("nodes") or {"items": []},
            "failedNodes": {},
            "error": "",
        }

    def reset_stats(self) -> dict:
        """Clear the latency ring (decision counters stay): scopes a
        measurement window so ``/stats`` percentiles cover exactly the
        requests since the reset. Round-4 finding: the 4096-entry ring
        spans ~3 consecutive 1500-request bench runs, so per-configuration
        percentiles were contaminated by the preceding run's traffic.
        Lifetime counters — histograms (end-to-end AND per-phase),
        fail-opens, SLO counters, trace-writer stats, and the pool's
        promotion/rollback totals — are deliberately NOT cleared
        (Prometheus monotonicity; pinned by test)."""
        self.stats.reset()
        for stats in self.phase_stats.values():
            stats.reset()
        return {"status": "reset"}

    def breakers(self) -> dict:
        """Name -> snapshot of every circuit breaker on this serving
        stack's host-I/O boundaries: the backend decision path, the
        Prometheus telemetry source (when configured), and the kube pod
        placer (when configured)."""
        out = {self.backend_breaker.name: self.backend_breaker.snapshot()}
        for cpu_breaker in getattr(self.telemetry.cpu, "breakers",
                                   {}).values():
            out[cpu_breaker.name] = cpu_breaker.snapshot()
        for placer_breaker in getattr(self._placer_impl, "breakers",
                                      {}).values():
            out[placer_breaker.name] = placer_breaker.snapshot()
        return out

    def health(self) -> dict:
        out = {"status": "ok", "backend": self.backend.name,
               "family": self.family}
        if self.slo is not None:
            # Fast-burn degradation is VISIBLE on the data-plane health
            # body but stays HTTP 200 there: k8s liveness must not
            # restart-storm a process that is merely slow. The pool
            # control plane (the readiness probe) answers 503 while
            # degraded (scheduler/pool.py).
            snap = self.slo.snapshot()
            out["slo"] = {
                "degraded": snap["degraded"],
                "burning": sorted(name for name, o in
                                  snap["objectives"].items()
                                  if o["burning"]),
            }
            if snap["degraded"]:
                out["status"] = "degraded"
        if self.drift is not None:
            # Body-only (status untouched): a drifting stream is a
            # RETRAIN trigger for the loop daemon, not a liveness or
            # readiness failure — the plane still answers correctly,
            # just under a moved distribution.
            snap = self.drift.snapshot(generation=self.generation)
            out["drift"] = {
                "drifting": snap["drifting"],
                "reference": bool(snap["reference"]),
                "statuses": {name: s["status"]
                             for name, s in snap["scores"].items()},
            }
        if self.scenario is not None:
            out["scenario"] = self.scenario
        if self.pool_info is not None:
            out.update(self.pool_info)
        return out

    def statistics(self) -> dict:
        with self._lock:
            decisions = dict(self._decisions)
            fail_open = self._fail_open_total
        total = sum(decisions.values())
        out = {
            "backend": self.backend.name,
            "family": self.family,
            "generation": self.generation,
            "decisions": decisions,
            "choice_fractions": {
                c: (n / total if total else 0.0) for c, n in decisions.items()
            },
            "latency": self.stats.percentiles_ms(),
            # Lifetime fail-open count (open breaker / backend raise):
            # the rollout canary gate compares deltas of this.
            "fail_open_total": fail_open,
        }
        if self.spans_enabled:
            # graftlens: per-phase percentiles (reset-scoped ring) plus
            # lifetime mean/count from the monotonic histogram — the
            # merge-safe numbers tools/decisionview's phase table reads.
            out["phases"] = {
                phase: self._phase_entry(stats)
                for phase, stats in self.phase_stats.items()
            }
            cumulative, total_sum, count = self.stats.histogram()
            out["latency"]["lifetime_mean_ms"] = (
                round(total_sum / count * 1e3, 4) if count else None)
            out["latency"]["lifetime_count"] = count
        fastpath = self.fastpath_snapshot()
        if fastpath:
            out["fastpath"] = fastpath
        if self.slo is not None:
            out["slo"] = self.slo.snapshot()
        if self.drift is not None:
            # graftdrift section: sketches + scores vs the loaded
            # reference (scheduler/drift.py). Lifetime counts are
            # monotonic like the histograms — /stats/reset never rewinds
            # them (pinned by test).
            out["drift"] = self.drift.snapshot(generation=self.generation)
        if self.shadow is not None:
            out["shadow"] = self.shadow.snapshot()
        if self.trace is not None:
            # Trace-writer counters (records/dropped/write_errors/
            # segments). Lifetime-monotonic like the histogram —
            # /stats/reset never clears them (docs/serving.md).
            out["trace"] = self.trace.snapshot()
        shed = getattr(self.backend, "shed_fraction", None)
        if shed is not None:
            # The load-aware backends' off-primary fraction (admission
            # overflow + the large-N reroute) — same signal /metrics
            # exports as a gauge.
            out["shed_fraction"] = round(float(shed), 4)
        reroute = getattr(self.backend, "reroute_fraction", None)
        if reroute is not None:
            # Latency-based routing decisions that chose the host path
            # (AdaptiveLatencyRouter) — deliberately separate from
            # shed_fraction so overload stays distinguishable from
            # the-host-path-is-simply-faster steady states.
            out["reroute_fraction"] = round(float(reroute), 4)
        if self.placer is not None:
            out["placements_dropped"] = self.placer.dropped
        # graftguard breaker states: "is a dependency down" is a /stats
        # read, not a log dive (docs/robustness.md).
        out["breakers"] = self.breakers()
        return out

    def fastpath_snapshot(self) -> dict:
        """The ``/stats`` body's graftfwd section: per-lever counters
        (score cache, micro-batcher, int8 agreement) — empty dict when
        no lever is armed, so pre-graftfwd readers see an unchanged
        body. Counters are lifetime-monotonic; the pool sums them
        (pool.sum_fastpath)."""
        out: dict = {}
        if self.score_cache is not None:
            out["cache"] = self.score_cache.snapshot()
        if self.batcher is not None:
            out["batch"] = self.batcher.snapshot()
        agreement = getattr(self.backend, "agreement", None)
        if agreement is not None:
            out["int8"] = {
                "agreement": round(float(agreement), 4),
                "scales_recorded": len(getattr(
                    self.backend, "quantization_scales", []) or []),
            }
        return out

    @staticmethod
    def _phase_entry(stats: "LatencyStats") -> dict:
        """One phase's ``/stats`` body: ring percentiles + lifetime
        mean/count (lifetime numbers merge exactly across workers; ring
        percentiles are this process's reset-scoped window)."""
        entry = stats.percentiles_ms()
        _, total_sum, count = stats.histogram()
        entry["lifetime_mean_ms"] = (round(total_sum / count * 1e3, 4)
                                     if count else None)
        entry["lifetime_count"] = count
        return entry

    def metrics_text(self) -> str:
        """Prometheus text exposition (``GET /metrics``): decision
        counters by cloud, a lifetime latency histogram, the load-aware
        shed fraction when the backend tracks one, and an info gauge.
        The framework already READS Prometheus for telemetry
        (``telemetry.PrometheusCpu``); this closes the loop so the
        serving path is scrapeable by the same stack (scrape-config
        snippet in docs/serving.md)."""
        with self._lock:
            decisions = dict(self._decisions)
        p = "rl_scheduler_extender"
        lines = [
            f"# HELP {p}_decisions_total Placement decisions by cloud.",
            f"# TYPE {p}_decisions_total counter",
        ]
        for cloud, n in sorted(decisions.items()):
            lines.append(f'{p}_decisions_total{{cloud="{cloud}"}} {n}')
        cumulative, total_sum, count = self.stats.histogram()
        lines += [
            f"# HELP {p}_decision_latency_seconds Server-side decision "
            "latency (lifetime histogram; /stats/reset does not clear it).",
            f"# TYPE {p}_decision_latency_seconds histogram",
        ]
        bounds = [f"{b:g}" for b in LatencyStats.BUCKETS] + ["+Inf"]
        for bound, c in zip(bounds, cumulative):
            lines.append(
                f'{p}_decision_latency_seconds_bucket{{le="{bound}"}} {c}'
            )
        lines.append(f"{p}_decision_latency_seconds_sum {total_sum:.9g}")
        lines.append(f"{p}_decision_latency_seconds_count {count}")
        if self.spans_enabled:
            lines += phase_metric_lines(
                p, {phase: stats.histogram()
                    for phase, stats in self.phase_stats.items()})
        if self.slo is not None:
            lines += slo_metric_lines(p, self.slo.snapshot())
        if self.drift is not None:
            lines += drift_metric_lines(
                p, self.drift.snapshot(generation=self.generation))
        if self.shadow is not None:
            lines += shadow_metric_lines(p, self.shadow.snapshot())
        lines += fastpath_metric_lines(p, self.fastpath_snapshot())
        shed = getattr(self.backend, "shed_fraction", None)
        if shed is not None:
            lines += [
                f"# HELP {p}_shed_fraction Fraction of requests served "
                "off the primary path by the load-aware backend.",
                f"# TYPE {p}_shed_fraction gauge",
                f"{p}_shed_fraction {shed:.9g}",
            ]
        reroute = getattr(self.backend, "reroute_fraction", None)
        if reroute is not None:
            lines += [
                f"# HELP {p}_reroute_fraction Fraction of latency-router "
                "decisions served by the host path (distinct from "
                "overload shedding).",
                f"# TYPE {p}_reroute_fraction gauge",
                f"{p}_reroute_fraction {reroute:.9g}",
            ]
        if self.placer is not None:
            lines += [
                f"# HELP {p}_placements_dropped_total Dry-run placements "
                "dropped by the bounded async queue.",
                f"# TYPE {p}_placements_dropped_total counter",
                f"{p}_placements_dropped_total {self.placer.dropped}",
            ]
        with self._lock:
            fail_open = self._fail_open_total
        lines += [
            f"# HELP {p}_fail_open_total Requests answered by a fail-open "
            "path (open breaker or backend raise), lifetime.",
            f"# TYPE {p}_fail_open_total counter",
            f"{p}_fail_open_total {fail_open}",
        ]
        if self.trace is not None:
            trace = self.trace.snapshot()
            lines += [
                f"# HELP {p}_trace_records_total Decision records appended "
                "to the durable trace log (lifetime; /stats/reset never "
                "clears it).",
                f"# TYPE {p}_trace_records_total counter",
                f"{p}_trace_records_total {trace['records_total']}",
                f"# HELP {p}_trace_dropped_total Trace records dropped by "
                "the bounded queue's drop-oldest backpressure.",
                f"# TYPE {p}_trace_dropped_total counter",
                f"{p}_trace_dropped_total {trace['dropped_total']}",
                f"# HELP {p}_trace_write_errors_total Trace segment writes "
                "that failed (record dropped, serving unaffected).",
                f"# TYPE {p}_trace_write_errors_total counter",
                f"{p}_trace_write_errors_total {trace['write_errors_total']}",
                f"# HELP {p}_trace_segments_total Trace segments sealed "
                "(fsync + rename).",
                f"# TYPE {p}_trace_segments_total counter",
                f"{p}_trace_segments_total {trace['segments_total']}",
                f"# HELP {p}_trace_segments_pruned_total Sealed segments "
                "dropped by the --trace-max-segments retention cap "
                "(oldest first).",
                f"# TYPE {p}_trace_segments_pruned_total counter",
                f"{p}_trace_segments_pruned_total "
                f"{trace['segments_pruned_total']}",
            ]
        from rl_scheduler_tpu.utils.retry import CircuitBreaker

        snapshots = self.breakers()
        lines += [
            f"# HELP {p}_circuit_state Circuit breaker state per host-I/O "
            "boundary (0=closed, 1=half_open, 2=open).",
            f"# TYPE {p}_circuit_state gauge",
        ]
        for name, snap in sorted(snapshots.items()):
            code = CircuitBreaker.STATE_CODES[snap["state"]]
            lines.append(f'{p}_circuit_state{{breaker="{name}"}} {code}')
        lines += [
            f"# HELP {p}_circuit_opens_total Times each breaker tripped "
            "open (lifetime).",
            f"# TYPE {p}_circuit_opens_total counter",
        ]
        for name, snap in sorted(snapshots.items()):
            lines.append(
                f'{p}_circuit_opens_total{{breaker="{name}"}} '
                f'{snap["opens_total"]}')
        lines += [
            f"# HELP {p}_info Serving backend and decision family.",
            f"# TYPE {p}_info gauge",
            f'{p}_info{{backend="{self.backend.name}",'
            f'family="{self.family}"}} 1',
        ]
        return "\n".join(lines) + "\n"


class _Handler(BaseHTTPRequestHandler):
    policy: ExtenderPolicy  # set by make_server

    def _send(self, code: int, payload) -> None:
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802 (stdlib API)
        if self.path == "/healthz":
            self._send(200, self.policy.health())
        elif self.path == "/stats":
            self._send(200, self.policy.statistics())
        elif self.path == "/metrics":
            body = self.policy.metrics_text().encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        else:
            self._send(404, {"error": f"unknown path {self.path}"})

    def do_POST(self):  # noqa: N802
        length = int(self.headers.get("Content-Length", 0))
        ctype = (self.headers.get("Content-Type") or "").split(";")[0].strip()
        if ctype == WIRE_CONTENT_TYPE:
            # graftfront compact wire (wire.py): both fronts serve both
            # encodings on one port, so the A/B isolates the transport.
            body = self.rfile.read(length)
            try:
                answer = serve_wire(self.policy, self.path, body)
            except WireError as exc:
                # A refusal, never a dropped connection (codec contract).
                self._send(400, {"error": f"bad wire: {exc}"})
                return
            except ValueError:
                self._send(404, {"error": f"unknown path {self.path}"})
                return
            self.send_response(200)
            self.send_header("Content-Type", WIRE_CONTENT_TYPE)
            self.send_header("Content-Length", str(len(answer)))
            self.end_headers()
            self.wfile.write(answer)
            return
        try:
            args = json.loads(self.rfile.read(length) or b"{}")
        except json.JSONDecodeError as exc:
            self._send(400, {"error": f"bad json: {exc}"})
            return
        # Normalize extender-protocol field capitalization (Go marshals
        # Nodes/NodeNames/Pod; be liberal in what we accept).
        args = {k.lower(): v for k, v in args.items()}
        # Last-line fail-open backstop: whatever a malformed-but-valid-JSON
        # payload does to the decision path, the scheduler must get a
        # RESPONSE, not a dropped connection — filter echoes the request's
        # node fields back (nothing filtered), prioritize returns an empty
        # HostPriorityList.
        if self.path == "/filter":
            try:
                result = self.policy.filter(args)
            except Exception:  # noqa: BLE001
                logger.exception("filter failed on malformed request; "
                                 "passing nodes through")
                result = ExtenderPolicy._passthrough(args)
            self._send(200, result)
        elif self.path == "/prioritize":
            try:
                result = self.policy.prioritize(args)
            except Exception:  # noqa: BLE001
                logger.exception("prioritize failed on malformed request; "
                                 "empty priority list")
                result = []
            self._send(200, result)
        elif self.path == "/stats/reset":
            self._send(200, self.policy.reset_stats())
        else:
            self._send(404, {"error": f"unknown path {self.path}"})

    def log_message(self, fmt, *log_args):  # quiet by default
        logger.debug("%s " + fmt, self.address_string(), *log_args)


FRONTS = ("threading", "asyncio")


def make_server(policy: ExtenderPolicy, host: str = "0.0.0.0", port: int = 8787,
                reuse_port: bool = False, inherited_socket=None,
                front: str = "threading"):
    """The extender's HTTP server. Two pool-worker variants (graftserve,
    ``scheduler/pool.py``) share the handler stack unchanged:

    - ``reuse_port=True``: bind our own listener with ``SO_REUSEPORT`` so
      N worker processes share one port and the kernel balances
      connections across them.
    - ``inherited_socket``: skip bind/listen entirely and ``accept()`` on
      a listener the supervisor bound before forking — the fallback where
      ``SO_REUSEPORT`` is unavailable (pre-fork accept sharing).

    ``front`` picks the transport (graftfront): ``"threading"`` is the
    classic ``ThreadingHTTPServer`` (default; one thread per
    connection), ``"asyncio"`` the event-loop data plane in ``front.py``
    (keep-alive, 10k+ concurrent connections, same facade: construction
    binds, ``serve_forever()`` blocks, ``shutdown()`` drains,
    ``server_close()`` releases). Both serve identical routes and
    semantics — the graftlens agreement suites run against each.
    """
    if front not in FRONTS:
        raise ValueError(f"unknown front {front!r} (choose from {FRONTS})")
    if front == "asyncio":
        from rl_scheduler_tpu.scheduler.front import AsyncFrontServer

        return AsyncFrontServer(policy, host, port, reuse_port=reuse_port,
                                inherited_socket=inherited_socket)
    handler = type("BoundHandler", (_Handler,), {"policy": policy})
    if inherited_socket is not None:
        server = ThreadingHTTPServer((host, port), handler,
                                     bind_and_activate=False)
        server.socket.close()  # the unbound placeholder from __init__
        server.socket = inherited_socket
        server.server_address = inherited_socket.getsockname()
        return server
    if not reuse_port:
        return ThreadingHTTPServer((host, port), handler)
    import socket as _socket

    if not hasattr(_socket, "SO_REUSEPORT"):
        raise ValueError("reuse_port=True: SO_REUSEPORT unavailable on "
                         "this platform (the pool's inherit mode is the "
                         "fallback)")
    server = ThreadingHTTPServer((host, port), handler,
                                 bind_and_activate=False)
    server.socket.setsockopt(_socket.SOL_SOCKET, _socket.SO_REUSEPORT, 1)
    server.server_bind()
    server.server_activate()
    return server


def build_policy(
    backend: str = "jax",
    run: str | None = None,
    run_root: str | None = None,
    data_path: str | None = None,
    prometheus: bool = False,
    dry_run_place: bool = False,
    cpu_seed: int | None = None,
    serve_device: str = "cpu",
    node_capacity_cores: float = DEFAULT_NODE_CAPACITY_CORES,
    price_replay: str = "counter",
    price_replay_period_s: float = 300.0,
    warm_nodes: tuple | None = None,
    max_score_nodes: int = 0,
    price_counter=None,
    table_counter=None,
    scenario: str | None = None,
    trace_dir: str | None = None,
    trace_prefix: str = "",
    trace_max_segments: int = 0,
    spans: bool = True,
    slo_p99_ms: float | None = None,
    slo_avail: float | None = None,
    batch_window_ms: float = 0.0,
    batch_max: int = 8,
    score_cache_epoch_s: float = 0.0,
    score_cache_entries: int = 256,
    drift: bool = False,
    drift_ref: str | None = None,
    drift_threshold: float | None = None,
    drift_fast_window_s: float | None = None,
    drift_slow_window_s: float | None = None,
    drift_min_count: int | None = None,
    drift_bucket_s: float | None = None,
    shadow_run: str | None = None,
) -> ExtenderPolicy:
    """Assemble the serving stack: checkpoint -> backend -> telemetry.

    ``scenario`` is the serve config's conformance demand (``--scenario``):
    the checkpoint's recorded scenario meta must MATCH it or the build
    refuses — serving a churn-trained policy where the operator deployed
    for the heterogeneous workload (or vice versa) is a silent
    distribution mismatch, and for the heterogeneous family an outright
    observation-width mismatch. A scenario-trained cluster_set checkpoint
    also auto-configures the widened observation path from its
    ``node_feat`` meta (no flag needed); the demand flag exists so a
    DEPLOYMENT can pin what it expects.

    ``price_counter``/``table_counter`` are graftserve's pool seams
    (``scheduler/pool.SharedCounter``): cross-process replay positions so
    every worker of one pool walks the single-process trajectory.

    Serves three checkpoint families: flat ``multi_cloud`` MLP/DQN runs
    (cloud-level decision), ``cluster_set`` set-transformer runs
    (per-node pointer decision, ``set_backend.py``), and
    ``cluster_graph`` GNN runs (per-node pointer decision over a
    per-request topology, ``graph_backend.py``). ``single_cluster`` is
    refused — its observation space doesn't map onto the extender's
    telemetry.
    """
    params_tree = None
    hidden = (256, 256)
    algo = "ppo"
    backend_obj = None
    ckpt_scenario = None
    num_resources = 0
    meta = None
    if backend != "greedy":
        tree = run_dir = None
        try:
            from rl_scheduler_tpu.config import RuntimeConfig
            from rl_scheduler_tpu.utils.checkpoint import (
                find_latest_run,
                load_policy_params,
            )
            from pathlib import Path

            run_dir = (
                Path(run) if run else find_latest_run(run_root or RuntimeConfig().checkpoint_dir)
            )
            tree, meta = load_policy_params(run_dir)
        except Exception:  # corrupt/missing checkpoint must not keep the
            # extender down — greedy fallback absorbs it (SURVEY.md §5.3).
            logger.exception("checkpoint load failed; serving cost-greedy fallback")
        if meta is not None:
            ckpt_env = meta.get("env", "multi_cloud")
            # graftmix: a mixture-trained generalist answers the
            # conformance demand with its canonical mixture name (the
            # same one-string round-trip as trace_replay scenarios) —
            # the obs layout is the classic set layout, so serving is
            # otherwise identical.
            ckpt_scenario = meta.get("scenario") or meta.get("mixture")
            node_feat = meta.get("node_feat")
            if (ckpt_env == "cluster_set" and node_feat
                    and node_feat != 6):
                # Heterogeneous-scenario checkpoint: the embed kernel
                # bakes the widened layout (4 + 3R features,
                # scenarios/het_env.py) — serve the matching observation.
                num_resources = (int(node_feat) - 4) // 3
                logger.info(
                    "scenario checkpoint (%s): serving the widened "
                    "%d-feature observation (%d resources)",
                    ckpt_scenario, node_feat, num_resources)
            if ckpt_env == "cluster_set":
                # The set policy's pointer logits score candidate nodes
                # directly — exactly the /prioritize contract. Both the
                # flax and the --fused-set training paths checkpoint the
                # identical tree (train_ppo.py meta note).
                from rl_scheduler_tpu.scheduler.set_backend import (
                    make_set_backend,
                )

                logger.info("serving cluster_set checkpoint from %s", run_dir)
                if warm_nodes is None:
                    # Default: warm the checkpoint's own training N (fleet
                    # checkpoints AOT-compile their fleet size up front;
                    # pre-fleet meta lacks the key -> 8).
                    warm_nodes = (meta.get("num_nodes") or 8,)
                backend_obj, _ = make_set_backend(
                    backend, tree, num_heads=meta.get("num_heads") or 1,
                    device=serve_device, warm_counts=tuple(warm_nodes),
                    node_feat=node_feat,
                )
            elif ckpt_env == "cluster_graph":
                # The GNN's pointer head also scores nodes directly; its
                # GCN weights are node-count-independent, so the per-
                # request topology slots in at serving time
                # (graph_backend.py). fused_gnn checkpoints are the same
                # tree.
                from rl_scheduler_tpu.scheduler.graph_backend import (
                    make_graph_backend,
                )

                logger.info("serving cluster_graph checkpoint from %s",
                            run_dir)
                backend_obj, _ = make_graph_backend(backend, tree)
            elif ckpt_env != "multi_cloud":
                # A different env family means a different observation
                # space: the net would load fine but raise (fail-open) on
                # every 6-dim request.
                msg = (
                    f"checkpoint {run_dir} is for env {ckpt_env!r}; the "
                    "extender serves multi_cloud (flat), cluster_set and "
                    "cluster_graph (per-node) observations — pass --run "
                    "pointing at one of those"
                )
                if run:  # same truthiness as the discovery branch above
                    # Operator named this checkpoint explicitly: refuse to
                    # start rather than silently serve something else.
                    raise ValueError(msg)
                # Auto-discovered newest run happens to be the wrong family:
                # stay up (fail-open), but say exactly what is being served.
                logger.error("%s; serving cost-greedy fallback", msg)
            else:
                try:
                    hidden = tuple(meta.get("hidden") or hidden)
                    # The meta's algo key selects the network family — a DQN
                    # run being the newest must serve a Q-network, not be
                    # misread as an actor-critic tree.
                    algo = meta.get("algo", "ppo")
                    # tp-trained runs checkpoint full global matrices in
                    # TPActorCritic layout; converting to the ActorCritic
                    # tree (identical function) lets every backend —
                    # numpy, native C++, torch, jax AOT — serve them
                    # unchanged.
                    from rl_scheduler_tpu.parallel.tensor_parallel import (
                        untp_checkpoint_tree,
                    )

                    params_tree = untp_checkpoint_tree(meta, tree)
                    logger.info("serving %s checkpoint from %s", algo, run_dir)
                except Exception:  # malformed meta (e.g. hand-edited
                    # non-iterable "hidden") is a corrupt checkpoint too:
                    # stay up on the greedy fallback (SURVEY.md §5.3).
                    logger.exception(
                        "malformed checkpoint meta at %s; serving cost-greedy "
                        "fallback", run_dir,
                    )
    if scenario is not None and ckpt_scenario != scenario:
        # The serve config demanded a scenario this checkpoint was not
        # trained for (or no checkpoint loaded at all, so nothing vouches
        # for it): refuse to start rather than serve a silently mismatched
        # distribution — for the heterogeneous family, a mismatched
        # observation WIDTH (docs/scenarios.md conformance contract).
        trained = (f"scenario {ckpt_scenario!r}" if ckpt_scenario
                   else "the CSV replay (no scenario meta)")
        raise ValueError(
            f"--scenario {scenario}: the loaded checkpoint was trained on "
            f"{trained}; serve a matching checkpoint or drop the demand")
    if backend_obj is None:
        backend_obj, _ = make_backend(backend, params_tree, hidden,
                                      serve_device, algo)
    cpu_source = PrometheusCpu() if prometheus else RandomCpu(seed=cpu_seed)
    telemetry = TableTelemetry.from_table(data_path, cpu_source,
                                          counter=table_counter)
    placer = None
    if dry_run_place:
        from rl_scheduler_tpu.scheduler.k8s_client import DryRunPodPlacer

        placer = DryRunPodPlacer()
    slo = None
    if slo_p99_ms is not None or slo_avail is not None:
        # graftlens SLO engine (scheduler/slo.py): SloConfig validates
        # the objectives up front — a bad threshold refuses before
        # traffic, like every other serve-config knob.
        from rl_scheduler_tpu.scheduler.slo import SloConfig, SloTracker

        slo = SloTracker(SloConfig(p99_ms=slo_p99_ms,
                                   availability=slo_avail))
    policy = ExtenderPolicy(backend_obj, telemetry, placer,
                            node_capacity_cores=node_capacity_cores,
                            price_replay=price_replay,
                            price_replay_period_s=price_replay_period_s,
                            max_score_nodes=max_score_nodes,
                            price_counter=price_counter)
    # Scenario provenance (and the graftlens knobs below) set
    # post-construction (the attributes default in __init__): policy
    # stand-ins that mimic the historical ctor signature keep working,
    # and only checkpoint-meta/serve-config-driven builds flip them.
    if not spans:
        policy.spans_enabled = False
    if slo is not None:
        policy.slo = slo
    if num_resources:
        policy.num_resources = num_resources
    if ckpt_scenario is not None:
        policy.scenario = ckpt_scenario
    if trace_dir is not None:
        # graftroll: the durable decision trace (scheduler/tracelog.py).
        # Attached post-construction like the scenario provenance above;
        # pool workers pass a per-worker prefix so one shared directory
        # carries every worker's stream without write contention.
        from rl_scheduler_tpu.scheduler.tracelog import TraceLog

        policy.trace = TraceLog(trace_dir, prefix=trace_prefix,
                                max_segments=trace_max_segments)
    if max_score_nodes and policy.family not in ExtenderPolicy.STRUCTURED:
        # Same refuse-before-traffic rule as price_replay below: the flat
        # family scores per CLOUD (two logits however long the node list
        # is), so a candidate cap would silently do nothing.
        raise ValueError(
            f"max_score_nodes={max_score_nodes}: the candidate cap bounds "
            f"the structured families' per-node forward; the loaded "
            f"checkpoint serves family {policy.family!r} (drop the flag "
            "or serve a cluster_set/cluster_graph checkpoint)"
        )
    if price_replay != "counter" and policy.family != "graph":
        # Refuse here (not just in the CLI) so every entry point —
        # embeddings, tests — learns the flag did nothing BEFORE traffic:
        # price replay drives the graph family's raw-dollar features only.
        raise ValueError(
            f"price_replay={price_replay!r}: price replay drives the "
            f"cluster_graph family; the loaded checkpoint serves family "
            f"{policy.family!r} (drop the flag or serve a cluster_graph "
            "checkpoint)"
        )
    # graftfwd levers (scheduler/fastpath.py) — same refuse-before-
    # traffic rule as max_score_nodes: both levers exist for the set
    # family's per-node forward, and a greedy fallback (corrupt
    # checkpoint) must not silently serve with a demanded lever off.
    if batch_window_ms:
        if policy.family != "set":
            raise ValueError(
                f"batch_window_ms={batch_window_ms}: cross-request "
                f"micro-batching coalesces the set family's per-node "
                f"forwards; the loaded checkpoint serves family "
                f"{policy.family!r} (drop the flag or serve a "
                "cluster_set checkpoint)")
        from rl_scheduler_tpu.scheduler.fastpath import MicroBatcher

        policy.batcher = MicroBatcher(policy.backend,
                                      window_s=batch_window_ms / 1e3,
                                      max_batch=batch_max)
    if score_cache_epoch_s:
        if policy.family != "set":
            raise ValueError(
                f"score_cache_epoch_s={score_cache_epoch_s}: the "
                f"telemetry-epoch score cache keys the set family's "
                f"node-set observations; the loaded checkpoint serves "
                f"family {policy.family!r} (drop the flag or serve a "
                "cluster_set checkpoint)")
        from rl_scheduler_tpu.scheduler.fastpath import ScoreCache

        policy.score_cache = ScoreCache(epoch_s=score_cache_epoch_s,
                                        max_entries=score_cache_entries)
    # graftdrift (scheduler/drift.py) — refuse-before-traffic like every
    # serve-config knob above: a drift sub-flag without --drift would
    # silently track nothing.
    drift_sub = {"drift_ref": drift_ref, "drift_threshold": drift_threshold,
                 "drift_fast_window_s": drift_fast_window_s,
                 "drift_slow_window_s": drift_slow_window_s,
                 "drift_min_count": drift_min_count,
                 "drift_bucket_s": drift_bucket_s}
    if not drift and any(v is not None for v in drift_sub.values()):
        named = sorted(k for k, v in drift_sub.items() if v is not None)
        raise ValueError(
            f"{', '.join(named)}: drift knobs configure the --drift "
            "tracker; pass drift=True (--drift) or drop them")
    if drift:
        from rl_scheduler_tpu.scheduler.drift import (
            DriftConfig,
            DriftTracker,
            load_reference,
        )

        cfg_kwargs: dict = {}
        if drift_threshold is not None:
            cfg_kwargs["threshold"] = drift_threshold
        if drift_fast_window_s is not None:
            cfg_kwargs["fast_window_s"] = drift_fast_window_s
        if drift_slow_window_s is not None:
            cfg_kwargs["slow_window_s"] = drift_slow_window_s
        if drift_min_count is not None:
            cfg_kwargs["min_window_count"] = drift_min_count
        if drift_bucket_s is not None:
            cfg_kwargs["bucket_s"] = drift_bucket_s
        # DriftConfig validates up front (bad windows/threshold refuse
        # before traffic, like SloConfig).
        policy.drift = DriftTracker(DriftConfig(**cfg_kwargs))
        if drift_ref is not None:
            policy.drift.set_reference(load_reference(drift_ref))
    # graftpilot: record the backend request so set_shadow can rebuild a
    # candidate at runtime under the same restore path.
    policy._shadow_build = {"backend": backend,
                            "serve_device": serve_device}
    if shadow_run is not None:
        policy.shadow = build_shadow_scorer(policy, shadow_run,
                                            backend=backend,
                                            serve_device=serve_device)
    return policy


def build_shadow_scorer(policy: ExtenderPolicy, shadow_run: str,
                        backend: str = "jax",
                        serve_device: str = "cpu"):
    """graftdrift shadow scoring: a SECOND policy build supplies the
    candidate backend (same checkpoint restore + warm path as the
    incumbent); only its backend is kept. The family must match —
    comparing a per-node pointer to a cloud argmax is not an agreement
    signal — and a shadow that fell back to greedy (corrupt/missing
    checkpoint) is refused outright: silently grading the incumbent
    against the fallback would report meaningless agreement. Shared by
    the startup path (``--shadow-run``) and graftpilot's runtime
    :meth:`ExtenderPolicy.set_shadow`."""
    if policy.family == "graph":
        raise ValueError(
            "shadow_run: shadow scoring covers the cloud and set "
            "families; the graph family's per-request topology is "
            "not reproducible from the queued observation alone")
    shadow_policy = build_policy(
        backend=backend, run=shadow_run, serve_device=serve_device,
        spans=False)
    shadow_backend = shadow_policy.backend
    shadow_name = getattr(shadow_backend, "name",
                          shadow_backend.__class__.__name__)
    if backend != "greedy" and shadow_name == "greedy":
        raise ValueError(
            f"shadow_run={shadow_run}: the shadow checkpoint failed "
            "to load (greedy fallback) — fix the run dir; a greedy "
            "shadow grades nothing")
    if shadow_policy.family != policy.family:
        raise ValueError(
            f"shadow_run={shadow_run}: shadow family "
            f"{shadow_policy.family!r} != incumbent family "
            f"{policy.family!r}; shadow a matching checkpoint")
    from rl_scheduler_tpu.scheduler.drift import ShadowScorer

    def _softmax_top1(action, logits):
        z = logits - logits.max()
        probs = np.exp(z) / np.exp(z).sum()
        return int(action), float(probs[int(action)])

    if policy.family == "set":
        def _shadow_score(obs):
            action, logits = shadow_backend.decide_nodes(obs)
            return _softmax_top1(action, np.asarray(logits))
    else:
        def _shadow_score(obs):
            action, logits = shadow_backend.decide(obs)
            return _softmax_top1(action, np.asarray(logits))

    def _shadow_record(action, score, latency_ms, obs):
        if policy.trace is None:
            return
        arr = np.asarray(obs) if obs is not None else None
        candidates = (len(arr) if arr is not None and arr.ndim == 2
                      else len(CLOUDS))
        chosen = (CLOUDS[action]
                  if policy.family == "cloud" and action < len(CLOUDS)
                  else f"candidate-{action}")
        policy.trace.append(decision_record(
            endpoint="shadow", family=policy.family,
            backend=shadow_name, candidates=candidates, chosen=chosen,
            score=score, latency_ms=latency_ms,
            worker_id=(policy.pool_info or {}).get("worker_id"),
            generation=policy.generation))

    return ShadowScorer(_shadow_score, record_fn=_shadow_record)


def check_warm_nodes_served(policy: ExtenderPolicy,
                            warm_nodes: tuple | None) -> None:
    """Refuse a ``--warm-nodes`` request the built policy cannot honor:
    the no-op (wrong checkpoint family / non-jax backend) AND the
    silently-degraded case (a failed warm compile falls back to greedy,
    family "cloud") — the operator asked for pre-compiled executables
    and must not boot without them. Runs after ``build_policy`` in the
    single-process CLI and inside every pool worker (graftserve), so a
    pool cannot come up half-warmed either."""
    if warm_nodes is not None and (
            policy.family != "set" or policy.backend.name != "jax"):
        raise SystemExit(
            f"--warm-nodes applies to cluster_set checkpoints on "
            f"--backend jax; the loaded policy serves family "
            f"{policy.family!r} via backend {policy.backend.name!r} "
            "(if you passed a set checkpoint with --backend jax, a warm "
            "AOT compile failed — see the log above)"
        )


def main(argv: list[str] | None = None) -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--backend", default="jax",
                   choices=("jax", "cpu", "native", "native-int8", "torch",
                            "greedy"))
    p.add_argument("--run", default=None, help="checkpoint run dir")
    p.add_argument("--run-root", default=None)
    p.add_argument("--data", default=None, metavar="CSV",
                   help="telemetry replay table (cluster trace CSV) the "
                        "serving-path TableTelemetry walks; defaults to "
                        "the bundled table. Pin this when a drill or "
                        "soak needs a known regime before a "
                        "/telemetry/flip")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=8787)
    p.add_argument("--front", default="threading", choices=FRONTS,
                   help="graftfront: data-plane transport. 'threading' "
                        "(default) is the classic ThreadingHTTPServer — "
                        "one thread per connection; 'asyncio' is the "
                        "event-loop front (scheduler/front.py): keep-"
                        "alive, 10k+ concurrent connections, policy "
                        "decisions in a bounded executor, identical "
                        "/stats//metrics/trace/SLO semantics. Applies "
                        "per worker in pool mode (docs/serving.md)")
    p.add_argument("--workers", type=int, default=None, metavar="N",
                   help="graftserve pool mode: fork N worker processes "
                        "sharing --port via SO_REUSEPORT (fork-after-bind "
                        "inheritance where unavailable), with a supervisor "
                        "that restarts dead workers and serves pool-wide "
                        "aggregated /stats, /metrics, /stats/reset and "
                        "/healthz on --control-port. Omit for the classic "
                        "single-process server (docs/serving.md)")
    p.add_argument("--control-port", type=int, default=None,
                   help="pool mode only: port for the supervisor's "
                        "aggregated control plane (default: --port + 1)")
    p.add_argument("--control-host", default=None,
                   help="pool mode only: bind address for the control "
                        "plane (default: --host, so k8s probes and "
                        "Prometheus reach it wherever the data plane is "
                        "reachable; pass 127.0.0.1 to keep it "
                        "operator-local)")
    p.add_argument("--blas-threads", type=int, default=None, metavar="T",
                   help="pool mode only: BLAS intra-op threads per worker "
                        "(default: cores//workers, min 1 — worker "
                        "processes are the parallelism, and leaving every "
                        "worker a full per-core BLAS pool oversubscribes "
                        "the host workers-fold; 0 leaves library "
                        "defaults untouched)")
    p.add_argument("--serve-device", default="cpu",
                   help="XLA device for the jax backend: cpu (default; "
                        "single-obs serving is dispatch-bound) or tpu")
    p.add_argument("--node-capacity-cores", type=float,
                   default=DEFAULT_NODE_CAPACITY_CORES,
                   help="cores per node, for normalizing a pod's cpu "
                        "request into the set policy's [0,1] pod_cpu "
                        "feature (cluster_set checkpoints only)")
    p.add_argument("--prometheus", action="store_true",
                   help="query Prometheus for CPU telemetry (else random parity)")
    p.add_argument("--dry-run-place", action="store_true",
                   help="dry-run pod creation on the chosen kind cluster")
    p.add_argument("--price-replay", default="counter",
                   choices=("counter", "wallclock"),
                   help="graph-family raw-price replay position: 'counter' "
                        "advances per request (training parity; process-"
                        "local — restarts start over and replicas walk "
                        "independent trajectories), 'wallclock' derives "
                        "the row from wall time so all replicas and "
                        "restarts agree with zero coordination")
    p.add_argument("--warm-nodes", default=None,
                   help="cluster_set + --backend jax only: comma-separated "
                        "node counts to AOT-compile at startup (default: "
                        "the checkpoint's own training N). Warm your "
                        "fleet's actual candidate-list sizes so no first "
                        "request is served by the overflow forward while "
                        "a background compile runs")
    p.add_argument("--scenario", default=None,
                   help="conformance demand: refuse to start unless the "
                        "loaded checkpoint's scenario meta matches this "
                        "name (docs/scenarios.md). Scenario checkpoints "
                        "auto-configure their observation width either "
                        "way; this flag pins what the DEPLOYMENT expects "
                        "so a mismatched checkpoint cannot silently serve")
    p.add_argument("--max-score-nodes", type=int, default=0, metavar="K",
                   help="structured families: score at most K candidate "
                        "nodes per request (a uniform per-request sample; "
                        "unsampled nodes score 0). The kube-scheduler's "
                        "percentageOfNodesToScore idea — bounds the "
                        "per-request forward at fleet-giant N and pins "
                        "large requests to one AOT executable size. "
                        "0 scores every candidate")
    p.add_argument("--trace-dir", default=None, metavar="DIR",
                   help="graftroll: append every decision to a durable "
                        "JSONL trace log under DIR (crash-safe rotating "
                        "segments; bounded queue, drop-oldest — the hot "
                        "path never blocks). In pool mode each worker "
                        "writes its own w<id>- stream into the shared "
                        "directory. Omit to disable (docs/serving.md)")
    p.add_argument("--trace-max-segments", type=int, default=0, metavar="N",
                   help="trace retention: keep at most N sealed segments "
                        "PER WORKER STREAM, pruning oldest-first (counted "
                        "on *_trace_segments_pruned_total) so a long-"
                        "serving pool's trace dir is bounded at roughly "
                        "N x workers x 4096 records. graftloop snapshots "
                        "the dir before compiling, so pruning never races "
                        "a retrain (docs/serving.md). 0 keeps everything")
    p.add_argument("--no-spans", action="store_true",
                   help="graftlens: disable the per-phase decision-path "
                        "spans (parse/observe/forward/marshal/trace). "
                        "The A/B knob for the measured span-overhead "
                        "bound (docs/serving.md); leave spans ON in "
                        "production — they are what makes the latency "
                        "decomposable")
    p.add_argument("--slo-p99-ms", type=float, default=None, metavar="MS",
                   help="graftlens SLO: arm the latency objective — 99%% "
                        "of decisions under MS milliseconds. Burn-rate "
                        "gauges on /metrics, degraded /healthz on "
                        "fast+slow-window burn, and (pool mode) a canary "
                        "gate for POST /promote (docs/observability.md)")
    p.add_argument("--slo-avail", type=float, default=None, metavar="F",
                   help="graftlens SLO: arm the availability objective — "
                        "at least fraction F of requests answered by a "
                        "real policy decision (fail-open passthroughs "
                        "are the error budget), e.g. 0.999")
    p.add_argument("--price-replay-period", type=float, default=300.0,
                   help="wallclock replay only: real-world seconds one "
                        "pricing-table row represents (default 300 — the "
                        "5-minute cloud-pricing update cadence)")
    p.add_argument("--batch-window-ms", type=float, default=0.0,
                   metavar="MS",
                   help="graftfwd lever (i): coalesce concurrent "
                        "cluster_set decide requests for MS milliseconds "
                        "into ONE [k, N, F] forward (same generation + "
                        "obs spec; bitwise per-row agreement on the AOT "
                        "path; the batch_wait phase carries the window "
                        "time). 0 disables (docs/serving.md)")
    p.add_argument("--batch-max", type=int, default=8, metavar="K",
                   help="micro-batching: close an admission window early "
                        "once K requests joined (default 8 — the 8-way "
                        "regime the levers were measured at)")
    p.add_argument("--score-cache-epoch-s", type=float, default=0.0,
                   metavar="S",
                   help="graftfwd lever (iii): cache cluster_set scores "
                        "keyed on (telemetry epoch, node-set, pod "
                        "request, generation) for S-second epochs "
                        "(wallclock-derived like --price-replay "
                        "wallclock; 15 matches the Prometheus scrape "
                        "cadence). A hit skips observe AND forward; "
                        "promote flushes; 0 disables")
    p.add_argument("--score-cache-entries", type=int, default=256,
                   metavar="N",
                   help="score cache LRU bound (default 256)")
    p.add_argument("--drift", action="store_true",
                   help="graftdrift: track per-decision distribution "
                        "sketches (score/action/cost/latency streams) "
                        "and grade them against a frozen reference — "
                        "drift section on /stats, *_drift_score/"
                        "*_drifting on /metrics, drift body on /healthz "
                        "(docs/observability.md#graftdrift)")
    p.add_argument("--drift-ref", default=None, metavar="FILE",
                   help="load a frozen reference distribution at startup "
                        "(the `drift snapshot` CLI's fingerprinted "
                        "output); also loadable live via the pool's "
                        "POST /drift/reference")
    p.add_argument("--drift-threshold", type=float, default=None,
                   metavar="F",
                   help="PSI alarm bar per stream (default 0.2, the "
                        "classic significant-shift bound)")
    p.add_argument("--drift-fast-window", type=float, default=None,
                   metavar="S",
                   help="short drift window seconds (default 60); "
                        "drifting requires BOTH windows over threshold")
    p.add_argument("--drift-slow-window", type=float, default=None,
                   metavar="S",
                   help="long drift window seconds (default 600)")
    p.add_argument("--drift-min-count", type=int, default=None,
                   metavar="N",
                   help="observations a window needs before it can "
                        "alarm (default 20 — sampling noise is not "
                        "drift)")
    p.add_argument("--drift-bucket-s", type=float, default=None,
                   metavar="S",
                   help="drift ring bucket seconds (default: fast "
                        "window / 8, clamped to [0.05, 1])")
    p.add_argument("--shadow-run", default=None, metavar="DIR",
                   help="graftdrift shadow scoring: a candidate "
                        "checkpoint that re-scores live requests off the "
                        "serving thread, never answering — incumbent-vs-"
                        "shadow agreement + score-delta histogram on "
                        "/stats (endpoint=shadow in the trace; excluded "
                        "from every served-traffic histogram like "
                        "probes)")
    args = p.parse_args(argv)
    if args.batch_window_ms < 0:
        raise SystemExit(
            f"--batch-window-ms {args.batch_window_ms}: pass a positive "
            "window (0 disables micro-batching)")
    if args.batch_window_ms and args.batch_max < 2:
        raise SystemExit(
            f"--batch-max {args.batch_max}: a 1-request batch is the "
            "unbatched path; pass at least 2")
    if args.score_cache_epoch_s < 0:
        raise SystemExit(
            f"--score-cache-epoch-s {args.score_cache_epoch_s}: pass a "
            "positive epoch (0 disables the score cache)")
    if args.score_cache_epoch_s and args.score_cache_entries < 1:
        raise SystemExit(
            f"--score-cache-entries {args.score_cache_entries}: pass at "
            "least 1")
    if args.max_score_nodes < 0 or args.max_score_nodes == 1:
        raise SystemExit(
            f"--max-score-nodes {args.max_score_nodes}: pass a cap >= 2 "
            "(a 1-node sample is a coin flip, not a policy decision; "
            "0 disables the cap)"
        )
    if args.trace_max_segments < 0:
        raise SystemExit(
            f"--trace-max-segments {args.trace_max_segments}: pass a "
            "sealed-segment cap >= 1 (0 keeps everything)")
    if args.trace_max_segments and args.trace_dir is None:
        raise SystemExit(
            "--trace-max-segments bounds the --trace-dir stream; pass "
            "--trace-dir (or drop the retention cap)")
    drift_sub_flags = {"--drift-ref": args.drift_ref,
                       "--drift-threshold": args.drift_threshold,
                       "--drift-fast-window": args.drift_fast_window,
                       "--drift-slow-window": args.drift_slow_window,
                       "--drift-min-count": args.drift_min_count,
                       "--drift-bucket-s": args.drift_bucket_s}
    if not args.drift and any(v is not None
                              for v in drift_sub_flags.values()):
        named = sorted(k for k, v in drift_sub_flags.items()
                       if v is not None)
        raise SystemExit(
            f"{', '.join(named)}: drift knobs configure the --drift "
            "tracker; pass --drift (or drop them)")
    if args.price_replay_period <= 0:
        # RawPriceReplay validates too (for programmatic entry points);
        # refusing here keeps the CLI's exit clean and pre-startup.
        raise SystemExit(
            f"--price-replay-period {args.price_replay_period}: must be "
            "a positive number of seconds"
        )
    if args.price_replay != "wallclock" and args.price_replay_period != 300.0:
        # counter mode never reads the period: refuse the no-op flag
        # rather than let the operator believe prices advance per-60s.
        raise SystemExit(
            f"--price-replay-period {args.price_replay_period} only "
            "applies to --price-replay wallclock (counter mode advances "
            "per request)"
        )
    warm_nodes = None
    if args.warm_nodes is not None:
        try:
            warm_nodes = tuple(int(n) for n in args.warm_nodes.split(","))
        except ValueError:
            raise SystemExit(
                f"--warm-nodes {args.warm_nodes!r}: pass comma-separated "
                "integers, e.g. 8,64,100"
            )
        if not warm_nodes or any(n < 1 for n in warm_nodes):
            raise SystemExit(
                f"--warm-nodes {args.warm_nodes!r}: node counts must be "
                "positive"
            )

    if args.workers is not None and args.workers < 1:
        raise SystemExit(
            f"--workers {args.workers}: pass at least 1 worker process "
            "(omit the flag for the classic single-process server)"
        )
    if args.control_port is not None and args.workers is None:
        raise SystemExit(
            "--control-port only applies to pool mode (pass --workers N); "
            "the single-process server exposes /stats and /metrics on "
            "--port itself"
        )
    if args.control_host is not None and args.workers is None:
        raise SystemExit(
            "--control-host only applies to pool mode (pass --workers N)"
        )
    if args.blas_threads is not None and args.workers is None:
        raise SystemExit(
            "--blas-threads only applies to pool mode (pass --workers N); "
            "set OPENBLAS_NUM_THREADS/OMP_NUM_THREADS for the "
            "single-process server"
        )
    if args.blas_threads is not None and args.blas_threads < 0:
        raise SystemExit(
            f"--blas-threads {args.blas_threads}: pass a positive count "
            "or 0 to leave library defaults untouched"
        )

    logging.basicConfig(level=logging.INFO)
    build_kwargs = dict(
        backend=args.backend, run=args.run, run_root=args.run_root,
        data_path=args.data,
        prometheus=args.prometheus, dry_run_place=args.dry_run_place,
        serve_device=args.serve_device,
        node_capacity_cores=args.node_capacity_cores,
        price_replay=args.price_replay,
        price_replay_period_s=args.price_replay_period,
        warm_nodes=warm_nodes,
        max_score_nodes=args.max_score_nodes,
        scenario=args.scenario,
        trace_dir=args.trace_dir,
        trace_max_segments=args.trace_max_segments,
        spans=not args.no_spans,
        slo_p99_ms=args.slo_p99_ms,
        slo_avail=args.slo_avail,
        batch_window_ms=args.batch_window_ms,
        batch_max=args.batch_max,
        score_cache_epoch_s=args.score_cache_epoch_s,
        score_cache_entries=args.score_cache_entries,
        drift=args.drift,
        drift_ref=args.drift_ref,
        drift_threshold=args.drift_threshold,
        drift_fast_window_s=args.drift_fast_window,
        drift_slow_window_s=args.drift_slow_window,
        drift_min_count=args.drift_min_count,
        drift_bucket_s=args.drift_bucket_s,
        shadow_run=args.shadow_run,
    )
    if args.workers is not None:
        # graftserve: the supervisor never builds a policy (workers each
        # restore the checkpoint and compile their backend AFTER the
        # fork, so the supervisor process stays jax-free and tiny); any
        # build_policy refusal kills every worker identically and the
        # pool reports it as a startup failure.
        from rl_scheduler_tpu.scheduler.pool import run_pool

        run_pool(build_kwargs, workers=args.workers, host=args.host,
                 port=args.port, control_port=args.control_port,
                 control_host=args.control_host,
                 blas_threads=args.blas_threads, front=args.front)
        return
    try:
        policy = build_policy(**build_kwargs)
    except ValueError as e:
        # build_policy refuses misconfigurations (explicitly-named
        # wrong-family checkpoint; --price-replay on a non-graph family)
        # with actionable messages — exit cleanly, not with a traceback.
        raise SystemExit(str(e))
    check_warm_nodes_served(policy, warm_nodes)
    server = make_server(policy, args.host, args.port, front=args.front)
    print(f"Scheduler extender serving on {args.host}:{args.port} "
          f"(backend={policy.backend.name}, front={args.front})", flush=True)
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        server.shutdown()
    finally:
        if policy.trace is not None:
            # Drain + seal the trace on every exit path: an unclosed
            # trace would leave the final records queued, and "the log
            # replays every decision" is the acceptance contract.
            policy.trace.close()


if __name__ == "__main__":
    main()
