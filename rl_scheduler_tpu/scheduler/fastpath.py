"""graftfwd: the serving fast path — the three ROADMAP-item-2 levers.

PR 12 (graftlens) measured the N=1024 decision budget precisely:
``forward`` is 97.3% of the 15.2 ms mean on the best host path
(docs/serving.md phase table), and the instrument — per-phase spans, SLO
burn gauges, ``make serve-report`` — was built so the levers could be
attacked one at a time. This module is the three levers, each
independently toggleable and each shipping with an exact-agreement test
against the unmodified path:

- :class:`MicroBatcher` **(i) cross-request micro-batching**: a few-ms
  admission window (``--batch-window-ms``, 0 = off) on the extender that
  coalesces concurrent decide requests for the same (generation,
  obs-spec) into ONE ``[k, N, F]`` forward. The set policy is vmappable
  over requests, so the batched AOT executable is ``jax.vmap`` of the
  very apply the single path runs — bitwise-identical logits per row
  (pinned by test) — and the host fallbacks run one stacked BLAS/ATen
  forward instead of k GIL-contending ones. 8-way fleet-N traffic is
  exactly where graftserve's queueing collapsed; batch occupancy and the
  window wait ride the graftlens span machinery as the ``batch_wait``
  phase so decisionview's coverage-reconciliation row still closes.
- :class:`ScoreCache` **(iii) telemetry-epoch score cache**: scores
  keyed on (telemetry epoch, node-set hash, pod request vector, policy
  generation). Telemetry advances on a ~15 s scrape cadence, so between
  scrapes identical candidate lists answer from cache — a hit skips
  ``observe`` AND ``forward`` and returns the stored decision
  bitwise-unchanged, with the ORIGINAL observation and replay position
  as trace provenance. Invalidation semantics are pinned like
  ``--price-replay``'s wallclock mode (the epoch is
  ``int(now / epoch_s)`` — all entries die at the epoch boundary), plus
  a mandatory :meth:`ScoreCache.flush` on promote: a stale-generation
  hit after a graftroll rollout is a correctness bug (the generation is
  in the key AND the rollout gate flushes, chaos-tested via the
  ``fastpath.agree`` site). Hit/miss/invalidation counters ride
  ``/stats`` and ``/metrics``.
- :func:`check_int8_agreement` **(ii) the int8 native gate**: the
  C++ set core (``native/set_infer.cpp``) grew an int8-quantized,
  blocked-attention fleet forward (``--backend native-int8``,
  ``set_backend.Int8NativeSetBackend``). Quantization happens at
  checkpoint-load time with a recorded scale per tensor; activation is
  gated on a measured top-1-agreement threshold (>= 99.5% vs the fp32
  forward on a seeded candidate corpus) checked at startup — the build
  REFUSES to serve quantized otherwise. The same check re-runs per
  worker on promote (``ExtenderPolicy.fastpath_verify`` via the pool's
  ``fastpath`` control command), so a candidate checkpoint that
  quantizes badly fails the canary gate instead of silently serving.

Everything here is pure stdlib + numpy on the hot path; the jax/torch
specializations live in the backends (``set_backend.py``).
"""

from __future__ import annotations

import logging
import threading
import time

import numpy as np

logger = logging.getLogger(__name__)

# The startup/promote gate: measured top-1 agreement between the int8
# and fp32 forwards on the seeded corpus must meet this bar or the
# quantized path refuses to serve (docs/serving.md).
INT8_AGREEMENT_MIN = 0.995
# Seeded-corpus size for the agreement check: the resolution must be
# finer than the 0.5% error budget (1/256 = 0.39% — at 64 samples a
# SINGLE flip read as 1.6% and failed an actually-99.6%-agreeing
# forward), while a fleet-N startup check stays sub-second.
AGREEMENT_SAMPLES = 256


class ScoreCache:
    """Telemetry-epoch score cache for the set family's decide path.

    One entry per (generation, node-set, pod-request) key within the
    current epoch: ``(action, logits, obs, replay_pos)`` — the stored
    decision is returned bitwise-unchanged, and the stored observation/
    replay position keep trace provenance exact (the record names the
    inputs the score was actually computed from, not the row a recompute
    would have consumed). Epoch semantics mirror ``--price-replay
    wallclock``: ``epoch = int(now / epoch_s)``; crossing the boundary
    invalidates every entry at once (lazily, on the next access).
    Thread-safe; bounded LRU (``max_entries``) so candidate-list
    diversity cannot grow memory without bound.
    """

    def __init__(self, epoch_s: float = 15.0, max_entries: int = 256,
                 clock=time.time):
        # clock defaults to WALLCLOCK (not monotonic) deliberately: the
        # epoch construction mirrors --price-replay wallclock, so every
        # worker of a pool — and a restarted worker — rolls its epoch at
        # the SAME instant, aligned with the real scrape cadence the
        # epoch length is tuned to. Injectable for tests.
        if epoch_s <= 0:
            raise ValueError(f"epoch_s={epoch_s}: pass a positive number "
                             "of seconds (the telemetry scrape cadence)")
        if max_entries < 1:
            raise ValueError(f"max_entries={max_entries}: pass at least 1")
        import collections

        self.epoch_s = float(epoch_s)
        self.max_entries = int(max_entries)
        self._clock = clock
        self._entries: "collections.OrderedDict" = collections.OrderedDict()
        self._epoch = None
        self._lock = threading.Lock()
        # Lifetime counters (monotonic — /stats/reset never clears them,
        # the same contract as every serving counter).
        self.hits_total = 0
        self.misses_total = 0
        # Epoch rollovers + explicit flushes, each counted once however
        # many entries died.
        self.invalidations_total = 0

    def epoch(self) -> int:
        """The current telemetry epoch (wallclock-derived, like
        ``--price-replay wallclock`` derives its row)."""
        return int(self._clock() / self.epoch_s)

    @staticmethod
    def make_key(generation: int, clouds, pod_cpu: float,
                 pod_reqs) -> tuple:
        """The cache key for one decide: policy generation, the node
        set's cloud layout (the only node input the observation reads),
        and the pod's parsed request vector. Display names are NOT part
        of the key — two requests with the same cloud layout score
        identically by construction (``telemetry.observe_nodes``)."""
        return (generation, tuple(clouds), float(pod_cpu),
                None if pod_reqs is None else tuple(pod_reqs))

    def _roll_epoch_locked(self) -> None:
        now_epoch = self.epoch()
        if self._epoch != now_epoch:
            if self._entries:
                self.invalidations_total += 1
                self._entries.clear()
            self._epoch = now_epoch

    def get(self, key: tuple):
        """``(action, logits, obs, replay_pos)`` or ``None``. A hit is
        the stored tuple itself — bitwise the decision that was computed
        (pinned by test)."""
        with self._lock:
            self._roll_epoch_locked()
            entry = self._entries.get(key)
            if entry is None:
                self.misses_total += 1
                return None
            self._entries.move_to_end(key)
            self.hits_total += 1
            return entry

    def put(self, key: tuple, action: int, logits, obs,
            replay_pos) -> None:
        with self._lock:
            self._roll_epoch_locked()
            self._entries[key] = (int(action), logits, obs, replay_pos)
            self._entries.move_to_end(key)
            while len(self._entries) > self.max_entries:
                self._entries.popitem(last=False)

    def flush(self, reason: str = "") -> int:
        """Drop every entry NOW (mandatory on promote: a
        stale-generation hit after a graftroll rollout is a correctness
        bug even though the generation is in the key — flushing frees
        the dead generation's memory and makes the invalidation
        observable). Returns the number of entries dropped."""
        with self._lock:
            n = len(self._entries)
            self._entries.clear()
            if n:
                self.invalidations_total += 1
        if reason:
            logger.info("score cache flushed (%d entries): %s", n, reason)
        return n

    def snapshot(self) -> dict:
        """The ``/stats`` body's cache section (counters lifetime-
        monotonic; ``entries`` is the instantaneous size)."""
        with self._lock:
            requests = self.hits_total + self.misses_total
            return {
                "epoch_s": self.epoch_s,
                "epoch": self._epoch,
                "entries": len(self._entries),
                "max_entries": self.max_entries,
                "hits_total": self.hits_total,
                "misses_total": self.misses_total,
                "invalidations_total": self.invalidations_total,
                "hit_rate": (round(self.hits_total / requests, 4)
                             if requests else None),
            }


class _Batch:
    """One in-flight admission window: the leader's collection point."""

    def __init__(self):
        self.rows: list = []        # observation arrays, arrival order
        self.results = None         # (actions [k], logits [k, N]) when done
        self.error = None           # the leader's exception, fanned out
        self.forward_s = 0.0        # the shared batched-forward duration
        self.done = threading.Event()


class MicroBatcher:
    """Cross-request micro-batching for the set family's forward.

    :meth:`submit` blocks the calling request thread until its row's
    result is ready. The FIRST request for a given (shape, generation)
    becomes the window's leader: it waits up to ``window_s`` (or until
    ``max_batch`` rows arrive), stacks the window's observations into
    one ``[k, N, F]`` array, runs ``backend.decide_nodes_batch`` once,
    and fans the per-row results out. Followers just wait. A leader
    exception fans out to every member — each request's own fail-open
    handler (and the circuit breaker wrapping each ``submit``) sees it,
    so a poisoned batch counts k failures, not one.

    Window membership is keyed on (obs shape, generation): requests for
    different candidate-list sizes, observation widths, or policy
    generations never share a forward (the AOT executable and the
    checkpoint must match every row).
    """

    def __init__(self, backend, window_s: float, max_batch: int = 8):
        if window_s <= 0:
            raise ValueError(f"window_s={window_s}: the batcher exists "
                             "only for a positive admission window "
                             "(0 = off is the caller's branch)")
        if max_batch < 2:
            raise ValueError(f"max_batch={max_batch}: a 1-row batch is "
                             "the unbatched path; pass >= 2")
        if not hasattr(backend, "decide_nodes_batch"):
            raise ValueError(
                f"backend {getattr(backend, 'name', backend)!r} has no "
                "decide_nodes_batch — micro-batching needs a batched "
                "set forward (set_backend.py)")
        self._backend = backend
        self.window_s = float(window_s)
        self.max_batch = int(max_batch)
        self._pending: dict = {}
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        # Lifetime counters for /stats + /metrics (monotonic).
        self.batches_total = 0
        self.requests_total = 0
        self.coalesced_total = 0   # requests that shared a k>=2 forward
        self.occupancy_sum = 0     # sum of k over batches (mean = /batches)
        self.max_occupancy = 0

    def submit(self, obs: np.ndarray,
               generation: int) -> tuple[int, np.ndarray, float]:
        """One request's forward through the admission window:
        ``(action, logits, forward_s)`` where ``forward_s`` is the
        shared batched-forward duration (the caller charges it to the
        ``forward`` phase and the remaining blocked time to
        ``batch_wait``)."""
        key = (obs.shape, generation)
        with self._lock:
            self.requests_total += 1
            batch = self._pending.get(key)
            if batch is not None and len(batch.rows) < self.max_batch:
                batch.rows.append(obs)
                index = len(batch.rows) - 1
                if len(batch.rows) >= self.max_batch:
                    self._cond.notify_all()  # wake the leader early
                leader = False
            else:
                batch = _Batch()
                batch.rows.append(obs)
                index = 0
                self._pending[key] = batch
                leader = True
        if leader:
            self._run_window(key, batch)
        else:
            batch.done.wait()
        if batch.error is not None:
            raise batch.error
        actions, logits = batch.results
        return int(actions[index]), logits[index], batch.forward_s

    def _run_window(self, key, batch: _Batch) -> None:
        deadline = time.monotonic() + self.window_s
        with self._lock:
            while (len(batch.rows) < self.max_batch
                   and (remaining := deadline - time.monotonic()) > 0):
                self._cond.wait(remaining)
            # Close admission BEFORE forwarding: a request arriving now
            # starts the next window instead of racing the stack below.
            if self._pending.get(key) is batch:
                del self._pending[key]
            rows = list(batch.rows)
        t0 = time.perf_counter()
        try:
            stacked = np.stack(rows)
            actions, logits = self._backend.decide_nodes_batch(stacked)
            batch.results = (np.asarray(actions), np.asarray(logits))
        except Exception as e:  # noqa: BLE001 — fanned out to every member
            # Not swallowed: every member's submit re-raises this into
            # its own fail-open handler + breaker accounting; the log
            # line keeps the batch-level event greppable (one line per
            # batch, not per member).
            logger.warning("batched forward failed; fanning out to %d "
                           "member(s): %s", len(rows), e)
            batch.error = e
        finally:
            batch.forward_s = time.perf_counter() - t0
            with self._lock:
                k = len(rows)
                self.batches_total += 1
                self.occupancy_sum += k
                self.max_occupancy = max(self.max_occupancy, k)
                if k >= 2:
                    self.coalesced_total += k
            batch.done.set()

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "window_ms": round(self.window_s * 1e3, 3),
                "max_batch": self.max_batch,
                "requests_total": self.requests_total,
                "batches_total": self.batches_total,
                "coalesced_total": self.coalesced_total,
                "max_occupancy": self.max_occupancy,
                "mean_occupancy": (round(self.occupancy_sum
                                         / self.batches_total, 3)
                                   if self.batches_total else None),
            }


def agreement_corpus(node_feat: int, node_counts=(8, 64),
                     samples: int = AGREEMENT_SAMPLES,
                     seed: int = 0) -> list:
    """The seeded candidate corpus the int8 gate scores: ``samples``
    observation arrays cycling through ``node_counts``, drawn from the
    serving observation's value ranges (costs/latencies/cpu in [0, 1],
    cloud ids in {0, 0.5, 1}) — deterministic from the seed, so the
    startup check and a test measure the SAME corpus."""
    rng = np.random.default_rng(seed)
    corpus = []
    for i in range(samples):
        n = int(node_counts[i % len(node_counts)])
        obs = rng.uniform(0.0, 1.0, (n, node_feat)).astype(np.float32)
        obs[:, min(3, node_feat - 1)] = rng.choice(
            np.asarray([0.0, 0.5, 1.0], np.float32), n)
        corpus.append(obs)
    return corpus


def check_int8_agreement(int8_backend, ref_backend, node_feat: int,
                         node_counts=(8, 64),
                         samples: int = AGREEMENT_SAMPLES, seed: int = 0,
                         min_agreement: float = INT8_AGREEMENT_MIN,
                         fault_plan=None) -> tuple[float, bool]:
    """``(top1_agreement_fraction, ok)`` for the quantized forward vs
    the fp32 reference on the seeded corpus. ``ok`` is the activation
    gate: ``agreement >= min_agreement`` (99.5% by default — the bar
    docs/serving.md publishes). ``fault_plan`` is the chaos seam (site
    ``fastpath.agree``): a fired fault raises, and the caller — startup
    or the rollout gate — must REFUSE the quantized path, never fall
    through to serving it unverified."""
    if fault_plan is not None:
        fault_plan.check("fastpath.agree", RuntimeError)
    corpus = agreement_corpus(node_feat, node_counts, samples, seed)
    agree = 0
    for obs in corpus:
        a_q, _ = int8_backend.decide_nodes(obs)
        a_f, ref_logits = ref_backend.decide_nodes(obs)
        # An EXACT fp32 tie (the quantized argmax scores bit-identical
        # to the reference argmax) is agreement: either choice is the
        # same decision by the reference's own scoring, and argmax
        # tie-breaking order is not a quantization error.
        if a_q == a_f or ref_logits[a_q] == ref_logits[a_f]:
            agree += 1
    fraction = agree / len(corpus)
    return fraction, fraction >= min_agreement
