"""graftroll part 2: zero-downtime policy rollout for the serving pool.

The graftserve pool (scheduler/pool.py) can restart a DEAD worker, but
the only way to serve a NEW checkpoint was to kill the whole pool. This
module is the promotion path ROADMAP item 1(d) asks for: a
generation-tracked rolling restart, canary-gated, with automatic
rollback — the pool serves continuously while a checkpoint lands.

``POST /promote {"checkpoint": <run_dir>}`` on the pool control plane:

1. **Verify before touching anything.** The candidate is checked against
   graftguard's integrity manifests (the same digests
   ``utils/checkpoint.CheckpointManager.latest_verified_step`` trusts,
   re-implemented here over plain hashlib/json so the supervisor stays
   jax/orbax-free). A corrupt or unfinalized newest step REFUSES the
   promote — a bad checkpoint is never partially rolled.
2. **Single writer.** A second promote during an in-flight rollout is
   refused (409) — non-blocking acquisition plus, when ``lock_dir`` is
   set, the same ``O_CREAT|O_EXCL`` pidfile discipline as graftstudy's
   runner lock (stale locks from dead pids are cleared).
3. **Canary first.** One worker is respawned onto the new generation,
   gated on joining the control plane alive plus ``probe_count`` warm-up
   decision probes (a probe that fails open is a gate failure), then
   held for ``canary_hold_s`` of live traffic while its latency-EWMA
   (histogram mean over the hold window) and breaker/fail-open deltas
   are compared against the incumbent workers.
4. **Roll or roll back.** Surviving the canary gate promotes the rest
   worker-by-worker (same spawn/health gates). ANY gate failure —
   spawn error, death, failed probe, tripped breaker, latency blow-up —
   rolls every already-promoted worker back onto the incumbent
   generation and increments ``rollbacks_total``. The pool's generation
   only advances after the LAST worker promotes, so a rollback restores
   the incumbent by construction.

Chaos seams (utils/faults.py): ``rollout.spawn`` fires as a respawn
failure, ``rollout.health`` as a health-gate failure — both must take
the rollback path, and the chaos suite asserts they fired.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import logging
import os
import threading
import time
from pathlib import Path

logger = logging.getLogger(__name__)

# Mirrors utils/checkpoint.py — duplicated as strings (not imported) so
# the supervisor process never pulls orbax/jax just to verify digests.
MANIFEST_DIR = "checkpoint_manifests"
ROLLOUT_LOCK_NAME = "rollout.lock"

IDLE = "idle"
PROMOTING = "promoting"
ROLLING_BACK = "rolling_back"
STATE_CODES = {IDLE: 0, PROMOTING: 1, ROLLING_BACK: 2}


@dataclasses.dataclass(frozen=True)
class WorkerSpec:
    """What one pool worker serves: the policy generation (monotonic,
    bumped per successful promote) and the checkpoint run dir (``None``
    = the factory's configured default). Slots carry their spec so the
    supervisor's crash-restart path respawns a worker onto ITS
    generation, mid-rollout included."""

    generation: int = 0
    checkpoint: str | None = None


def _digest_file(path: Path) -> tuple[str, int]:
    h = hashlib.sha256()
    with path.open("rb") as fh:
        for chunk in iter(lambda: fh.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest(), path.stat().st_size


def verify_candidate(run_dir: str | Path) -> tuple[int | None, str]:
    """``(verified_step, reason)`` for a promotion candidate; ``None``
    step means REFUSE.

    The newest checkpoint step must pass graftguard's manifest digests
    (sha256 + size per file — the identical check
    ``CheckpointManager.verify_step`` performs, minus the orbax
    dependency). Unlike restore-time auto-selection this does NOT fall
    back to an older step: the operator promoted THIS checkpoint, and
    silently rolling out something older would lie. A manifest-less
    newest step in a run that HAS manifests is an unfinalized save —
    refused; a fully legacy run (no manifest dir at all) is accepted
    with a logged warning, mirroring restore's legacy acceptance.
    """
    run_dir = Path(run_dir)
    steps = sorted(
        (int(d.name) for d in (run_dir / "checkpoints").glob("*")
         if d.is_dir() and d.name.isdigit()),
        reverse=True,
    ) if (run_dir / "checkpoints").is_dir() else []
    if not steps:
        return None, f"no checkpoint steps under {run_dir}"
    step = steps[0]
    mpath = run_dir / MANIFEST_DIR / f"{step}.json"
    if not mpath.exists():
        if (run_dir / MANIFEST_DIR).is_dir():
            return None, (f"newest step {step} has no integrity manifest "
                          "(unfinalized save?) — refusing to roll it out")
        logger.warning("promotion candidate %s has no integrity manifests "
                       "(pre-graftguard run); promoting unverified", run_dir)
        return step, "legacy"
    try:
        manifest = json.loads(mpath.read_text())
    except (OSError, json.JSONDecodeError) as e:
        return None, f"unreadable manifest for step {step}: {e}"
    step_dir = run_dir / "checkpoints" / str(step)
    want = manifest.get("files", {})
    for rel, meta in sorted(want.items()):
        path = step_dir / rel
        if not path.is_file():
            return None, f"step {step}: missing file {rel}"
        sha, size = _digest_file(path)
        if size != meta["size"]:
            return None, (f"step {step}: {rel} size {size} != manifest "
                          f"{meta['size']} (truncated write)")
        if sha != meta["sha256"]:
            return None, f"step {step}: {rel} sha256 mismatch (corrupt write)"
    return step, "verified"


class RolloutController:
    """Promotion/rollout controller for one :class:`ServingPool`
    (module doc). All mutation of pool slots happens on the controller's
    background thread under the single-writer lock; the monitor skips
    slots the controller holds (``slot.hold``), so deliberate
    replacements are never raced by crash-restarts."""

    def __init__(self, pool, fault_plan=None, canary_hold_s: float = 2.0,
                 probe_count: int = 3, probe_timeout_s: float = 10.0,
                 ready_timeout_s: float = 30.0,
                 max_latency_ratio: float = 4.0,
                 min_compare_requests: int = 20,
                 lock_dir: str | Path | None = None,
                 slo=None):
        self._pool = pool
        self.fault_plan = fault_plan
        self.canary_hold_s = canary_hold_s
        self.probe_count = probe_count
        self.probe_timeout_s = probe_timeout_s
        self.ready_timeout_s = ready_timeout_s
        self.max_latency_ratio = max_latency_ratio
        self.min_compare_requests = min_compare_requests
        self.lock_dir = Path(lock_dir) if lock_dir is not None else None
        # graftlens (scheduler/slo.py): with an SloConfig carrying a
        # latency objective, the canary gate additionally judges the
        # hold window's over-threshold fraction against the error
        # budget — a principled bound (the SLO the pool is actually
        # held to) next to the relative latency-ratio heuristic.
        self.slo = slo
        self._busy = threading.Lock()   # the single writer
        self._state_lock = threading.Lock()
        self.state = IDLE
        self.phase = IDLE
        self.candidate: str | None = None
        self.target_generation: int | None = None
        self.last_error: str | None = None
        self.promotions_total = 0
        self.rollbacks_total = 0
        self.refusals_total = 0
        self.conflicts_total = 0
        # Warm-up probes that actually ran a decision in a worker: every
        # one appends a trace record, so (client requests + probes_total)
        # is the exact record count the drill's replay check expects.
        self.probes_total = 0

    # ------------------------------------------------------------ queries

    @property
    def active(self) -> bool:
        return self.state != IDLE

    def counters(self) -> dict:
        """Lifetime rollout counters for /stats, /metrics and /healthz.
        MONOTONIC: ``/stats/reset`` must never clear these (pinned by
        test, mirroring the histogram rule)."""
        with self._state_lock:
            return {
                "state": self.state,
                "active": self.state != IDLE,
                "promotions_total": self.promotions_total,
                "rollbacks_total": self.rollbacks_total,
                "refusals_total": self.refusals_total,
                "conflicts_total": self.conflicts_total,
                "probes_total": self.probes_total,
            }

    def status(self) -> dict:
        """The ``GET /rollout`` body: state machine position plus the
        per-worker generation map the drill reads."""
        out = self.counters()
        with self._state_lock:
            out.update({
                "phase": self.phase,
                "candidate": self.candidate,
                "target_generation": self.target_generation,
                "last_error": self.last_error,
            })
        out["generation"] = self._pool.generation
        out["checkpoint"] = self._pool.checkpoint
        out["workers"] = [
            {"worker_id": slot.worker_id,
             "generation": slot.spec.generation,
             "alive": slot.alive}
            for slot in self._pool._slots
        ]
        return out

    # ------------------------------------------------------------ promote

    def request_promote(self, checkpoint) -> tuple[int, dict]:
        """Validate + verify a candidate and launch the rollout thread.
        Returns ``(http_status, body)``: 202 accepted (poll
        ``GET /rollout``), 409 a rollout is in flight, 422 refused."""
        if not checkpoint or not isinstance(checkpoint, str):
            return 400, {"error": "pass {\"checkpoint\": \"<run_dir>\"}"}
        run_dir = Path(checkpoint)
        if not self._busy.acquire(blocking=False):
            with self._state_lock:
                self.conflicts_total += 1
            return 409, {"error": "a rollout is already in flight "
                                  "(single-writer; retry after it lands)",
                         "state": self.state}
        lock_file = None
        try:
            lock_file = self._acquire_lock_file()
        except RuntimeError as e:
            with self._state_lock:
                self.conflicts_total += 1
            self._busy.release()
            return 409, {"error": str(e)}
        step, reason = (None, f"checkpoint dir {run_dir} does not exist") \
            if not run_dir.is_dir() else verify_candidate(run_dir)
        if step is None:
            with self._state_lock:
                self.refusals_total += 1
                self.last_error = f"promote refused: {reason}"
            self._release_lock_file(lock_file)
            self._busy.release()
            logger.error("promote of %s refused: %s", checkpoint, reason)
            return 422, {"error": f"promote refused: {reason}"}
        target = self._pool.generation + 1
        with self._state_lock:
            self.state = PROMOTING
            self.phase = "verify"
            self.candidate = str(run_dir)
            self.target_generation = target
            self.last_error = None
        threading.Thread(
            target=self._run_promote, args=(run_dir, target, lock_file),
            daemon=True, name="graftroll-promote",
        ).start()
        return 202, {"status": "promoting", "target_generation": target,
                     "verified_step": step, "verification": reason}

    def _acquire_lock_file(self) -> Path | None:
        """graftstudy's runner-lock discipline, when a ``lock_dir`` is
        configured: exclusive-create a pidfile, clearing stale locks
        from dead pids (the shared ``utils/pidlock.py`` implementation);
        a live holder refuses the promote."""
        if self.lock_dir is None:
            return None
        from rl_scheduler_tpu.utils.pidlock import acquire_pidfile_lock

        self.lock_dir.mkdir(parents=True, exist_ok=True)
        return acquire_pidfile_lock(
            self.lock_dir / ROLLOUT_LOCK_NAME,
            "a rollout is already in flight (pid {pid} holds {lock}); "
            "a second writer would interleave worker restarts")

    @staticmethod
    def _release_lock_file(lock_file: Path | None) -> None:
        if lock_file is not None:
            lock_file.unlink(missing_ok=True)

    # ------------------------------------------------------ rollout thread

    def _run_promote(self, run_dir: Path, target: int,
                     lock_file: Path | None) -> None:
        pool = self._pool
        incumbent = WorkerSpec(pool.generation, pool.checkpoint)
        new_spec = WorkerSpec(target, str(run_dir))
        promoted: list = []
        in_flight = None
        try:
            for slot in pool._slots:
                if slot.failed:
                    continue  # a slot the supervisor gave up on stays down
                is_canary = not promoted
                in_flight = slot
                slot.hold = True
                try:
                    ok, why = self._replace(slot, new_spec)
                    if ok and is_canary:
                        ok, why = self._canary_gate(slot)
                finally:
                    # The hold MUST clear even if a gate crashes: a
                    # leaked hold makes the monitor skip this slot
                    # forever (a later worker death would never restart).
                    slot.hold = False
                if not ok:
                    self._rollback(promoted + [slot], incumbent,
                                   f"worker {slot.worker_id}: {why}")
                    return
                promoted.append(slot)
                in_flight = None
            # Generation advances only now: every worker serves the new
            # checkpoint, so a crash-restart respawns onto it too.
            pool.generation = target
            pool.checkpoint = new_spec.checkpoint
            with self._state_lock:
                self.promotions_total += 1
                self.state = IDLE
                self.phase = IDLE
            logger.info("promoted pool to generation %d (%s)", target,
                        run_dir)
        except Exception as e:  # noqa: BLE001 — a rollout crash must
            # still try to restore the incumbent, never leave a mixed
            # pool: the in-flight slot may already serve the candidate
            # generation, so it rolls back with the promoted ones.
            logger.exception("rollout to generation %d crashed", target)
            touched = promoted + ([in_flight] if in_flight is not None
                                  else [])
            self._rollback(touched, incumbent, f"rollout crashed: {e}")
        finally:
            with self._state_lock:
                self.candidate = None
                self.target_generation = None
            self._release_lock_file(lock_file)
            self._busy.release()

    def _replace(self, slot, spec: WorkerSpec,
                 gate: bool = True) -> tuple[bool, str]:
        """Terminate one worker and respawn it onto ``spec``; with
        ``gate`` (every promote-path replace) the new worker must join
        the control plane and answer warm-up decision probes. The caller
        holds ``slot.hold``."""
        pool = self._pool
        if pool._shutdown.is_set():
            # The supervisor is tearing the pool down: spawning now
            # would fork orphan workers onto a closed control plane.
            return False, "pool is shutting down"
        with self._state_lock:
            self.phase = f"replace:{slot.worker_id}"
        proc = slot.process
        if proc is not None and proc.is_alive():
            proc.terminate()
            proc.join(timeout=10.0)
            if proc.is_alive():
                proc.kill()
                proc.join(timeout=10.0)
        with slot.conn_lock:
            if slot.conn is not None:
                slot.conn.close()
                slot.conn = None
        if self.fault_plan is not None:
            try:
                self.fault_plan.check("rollout.spawn", RuntimeError)
            except RuntimeError as e:
                slot.spec = spec  # the slot is down either way; record
                # what it WOULD have served so rollback restores it
                return False, f"spawn failed: {e}"
        slot.spec = spec
        try:
            pool._spawn(slot)
        except Exception as e:  # noqa: BLE001 — fork/exec can fail for
            # host reasons (fd limits); a failed spawn is a gate failure
            logger.exception("rollout spawn of worker %d failed",
                             slot.worker_id)
            return False, f"spawn failed: {e}"
        deadline = time.monotonic() + self.ready_timeout_s
        while time.monotonic() < deadline:
            if not slot.alive:
                return False, (f"worker died during spawn (exitcode "
                               f"{slot.process.exitcode})")
            with slot.conn_lock:
                joined = slot.conn is not None
            if joined:
                break
            time.sleep(0.02)
        else:
            return False, (f"worker not on the control plane after "
                           f"{self.ready_timeout_s:.0f}s")
        if not gate:
            return True, ""
        with self._state_lock:
            self.phase = f"gate:{slot.worker_id}"
        if self.fault_plan is not None:
            try:
                self.fault_plan.check("rollout.health", RuntimeError)
            except RuntimeError as e:
                return False, f"health gate failed: {e}"
        for k in range(self.probe_count):
            ack = pool._command(slot, "probe", self.probe_timeout_s)
            if ack is None or not ack.get("ok"):
                return False, f"warm-up probe {k + 1} got no answer"
            with self._state_lock:
                self.probes_total += 1
            if not ack.get("decided"):
                return False, (f"warm-up probe {k + 1} failed open — the "
                               "new checkpoint is not deciding")
        # graftfwd gate: flush the respawned worker's score cache and
        # re-run the int8 agreement check on the candidate checkpoint
        # BEFORE it takes traffic — a stale-generation cache hit after
        # a rollout is a correctness bug, and a candidate that
        # quantizes badly must refuse the promote, not silently serve
        # (fp32 or otherwise). ``fastpath.agree`` is the chaos seam.
        if self.fault_plan is not None:
            try:
                self.fault_plan.check("fastpath.agree", RuntimeError)
            except RuntimeError as e:
                return False, f"fastpath agreement check failed: {e}"
        # Longer timeout than a probe: the verify re-runs the full
        # seeded-corpus agreement check AT THE SERVING NODE COUNTS —
        # fleet-N int8+fp32 forwards take seconds, not probe-milliseconds.
        ack = pool._command(slot, "fastpath",
                            max(self.probe_timeout_s, 30.0))
        if ack is None:
            return False, "fastpath verify got no answer"
        if "error" in ack and "ok" not in ack:
            # Pre-graftfwd worker build ("unknown cmd"): nothing to
            # verify — the gate only binds where the levers exist.
            pass
        elif not ack.get("ok"):
            why = ack.get("error") or "int8 agreement below the gate"
            return False, f"fastpath verify failed: {why}"
        return True, ""

    def _canary_gate(self, slot) -> tuple[bool, str]:
        """Hold the canary under live traffic and compare it against the
        incumbents: it must stay alive, trip no breakers, add no
        fail-opens, and (when both sides served enough requests to
        compare) keep its mean decision latency within
        ``max_latency_ratio`` of the incumbent pool's over the window."""
        pool = self._pool
        with self._state_lock:
            self.phase = "canary_hold"
        start = pool._command(slot, "snapshot", self.probe_timeout_s)
        others = [s for s in pool._slots if s is not slot and s.alive]
        inc_start = [snap for s in others
                     if (snap := pool._command(s, "snapshot",
                                               self.probe_timeout_s))]
        deadline = time.monotonic() + self.canary_hold_s
        while time.monotonic() < deadline:
            if not slot.alive:
                return False, "canary died during the hold"
            time.sleep(min(0.05, max(deadline - time.monotonic(), 0.0)))
        if not slot.alive:
            return False, "canary died during the hold"
        end = pool._command(slot, "snapshot", self.probe_timeout_s)
        if start is None or end is None:
            return False, "canary stopped answering snapshots"
        opens = (_breaker_opens(end) - _breaker_opens(start))
        if opens > 0:
            return False, f"canary tripped {opens} breaker open(s)"
        fails = (_fail_opens(end) - _fail_opens(start))
        if fails > 0:
            return False, f"canary failed open {fails} time(s)"
        inc_end = [snap for s in others
                   if (snap := pool._command(s, "snapshot",
                                             self.probe_timeout_s))]
        c_mean, c_count = _window_mean(start, end)
        i_mean, i_count = _pool_window_mean(inc_start, inc_end)
        if (c_count >= self.min_compare_requests
                and i_count >= self.min_compare_requests
                and i_mean > 0.0 and c_mean > self.max_latency_ratio * i_mean):
            return False, (f"canary latency regressed: {c_mean * 1e3:.2f} ms "
                           f"mean vs incumbent {i_mean * 1e3:.2f} ms over "
                           "the hold window")
        if self.slo is not None and self.slo.p99_ms is not None:
            ok, why = self._slo_gate(start, end, inc_start, inc_end)
            if not ok:
                return False, why
        return True, ""

    def _slo_gate(self, start: dict, end: dict, inc_start: list,
                  inc_end: list) -> tuple[bool, str]:
        """graftlens SLO canary gate: over the hold window the canary's
        fraction of decisions above the SLO latency threshold must not
        exceed the fast-burn budget (``budget * fast_burn`` — the rate a
        page fires at) WHILE the incumbents keep theirs under it — a
        pool-wide slowdown (hot telemetry source, noisy neighbor) is not
        the canary's fault and must not block every promote. Exact
        monotone-counter deltas of the lifetime histogram, bucket-
        granular via ``slo.histogram_bad_fraction``."""
        from rl_scheduler_tpu.scheduler.extender import LatencyStats
        from rl_scheduler_tpu.scheduler.slo import (
            LATENCY_TARGET,
            histogram_bad_fraction,
        )

        threshold_ms = self.slo.p99_ms
        budget = 1.0 - LATENCY_TARGET
        limit = budget * self.slo.fast_burn
        c_frac, c_count = histogram_bad_fraction(
            start["histogram"], end["histogram"], threshold_ms,
            LatencyStats.BUCKETS)
        by_id = {s["worker_id"]: s for s in inc_start}
        i_bad = i_count = 0
        for inc in inc_end:
            s = by_id.get(inc["worker_id"])
            if s is None:
                continue
            frac, count = histogram_bad_fraction(
                s["histogram"], inc["histogram"], threshold_ms,
                LatencyStats.BUCKETS)
            i_bad += frac * count
            i_count += count
        i_frac = i_bad / i_count if i_count else 0.0
        if (c_count >= self.min_compare_requests
                and i_count >= self.min_compare_requests
                and c_frac > limit and i_frac <= limit):
            return False, (
                f"canary burns the SLO: {c_frac * 100:.1f}% of hold-window "
                f"decisions over {threshold_ms:g} ms (budget x fast-burn "
                f"allows {limit * 100:.1f}%; incumbents at "
                f"{i_frac * 100:.1f}%)")
        return True, ""

    def _rollback(self, slots: list, incumbent: WorkerSpec,
                  why: str) -> None:
        """Respawn every touched worker onto the incumbent spec. Gates
        are skipped (the incumbent already proved itself); a respawn
        failure here releases the slot to the supervisor's monitor,
        which retries on its backoff with the incumbent spec."""
        with self._state_lock:
            self.state = ROLLING_BACK
            self.phase = "rollback"
            self.last_error = why
        logger.error("rolling back: %s", why)
        for slot in slots:
            if self._pool._shutdown.is_set():
                logger.warning("pool shutdown during rollback; leaving "
                               "worker %d down", slot.worker_id)
                continue
            slot.hold = True
            ok, detail = self._replace(slot, incumbent, gate=False)
            slot.hold = False
            if not ok:
                logger.error(
                    "rollback respawn of worker %d failed (%s); the "
                    "supervisor's restart schedule takes over",
                    slot.worker_id, detail)
        with self._state_lock:
            self.rollbacks_total += 1
            self.state = IDLE
            self.phase = IDLE
        logger.warning("rollback complete; pool stays on generation %d",
                       self._pool.generation)


def _breaker_opens(snapshot: dict) -> int:
    return sum(b.get("opens_total", 0)
               for b in snapshot["stats"].get("breakers", {}).values())


def _fail_opens(snapshot: dict) -> int:
    return int(snapshot["stats"].get("fail_open_total", 0))


def _window_mean(start: dict, end: dict) -> tuple[float, int]:
    """Mean decision latency (seconds) and request count over the window
    between two snapshots of ONE worker, from the lifetime histogram
    deltas (exact — sums and counts are monotone counters)."""
    d_sum = end["histogram"]["sum"] - start["histogram"]["sum"]
    d_count = end["histogram"]["count"] - start["histogram"]["count"]
    return (d_sum / d_count if d_count > 0 else 0.0), max(d_count, 0)


def _pool_window_mean(starts: list, ends: list) -> tuple[float, int]:
    """The incumbents' request-weighted window mean: per-worker deltas
    joined on worker_id (a worker that answered only one side of the
    window contributes nothing — no torn deltas)."""
    by_id = {s["worker_id"]: s for s in starts}
    total_sum = 0.0
    total_count = 0
    for end in ends:
        start = by_id.get(end["worker_id"])
        if start is None:
            continue
        total_sum += end["histogram"]["sum"] - start["histogram"]["sum"]
        total_count += end["histogram"]["count"] - start["histogram"]["count"]
    return (total_sum / total_count if total_count > 0 else 0.0), \
        max(total_count, 0)
