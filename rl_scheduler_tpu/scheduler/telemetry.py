"""Host-side telemetry for serving-time observations.

The env's observation is ``[cost_aws, cost_azure, lat_aws, lat_azure,
cpu_aws, cpu_azure]``. At serving time the cost/latency half comes from the
normalized pricing table (replayed just like training data), and the CPU
half from a pluggable source:

- ``RandomCpu``: uniform(0.1, 0.8) — exact parity with the reference's
  ``_get_live_cpu`` placeholder (``k8s_multi_cloud_env.py:84-88``).
- ``PrometheusCpu``: actually queries Prometheus for cluster CPU, which the
  reference only stubbed (URLs at ``k8s_multi_cloud_env.py:32-33``, never
  used). Falls back to ``RandomCpu`` per-request on any error.

All of this is ordinary impure Python that stays outside jit; the policy
backend only ever sees a finished numpy observation.
"""

from __future__ import annotations

import logging
import random
import threading
import time

import numpy as np

logger = logging.getLogger(__name__)

PROMETHEUS_URLS = {  # reference parity defaults (k8s_multi_cloud_env.py:32-33)
    "aws": "http://localhost:39090",
    "azure": "http://localhost:39091",
}


class RandomCpu:
    def __init__(self, low: float = 0.1, high: float = 0.8, seed: int | None = None):
        self.low, self.high = low, high
        self._rng = random.Random(seed)

    def sample(self) -> tuple[float, float]:
        return (
            self._rng.uniform(self.low, self.high),
            self._rng.uniform(self.low, self.high),
        )


class PrometheusCpu:
    """Real cluster CPU via the Prometheus HTTP API (instant query).

    Query: 1 - average idle fraction over all nodes of the cluster.

    Serving-latency contract: ``sample()`` NEVER blocks on HTTP — it
    returns the cached reading and, when that reading is older than
    ``ttl_s``, kicks one background refresh thread. Until the first
    refresh lands (or when Prometheus is down) it serves the random
    fallback, so the extender's <1 ms p50 holds regardless of Prometheus
    health.

    graftguard (docs/robustness.md): scrapes run under the unified
    ``utils/retry.py`` policy — one bounded retry with backoff inside
    each refresh, behind one circuit breaker PER endpoint (a dead aws
    Prometheus must not have its failure streak reset by a healthy
    azure, nor an open aws breaker refuse azure scrapes), so a dead
    endpoint is probed at the breaker's recovery cadence instead of
    every ttl expiry. Breaker state rides the extender's
    ``/stats``/``/metrics`` (``breakers["prometheus_aws"]``/
    ``["prometheus_azure"]``). ``fault_plan`` is the chaos seam (site
    ``telemetry.scrape``).
    """

    QUERY = '1 - avg(rate(node_cpu_seconds_total{mode="idle"}[1m]))'

    def __init__(self, urls: dict | None = None, timeout_s: float = 0.2,
                 ttl_s: float = 1.0, retry=None, breakers=None,
                 fault_plan=None):
        from rl_scheduler_tpu.utils.retry import CircuitBreaker, RetryPolicy

        self.urls = dict(urls or PROMETHEUS_URLS)
        self.timeout_s = timeout_s
        self.ttl_s = ttl_s
        self.fault_plan = fault_plan
        # Deadline caps the retried scrape well under a ttl so a slow
        # Prometheus cannot make refreshes pile up.
        self.retry = retry if retry is not None else RetryPolicy(
            max_attempts=2, base_delay_s=0.05, max_delay_s=0.2,
            deadline_s=max(2 * timeout_s, 0.5), seed=0,
        )
        self.breakers = {
            cloud: CircuitBreaker(name=f"prometheus_{cloud}",
                                  failure_threshold=3, reset_timeout_s=15.0)
            for cloud in ("aws", "azure")
        }
        self.breakers.update(breakers or {})
        self._fallback = RandomCpu()
        self._cached: tuple[float, float] | None = None
        self._cached_at = 0.0
        self._refreshing = False
        self._lock = threading.Lock()

    def _query_one(self, base_url: str) -> float:
        import json
        import urllib.parse
        import urllib.request

        if self.fault_plan is not None:
            # Simulated scrape timeout — the exact exception family a
            # stalled socket raises through urlopen.
            self.fault_plan.check("telemetry.scrape", TimeoutError)
        url = (
            f"{base_url}/api/v1/query?"
            + urllib.parse.urlencode({"query": self.QUERY})
        )
        with urllib.request.urlopen(url, timeout=self.timeout_s) as resp:
            payload = json.load(resp)
        return float(payload["data"]["result"][0]["value"][1])

    def _refresh(self) -> None:
        try:
            out = []
            for cloud in ("aws", "azure"):
                breaker = self.breakers[cloud]
                if not breaker.allow():
                    # Open breaker: skip the HTTP attempt entirely and
                    # serve the fallback until a half-open probe heals it.
                    out.append(self._fallback.sample()[0])
                    continue
                try:
                    out.append(self.retry.call(self._query_one,
                                               self.urls[cloud]))
                    breaker.record_success()
                except Exception:
                    breaker.record_failure()
                    logger.warning(
                        "prometheus query failed for %s (breaker %s); "
                        "using random fallback", cloud, breaker.state)
                    out.append(self._fallback.sample()[0])
            with self._lock:
                self._cached = tuple(out)
                self._cached_at = time.monotonic()
        finally:
            # Never latch _refreshing=True: that would permanently disable
            # refreshes and freeze telemetry on the last (or fallback) value.
            with self._lock:
                self._refreshing = False

    def sample(self) -> tuple[float, float]:
        with self._lock:
            cached = self._cached
            stale = time.monotonic() - self._cached_at > self.ttl_s
            kick = stale and not self._refreshing
            if kick:
                self._refreshing = True
        if kick:
            try:
                threading.Thread(target=self._refresh, daemon=True).start()
            except RuntimeError:  # thread exhaustion: retry on a later sample
                with self._lock:
                    self._refreshing = False
        return cached if cached is not None else self._fallback.sample()


class TableTelemetry:
    """Builds full observations by replaying the normalized table.

    A monotonically increasing decision counter indexes the table (mod its
    length) — the serving-side analogue of the env's ``step_idx``.
    Thread-safe: the extender server handles requests concurrently.

    ``counter`` (graftserve, ``scheduler/pool.SharedCounter``) replaces
    the process-local step with a cross-process position, so every worker
    of one pool replays the single-process table trajectory — the same
    seam ``RawPriceReplay`` has for the graph family's raw prices.
    """

    def __init__(self, costs: np.ndarray, latencies: np.ndarray,
                 cpu_source=None, counter=None):
        self.costs = np.asarray(costs, np.float32)
        self.latencies = np.asarray(latencies, np.float32)
        self.cpu = cpu_source or RandomCpu()
        self.swaps_total = 0
        self._counter = counter
        self._step = 0
        self._lock = threading.Lock()
        # Per-thread record of the raw position the LAST observation on
        # that thread consumed (last_replay_position): the trace log's
        # provenance field must name the row a decision actually
        # observed, which a shared "current position" cannot do under
        # concurrent serving.
        self._local = threading.local()

    @classmethod
    def from_table(cls, data_path: str | None = None, cpu_source=None,
                   counter=None):
        from rl_scheduler_tpu.data.loader import load_table

        table = load_table(data_path)
        return cls(np.asarray(table.costs), np.asarray(table.latencies),
                   cpu_source, counter=counter)

    def swap_table(self, costs: np.ndarray, latencies: np.ndarray) -> None:
        """Replace the replayed table in place — the regime-flip seam
        (graftdrift's drill and ``extender_bench --flip-tables`` drive it
        through the pool's ``/telemetry/flip``). Validates the same
        contract ``data/loader.load_table`` enforces, then swaps both
        arrays under the lock so a concurrent observation reads a
        coherent pair (``_table``). The replay counter keeps running —
        a flip is a regime change, not a rewind."""
        costs = np.asarray(costs, np.float32)
        latencies = np.asarray(latencies, np.float32)
        if costs.shape != latencies.shape or costs.ndim != 2 \
                or costs.shape[1] != len(self.costs[0]) or len(costs) < 2:
            raise ValueError(
                f"swap_table: costs {costs.shape} / latencies "
                f"{latencies.shape}: need matching [T>=2, "
                f"{len(self.costs[0])}] arrays (loader.load_table shape)")
        for name, arr in (("costs", costs), ("latencies", latencies)):
            if not np.isfinite(arr).all() or arr.min() < 0 or arr.max() > 1:
                raise ValueError(f"swap_table: {name} must be normalized "
                                 "to [0, 1] and finite (loader contract)")
        with self._lock:
            self.costs = costs
            self.latencies = latencies
            self.swaps_total += 1

    def _table(self) -> tuple:
        """Coherent (costs, latencies) pair — never half of two tables."""
        with self._lock:
            return self.costs, self.latencies

    def _next_idx(self, length: int) -> int:
        if self._counter is not None:
            raw = self._counter.next_index()
        else:
            with self._lock:
                raw = self._step
                self._step += 1
        self._local.raw = raw
        return raw % length

    def note_replay_position(self, raw: int) -> None:
        """Overwrite THIS thread's last-observed replay position
        (graftfwd score cache): a cache hit serves a score computed from
        an EARLIER observation, so the trace record's provenance field
        must name the row that score actually consumed — not whatever
        this thread last replayed for some other request."""
        self._local.raw = raw

    def last_replay_position(self) -> int | None:
        """The RAW monotonic position (no ``% len``) consumed by THIS
        thread's most recent observation — the trace log's
        telemetry-epoch provenance field (scheduler/tracelog.py).
        Thread-local on purpose: under concurrent serving a shared
        "current position" names whatever row some OTHER request just
        consumed, but a replayed decision must join back to the exact
        row it observed. ``None`` before the thread's first
        observation."""
        return getattr(self._local, "raw", None)

    def observe(self) -> np.ndarray:
        costs, lats = self._table()
        idx = self._next_idx(len(costs))
        cpu_aws, cpu_azure = self.cpu.sample()
        return np.concatenate(
            [costs[idx], lats[idx], [cpu_aws, cpu_azure]]
        ).astype(np.float32)

    def observe_nodes(self, clouds: list, pod_cpu: float) -> np.ndarray:
        """Per-node observation for the set policy: ``[N, NODE_FEAT]``.

        ``clouds`` is one ``"aws"``/``"azure"``/``None`` entry per candidate
        node (from labels/name tokens). Feature columns match training
        (``env/cluster_set.py``): cost, latency, cpu_used, cloud_id,
        pod_cpu, step_frac. Cost/latency/CPU come from the node's cloud
        (cloud-level telemetry is the per-node utilization proxy — real
        per-node meters slot in here); unknown-cloud nodes get the
        cross-cloud mean and ``cloud_id = 0.5``, so they score from neutral
        features instead of being special-cased out of the decision.
        """
        table_costs, table_lats = self._table()
        idx = self._next_idx(len(table_costs))
        costs, lats = table_costs[idx], table_lats[idx]
        cpus = np.asarray(self.cpu.sample(), np.float32)
        step_frac = idx / max(len(table_costs) - 1, 1)
        cloud_idx = np.fromiter(
            ({"aws": 0, "azure": 1}.get(c, -1) for c in clouds),
            np.int64, count=len(clouds),
        )
        known = cloud_idx >= 0
        safe = np.where(known, cloud_idx, 0)
        n = len(clouds)
        rows = np.empty((n, 6), np.float32)
        rows[:, 0] = np.where(known, costs[safe], costs.mean())
        rows[:, 1] = np.where(known, lats[safe], lats.mean())
        rows[:, 2] = np.where(known, cpus[safe], cpus.mean())
        rows[:, 3] = np.where(known, cloud_idx, 0.5)
        rows[:, 4] = pod_cpu
        rows[:, 5] = step_frac
        return rows

    def observe_nodes_het(self, clouds: list, pod_reqs,
                          num_resources: int) -> np.ndarray:
        """Widened per-node observation for heterogeneous-scenario
        checkpoints: ``[N, 4 + 3R]`` matching the training layout
        (``scenarios/het_env.py``): cost, lat, used_0..R-1, cap_0..R-1,
        cloud_id, req_0..R-1, step_frac.

        Serving proxies, documented like the classic path's: utilization
        of EVERY resource is the node's cloud cpu telemetry (the one live
        meter — per-resource node meters slot in here), capacities are
        1.0 (unknown at serve time; a real inventory source slots in),
        and ``pod_reqs`` is the ``[R]`` request vector parsed from the
        pod manifest (``extender.pod_resource_fractions``).
        """
        base = self.observe_nodes(clouds, 0.0)     # shared cost/lat/cpu/cloud
        n, r = len(clouds), int(num_resources)
        reqs = np.zeros(r, np.float32)
        reqs[: len(pod_reqs)] = np.asarray(pod_reqs, np.float32)[:r]
        rows = np.empty((n, 4 + 3 * r), np.float32)
        rows[:, 0] = base[:, 0]                     # cost
        rows[:, 1] = base[:, 1]                     # latency
        rows[:, 2:2 + r] = base[:, 2:3]             # used_r (cpu proxy)
        rows[:, 2 + r:2 + 2 * r] = 1.0              # cap_r (neutral)
        rows[:, 2 + 2 * r] = base[:, 3]             # cloud_id
        rows[:, 3 + 2 * r:3 + 3 * r] = reqs         # req_r
        rows[:, 3 + 3 * r] = base[:, 5]             # step_frac
        return rows
