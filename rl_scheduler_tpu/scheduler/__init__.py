"""Kubernetes scheduler integration: extender server, backends, cluster hooks."""
