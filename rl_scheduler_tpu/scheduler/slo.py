"""graftlens part 2: serving SLOs and multi-window burn-rate tracking.

The serving plane had latency *measurements* (the `/stats` percentiles,
the `/metrics` histograms) but no *objectives*: nothing said what good
looks like, so nothing could say "we are eating the error budget faster
than we can afford" — the signal an operator pages on and a rollout
canary gate should hold against. This module is the objective layer:

- :class:`SloConfig` declares up to two objectives: **latency** ("99% of
  decisions complete under ``p99_ms`` milliseconds") and **availability**
  ("at least ``availability`` of requests are answered by a real policy
  decision, not a fail-open passthrough"). Either alone is valid.
- :class:`SloTracker` records one outcome per served decision into a
  1-second-bucketed ring and computes **multi-window burn rates** (the
  SRE-workbook construction): ``burn = bad_fraction / error_budget`` over
  a fast and a slow window. An objective is *burning* when BOTH windows
  exceed their thresholds — the fast window gives detection latency, the
  slow window keeps a 2-second blip from paging — and the tracker is
  *degraded* when any objective burns. The defaults (60 s @ 14.4x /
  600 s @ 6x) are the classic page-worthy burn pair scaled to a serving
  process you watch live; every knob is a flag.
- Synthetic traffic never lands here: the extender's ``warmup_probe``
  decisions and graftdrift's shadow scores (``endpoint`` in
  ``tracelog.SYNTHETIC_ENDPOINTS``, one shared predicate —
  ``is_synthetic_endpoint``) are excluded at record time, so neither a
  rollout's own gate probes nor a shadow checkpoint can burn the budget
  they are judged against.
- :func:`merge_snapshots` sums per-worker window counts and recomputes
  burn rates pool-wide (counts are linear, rates are not), the same
  discipline as ``LatencyStats.merged_histogram``.
- :func:`histogram_bad_fraction` derives the over-threshold request
  fraction from two lifetime-histogram snapshots — the seam graftroll's
  canary gate uses to judge a canary's SLO burn over the hold window
  without a tracker on the supervisor side (bucket-granular: the
  threshold rounds up to the nearest histogram bound).

Surfaced on ``/stats`` (``slo`` section), ``/metrics``
(``*_slo_burn_rate{objective=,window=}``, ``*_slo_degraded``) and
``/healthz`` (status ``degraded`` while burning) — docs/observability.md.
"""

from __future__ import annotations

import bisect
import dataclasses
import threading
import time

LATENCY = "latency"
AVAILABILITY = "availability"
# The latency objective is named by its percentile: "p99 under X ms"
# means 99% of decisions under X, i.e. a 1% error budget.
LATENCY_TARGET = 0.99
WINDOWS = ("fast", "slow")


@dataclasses.dataclass(frozen=True)
class SloConfig:
    """Serving objectives (module doc). ``p99_ms`` arms the latency
    objective, ``availability`` the availability objective; at least one
    must be set. Windows/thresholds are the multi-window burn pair."""

    p99_ms: float | None = None
    availability: float | None = None
    fast_window_s: float = 60.0
    slow_window_s: float = 600.0
    fast_burn: float = 14.4
    slow_burn: float = 6.0

    def __post_init__(self):
        if self.p99_ms is None and self.availability is None:
            raise ValueError(
                "SloConfig: arm at least one objective (p99_ms for "
                "latency, availability for fail-open fraction)")
        if self.p99_ms is not None and self.p99_ms <= 0:
            raise ValueError(f"p99_ms={self.p99_ms}: pass a positive "
                             "millisecond threshold")
        if self.availability is not None and not 0.0 < self.availability < 1.0:
            raise ValueError(
                f"availability={self.availability}: pass a fraction in "
                "(0, 1), e.g. 0.999")
        if not 0 < self.fast_window_s < self.slow_window_s:
            raise ValueError(
                f"windows fast={self.fast_window_s}s slow="
                f"{self.slow_window_s}s: fast must be positive and "
                "shorter than slow")
        if self.fast_burn <= 0 or self.slow_burn <= 0:
            raise ValueError("burn thresholds must be positive")

    def objectives(self) -> dict:
        """``{objective_name: (target, budget)}`` for the armed set."""
        out = {}
        if self.p99_ms is not None:
            out[LATENCY] = (LATENCY_TARGET, 1.0 - LATENCY_TARGET)
        if self.availability is not None:
            out[AVAILABILITY] = (self.availability, 1.0 - self.availability)
        return out


class SloTracker:
    """Per-process SLO outcome recorder + burn-rate computer (module
    doc). Thread-safe: the extender's serving threads record, the
    control-plane thread snapshots. ``clock`` is injectable for tests
    (monotonic seconds)."""

    BUCKET_S = 1.0

    def __init__(self, config: SloConfig, clock=time.monotonic):
        self.config = config
        self._clock = clock
        self._lock = threading.Lock()
        n = int(config.slow_window_s / self.BUCKET_S) + 2
        self._n = n
        self._ids = [-1] * n           # bucket id occupying each slot
        self._total = [0] * n          # requests (decided + fail-open)
        self._lat_bad = [0] * n        # decided requests over threshold
        self._avail_bad = [0] * n      # fail-open requests
        # Lifetime counters (monotonic — /stats/reset never clears them,
        # same contract as the latency histograms).
        self.requests_total = 0
        self.latency_bad_total = 0
        self.fail_open_total = 0

    # ------------------------------------------------------------ recording

    def _slot(self, now: float) -> int:
        bucket_id = int(now / self.BUCKET_S)
        slot = bucket_id % self._n
        if self._ids[slot] != bucket_id:
            self._ids[slot] = bucket_id
            self._total[slot] = self._lat_bad[slot] = self._avail_bad[slot] = 0
        return slot

    def observe(self, seconds: float) -> None:
        """One decided request with its decision latency."""
        over = (self.config.p99_ms is not None
                and seconds * 1e3 > self.config.p99_ms)
        with self._lock:
            slot = self._slot(self._clock())
            self._total[slot] += 1
            self.requests_total += 1
            if over:
                self._lat_bad[slot] += 1
                self.latency_bad_total += 1

    def observe_failure(self) -> None:
        """One fail-open request (open breaker / backend raise): bad for
        availability; excluded from the latency objective's denominator
        (a passthrough's latency says nothing about the decide path)."""
        with self._lock:
            slot = self._slot(self._clock())
            self._total[slot] += 1
            self._avail_bad[slot] += 1
            self.requests_total += 1
            self.fail_open_total += 1

    # ------------------------------------------------------------ snapshots

    def _window_counts(self, now: float, window_s: float) -> tuple[int, int, int]:
        """``(total, latency_bad, avail_bad)`` over the trailing window.
        Caller holds the lock."""
        now_id = int(now / self.BUCKET_S)
        first = now_id - int(window_s / self.BUCKET_S) + 1
        total = lat_bad = avail_bad = 0
        for bucket_id in range(first, now_id + 1):
            slot = bucket_id % self._n
            if self._ids[slot] != bucket_id:
                continue
            total += self._total[slot]
            lat_bad += self._lat_bad[slot]
            avail_bad += self._avail_bad[slot]
        return total, lat_bad, avail_bad

    def snapshot(self) -> dict:
        cfg = self.config
        with self._lock:
            now = self._clock()
            windows = {
                "fast": (cfg.fast_window_s,
                         *self._window_counts(now, cfg.fast_window_s)),
                "slow": (cfg.slow_window_s,
                         *self._window_counts(now, cfg.slow_window_s)),
            }
            lifetime = {
                "requests_total": self.requests_total,
                "latency_bad_total": self.latency_bad_total,
                "fail_open_total": self.fail_open_total,
            }
        return compute_burn(cfg, windows, lifetime)


def compute_burn(config: SloConfig, windows: dict, lifetime: dict) -> dict:
    """The snapshot body from raw window counts — shared by the tracker
    and the pool merge so per-worker and pool-wide snapshots can never
    disagree on the math. ``windows`` maps window name to
    ``(seconds, total, latency_bad, avail_bad)``."""
    thresholds = {"fast": config.fast_burn, "slow": config.slow_burn}
    objectives = {}
    for name, (target, budget) in config.objectives().items():
        per_window = {}
        burning = True
        for wname, (seconds, total, lat_bad, avail_bad) in windows.items():
            if name == LATENCY:
                bad, denom = lat_bad, max(total - avail_bad, 0)
            else:
                bad, denom = avail_bad, total
            frac = bad / denom if denom else 0.0
            burn = frac / budget if budget else 0.0
            per_window[wname] = {
                "seconds": seconds,
                "total": denom,
                "bad": bad,
                "bad_fraction": round(frac, 6),
                "burn_rate": round(burn, 4),
                "threshold": thresholds[wname],
            }
            burning = burning and burn >= thresholds[wname]
        objectives[name] = {
            "target": target,
            "budget": round(budget, 6),
            "windows": per_window,
            "burning": burning,
        }
        if name == LATENCY:
            objectives[name]["threshold_ms"] = config.p99_ms
    return {
        "objectives": objectives,
        "degraded": any(o["burning"] for o in objectives.values()),
        "windows_raw": {k: list(v) for k, v in windows.items()},
        "lifetime": dict(lifetime),
        "config": {
            "p99_ms": config.p99_ms,
            "availability": config.availability,
            "fast_window_s": config.fast_window_s,
            "slow_window_s": config.slow_window_s,
            "fast_burn": config.fast_burn,
            "slow_burn": config.slow_burn,
        },
    }


def config_from_snapshot(snapshot: dict) -> SloConfig:
    """Rebuild the config a snapshot was computed under (the pool merge's
    source of truth — workers of one pool share one serve config)."""
    return SloConfig(**snapshot["config"])


def merge_snapshots(snapshots: list) -> dict | None:
    """Pool-wide SLO snapshot: window counts and lifetime counters sum
    across workers (each worker owns its own stream), burn rates are
    recomputed from the sums — rates are NOT linear, counts are (the
    ``merged_histogram`` discipline). ``None`` when no worker tracks
    SLOs."""
    snapshots = [s for s in snapshots if s]
    if not snapshots:
        return None
    config = config_from_snapshot(snapshots[0])
    windows: dict = {}
    for wname in WINDOWS:
        seconds = snapshots[0]["windows_raw"][wname][0]
        sums = [0, 0, 0]
        for snap in snapshots:
            raw = snap.get("windows_raw", {}).get(wname)
            if raw is None:
                continue
            for i in range(3):
                sums[i] += raw[1 + i]
        windows[wname] = (seconds, *sums)
    lifetime: dict = {}
    for snap in snapshots:
        for key, value in snap.get("lifetime", {}).items():
            lifetime[key] = lifetime.get(key, 0) + value
    return compute_burn(config, windows, lifetime)


def histogram_bad_fraction(start: dict, end: dict, threshold_ms: float,
                           bounds) -> tuple[float, int]:
    """``(over_threshold_fraction, window_count)`` between two lifetime
    histogram snapshots (``{"cumulative": [...], "count": n}`` — the
    worker-snapshot shape). Bucket-granular: ``threshold_ms`` rounds UP
    to the nearest histogram bound, so the fraction is conservative
    (never over-reports a violation). The rollout canary gate judges a
    hold window with this — exact deltas of monotone counters, no
    tracker needed on the supervisor."""
    idx = bisect.bisect_left([b * 1e3 for b in bounds], threshold_ms)
    d_count = end["count"] - start["count"]
    if d_count <= 0:
        return 0.0, 0
    if idx >= len(bounds):
        return 0.0, d_count  # beyond the last finite bound: no signal
    d_under = end["cumulative"][idx] - start["cumulative"][idx]
    over = max(d_count - d_under, 0)
    return over / d_count, d_count
