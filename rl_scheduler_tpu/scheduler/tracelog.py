"""graftroll part 1: the durable decision/outcome trace log.

ROADMAP item 1 wants a scheduler that retrains on what it serves; today
nothing durable records what the serving plane decided, so there is no
trace to ever retrain from. This module is the record: every extender
decision appends ONE schema-versioned JSONL record (observation digest +
telemetry replay position, candidate count, chosen node, score, latency,
breaker/fail-open state, worker id, policy generation) through a
crash-safe rotating writer whose hot-path cost is one observation
digest (hashed at the source so it fingerprints exactly what was
served) plus one bounded-queue ``put_nowait``:

- **The hot path never blocks.** ``append`` enqueues onto a bounded
  queue; on overflow the OLDEST queued record drops and is counted
  (``dropped_total``) — the same backpressure policy as the extender's
  ``AsyncPlacer``. A background writer thread drains the queue, so disk
  latency is never decision latency.
- **Crash-safe segments.** The writer appends to an active
  ``*.jsonl.part`` file (flushed per record, so a SIGKILL loses only the
  in-queue tail, never flushed lines) and seals it at
  ``max_records_per_segment`` by fsync-then-rename to ``*.jsonl`` — the
  tmp-then-rename discipline graftguard's checkpoint manifests use: a
  sealed segment is whole by construction. A ``.part`` file orphaned by
  a crash is sealed at the next startup (recovery, not loss).
- **Chaos seam.** ``fault_plan`` site ``tracelog.append`` (utils/faults)
  fires inside the writer: a failed write is counted
  (``write_errors_total``) and the record dropped — the serving thread
  never sees storage errors.
- **Observability.** ``snapshot()`` exports the monotonic counters the
  pool aggregates onto ``/stats``/``/metrics`` (``records``, ``dropped``,
  ``write_errors``, ``segments``); like every lifetime counter here,
  ``/stats/reset`` never clears them.

``iter_trace`` replays a trace directory in write order (sealed segments
then active parts) — per-writer stream order. ``iter_trace_merged``
merges every writer's stream by timestamp (stable under ties) — the seam
graftloop's trace→Scenario compiler and decisionview's per-generation
report read, so a pool's interleaved traffic replays as one decision
sequence.

Retention (``max_segments``): a long-serving pool's trace dir is
bounded — after each seal, sealed segments of THIS writer's stream
beyond the cap are pruned oldest-first and counted
(``segments_pruned_total``). graftloop snapshots the directory before
compiling, so a prune can never yank rows out from under a compile
(docs/serving.md).
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import queue
import re
import threading
import time
from pathlib import Path

logger = logging.getLogger(__name__)

# Schema 2 added the OPTIONAL replay fields `clouds` (compact candidate
# cloud string, see clouds_token) and `pod_cpu` — what the trace→Scenario
# compiler and `extender_bench --replay-trace` reconstruct workloads
# from. Readers tolerate their absence (schema-1 records replay fine,
# minus pod-vector fidelity), per the additive-fields rule spans set.
TRACE_SCHEMA = 2
_SEG_RE = re.compile(r"^(?P<prefix>.*?)seg-(?P<seq>\d{6})\.jsonl(?P<part>\.part)?$")
_SENTINEL = object()


def obs_digest(obs) -> str | None:
    """Short stable digest of a finished observation array (the record's
    provenance key — small enough to log per decision, strong enough to
    join a replayed decision back to its exact inputs)."""
    if obs is None:
        return None
    import numpy as np

    arr = np.ascontiguousarray(np.asarray(obs))
    return hashlib.sha256(arr.tobytes()).hexdigest()[:16]


_CLOUD_CHARS = {"aws": "a", "azure": "z", None: "?"}


def clouds_token(clouds) -> str | None:
    """Compact per-candidate cloud string for the trace record: one char
    per candidate (``a``=aws, ``z``=azure, ``?``=unknown). A 1024-node
    request costs 1 KB as a list but ~1 KB of quotes/commas on top as
    JSON — the token keeps fleet-N records loggable per decision while
    still reconstructing the exact candidate-cloud layout a replayer
    (``extender_bench --replay-trace``) needs."""
    if clouds is None:
        return None
    return "".join(_CLOUD_CHARS.get(c, "?") for c in clouds)


def clouds_from_token(token: str | None) -> list | None:
    """Inverse of :func:`clouds_token` (``None`` stays ``None`` — a
    schema-1 record without the field)."""
    if token is None:
        return None
    rev = {"a": "aws", "z": "azure"}
    return [rev.get(ch) for ch in token]


# Endpoints whose records are synthetic traffic — warmup probes and
# graftdrift shadow scores — never a served decision. Every histogram
# family (e2e latency, phases, SLO, drift sketches) and every trace
# consumer (bench replay, loopback compile, decisionview, drift
# references) excludes them through THIS predicate; a new synthetic
# endpoint joins the frozenset once and every surface agrees (pinned by
# tests/test_graftdrift.py's exclusion audit).
SYNTHETIC_ENDPOINTS = frozenset({"probe", "shadow"})


def is_synthetic_endpoint(endpoint) -> bool:
    """True for trace/serving endpoints that must stay out of every
    served-traffic statistic (module comment on SYNTHETIC_ENDPOINTS)."""
    return endpoint in SYNTHETIC_ENDPOINTS


def decision_record(*, endpoint: str, family: str, backend: str,
                    candidates: int, chosen: str | None,
                    score: float | None, latency_ms: float,
                    obs=None, obs_sha: str | None = None,
                    telemetry_pos: int | None = None,
                    worker_id: int | None = None, generation: int = 0,
                    fail_open: bool = False,
                    breaker_state: str | None = None,
                    spans: dict | None = None,
                    clouds: list | None = None,
                    pod_cpu: float | None = None) -> dict:
    """One schema-versioned trace record. Kept a plain dict (JSONL is the
    contract, not a class) — ``schema`` gates future field changes the
    way the bench's ``schema_version`` does. ``obs_sha`` short-circuits
    the digest when the caller already hashed the observation (the
    extender times the digest as its trace-append span); ``spans`` is
    graftlens' per-phase millisecond breakdown
    (parse/observe/forward/marshal/trace), so every logged decision is
    attributable after the fact — ``None`` on pre-graftlens records and
    with spans disabled, which replayers must tolerate. ``clouds`` (the
    per-candidate cloud list, stored via :func:`clouds_token`) and
    ``pod_cpu`` (the parsed pod request fraction) are graftloop's schema-2
    replay fields — ``None`` on flat-family and legacy records."""
    return {
        "schema": TRACE_SCHEMA,
        "ts": round(time.time(), 6),
        "worker": worker_id,
        "generation": generation,
        "endpoint": endpoint,
        "family": family,
        "backend": backend,
        "obs_sha": obs_sha if obs_sha is not None else obs_digest(obs),
        "telemetry_pos": telemetry_pos,
        "candidates": candidates,
        "chosen": chosen,
        "score": None if score is None else round(float(score), 6),
        "latency_ms": round(latency_ms, 4),
        "fail_open": bool(fail_open),
        "breaker": breaker_state,
        "spans": spans,
        "clouds": clouds_token(clouds),
        "pod_cpu": None if pod_cpu is None else round(float(pod_cpu), 4),
    }


class TraceLog:
    """Crash-safe rotating JSONL writer for decision records (module doc).

    ``prefix`` namespaces one writer's segments inside a shared directory
    (graftserve gives each pool worker ``w<id>-`` so workers never
    contend on a file); ``autostart=False`` leaves the writer thread
    unstarted until :meth:`start` (tests exercise the backpressure
    policy that way — production never passes it).
    """

    def __init__(self, trace_dir: str | Path, prefix: str = "",
                 max_records_per_segment: int = 4096,
                 max_queue: int = 1024, fault_plan=None,
                 autostart: bool = True, max_segments: int = 0):
        if max_records_per_segment < 1:
            raise ValueError(
                f"max_records_per_segment={max_records_per_segment}: "
                "pass at least 1")
        if max_queue < 1:
            raise ValueError(f"max_queue={max_queue}: pass at least 1")
        if max_segments < 0:
            raise ValueError(f"max_segments={max_segments}: pass a sealed-"
                             "segment cap >= 1 (0 keeps everything)")
        self.trace_dir = Path(trace_dir)
        self.trace_dir.mkdir(parents=True, exist_ok=True)
        self.prefix = prefix
        self.max_records_per_segment = max_records_per_segment
        self.max_segments = max_segments
        self.fault_plan = fault_plan
        self._queue: queue.Queue = queue.Queue(maxsize=max_queue)
        self._lock = threading.Lock()
        self._appended = 0
        self._written = 0
        self._dropped = 0
        self._write_errors = 0
        self._sealed = 0
        self._pruned = 0
        self._active_records = 0
        self._closed = False
        self._fh = None
        self._part_path: Path | None = None
        self._seq = self._recover()
        self._thread: threading.Thread | None = None
        if autostart:
            self.start()

    # ------------------------------------------------------------ hot path

    def append(self, record: dict) -> bool:
        """Enqueue one record; NEVER blocks. Returns False when the
        record (or an older one) was dropped to make room — the counted
        drop-oldest policy, so a wedged disk degrades the trace, not the
        decision latency."""
        if self._closed:
            return False
        clean = True
        while True:
            try:
                self._queue.put_nowait(record)
                with self._lock:
                    self._appended += 1
                return clean
            except queue.Full:
                try:
                    self._queue.get_nowait()
                    with self._lock:
                        self._dropped += 1
                    clean = False
                except queue.Empty:
                    pass

    # ------------------------------------------------------------- writer

    def start(self) -> None:
        if self._thread is None:
            self._thread = threading.Thread(target=self._drain, daemon=True,
                                            name="tracelog-writer")
            self._thread.start()

    def _recover(self) -> int:
        """Seal any ``.part`` orphaned by a previous writer's crash (the
        flushed lines are intact — rename publishes them) and return the
        next segment sequence number for this prefix."""
        max_seq = 0
        for path in sorted(self.trace_dir.iterdir()):
            m = _SEG_RE.match(path.name)
            if m is None or m.group("prefix") != self.prefix:
                continue
            max_seq = max(max_seq, int(m.group("seq")))
            if m.group("part"):
                sealed = path.with_name(path.name[:-len(".part")])
                try:
                    path.replace(sealed)
                    logger.warning("tracelog: sealed orphaned segment %s "
                                   "from a previous writer", sealed.name)
                except OSError:
                    logger.exception("tracelog: could not recover %s", path)
        return max_seq + 1

    def _open_part(self) -> None:
        self._part_path = self.trace_dir / (
            f"{self.prefix}seg-{self._seq:06d}.jsonl.part")
        self._fh = self._part_path.open("a", encoding="utf-8")
        self._active_records = 0

    def _seal(self) -> None:
        """fsync-then-rename the active part into a sealed segment —
        after the rename the segment is immutable and whole."""
        if self._fh is None:
            return
        try:
            self._fh.flush()
            os.fsync(self._fh.fileno())
            self._fh.close()
            final = self._part_path.with_name(self._part_path.name[:-len(".part")])
            self._part_path.replace(final)
            with self._lock:
                self._sealed += 1
        except OSError:
            logger.exception("tracelog: sealing %s failed", self._part_path)
            with self._lock:
                self._write_errors += 1
        self._fh = None
        self._part_path = None
        self._seq += 1
        self._active_records = 0
        if self.max_segments:
            self._prune()

    def _prune(self) -> None:
        """Retention (``max_segments``): drop the OLDEST sealed segments
        of THIS writer's stream beyond the cap — the bounded-disk analogue
        of the queue's counted drop-oldest. Only sealed ``*.jsonl`` files
        of this prefix are candidates; the active part and other workers'
        streams are never touched."""
        sealed = sorted(
            p for p in self.trace_dir.iterdir()
            if (m := _SEG_RE.match(p.name)) is not None
            and m.group("prefix") == self.prefix and not m.group("part"))
        for path in sealed[:max(len(sealed) - self.max_segments, 0)]:
            try:
                path.unlink()
                with self._lock:
                    self._pruned += 1
                logger.info("tracelog: pruned sealed segment %s "
                            "(retention cap %d)", path.name,
                            self.max_segments)
            except OSError:
                logger.exception("tracelog: pruning %s failed", path)
                with self._lock:
                    self._write_errors += 1

    def _drain(self) -> None:
        while True:
            item = self._queue.get()
            if item is _SENTINEL:
                return
            try:
                if self.fault_plan is not None:
                    # Simulated disk-full mid-append: the exact family a
                    # failed write(2) raises. Counted, record dropped,
                    # writer keeps serving the queue.
                    self.fault_plan.check("tracelog.append", OSError)
                if self._fh is None:
                    self._open_part()
                self._fh.write(json.dumps(item, separators=(",", ":"))
                               + "\n")
                # Flush per record: a killed worker loses the in-queue
                # tail only, never lines already handed to the OS.
                self._fh.flush()
            except OSError:
                with self._lock:
                    self._write_errors += 1
                continue
            with self._lock:
                self._written += 1
            self._active_records += 1
            if self._active_records >= self.max_records_per_segment:
                self._seal()

    def close(self) -> None:
        """Drain the queue, seal the active segment, stop the writer.
        After close every record ever written lives in a sealed
        ``*.jsonl`` segment."""
        if self._closed:
            return
        self._closed = True
        if self._thread is not None:
            while True:
                try:
                    self._queue.put_nowait(_SENTINEL)
                    break
                except queue.Full:  # drop-oldest to guarantee shutdown
                    try:
                        self._queue.get_nowait()
                        with self._lock:
                            self._dropped += 1
                    except queue.Empty:
                        pass
            self._thread.join(timeout=30.0)
            if self._thread.is_alive():
                # Wedged writer (blocked write(2) on a dying mount): it
                # still owns self._fh, so sealing here would race its
                # next write. Leave the .part for the next startup's
                # _recover() to seal — losing the seal is recoverable,
                # a torn concurrent write is not.
                logger.error(
                    "tracelog: writer thread still alive after 30s drain "
                    "timeout; leaving active segment unsealed for "
                    "startup recovery")
                self._thread = None
                return
            self._thread = None
        self._seal()

    # -------------------------------------------------------------- stats

    def snapshot(self) -> dict:
        """Monotonic lifetime counters for /stats and /metrics export
        (``/stats/reset`` must never clear these — same contract as the
        latency histograms)."""
        with self._lock:
            return {
                "records_total": self._appended,
                "written_total": self._written,
                "dropped_total": self._dropped,
                "write_errors_total": self._write_errors,
                "segments_total": self._sealed,
                "segments_pruned_total": self._pruned,
            }


def iter_trace(trace_dir: str | Path, prefix: str | None = None):
    """Replay every record under ``trace_dir`` in write order: sealed
    segments first (by name — prefix then sequence), then active/orphan
    ``.part`` files. A torn trailing line (writer killed mid-write) is
    skipped, not fatal — a replayer must read a crashed worker's trace.
    ``prefix`` filters to one writer's stream."""
    trace_dir = Path(trace_dir)
    if not trace_dir.is_dir():
        return
    sealed, parts = [], []
    for path in sorted(trace_dir.iterdir()):
        m = _SEG_RE.match(path.name)
        if m is None:
            continue
        if prefix is not None and m.group("prefix") != prefix:
            continue
        (parts if m.group("part") else sealed).append(path)
    for path in sealed + parts:
        try:
            with path.open("r", encoding="utf-8") as fh:
                for line in fh:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        yield json.loads(line)
                    except json.JSONDecodeError:
                        logger.warning("tracelog: skipping torn line in %s",
                                       path.name)
        except OSError:
            logger.exception("tracelog: unreadable segment %s", path)


def trace_prefixes(trace_dir: str | Path) -> list:
    """The distinct writer prefixes present under ``trace_dir`` (a pool's
    ``w<id>-`` streams; ``""`` for the single-process writer), sorted."""
    trace_dir = Path(trace_dir)
    if not trace_dir.is_dir():
        return []
    found = {m.group("prefix") for p in trace_dir.iterdir()
             if (m := _SEG_RE.match(p.name)) is not None}
    return sorted(found)


def iter_trace_merged(trace_dir: str | Path):
    """Replay EVERY writer's stream under ``trace_dir`` as one
    timestamp-ordered decision sequence.

    Each per-prefix stream is time-ordered by construction (one writer
    thread appends wallclock stamps monotonically — almost: an NTP step
    can walk ``time.time`` backwards), so this is a k-way heap merge
    keyed ``(ts, prefix, position-in-stream)`` — records with EQUAL
    timestamps interleave deterministically by prefix then stream order
    (pinned by test; the compiler and decisionview used to each ad-hoc
    this). ``heapq.merge`` silently misorders UNsorted inputs, so each
    stream's key is clamped to its running maximum (a clock step-back
    keeps stream order and logs once per stream rather than corrupting
    the merge); records without a ``ts`` field (hand-built test records,
    foreign lines) inherit the stream's last timestamp — or sort first
    when the stream starts without one — again keeping stream order.
    Torn lines and unreadable segments degrade exactly as
    :func:`iter_trace`."""
    import heapq

    def _keyed(prefix: str):
        high = float("-inf")
        warned = False
        for n, record in enumerate(iter_trace(trace_dir, prefix=prefix)):
            ts = record.get("ts")
            if ts is None:
                ts = high
            elif ts < high:
                if not warned:
                    logger.warning(
                        "tracelog: stream %r timestamps step backwards "
                        "(%s < %s; clock adjustment?) — clamping to "
                        "keep the merge stream-ordered", prefix, ts, high)
                    warned = True
                ts = high
            high = ts
            yield ((ts, prefix, n), record)

    streams = [_keyed(prefix) for prefix in trace_prefixes(trace_dir)]
    for _key, record in heapq.merge(*streams, key=lambda kr: kr[0]):
        yield record
