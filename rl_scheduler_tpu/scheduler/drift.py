"""graftdrift: online distribution-shift observability for the serving plane.

graftlens (slo.py) watches *how fast* the extender answers; nothing
watches *what it is answering* — the policy could latch onto one cloud,
the telemetry table could enter a price-spike regime, and every latency
gauge would stay green. This module is the drift layer, the instrument
ROADMAP item 3's loop daemon triggers on:

- :class:`DriftTracker` keeps **online sketches** of four per-decision
  streams on the decide hot path — the chosen decision's ``score``
  (softmax probability), the chosen-cloud ``action`` categorical, and
  the input telemetry's ``cost``/``latency`` feature columns — each
  accumulated into fixed-bucket histograms (the ``LatencyStats``
  discipline: bucket counts are the ONE shape that merges exactly
  across workers) twice over: a time-bucketed ring for trailing
  fast/slow windows (the ``SloTracker`` ring construction) and
  lifetime-monotonic counts with a host-side Welford accumulator (the
  flight-recorder pattern). One observation per stream per served
  decision; probes, shadow scores and fail-opens are excluded at record
  time (``tracelog.is_synthetic_endpoint``), so drift can never be
  tripped by the gates that watch it.
- **Frozen references**: :func:`build_reference` freezes a fingerprinted
  per-(generation, stream) distribution from a live ``/stats`` drift
  section or a recorded trace dir (``python -m
  rl_scheduler_tpu.scheduler.drift snapshot``). The server grades live
  windows against the loaded reference with bucket-wise **PSI** (with
  epsilon-floored probabilities) and **KS** distance. A reference is
  generation-keyed: after a promote the scores report
  ``generation_mismatch`` — never a false drift alarm — until the
  operator re-snapshots (docs/observability.md §5).
- **Multi-window verdicts reuse ``slo.compute_burn``**: each stream's
  PSI, normalized by the configured threshold, is fed through the SLO
  burn machinery as a pseudo-availability objective (budget = 0.5,
  both burn thresholds = 1.0, window counts at ``_SCALE`` resolution),
  so ``drifting`` is true exactly when the normalized score is over
  threshold in BOTH the fast and the slow window — a transient spike
  never trips it, the same contract that keeps a 2-second latency blip
  from paging.
- :func:`merge_snapshots` is the pool/fleet merge: window and lifetime
  counts sum, distances and verdicts recompute from the sums (the
  ``merged_histogram`` discipline — rates and distances are not
  linear). Its output is shaped exactly like a tracker snapshot, so a
  fleet-of-pools re-merges pool sections the same way a pool merges
  workers.
- :class:`ShadowScorer` is the item-3c substrate: an optional candidate
  checkpoint scores live requests in shadow off the serving thread
  (bounded queue, drop-oldest — the AsyncPlacer discipline), recording
  incumbent-vs-shadow top-1 agreement and a score-delta histogram.
  Shadow decisions are tagged ``endpoint=shadow`` and excluded from
  SLO/latency/phase/drift recording exactly like probes.

Surfaced on ``/stats`` (``drift``/``shadow`` sections), ``/metrics``
(``*_drift_score{stream=,window=,kind=}``, ``*_drifting{stream=}``,
``*_shadow_agreement``) and the ``/healthz`` body. ``tools/driftview``
joins the sections into the gated drift report.
"""

from __future__ import annotations

import argparse
import bisect
import dataclasses
import hashlib
import json
import logging
import math
import queue
import sys
import threading
import time

from rl_scheduler_tpu.scheduler import slo as slo_mod

logger = logging.getLogger(__name__)

DRIFT_SCHEMA = 1
REFERENCE_SCHEMA = 1

# One observation per stream per served decision (the count-uniformity
# discipline, applied to sketches): score = the chosen decision's
# probability, action = the chosen cloud, cost/latency = the mean of the
# observation's cost/latency feature columns. All four live on [0, 1]
# by construction (softmax / normalized table), so one uniform bucket
# grid serves every numeric stream.
STREAMS = ("score", "action", "cost", "latency")
ACTION_CATEGORIES = ("aws", "azure", "unknown")
NUM_BINS = 16
UNIT_EDGES = tuple(round((i + 1) / NUM_BINS, 6) for i in range(NUM_BINS - 1))
# Shadow score deltas live on [-1, 1] (difference of two probabilities).
DELTA_EDGES = tuple(round(-1.0 + 2.0 * (i + 1) / NUM_BINS, 6)
                    for i in range(NUM_BINS - 1))

_STREAM_SPECS: dict = {
    "score": {"edges": UNIT_EDGES},
    "action": {"categories": ACTION_CATEGORIES},
    "cost": {"edges": UNIT_EDGES},
    "latency": {"edges": UNIT_EDGES},
}

# compute_burn is reused verbatim for the drifting verdict: the
# threshold-normalized PSI becomes a pseudo-availability bad-fraction at
# _SCALE resolution against a 0.5 error budget with both burn thresholds
# at 1.0, so burn_rate == min(psi/threshold, _BURN_CAP) and burning ==
# over threshold in BOTH windows. Pinned by test against compute_burn.
_SCALE = 1_000_000
_BURN_BUDGET = 0.5
_BURN_CAP = 8.0


@dataclasses.dataclass(frozen=True)
class DriftConfig:
    """Drift scoring knobs. ``threshold`` is the PSI alarm bar (0.2 is
    the classic "significant shift" bound); the window pair is the
    multi-window burn construction; ``min_window_count`` keeps a
    near-empty window from alarming on sampling noise; ``bucket_s`` is
    the ring granularity (defaults to fast_window_s/8, clamped to
    [0.05, 1] — sub-second buckets are what let a drill run fast
    windows of a couple of seconds)."""

    threshold: float = 0.2
    fast_window_s: float = 60.0
    slow_window_s: float = 600.0
    min_window_count: int = 20
    bucket_s: float | None = None

    def __post_init__(self):
        if self.threshold <= 0:
            raise ValueError(f"drift threshold={self.threshold}: pass a "
                             "positive PSI bound (e.g. 0.2)")
        if not 0 < self.fast_window_s < self.slow_window_s:
            raise ValueError(
                f"drift windows fast={self.fast_window_s}s slow="
                f"{self.slow_window_s}s: fast must be positive and "
                "shorter than slow")
        if self.min_window_count < 1:
            raise ValueError("drift min_window_count must be >= 1")
        if self.bucket_s is not None and not (
                0 < self.bucket_s <= self.fast_window_s):
            raise ValueError(
                f"drift bucket_s={self.bucket_s}: must be positive and "
                "no longer than the fast window")

    @property
    def ring_bucket_s(self) -> float:
        if self.bucket_s is not None:
            return self.bucket_s
        return max(0.05, min(1.0, self.fast_window_s / 8.0))

    def to_dict(self) -> dict:
        return {
            "threshold": self.threshold,
            "fast_window_s": self.fast_window_s,
            "slow_window_s": self.slow_window_s,
            "min_window_count": self.min_window_count,
            "bucket_s": self.ring_bucket_s,
        }


def config_from_snapshot(snapshot: dict) -> DriftConfig:
    cfg = dict(snapshot["config"])
    return DriftConfig(**cfg)


def stream_size(name: str) -> int:
    spec = _STREAM_SPECS[name]
    if "categories" in spec:
        return len(spec["categories"])
    return len(spec["edges"]) + 1


def bucket_index(name: str, value) -> int | None:
    """Bucket index for one observation, or None when the value cannot
    land (non-finite numeric, unknown stream)."""
    spec = _STREAM_SPECS[name]
    if "categories" in spec:
        cats = spec["categories"]
        label = value if value in cats else cats[-1]
        return cats.index(label)
    try:
        v = float(value)
    except (TypeError, ValueError):
        return None
    if math.isnan(v) or math.isinf(v):
        return None
    return min(bisect.bisect_right(spec["edges"], v), len(spec["edges"]))


# --------------------------------------------------------------- distances


def psi(live_counts, ref_counts, eps: float = 1e-4) -> float | None:
    """Population Stability Index between two bucket-count vectors:
    ``sum((p - q) * ln(p / q))`` over epsilon-floored probabilities.
    ``None`` when the reference is empty (no basis to grade against);
    0.0 when the live side is empty (no evidence of movement)."""
    ref_total = sum(ref_counts)
    if ref_total <= 0:
        return None
    live_total = sum(live_counts)
    if live_total <= 0:
        return 0.0
    out = 0.0
    for c, r in zip(live_counts, ref_counts):
        p = max(c / live_total, eps)
        q = max(r / ref_total, eps)
        out += (p - q) * math.log(p / q)
    return out


def ks(live_counts, ref_counts) -> float | None:
    """Kolmogorov-Smirnov distance (max CDF gap) between two bucket-count
    vectors over the same fixed bucket order. On the categorical stream
    the bucket order is the fixed ACTION_CATEGORIES order — stable, if
    arbitrary, which is all KS needs to be comparable over time."""
    ref_total = sum(ref_counts)
    if ref_total <= 0:
        return None
    live_total = sum(live_counts)
    if live_total <= 0:
        return 0.0
    worst = cdf_live = cdf_ref = 0.0
    for c, r in zip(live_counts, ref_counts):
        cdf_live += c / live_total
        cdf_ref += r / ref_total
        worst = max(worst, abs(cdf_live - cdf_ref))
    return worst


# ----------------------------------------------------------------- scoring


def compute_scores(config: DriftConfig, streams: dict,
                   reference: dict | None, generation: int) -> dict:
    """Per-stream drift scores from raw window counts — shared by the
    tracker snapshot and the pool/fleet merge (the ``compute_burn``
    sharing discipline: per-worker and merged sections can never
    disagree on the math). The drifting verdict itself is delegated to
    ``slo.compute_burn`` (module doc)."""
    ref_streams = (reference or {}).get("streams") or {}
    ref_generation = (reference or {}).get("generation")
    scores: dict = {}
    for name, entry in streams.items():
        if reference is None or name not in ref_streams:
            status = "no_reference"
        elif ref_generation is not None and ref_generation != generation:
            status = "generation_mismatch"
        else:
            status = "ok"
        ref_counts = (ref_streams.get(name) or {}).get("counts")
        windows: dict = {}
        psi_by_window: dict = {}
        ks_by_window: dict = {}
        burn_windows: dict = {}
        for wname in slo_mod.WINDOWS:
            raw = entry["windows_raw"][wname]
            counts = raw["counts"]
            n = sum(counts)
            psi_v = ks_v = None
            if status == "ok" and ref_counts:
                psi_v = psi(counts, ref_counts)
                ks_v = ks(counts, ref_counts)
            psi_by_window[wname] = (None if psi_v is None
                                    else round(psi_v, 6))
            ks_by_window[wname] = None if ks_v is None else round(ks_v, 6)
            windows[wname] = {"count": n,
                              "sufficient": n >= config.min_window_count}
            normalized = 0.0
            if psi_v is not None and n >= config.min_window_count:
                normalized = min(psi_v / config.threshold, _BURN_CAP)
            burn_windows[wname] = (
                raw["seconds"], _SCALE, 0,
                int(round(normalized * _BURN_BUDGET * _SCALE)))
        verdict = slo_mod.compute_burn(
            slo_mod.SloConfig(availability=1.0 - _BURN_BUDGET,
                              fast_window_s=config.fast_window_s,
                              slow_window_s=config.slow_window_s,
                              fast_burn=1.0, slow_burn=1.0),
            burn_windows, lifetime={})
    # burn_rate per window == min(psi/threshold, cap); burning ==
    # over threshold in BOTH windows (compute_burn's AND).
        availability = verdict["objectives"][slo_mod.AVAILABILITY]
        scores[name] = {
            "status": status,
            "psi": psi_by_window,
            "ks": ks_by_window,
            "windows": windows,
            "burn": {w: availability["windows"][w]["burn_rate"]
                     for w in slo_mod.WINDOWS},
            "drifting": bool(availability["burning"]),
        }
    return scores


# --------------------------------------------------------------- the tracker


class DriftTracker:
    """Online per-stream sketches + drift scoring (module doc).

    Thread-safe: serving threads record, the control-plane thread
    snapshots. ``clock`` is injectable for tests (monotonic seconds).
    Lifetime counts are monotonic — ``/stats/reset`` never rewinds them,
    the same contract as the latency histograms (pinned by test)."""

    def __init__(self, config: DriftConfig | None = None,
                 clock=time.monotonic):
        self.config = config or DriftConfig()
        self._clock = clock
        self._lock = threading.Lock()
        bucket_s = self.config.ring_bucket_s
        self._bucket_s = bucket_s
        n = int(self.config.slow_window_s / bucket_s) + 2
        self._n = n
        self._ids = [-1] * n
        self._ring = {name: [[0] * stream_size(name) for _ in range(n)]
                      for name in STREAMS}
        self._life_counts = {name: [0] * stream_size(name)
                             for name in STREAMS}
        self._life_n = {name: 0 for name in STREAMS}
        # Host-side Welford per numeric stream (count, mean, m2, min, max)
        # — the flight-recorder accumulator, merged with Chan's formula.
        self._welford = {name: [0, 0.0, 0.0, math.inf, -math.inf]
                         for name in STREAMS
                         if "edges" in _STREAM_SPECS[name]}
        self._reference: dict | None = None

    # ------------------------------------------------------------ recording

    def set_reference(self, reference: dict | None) -> None:
        with self._lock:
            self._reference = reference

    @property
    def reference(self) -> dict | None:
        with self._lock:
            return self._reference

    def _slot(self, now: float) -> int:
        bucket_id = int(now / self._bucket_s)
        slot = bucket_id % self._n
        if self._ids[slot] != bucket_id:
            self._ids[slot] = bucket_id
            for rows in self._ring.values():
                row = rows[slot]
                for i in range(len(row)):
                    row[i] = 0
        return slot

    def observe_decision(self, cloud, score, cost=None,
                         latency=None) -> None:
        """One served decision: at most one observation per stream.
        ``None`` feature values (a family whose observation carries no
        cost/latency columns) skip that stream — never a zero-fill."""
        samples = {"score": score, "action": cloud,
                   "cost": cost, "latency": latency}
        with self._lock:
            slot = self._slot(self._clock())
            for name, value in samples.items():
                if value is None:
                    continue
                idx = bucket_index(name, value)
                if idx is None:
                    continue
                self._ring[name][slot][idx] += 1
                self._life_counts[name][idx] += 1
                self._life_n[name] += 1
                acc = self._welford.get(name)
                if acc is not None:
                    v = float(value)
                    acc[0] += 1
                    delta = v - acc[1]
                    acc[1] += delta / acc[0]
                    acc[2] += delta * (v - acc[1])
                    acc[3] = min(acc[3], v)
                    acc[4] = max(acc[4], v)

    # ------------------------------------------------------------ snapshots

    def _window_counts(self, name: str, now: float,
                       window_s: float) -> list:
        """Bucket counts over the trailing window. Caller holds lock."""
        now_id = int(now / self._bucket_s)
        first = now_id - int(window_s / self._bucket_s) + 1
        counts = [0] * stream_size(name)
        rows = self._ring[name]
        for bucket_id in range(first, now_id + 1):
            slot = bucket_id % self._n
            if self._ids[slot] != bucket_id:
                continue
            row = rows[slot]
            for i, c in enumerate(row):
                counts[i] += c
        return counts

    def snapshot(self, generation: int = 0) -> dict:
        cfg = self.config
        with self._lock:
            now = self._clock()
            streams: dict = {}
            for name in STREAMS:
                spec = _STREAM_SPECS[name]
                entry: dict = {
                    "windows_raw": {
                        "fast": {"seconds": cfg.fast_window_s,
                                 "counts": self._window_counts(
                                     name, now, cfg.fast_window_s)},
                        "slow": {"seconds": cfg.slow_window_s,
                                 "counts": self._window_counts(
                                     name, now, cfg.slow_window_s)},
                    },
                    "lifetime": {
                        "count": self._life_n[name],
                        "counts": list(self._life_counts[name]),
                    },
                }
                if "edges" in spec:
                    entry["edges"] = list(spec["edges"])
                    acc = self._welford[name]
                    life = entry["lifetime"]
                    life["mean"] = round(acc[1], 6) if acc[0] else None
                    life["m2"] = round(acc[2], 6)
                    life["std"] = (round(math.sqrt(acc[2] / acc[0]), 6)
                                   if acc[0] else None)
                    life["min"] = acc[3] if acc[0] else None
                    life["max"] = acc[4] if acc[0] else None
                else:
                    entry["categories"] = list(spec["categories"])
                streams[name] = entry
            reference = self._reference
        scores = compute_scores(cfg, streams, reference, generation)
        return {
            "schema": DRIFT_SCHEMA,
            "generation": generation,
            "config": cfg.to_dict(),
            "streams": streams,
            "reference": reference,
            "scores": scores,
            "drifting": sorted(name for name, s in scores.items()
                               if s["drifting"]),
        }


def merge_snapshots(snapshots: list) -> dict | None:
    """Pool/fleet-wide drift section: window and lifetime bucket counts
    sum across workers, Welford moments merge with Chan's formula, and
    distances + verdicts recompute from the sums via
    :func:`compute_scores`. ``None`` when no worker tracks drift — a
    version-skewed worker or pool without a drift section contributes
    NOTHING, it is never zero-filled into a distance. The output is
    shaped like a tracker snapshot, so the fleet re-merges pool
    sections with this same function (closed under merge)."""
    snapshots = [s for s in snapshots if s]
    if not snapshots:
        return None
    config = config_from_snapshot(snapshots[0])
    generation = max(s.get("generation", 0) for s in snapshots)
    streams: dict = {}
    for name in STREAMS:
        entries = [s["streams"][name] for s in snapshots
                   if name in s.get("streams", {})]
        if not entries:
            continue
        size = stream_size(name)
        merged: dict = {"windows_raw": {}}
        for wname in slo_mod.WINDOWS:
            counts = [0] * size
            seconds = 0.0
            for entry in entries:
                raw = entry["windows_raw"][wname]
                seconds = max(seconds, raw["seconds"])
                for i, c in enumerate(raw["counts"][:size]):
                    counts[i] += c
            merged["windows_raw"][wname] = {"seconds": seconds,
                                            "counts": counts}
        life_counts = [0] * size
        life_n = 0
        for entry in entries:
            life = entry["lifetime"]
            life_n += life["count"]
            for i, c in enumerate(life["counts"][:size]):
                life_counts[i] += c
        merged["lifetime"] = {"count": life_n, "counts": life_counts}
        spec = _STREAM_SPECS[name]
        if "edges" in spec:
            merged["edges"] = list(spec["edges"])
            n_acc, mean, m2 = 0, 0.0, 0.0
            lo, hi = math.inf, -math.inf
            for entry in entries:
                life = entry["lifetime"]
                nb = life["count"]
                if not nb:
                    continue
                mb = life.get("mean") or 0.0
                m2b = life.get("m2") or 0.0
                delta = mb - mean
                total = n_acc + nb
                mean += delta * nb / total
                m2 += m2b + delta * delta * n_acc * nb / total
                n_acc = total
                if life.get("min") is not None:
                    lo = min(lo, life["min"])
                if life.get("max") is not None:
                    hi = max(hi, life["max"])
            life = merged["lifetime"]
            life["mean"] = round(mean, 6) if n_acc else None
            life["m2"] = round(m2, 6)
            life["std"] = (round(math.sqrt(m2 / n_acc), 6)
                           if n_acc else None)
            life["min"] = lo if n_acc else None
            life["max"] = hi if n_acc else None
        else:
            merged["categories"] = list(spec["categories"])
        streams[name] = merged
    references = [s.get("reference") for s in snapshots
                  if s.get("reference")]
    reference = references[0] if references else None
    fingerprints = {r.get("fingerprint") for r in references}
    scores = compute_scores(config, streams, reference, generation)
    out = {
        "schema": DRIFT_SCHEMA,
        "generation": generation,
        "config": config.to_dict(),
        "streams": streams,
        "reference": reference,
        "scores": scores,
        "drifting": sorted(name for name, s in scores.items()
                           if s["drifting"]),
    }
    if len(fingerprints) > 1:
        # Workers of one pool share one serve config; divergence (a
        # mid-roll reference swap) must be VISIBLE, never averaged away.
        out["reference_mixed"] = True
    return out


# -------------------------------------------------------------- references


def reference_fingerprint(reference: dict) -> str:
    """Content fingerprint over the distribution itself (schema +
    generation + stream counts/edges) — NOT over provenance fields, so
    re-capturing identical counts yields an identical fingerprint."""
    body = {
        "schema": reference.get("schema", REFERENCE_SCHEMA),
        "generation": reference.get("generation", 0),
        "streams": reference.get("streams") or {},
    }
    canonical = json.dumps(body, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(canonical.encode()).hexdigest()


def build_reference(drift_snapshot: dict, source: str = "") -> dict:
    """Freeze a reference from a drift section's LIFETIME counts (the
    full distribution the plane has served under this generation)."""
    streams: dict = {}
    for name, entry in (drift_snapshot.get("streams") or {}).items():
        life = entry.get("lifetime") or {}
        stream = {
            "counts": [int(c) for c in life.get("counts") or []],
            "count": int(life.get("count") or 0),
        }
        if entry.get("edges") is not None:
            stream["edges"] = list(entry["edges"])
        if entry.get("categories") is not None:
            stream["categories"] = list(entry["categories"])
        streams[name] = stream
    ref = {
        "schema": REFERENCE_SCHEMA,
        "generation": int(drift_snapshot.get("generation", 0)),
        "source": source,
        "streams": streams,
    }
    ref["fingerprint"] = reference_fingerprint(ref)
    return ref


def reference_from_trace(trace_dir: str) -> dict:
    """Freeze a reference from a recorded trace dir (the eval-corpus
    path). Trace records carry the chosen score and — for the flat
    family — the chosen cloud, but only an observation DIGEST, so a
    trace-built reference covers the ``score`` (and, flat-family,
    ``action``) streams; the feature streams stay ungraded
    (``no_reference``) until a live snapshot replaces it. Synthetic
    records (probe/shadow) and fail-opens are excluded, and only the
    NEWEST generation present is counted — references are
    per-generation."""
    from rl_scheduler_tpu.scheduler.tracelog import (
        is_synthetic_endpoint,
        iter_trace_merged,
    )

    generations: dict = {}
    for record in iter_trace_merged(trace_dir):
        if is_synthetic_endpoint(record.get("endpoint")):
            continue
        if record.get("fail_open"):
            continue
        gen = int(record.get("generation", 0))
        bucket = generations.setdefault(gen, {
            "score": [0] * stream_size("score"),
            "action": [0] * stream_size("action"),
            "records": 0, "actions": 0,
        })
        score = record.get("score")
        idx = bucket_index("score", score) if score is not None else None
        if idx is None:
            continue
        bucket["score"][idx] += 1
        bucket["records"] += 1
        chosen = record.get("chosen")
        if chosen in ACTION_CATEGORIES:
            bucket["action"][bucket_index("action", chosen)] += 1
            bucket["actions"] += 1
    if not generations or not any(b["records"]
                                  for b in generations.values()):
        raise ValueError(
            f"{trace_dir}: no scorable decision records (synthetic "
            "records and fail-opens are excluded) — serve real traffic "
            "before freezing a reference")
    gen = max(g for g, b in generations.items() if b["records"])
    bucket = generations[gen]
    streams = {"score": {"counts": bucket["score"],
                         "count": bucket["records"],
                         "edges": list(UNIT_EDGES)}}
    if bucket["actions"]:
        streams["action"] = {"counts": bucket["action"],
                             "count": bucket["actions"],
                             "categories": list(ACTION_CATEGORIES)}
    ref = {
        "schema": REFERENCE_SCHEMA,
        "generation": gen,
        "source": f"trace:{trace_dir}",
        "streams": streams,
    }
    ref["fingerprint"] = reference_fingerprint(ref)
    return ref


def save_reference(path: str, reference: dict) -> None:
    from rl_scheduler_tpu.utils.fsio import atomic_write_json

    atomic_write_json(path, reference)


def load_reference(path: str) -> dict:
    with open(path, encoding="utf-8") as fh:
        ref = json.load(fh)
    if not isinstance(ref, dict) or ref.get("schema") != REFERENCE_SCHEMA:
        raise ValueError(f"{path}: not a drift reference "
                         f"(schema {REFERENCE_SCHEMA} expected)")
    expected = reference_fingerprint(ref)
    if ref.get("fingerprint") != expected:
        raise ValueError(
            f"{path}: reference fingerprint mismatch (stored "
            f"{str(ref.get('fingerprint'))[:12]}…, distribution hashes "
            f"to {expected[:12]}…) — the file was edited or truncated; "
            "re-snapshot instead of repairing by hand")
    return ref


# ----------------------------------------------------------- shadow scoring


class ShadowScorer:
    """Candidate-checkpoint shadow scoring off the serving thread
    (module doc). ``score_fn(obs) -> (action, score)`` runs the
    candidate backend; serving threads call :meth:`submit` (bounded
    queue, drop-newest-on-full — the serving thread NEVER blocks), a
    single daemon worker drains it. ``record_fn(action, score,
    latency_ms, obs)``, when given, appends the ``endpoint=shadow``
    trace record. Errors count and never propagate: a broken shadow
    cannot touch serving."""

    def __init__(self, score_fn, record_fn=None, queue_size: int = 512):
        self._score_fn = score_fn
        self._record_fn = record_fn
        self._queue: queue.Queue = queue.Queue(maxsize=queue_size)
        self._lock = threading.Lock()
        self.submitted_total = 0
        self.scored_total = 0
        self.dropped_total = 0
        self.errors_total = 0
        self.agreements_total = 0
        # Paired win/loss/tie counts for graftpilot's live promote gate:
        # one pair per scored request, win = shadow top-1 confidence
        # strictly above the incumbent's score on the SAME observation.
        # These feed graftstudy's two-sided sign test, which only needs
        # the signs — so the counters sum exactly across workers.
        self.wins_total = 0
        self.losses_total = 0
        self.ties_total = 0
        self._delta_counts = [0] * (len(DELTA_EDGES) + 1)
        self._delta_sum = 0.0
        self._closed = False
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="shadow-scorer")
        self._thread.start()

    def submit(self, obs, action: int, score: float) -> None:
        with self._lock:
            self.submitted_total += 1
        try:
            self._queue.put_nowait((obs, int(action), float(score)))
        except queue.Full:
            with self._lock:
                self.dropped_total += 1

    def _loop(self) -> None:
        while True:
            item = self._queue.get()
            if item is None:
                return
            obs, action, score = item
            t0 = time.perf_counter()
            try:
                shadow_action, shadow_score = self._score_fn(obs)
            except Exception:  # noqa: BLE001 - shadow never hurts serving
                with self._lock:
                    self.errors_total += 1
                logger.warning("shadow score_fn failed", exc_info=True)
                continue
            latency_ms = (time.perf_counter() - t0) * 1e3
            delta = float(shadow_score) - score
            idx = min(bisect.bisect_right(DELTA_EDGES, delta),
                      len(DELTA_EDGES))
            with self._lock:
                self.scored_total += 1
                if int(shadow_action) == action:
                    self.agreements_total += 1
                if delta > 0.0:
                    self.wins_total += 1
                elif delta < 0.0:
                    self.losses_total += 1
                else:
                    self.ties_total += 1
                self._delta_counts[idx] += 1
                self._delta_sum += delta
            if self._record_fn is not None:
                try:
                    self._record_fn(int(shadow_action),
                                    float(shadow_score), latency_ms, obs)
                except Exception:  # noqa: BLE001 - trace is best-effort
                    with self._lock:
                        self.errors_total += 1
                    logger.warning("shadow record_fn failed", exc_info=True)

    def drain(self, timeout_s: float = 5.0) -> bool:
        """Wait for the queue to empty (tests and drills; serving never
        calls this)."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self._queue.empty():
                return True
            time.sleep(0.01)
        return self._queue.empty()

    def snapshot(self) -> dict:
        with self._lock:
            scored = self.scored_total
            return {
                "submitted_total": self.submitted_total,
                "scored_total": scored,
                "dropped_total": self.dropped_total,
                "errors_total": self.errors_total,
                "agreements_total": self.agreements_total,
                "wins_total": self.wins_total,
                "losses_total": self.losses_total,
                "ties_total": self.ties_total,
                "agreement_rate": (round(self.agreements_total / scored, 4)
                                   if scored else None),
                "score_delta": {
                    "edges": list(DELTA_EDGES),
                    "counts": list(self._delta_counts),
                    "count": scored,
                    "sum": round(self._delta_sum, 6),
                    "mean": (round(self._delta_sum / scored, 6)
                             if scored else None),
                },
            }

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            self._queue.put_nowait(None)
        except queue.Full:
            pass
        self._thread.join(timeout=2.0)
        if self._thread.is_alive():
            # A wedged score_fn survives the timed join; the daemon
            # thread dies with the interpreter and its in-flight shadow
            # record is lost — counters already on /stats stay valid.
            logger.error("shadow scorer failed to drain within 2s; "
                         "abandoning the worker thread")


def sum_shadow(sections: list) -> dict | None:
    """Pool/fleet-wide shadow section: counters and delta-histogram
    counts sum exactly across workers; the agreement rate and delta mean
    recompute from the sums. ``None`` when no worker shadows."""
    sections = [s for s in sections if s]
    if not sections:
        return None
    keys = ("submitted_total", "scored_total", "dropped_total",
            "errors_total", "agreements_total", "wins_total",
            "losses_total", "ties_total")
    out = {k: sum(int(s.get(k, 0)) for s in sections) for k in keys}
    scored = out["scored_total"]
    out["agreement_rate"] = (round(out["agreements_total"] / scored, 4)
                             if scored else None)
    counts = [0] * (len(DELTA_EDGES) + 1)
    delta_sum = 0.0
    for s in sections:
        delta = s.get("score_delta") or {}
        for i, c in enumerate((delta.get("counts") or [])[:len(counts)]):
            counts[i] += c
        delta_sum += delta.get("sum") or 0.0
    out["score_delta"] = {
        "edges": list(DELTA_EDGES),
        "counts": counts,
        "count": scored,
        "sum": round(delta_sum, 6),
        "mean": round(delta_sum / scored, 6) if scored else None,
    }
    return out


# -------------------------------------------------------------- exposition


def drift_metric_lines(prefix: str, snapshot: dict) -> list:
    """Prometheus exposition for a drift section — shared by the single
    plane, the pool supervisor and the fleet controller (the
    ``slo_metric_lines`` sharing discipline)."""
    p = prefix
    scores = snapshot.get("scores") or {}
    lines = [
        f"# HELP {p}_drift_score Distribution distance vs the frozen "
        "reference, per stream and trailing window.",
        f"# TYPE {p}_drift_score gauge",
    ]
    for name in sorted(scores):
        for kind in ("psi", "ks"):
            for wname in slo_mod.WINDOWS:
                value = scores[name][kind][wname]
                if value is None:
                    continue
                lines.append(
                    f'{p}_drift_score{{stream="{name}",window="{wname}",'
                    f'kind="{kind}"}} {value:.6g}')
    lines += [
        f"# HELP {p}_drifting Stream over the PSI threshold in BOTH "
        "burn windows (slo.compute_burn semantics).",
        f"# TYPE {p}_drifting gauge",
    ]
    for name in sorted(scores):
        lines.append(f'{p}_drifting{{stream="{name}"}} '
                     f'{1 if scores[name]["drifting"] else 0}')
    lines += [
        f"# HELP {p}_drift_observations_total Lifetime sketch "
        "observations per stream (monotonic; reset never rewinds).",
        f"# TYPE {p}_drift_observations_total counter",
    ]
    for name in sorted(snapshot.get("streams") or {}):
        count = snapshot["streams"][name]["lifetime"]["count"]
        lines.append(
            f'{p}_drift_observations_total{{stream="{name}"}} {count}')
    reference = snapshot.get("reference")
    lines += [
        f"# HELP {p}_drift_reference Loaded reference distribution "
        "(1 = loaded; fingerprint/generation as labels).",
        f"# TYPE {p}_drift_reference gauge",
    ]
    if reference:
        fp = str(reference.get("fingerprint", ""))[:12]
        lines.append(
            f'{p}_drift_reference{{fingerprint="{fp}",'
            f'generation="{reference.get("generation", 0)}"}} 1')
    else:
        lines.append(f'{p}_drift_reference 0')
    return lines


def shadow_metric_lines(prefix: str, section: dict) -> list:
    p = prefix
    lines = []
    for key, help_text in (
        ("scored_total", "Live requests re-scored by the shadow "
                         "candidate (lifetime)."),
        ("dropped_total", "Shadow submissions dropped by the bounded "
                          "queue (lifetime)."),
        ("errors_total", "Shadow scoring errors (lifetime; serving is "
                         "never affected)."),
        ("agreements_total", "Shadow top-1 choices agreeing with the "
                             "incumbent (lifetime)."),
        ("wins_total", "Paired requests where the shadow's top-1 "
                       "confidence beat the incumbent score (lifetime; "
                       "graftpilot's sign-test gate input)."),
        ("losses_total", "Paired requests the incumbent won (lifetime)."),
        ("ties_total", "Paired requests with an exactly equal score "
                       "(lifetime; excluded from the sign test)."),
    ):
        lines += [
            f"# HELP {p}_shadow_{key} {help_text}",
            f"# TYPE {p}_shadow_{key} counter",
            f"{p}_shadow_{key} {section.get(key, 0)}",
        ]
    rate = section.get("agreement_rate")
    lines += [
        f"# HELP {p}_shadow_agreement Incumbent-vs-shadow top-1 "
        "agreement rate (lifetime).",
        f"# TYPE {p}_shadow_agreement gauge",
        f"{p}_shadow_agreement {-1 if rate is None else rate}",
    ]
    mean = (section.get("score_delta") or {}).get("mean")
    lines += [
        f"# HELP {p}_shadow_score_delta_mean Mean (shadow top-1 score - "
        "incumbent score), lifetime.",
        f"# TYPE {p}_shadow_score_delta_mean gauge",
        f"{p}_shadow_score_delta_mean {0 if mean is None else mean:.6g}",
    ]
    return lines


# --------------------------------------------------------------------- CLI


def _load_stats(source: str) -> dict:
    if source.startswith(("http://", "https://")):
        import urllib.request

        with urllib.request.urlopen(source, timeout=10) as resp:
            return json.loads(resp.read())
    with open(source, encoding="utf-8") as fh:
        return json.load(fh)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m rl_scheduler_tpu.scheduler.drift",
        description="graftdrift reference tooling (module doc)")
    sub = parser.add_subparsers(dest="command", required=True)
    snap = sub.add_parser(
        "snapshot",
        help="freeze a fingerprinted reference distribution from a live "
             "pool's /stats (lifetime counts) or a recorded trace dir")
    snap.add_argument("--stats", default=None, metavar="URL|FILE",
                      help="a /stats body (live URL or saved JSON) whose "
                           "drift section's lifetime counts become the "
                           "reference")
    snap.add_argument("--trace", default=None, metavar="DIR",
                      help="a recorded trace dir (eval corpus): score/"
                           "action streams only — trace records carry "
                           "no feature columns")
    snap.add_argument("--out", required=True, metavar="FILE",
                      help="reference JSON path (written atomically)")
    args = parser.parse_args(argv)
    if (args.stats is None) == (args.trace is None):
        parser.error("snapshot: pass exactly one of --stats / --trace")
    if args.stats is not None:
        stats = _load_stats(args.stats)
        section = stats.get("drift")
        if not section:
            print(f"error: {args.stats} has no drift section — start "
                  "the server with --drift", file=sys.stderr)
            return 2
        if not any((e.get("lifetime") or {}).get("count")
                   for e in (section.get("streams") or {}).values()):
            print(f"error: {args.stats}: drift sketches are empty — "
                  "serve traffic before freezing a reference",
                  file=sys.stderr)
            return 2
        ref = build_reference(section, source=f"stats:{args.stats}")
    else:
        try:
            ref = reference_from_trace(args.trace)
        except ValueError as exc:
            print(f"error: {exc}", file=sys.stderr)
            return 2
    save_reference(args.out, ref)
    counts = {name: s["count"] for name, s in ref["streams"].items()}
    print(json.dumps({"out": args.out, "generation": ref["generation"],
                      "fingerprint": ref["fingerprint"],
                      "stream_counts": counts}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
