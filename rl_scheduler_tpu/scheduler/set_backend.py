"""Serving backends for the pointer-over-nodes set policy (config 4).

The reference never served anything (its extender is a 0-byte stub), and
round-3 of this framework could only serve the flat multi-cloud MLP — the
richest trained artifact (the ``cluster_set`` set-transformer, whose
logits are literally per-node scores) was unservable. These backends close
that: the pointer head's ``[N]`` logits map 1:1 onto the kube scheduler
extender protocol — ``/prioritize`` scores every candidate node from the
per-node logit, ``/filter`` keeps the argmax node.

Two families, mirroring the flat-MLP serving stack
(``policy_backend.py``):

- ``NumpySetBackend``: the full set-transformer forward in plain numpy.
  Variable node count for free (no compile per shape) and no jax dispatch
  on the request path — at serving sizes (N <= a few hundred nodes) the
  whole forward is tens of microseconds. This is also the overflow path
  under concurrent load (numpy matmuls hold the GIL; no thread-wakeup
  penalty — same measurement as the MLP backends).
- ``JaxSetAOTBackend``: ``net.apply`` AOT-compiled per node-count, params
  warm on the target device. XLA specializes on N, so each distinct node
  count compiles once (cached; first request for a new N pays the
  compile). Single-stream fastest at large N; for mixed/unknown fleets
  the numpy path has no such cliff.
- ``NativeSetBackend``: the same forward in the C++ core
  (``native/set_infer.cpp``), one ctypes hop, variable N, GIL-FREE for
  the call — fastest at serving-size node sets (~0.16 ms at N=8, flat
  from 1-way to 8-way) and the overflow path under load; numpy/BLAS
  wins single-stream at N~100+.
- ``LoadAwareSetBackend`` (the ``jax`` serving flag): AOT primary with
  native (else numpy) overflow past 2 in-flight dispatches — the same
  saturation fix as the MLP family's ``LoadAwareJaxBackend``.

Agreement between the two (and with the training-time flax apply) is
asserted to 1e-4 logits / argmax decisions in ``tests/test_extender.py``
— the same tolerance-level (not bitwise) guarantee the MLP backends make.

Both expose ``family = "set"`` and ``decide_nodes(node_obs) ->
(action, logits)`` with ``node_obs [N, NODE_FEAT]`` (features documented
in ``env/cluster_set.py``); the extender builds that observation from
telemetry + the request's node list (``telemetry.observe_nodes``).
"""

from __future__ import annotations

import logging
import threading
import time

import numpy as np

from rl_scheduler_tpu.scheduler.policy_backend import (
    AdaptiveLatencyRouter,
    ConcurrencyTracker,
    ShedGate,
)

logger = logging.getLogger(__name__)

SET_DIM = 64    # SetTransformerPolicy defaults (models/transformer.py)
SET_DEPTH = 2
_LN_EPS = 1e-6  # flax LayerNorm default


def _params_subtree(tree: dict) -> dict:
    return tree["params"] if "params" in tree else tree


def _np_tree(tree):
    if isinstance(tree, dict):
        return {k: _np_tree(v) for k, v in tree.items()}
    return np.asarray(tree, np.float32)


def _layer_norm(x: np.ndarray, p: dict) -> np.ndarray:
    mu = x.mean(-1, keepdims=True)
    var = ((x - mu) ** 2).mean(-1, keepdims=True)
    return (x - mu) / np.sqrt(var + _LN_EPS) * p["scale"] + p["bias"]


def _gelu(x: np.ndarray) -> np.ndarray:
    # flax nn.gelu default (approximate=True): tanh approximation.
    # x*x*x, not x**3: np.power is a per-element libm call (~100x slower
    # than the multiplies on the serving path).
    return 0.5 * x * (1.0 + np.tanh(
        np.float32(np.sqrt(2.0 / np.pi)) * (x + np.float32(0.044715) * (x * x * x))
    ))


def _mha(x: np.ndarray, p: dict) -> np.ndarray:
    """flax MultiHeadDotProductAttention forward: x [N, dim] -> [N, dim],
    or batched ``[k, N, dim]`` (graftfwd micro-batching — every op below
    is written on the trailing axes, so one code path serves both; the
    2-D behavior is unchanged).

    qkv kernels are [dim, H, head_dim]; out kernel is [H, head_dim, dim].
    Kernels fold to 2-D so every matmul hits BLAS (generic ``np.einsum``
    paths measured ~10x slower on the request path); heads run as a short
    Python loop over trailing-axis slices.
    """
    wq, wk, wv = (p[n]["kernel"] for n in ("query", "key", "value"))
    dim, num_heads, head_dim = wq.shape
    fold = lambda w: w.reshape(dim, num_heads * head_dim)
    q = x @ fold(wq) + p["query"]["bias"].reshape(-1)   # [..., N, H*hd]
    k = x @ fold(wk) + p["key"]["bias"].reshape(-1)
    v = x @ fold(wv) + p["value"]["bias"].reshape(-1)
    scale = 1.0 / np.sqrt(head_dim)
    ctx = np.empty_like(q)
    for h in range(num_heads):
        sl = slice(h * head_dim, (h + 1) * head_dim)
        scores = np.matmul(q[..., sl],
                           np.swapaxes(k[..., sl], -1, -2)) * scale
        scores -= scores.max(-1, keepdims=True)
        weights = np.exp(scores)
        weights /= weights.sum(-1, keepdims=True)
        ctx[..., sl] = np.matmul(weights, v[..., sl])
    return ctx @ p["out"]["kernel"].reshape(num_heads * head_dim, dim) \
        + p["out"]["bias"]


class NumpySetBackend:
    """Set-transformer pointer forward in plain numpy (variable N)."""

    name = "cpu"
    family = "set"

    def __init__(self, params_tree: dict, num_heads: int = 1,
                 depth: int = SET_DEPTH):
        p = _np_tree(_params_subtree(params_tree))
        self._embed = p["embed"]
        self._blocks = [p[f"block_{i}"] for i in range(depth)]
        self._final = p["final_norm"]
        self._score = p["head"]["score_head"]
        del num_heads  # layout is shape-driven; kept for signature parity

    def _forward(self, obs: np.ndarray) -> np.ndarray:
        x = obs.astype(np.float32) @ self._embed["kernel"] + self._embed["bias"]
        for blk in self._blocks:
            h = _layer_norm(x, blk["LayerNorm_0"])
            x = x + _mha(h, blk["MultiHeadDotProductAttention_0"])
            h = _layer_norm(x, blk["LayerNorm_1"])
            h = _gelu(h @ blk["Dense_0"]["kernel"] + blk["Dense_0"]["bias"])
            x = x + h @ blk["Dense_1"]["kernel"] + blk["Dense_1"]["bias"]
        x = _layer_norm(x, self._final)
        return x @ self._score["kernel"][:, 0] + self._score["bias"][0]

    def decide_nodes(self, node_obs: np.ndarray) -> tuple[int, np.ndarray]:
        logits = self._forward(np.asarray(node_obs))
        return int(np.argmax(logits)), logits

    def decide_nodes_batch(
            self, batch_obs: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """graftfwd micro-batching: ONE stacked ``[k, N, F]`` forward ->
        ``(actions [k], logits [k, N])``. The forward is the same code
        as :meth:`decide_nodes` broadcast over the leading axis — the
        batched BLAS calls replace k GIL-contending single forwards
        (per-row agreement vs sequential is tolerance-tested; the
        bitwise batched guarantee lives on the AOT path)."""
        logits = self._forward(np.asarray(batch_obs))
        return np.argmax(logits, axis=-1), logits


class TorchSetBackend:
    """Set-transformer pointer forward mirrored into torch CPU tensors —
    the same function as :class:`NumpySetBackend` for users migrating
    from the RLlib/torch checkpoint world (BASELINE's "CPU/torch
    fallback"; the flat-MLP family's ``TorchMLPBackend`` counterpart).
    Variable node count for free, no jax dependency on the request path;
    agreement with the numpy forward is tolerance-tested in
    ``tests/test_extender.py``."""

    name = "torch"
    family = "set"

    def __init__(self, params_tree: dict, num_heads: int = 1,
                 depth: int = SET_DEPTH):
        import torch

        self._torch = torch
        # np.array(copy=True): jax leaves convert zero-copy read-only and
        # torch.from_numpy warns on non-writable memory.
        to_t = lambda tree: {
            k: (to_t(v) if isinstance(v, dict)
                else torch.from_numpy(np.array(v, np.float32)))
            for k, v in tree.items()
        }
        p = to_t(_np_tree(_params_subtree(params_tree)))
        self._embed = p["embed"]
        self._blocks = [p[f"block_{i}"] for i in range(depth)]
        self._final = p["final_norm"]
        self._score = p["head"]["score_head"]
        del num_heads  # layout is shape-driven; kept for signature parity

    def _layer_norm(self, x, p):
        mu = x.mean(-1, keepdim=True)
        var = ((x - mu) ** 2).mean(-1, keepdim=True)
        return (x - mu) / self._torch.sqrt(var + _LN_EPS) * p["scale"] \
            + p["bias"]

    def _mha(self, x, p):
        # Trailing-axis ops: one code path for [N, dim] and the
        # micro-batched [k, N, dim] (graftfwd), like the numpy twin.
        torch = self._torch
        wq, wk, wv = (p[n]["kernel"] for n in ("query", "key", "value"))
        dim, num_heads, head_dim = wq.shape
        fold = lambda w: w.reshape(dim, num_heads * head_dim)
        q = x @ fold(wq) + p["query"]["bias"].reshape(-1)
        k = x @ fold(wk) + p["key"]["bias"].reshape(-1)
        v = x @ fold(wv) + p["value"]["bias"].reshape(-1)
        scale = 1.0 / float(np.sqrt(head_dim))
        ctx = torch.empty_like(q)
        for h in range(num_heads):
            sl = slice(h * head_dim, (h + 1) * head_dim)
            scores = (q[..., sl] @ k[..., sl].transpose(-1, -2)) * scale
            ctx[..., sl] = torch.softmax(scores, dim=-1) @ v[..., sl]
        return ctx @ p["out"]["kernel"].reshape(num_heads * head_dim, dim) \
            + p["out"]["bias"]

    def _forward(self, obs):
        torch = self._torch
        gelu = torch.nn.functional.gelu  # approximate="tanh" = flax gelu
        x = obs @ self._embed["kernel"] + self._embed["bias"]
        for blk in self._blocks:
            h = self._layer_norm(x, blk["LayerNorm_0"])
            x = x + self._mha(h, blk["MultiHeadDotProductAttention_0"])
            h = self._layer_norm(x, blk["LayerNorm_1"])
            h = gelu(h @ blk["Dense_0"]["kernel"] + blk["Dense_0"]["bias"],
                     approximate="tanh")
            x = x + h @ blk["Dense_1"]["kernel"] + blk["Dense_1"]["bias"]
        x = self._layer_norm(x, self._final)
        return x @ self._score["kernel"][:, 0] + self._score["bias"][0]

    def decide_nodes(self, node_obs: np.ndarray) -> tuple[int, np.ndarray]:
        torch = self._torch
        with torch.no_grad():
            obs = torch.from_numpy(np.asarray(node_obs, np.float32))
            logits = self._forward(obs).numpy()
        return int(np.argmax(logits)), logits

    def decide_nodes_batch(
            self, batch_obs: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """graftfwd: one stacked ``[k, N, F]`` ATen forward (see the
        numpy twin's docstring)."""
        torch = self._torch
        with torch.no_grad():
            obs = torch.from_numpy(np.asarray(batch_obs, np.float32))
            logits = self._forward(obs).numpy()
        return np.argmax(logits, axis=-1), logits


class NativeSetBackend:
    """Set-transformer pointer forward in the C++ core
    (``native/set_infer.cpp``): one ctypes hop per decision, variable N,
    and — unlike the numpy forward — GIL-FREE for the call's duration
    (ctypes releases the GIL), so concurrent server threads genuinely run
    in parallel at sustained saturation."""

    name = "native"
    family = "set"

    def __init__(self, params_tree: dict, num_heads: int = 1,
                 depth: int = SET_DEPTH):
        from rl_scheduler_tpu.native import NativeSetTransformer

        del num_heads  # read from the param tree's head axis by pack_set
        self._net = NativeSetTransformer(params_tree, depth)

    def decide_nodes(self, node_obs: np.ndarray) -> tuple[int, np.ndarray]:
        return self._net.decide(np.asarray(node_obs, np.float32))

    def decide_nodes_batch(
            self, batch_obs: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """graftfwd: the C++ core scores rows one ctypes hop each —
        every hop GIL-free, so the loop still beats k threads contending
        on the GIL-holding paths (a batched C++ entry point would save
        only the per-hop microseconds)."""
        return _native_batch_rows(self._net, batch_obs)


def _native_batch_rows(net, batch_obs) -> tuple[np.ndarray, np.ndarray]:
    """Shared per-row batch loop for the C++ cores (fp32 and int8): one
    GIL-free ctypes hop per row into preallocated outputs."""
    batch = np.asarray(batch_obs, np.float32)
    actions = np.empty(batch.shape[0], np.int64)
    logits = np.empty(batch.shape[:2], np.float32)
    for i, obs in enumerate(batch):
        actions[i], logits[i] = net.decide(obs)
    return actions, logits


class Int8NativeSetBackend:
    """graftfwd lever (ii): the int8-quantized C++ fleet forward
    (``native/set_infer.cpp set_decide_int8`` — int8 dual-plane weights
    folded for the pmaddwd path, blocked attention, GIL-free). The fleet
    crossover says large-N scoring is bandwidth/layout-bound, which is
    what the narrower operands and the blocked j-walk attack: measured
    1.25x the numpy forward at N=1024 single-stream on the 1-core
    container (33.5 vs 41.9 ms), 3.3x the fp32 C++ core.

    Construction only does the math. ACTIVATION is gated: callers go
    through :func:`make_set_backend` (``--backend native-int8``), which
    runs ``fastpath.check_int8_agreement`` on the seeded corpus and
    REFUSES to serve below the 99.5% top-1 bar — a checkpoint that
    quantizes badly must fail loudly at startup (and at the rollout
    gate, ``ExtenderPolicy.fastpath_verify``), never degrade silently.
    ``quantization_scales`` is the recorded per-tensor scale list;
    ``agreement`` is stamped by the gate for /stats."""

    name = "native-int8"
    family = "set"

    def __init__(self, params_tree: dict, num_heads: int = 1,
                 depth: int = SET_DEPTH):
        from rl_scheduler_tpu.native import NativeSetTransformerInt8

        del num_heads  # read from the param tree's head axis by pack_set
        self._net = NativeSetTransformerInt8(params_tree, depth)
        self.quantization_scales = self._net.scales
        # Stamped by the startup gate (make_set_backend): the measured
        # agreement, plus the fp32 reference, obs width, and the gated
        # node counts so the rollout gate can RE-RUN the identical check
        # per promote (fastpath_verify).
        self.agreement: float | None = None
        self.reference = None
        self.node_feat: int | None = None
        self.agreement_node_counts: tuple = (8, 64)

    def decide_nodes(self, node_obs: np.ndarray) -> tuple[int, np.ndarray]:
        return self._net.decide(np.asarray(node_obs, np.float32))

    def decide_nodes_batch(
            self, batch_obs: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """One GIL-free C++ hop per row (see NativeSetBackend)."""
        return _native_batch_rows(self._net, batch_obs)


class JaxSetAOTBackend:
    """AOT-compiled set-transformer apply, one executable per node count.

    XLA specializes on N, and a kube-scheduler's candidate list varies per
    pod (affinity/taint pre-filters shrink it arbitrarily), so compiles
    MUST stay off the request path: a request for an uncached N serves the
    numpy forward (same function, tolerance-tested) while ONE background
    thread compiles that N; later requests pick up the executable. The
    cache is a bounded LRU (``max_cached`` executables, least-recently-
    used N evicted) so a high-variance fleet cannot grow it without
    bound. ``warm_counts`` pre-compiles at startup (synchronously) so the
    common fleet sizes are AOT from the first request.
    """

    name = "jax"
    family = "set"

    def __init__(self, params_tree: dict, num_heads: int = 1,
                 depth: int = SET_DEPTH, device: str = "cpu",
                 warm_counts: tuple = (8,), max_cached: int = 16,
                 node_feat: int | None = None,
                 warm_batches: tuple = ()):
        import collections

        import jax

        from rl_scheduler_tpu.env.cluster_set import NODE_FEAT
        from rl_scheduler_tpu.models.transformer import SetTransformerPolicy

        self._jax = jax
        # Scenario-trained checkpoints can widen the observation (the
        # heterogeneous family's multi-resource features); the AOT
        # executable's obs spec must match the trained width or the
        # warm compile raises at startup (checkpoint meta `node_feat`).
        self._node_feat = NODE_FEAT if node_feat is None else int(node_feat)
        self._net = SetTransformerPolicy(dim=SET_DIM, depth=depth,
                                         num_heads=num_heads)
        try:
            dev = jax.devices(device)[0]
        except RuntimeError:
            dev = jax.devices()[0]
        self._dev = dev
        self._params = jax.device_put(
            {"params": _params_subtree(params_tree)}, dev
        )
        self._fallback = NumpySetBackend(params_tree, num_heads, depth)
        self._compiled: collections.OrderedDict[int, object] = (
            collections.OrderedDict()
        )
        self._max_cached = max(max_cached, len(warm_counts) or 1)
        self._compiling: set[int] = set()
        # graftfwd micro-batching: AOT executables for stacked
        # [k, N, F] forwards, keyed (k, n) — jax.vmap of the SAME apply
        # the single path runs, so per-row logits are bitwise-identical
        # (pinned by test). Same bounded-LRU/background-compile
        # discipline as the single-obs cache.
        self._batch_compiled: collections.OrderedDict[tuple, object] = (
            collections.OrderedDict()
        )
        self._batch_compiling: set[tuple] = set()
        self._lock = threading.Lock()
        for n in warm_counts:
            self._compiled[n] = self._compile(n)
        for k, n in warm_batches:
            self._batch_compiled[(k, n)] = self._compile_batch(k, n)

    def _compile(self, n: int):
        import jax.numpy as jnp

        jax = self._jax

        def apply(params, obs):
            logits, _ = self._net.apply(params, obs)
            return logits

        obs_spec = jax.ShapeDtypeStruct((n, self._node_feat), jnp.float32)
        params_spec = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), self._params
        )
        with jax.default_device(self._dev):
            fn = jax.jit(apply).lower(params_spec, obs_spec).compile()
        # Warm the dispatch path so the first live request is not cold.
        np.asarray(fn(self._params,
                      np.zeros((n, self._node_feat), np.float32)))
        return fn

    def _compile_in_background(self, n: int) -> None:
        try:
            fn = self._compile(n)
            with self._lock:
                self._compiled[n] = fn
                while len(self._compiled) > self._max_cached:
                    evicted, _ = self._compiled.popitem(last=False)
                    logger.info("evicted AOT set executable for N=%d (LRU, "
                                "cache cap %d)", evicted, self._max_cached)
        except Exception:  # compile failure must not take serving down
            logger.exception("background AOT compile for N=%d failed; "
                             "numpy forward keeps serving that size", n)
        finally:
            with self._lock:
                self._compiling.discard(n)

    def decide_nodes(self, node_obs: np.ndarray) -> tuple[int, np.ndarray]:
        obs = np.asarray(node_obs, np.float32)
        n = obs.shape[0]
        kick = False
        with self._lock:
            fn = self._compiled.get(n)
            if fn is not None:
                self._compiled.move_to_end(n)  # LRU freshness
            elif n not in self._compiling:
                self._compiling.add(n)
                kick = True
        if fn is not None:
            logits = np.asarray(fn(self._params, obs))
            return int(np.argmax(logits)), logits
        if kick:
            try:
                threading.Thread(
                    target=self._compile_in_background, args=(n,), daemon=True
                ).start()
            except RuntimeError:  # thread exhaustion: retry on a later request
                with self._lock:
                    self._compiling.discard(n)
        # Uncached N: the numpy forward answers NOW (tolerance-tested same
        # function); the executable takes over once the compile lands.
        return self._fallback.decide_nodes(obs)

    # ------------------------------------------------- graftfwd batching

    def _compile_batch(self, k: int, n: int):
        import jax.numpy as jnp

        jax = self._jax

        def apply(params, obs):
            logits, _ = self._net.apply(params, obs)
            return logits

        obs_spec = jax.ShapeDtypeStruct((k, n, self._node_feat),
                                        jnp.float32)
        params_spec = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), self._params
        )
        with jax.default_device(self._dev):
            fn = (jax.jit(jax.vmap(apply, in_axes=(None, 0)))
                  .lower(params_spec, obs_spec).compile())
        np.asarray(fn(self._params,
                      np.zeros((k, n, self._node_feat), np.float32)))
        return fn

    def _compile_batch_in_background(self, k: int, n: int) -> None:
        try:
            fn = self._compile_batch(k, n)
            with self._lock:
                self._batch_compiled[(k, n)] = fn
                while len(self._batch_compiled) > self._max_cached:
                    evicted, _ = self._batch_compiled.popitem(last=False)
                    logger.info("evicted AOT batch executable for %s (LRU, "
                                "cache cap %d)", evicted, self._max_cached)
        except Exception:  # compile failure must not take serving down
            logger.exception("background AOT batch compile for (%d, %d) "
                             "failed; the host batch forward keeps serving "
                             "that shape", k, n)
        finally:
            with self._lock:
                self._batch_compiling.discard((k, n))

    def warm_batch_async(self, k: int, n: int) -> None:
        """Kick ONE background compile of the ``[k, n, F]`` batch
        executable if it is neither live nor in flight — the seam the
        load-aware router uses so host-served batch shapes graduate to
        the AOT path without ever stalling a window."""
        with self._lock:
            if ((k, n) in self._batch_compiled
                    or (k, n) in self._batch_compiling):
                return
            self._batch_compiling.add((k, n))
        try:
            threading.Thread(
                target=self._compile_batch_in_background, args=(k, n),
                daemon=True,
            ).start()
        except RuntimeError:  # thread exhaustion: retry on a later batch
            with self._lock:
                self._batch_compiling.discard((k, n))

    def decide_nodes_batch(
            self, batch_obs: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """graftfwd: ONE ``[k, N, F]`` AOT forward — ``jax.vmap`` of the
        single-request apply, bitwise-identical per row (pinned by
        test). An uncompiled (k, n) answers from the numpy batch forward
        while a background compile runs, like the single-obs path."""
        batch = np.asarray(batch_obs, np.float32)
        k, n = batch.shape[0], batch.shape[1]
        with self._lock:
            fn = self._batch_compiled.get((k, n))
            if fn is not None:
                self._batch_compiled.move_to_end((k, n))
        if fn is not None:
            logits = np.asarray(fn(self._params, batch))
            return np.argmax(logits, axis=-1), logits
        self.warm_batch_async(k, n)
        return self._fallback.decide_nodes_batch(batch)

    def has_batch_executable(self, k: int, n: int) -> bool:
        with self._lock:
            return self._batch_compiled.get((k, n)) is not None

    def has_executable(self, n: int) -> bool:
        """True when an AOT executable for this node count is live. The
        latency-aware router only attributes timings to the AOT path for
        calls that actually dispatched it — a compiling-fallback call is
        the numpy forward and must not read as tunnel degradation."""
        with self._lock:
            return self._compiled.get(n) is not None


class LoadAwareSetBackend:
    """Set-family ``jax`` flag: AOT dispatcher with native/numpy overflow.

    The same load-aware routing as the MLP family's
    ``LoadAwareJaxBackend`` (see its docstring for the measured GIL
    mechanics): up to ``max_concurrent_jax`` requests use the AOT
    executable (fastest single-stream); overflow concurrency routes by
    node count at the measured crossovers: the C++ set core up to
    ``NATIVE_OVERFLOW_MAX_N`` — GIL-FREE, so overflow decisions execute
    truly in parallel (soak p50 0.46 ms vs 3.3 ms with the numpy-only
    overflow) — numpy/BLAS in the mid range (its matmuls beat the C++
    loops there and release the GIL themselves), and torch's fused CPU
    kernels from ``TORCH_OVERFLOW_MIN_N`` up (3.6x numpy at N >= 1024,
    single-threaded; ATen releases the GIL too). Numpy serves all sizes
    when the native toolchain / torch are missing.

    Large node sets route the PRIMARY path too (round 5, VERDICT r4
    item 2): at N > ``NATIVE_OVERFLOW_MAX_N`` a request that arrives
    while any other decision is in flight goes straight to numpy/BLAS
    instead of the AOT dispatcher. Under sustained saturation the mixed
    AOT+overflow traffic GIL-churns — measured 7.4 ms p50 at N=100
    @8-way vs 1.4 ms on the uniform numpy path — so under concurrency
    the backend serves the uniform path itself rather than asking the
    operator to switch flags; single-stream large-N requests still take
    the AOT executable (0.87 vs 1.14 ms single-stream at N=100).

    The AOT path is also LATENCY-AWARE per node count (round 5): the
    dispatch rides a tunnel whose round-trip is pool-dependent (measured
    sub-ms in quiet windows, 100+ ms under pool contention) while the
    host forwards are deterministic, so the backend tracks a latency
    EWMA of both paths per N and demotes the AOT dispatch once it runs
    ``ADAPTIVE_MARGIN`` x worse than the host path — serving host-side
    with 1-in-``ADAPTIVE_PROBE_EVERY`` recovery probes, so a recovered
    pool promotes AOT back with no operator action.

    Decisions agree between the paths at the tested tolerance (logits
    ~1e-4/2e-5), so shedding is invisible to the scheduler. Shedding only
    applies when the AOT path serves from host XLA-CPU — for an
    accelerator serve device the overflow path is disabled rather than
    serving inconsistently (same rule as the MLP family).
    """

    name = "jax"
    family = "set"

    def __init__(self, params_tree: dict, num_heads: int = 1,
                 device: str = "cpu", max_concurrent_jax: int = 2,
                 warm_counts: tuple = (8,), node_feat: int | None = None):
        self._jax = JaxSetAOTBackend(params_tree, num_heads, device=device,
                                     warm_counts=warm_counts,
                                     node_feat=node_feat)
        if device != "cpu":
            logger.info(
                "load-aware shedding disabled for serve device %r (the host "
                "overflow forward diverges too far from it for tested "
                "decision agreement)", device
            )
            max_concurrent_jax = float("inf")
            self._overflow_native = self._overflow_numpy = None
            self._overflow_torch = None
            overflow_label = "-"
        else:
            # Overflow routes by node count at the measured crossover:
            # the C++ core wins below ~N=20 (0.16 vs 0.38 ms at N=8, and
            # GIL-free under thread pressure); numpy/BLAS wins above
            # (0.96 vs 2.93 ms at N=100 — BLAS matmuls dominate there
            # and release the GIL themselves).
            self._overflow_numpy = NumpySetBackend(params_tree, num_heads)
            try:
                self._overflow_native = NativeSetBackend(params_tree,
                                                         num_heads)
                overflow_label = "the native set core / numpy / torch (by N)"
            except Exception as e:  # noqa: BLE001 - missing toolchain/.so
                logger.info("native set overflow unavailable (%s); numpy", e)
                self._overflow_native = None
                overflow_label = "the numpy / torch set forward (by N)"
            try:
                # Fleet-giant node sets: torch's fused CPU kernels beat
                # the numpy forward from N ~192 up (measured single-
                # stream, 1-core host: 1.87 vs 2.24 ms at N=192, 9.4 vs
                # 33.5 ms at N=1024 — same ~3.6x at N=2048), and ATen
                # ops release the GIL like BLAS does.
                self._overflow_torch = TorchSetBackend(params_tree,
                                                       num_heads)
            except Exception as e:  # noqa: BLE001 - torch missing
                logger.info("torch set overflow unavailable (%s); numpy "
                            "serves large node sets", e)
                self._overflow_torch = None
        self._gate = ShedGate(max_concurrent_jax,
                              primary="set jax dispatcher",
                              overflow=overflow_label)
        self._tracker = ConcurrencyTracker()   # shared impl (policy_backend)
        # Adaptive routing state (see the ADAPTIVE_* constants): the
        # shared router keyed on node count (policy_backend.py — one
        # implementation for both serving families).
        self._adaptive = AdaptiveLatencyRouter(
            label="AOT set dispatch",
            alpha=self.ADAPTIVE_ALPHA,
            margin=self.ADAPTIVE_MARGIN,
            probe_every=self.ADAPTIVE_PROBE_EVERY,
            min_samples=self.ADAPTIVE_MIN_SAMPLES,
            max_tracked=self.ADAPTIVE_MAX_TRACKED_N,
        )
        self._seed_lock = threading.Lock()
        self._seeding = set()                  # n values mid host-seed

    NATIVE_OVERFLOW_MAX_N = 20  # measured single-stream crossover
    # numpy -> torch crossover for the host forwards (measured: numpy
    # wins to ~160, torch from ~192 — and by 3.6x at N >= 1024).
    TORCH_OVERFLOW_MIN_N = 192
    # Latency-aware demotion (per node count): the AOT dispatch rides a
    # tunnel whose round-trip is pool-dependent — measured sub-ms in
    # quiet windows and 100+ ms under pool contention, while the host
    # forwards are deterministic. Track an EWMA of each path's decide
    # latency per N; once the AOT path has ADAPTIVE_MIN_SAMPLES and its
    # EWMA exceeds ADAPTIVE_MARGIN x the host path's, route single-stream
    # traffic host-side and keep probing 1-in-ADAPTIVE_PROBE_EVERY
    # requests through AOT so recovery promotes it back automatically.
    # Values aliased from the shared router so both serving families
    # tune from ONE source of truth (policy_backend.AdaptiveLatencyRouter).
    ADAPTIVE_ALPHA = AdaptiveLatencyRouter.ALPHA
    ADAPTIVE_MARGIN = AdaptiveLatencyRouter.MARGIN
    ADAPTIVE_PROBE_EVERY = AdaptiveLatencyRouter.PROBE_EVERY
    ADAPTIVE_MIN_SAMPLES = AdaptiveLatencyRouter.MIN_SAMPLES
    # Bound on tracked node counts (same rationale as the AOT executable
    # LRU: a kube-scheduler's candidate-list size varies per pod, so
    # per-N state must not grow without bound). Oldest-tracked evicts.
    ADAPTIVE_MAX_TRACKED_N = AdaptiveLatencyRouter.MAX_TRACKED
    # After concurrency is observed, large-N requests stay on the uniform
    # numpy path for this long even if in-flight momentarily drops to 0:
    # under a sustained 8-way bench the pool's arrival gaps let single
    # requests slip onto the AOT path and re-mix the traffic (measured
    # 1.4 vs 1.1 ms p50 residual vs the pure-numpy flag without the
    # cooldown at N=100 @8-way).
    CONCURRENT_COOLDOWN_S = 0.25

    def _overflow_for(self, n: int):
        if (self._overflow_native is not None
                and n <= self.NATIVE_OVERFLOW_MAX_N):
            return self._overflow_native
        if (self._overflow_torch is not None
                and n >= self.TORCH_OVERFLOW_MIN_N):
            return self._overflow_torch
        return self._overflow_numpy

    @property
    def shed_fraction(self) -> float:
        return self._gate.shed_fraction

    @property
    def reroute_fraction(self) -> float:
        """Fraction of routing decisions the latency router sent host-
        side — separate from ``shed_fraction`` (overload): on a degraded
        pool, rerouting is the healthy steady state and must not
        saturate the overload metric."""
        return self._adaptive.reroute_fraction

    @property
    def _lat(self) -> dict:
        """The router's EWMA tables (kept as an attribute-shaped view —
        tests and debugging tooling read/seed it directly)."""
        return self._adaptive.lat

    def _observe_latency(self, path: str, n: int, ms: float) -> None:
        self._adaptive.observe(path, n, ms)

    def _host_decide(self, node_obs: np.ndarray,
                     record: bool = True) -> tuple[int, np.ndarray]:
        """Serve from the host path for this N. ``record=False`` for
        calls made under concurrency: queued/contended wall times would
        inflate the host EWMA and mask real AOT degradation, so only
        single-stream samples feed the comparison — including calls
        that were single-stream at ENTRY but got joined mid-call."""
        n = len(node_obs)
        t0m = time.monotonic()
        t0 = time.perf_counter()
        out = self._overflow_for(n).decide_nodes(node_obs)
        if record and self._tracker.clean_since(t0m):
            self._observe_latency("host", n,
                                  (time.perf_counter() - t0) * 1e3)
        return out

    def _aot_route(self, n: int) -> tuple[bool, bool]:
        """``(route_aot, is_probe)`` for single-stream traffic at this N
        (the shared router's decision — see ``AdaptiveLatencyRouter``)."""
        return self._adaptive.route_aot(n)

    def _refund_probe(self, n: int) -> None:
        self._adaptive.refund_probe(n)

    def decide_nodes(self, node_obs: np.ndarray) -> tuple[int, np.ndarray]:
        if self._overflow_numpy is None:
            # Accelerator serve device: no host overflow paths, no routing.
            return self._jax.decide_nodes(node_obs)
        joined = self._tracker.enter()
        concurrent = (joined
                      or time.monotonic() - self._tracker.last_concurrent
                      < self.CONCURRENT_COOLDOWN_S)
        try:
            if concurrent and len(node_obs) > self.NATIVE_OVERFLOW_MAX_N:
                # Large-N under concurrency: serve the uniform host path
                # directly (see class docstring — mixing AOT dispatches
                # with overflow forwards GIL-churns to ~7 ms p50 at N=100
                # @8-way, while the uniform path holds ~1.4 ms). numpy
                # through the mid range, torch from the measured
                # fleet-giant crossover. Recorded as shed traffic so
                # shed_fraction/logs cover this route.
                log_line = self._gate.record_shed(
                    f"concurrent large-N ({len(node_obs)} nodes)"
                )
                if log_line:
                    logger.info("%s", log_line)
                return self._host_decide(node_obs, record=False)
            n = len(node_obs)
            route_aot, is_probe = self._aot_route(n)
            if not route_aot:
                # The host forward measures faster at this N right now
                # (latency EWMA, class docstring). Router-counted as a
                # reroute — NOT overload shed: on a degraded pool this
                # is the healthy steady state and must not saturate
                # shed_fraction. The one-time demotion warning is the
                # operator signal.
                return self._host_decide(node_obs, record=not concurrent)
            take_jax, log_line = self._gate.admit()
            if not take_jax:
                if log_line:
                    logger.info("%s", log_line)
                if is_probe:
                    # The probe never reached the AOT path; hand it back
                    # or sustained concurrency starves recovery.
                    self._refund_probe(n)
                # Gate-shed implies another decision in flight: don't
                # record the contended wall time.
                return self._host_decide(node_obs, record=False)
            try:
                with self._seed_lock:
                    # Seed only single-stream: a contended seed sample
                    # would become a permanently inflated host baseline
                    # (it is rarely updated later) and mask degradation.
                    need_seed = (not concurrent
                                 and not self._adaptive.host_known(n)
                                 and n not in self._seeding)
                    if need_seed:
                        self._seeding.add(n)
                if need_seed:
                    # First request at this N: seed the host EWMA with a
                    # synchronous host forward so the AOT comparison has
                    # a baseline. One UNTIMED warmup first — the first
                    # call pays lazy-init (torch kernel setup measured 2x
                    # its steady state at N=1024), which would bias the
                    # baseline against demotion. Costs two extra host
                    # forwards, once per N per process.
                    try:
                        self._overflow_for(n).decide_nodes(node_obs)
                        self._host_decide(node_obs)
                    finally:
                        with self._seed_lock:
                            self._seeding.discard(n)
                # Attribute the timing to the AOT path only when the
                # executable will actually serve it — the compiling-
                # window fallback is the numpy forward, and counting it
                # would false-demote a healthy AOT path at exactly the
                # Ns that compile on demand.
                served_aot = self._jax.has_executable(n)
                t0m = time.monotonic()
                t0 = time.perf_counter()
                out = self._jax.decide_nodes(node_obs)
                if (not concurrent and served_aot
                        and self._tracker.clean_since(t0m)):
                    self._observe_latency("aot", n,
                                          (time.perf_counter() - t0) * 1e3)
                elif is_probe and not served_aot:
                    # The probe never reached the executable (still
                    # compiling — the cheap fallback served): hand it
                    # back so recovery isn't starved. A probe that RAN
                    # the dispatch but whose timing was contaminated is
                    # NOT refunded — it paid the degraded latency, and
                    # refunding would make sustained concurrency probe
                    # near-continuously.
                    self._refund_probe(n)
                return out
            finally:
                self._gate.release()
        finally:
            self._tracker.exit()

    def decide_nodes_batch(
            self, batch_obs: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """graftfwd micro-batching through the load-aware flag: the
        batched AOT executable when it is live, else the host batch
        forward (torch from the fleet-giant crossover, numpy below)
        while a background compile graduates the shape — a batch exists
        BECAUSE of concurrency, so the uniform host path is the right
        fallback for exactly the reason single large-N requests shed
        under load."""
        batch = np.asarray(batch_obs, np.float32)
        k, n = batch.shape[0], batch.shape[1]
        if self._overflow_numpy is None:
            # Accelerator serve device: no host paths, no routing.
            return self._jax.decide_nodes_batch(batch)
        if self._jax.has_batch_executable(k, n):
            return self._jax.decide_nodes_batch(batch)
        self._jax.warm_batch_async(k, n)
        host = (self._overflow_torch
                if (self._overflow_torch is not None
                    and n >= self.TORCH_OVERFLOW_MIN_N)
                else self._overflow_numpy)
        return host.decide_nodes_batch(batch)


def make_set_backend(backend: str, params_tree: dict, num_heads: int = 1,
                     device: str = "cpu", warm_counts: tuple = (8,),
                     node_feat: int | None = None):
    """Build a set-family backend for the extender's ``--backend`` flag.

    ``jax`` -> load-aware AOT (per-N executable cache, native/numpy
    overflow); ``native`` -> the C++ core (``native/set_infer.cpp``,
    GIL-free, degrades to numpy when the toolchain/.so is missing);
    ``native-int8`` -> the quantized C++ fleet forward (graftfwd),
    GATED: the seeded-corpus top-1 agreement vs fp32 must clear the
    99.5% bar or construction RAISES — an operator who asked for the
    quantized path must not silently serve something else (no fallback,
    unlike ``native``); ``cpu`` -> numpy; ``torch`` -> the torch CPU
    mirror (degrades to numpy if torch is unavailable). ``greedy`` is
    handled by the caller.
    ``warm_counts`` pre-compiles the jax flag's AOT executables for
    those node counts at startup (``--warm-nodes``; fleet deployments
    warm their actual N so the first request is never answered by the
    overflow forward while a background compile runs). Returns
    ``(backend_obj, fallback_used: bool)`` like ``make_backend``.
    """
    if backend == "native-int8":
        from rl_scheduler_tpu.scheduler.fastpath import (
            INT8_AGREEMENT_MIN,
            check_int8_agreement,
        )

        if node_feat is None:
            from rl_scheduler_tpu.env.cluster_set import NODE_FEAT

            node_feat = NODE_FEAT
        try:
            q8 = Int8NativeSetBackend(params_tree, num_heads)
        except Exception as e:  # toolchain/.so missing: the operator
            # named the quantized path — refuse, never serve another one
            raise ValueError(
                f"--backend native-int8: the quantized C++ core is "
                f"unavailable ({e}); build the native toolchain or drop "
                "the flag") from e
        reference = NumpySetBackend(params_tree, num_heads)
        # The corpus must sample the node counts this deployment SERVES,
        # not just small sets: quantization noise flips top-1 most among
        # the near-tied candidates of a fleet-size N, and warm_counts is
        # exactly the declared serving-N list (checkpoint training N /
        # --warm-nodes). 8 and 64 stay as the small-set floor.
        gate_counts = tuple(sorted(
            {8, 64} | {int(n) for n in (warm_counts or ())}))
        agreement, ok = check_int8_agreement(q8, reference, int(node_feat),
                                             node_counts=gate_counts)
        if not ok:
            raise ValueError(
                f"--backend native-int8: measured top-1 agreement "
                f"{agreement:.4f} vs fp32 on the seeded corpus is below "
                f"the {INT8_AGREEMENT_MIN:.3f} activation gate — this "
                "checkpoint quantizes badly; refusing to serve the "
                "quantized forward (docs/serving.md)")
        q8.agreement = agreement
        q8.reference = reference
        q8.node_feat = int(node_feat)
        q8.agreement_node_counts = gate_counts
        logger.info("int8 native fleet forward armed: top-1 agreement "
                    "%.4f on the seeded corpus at N=%s (gate %.3f)",
                    agreement, list(gate_counts), INT8_AGREEMENT_MIN)
        return q8, False
    if backend == "torch":
        try:
            return TorchSetBackend(params_tree, num_heads), False
        except Exception as e:  # noqa: BLE001 - torch missing/import error
            logger.warning("torch set backend unavailable (%s); using cpu", e)
            backend = "cpu"
    if backend == "native":
        try:
            return NativeSetBackend(params_tree, num_heads), False
        except Exception as e:  # noqa: BLE001 - any build/load failure
            logger.warning("native set backend unavailable (%s); using cpu", e)
            backend = "cpu"
    try:
        if backend == "jax":
            return LoadAwareSetBackend(params_tree, num_heads, device=device,
                                       warm_counts=warm_counts,
                                       node_feat=node_feat), False
        return NumpySetBackend(params_tree, num_heads), False
    except Exception:
        from rl_scheduler_tpu.scheduler.policy_backend import GreedyBackend

        logger.exception(
            "set backend %r failed to initialize; falling back to greedy",
            backend,
        )
        return GreedyBackend(), True
