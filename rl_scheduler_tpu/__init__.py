"""rl_scheduler_tpu — TPU-native RL framework for multi-cloud Kubernetes scheduling.

A ground-up JAX/XLA re-design of the capabilities of
``saikumar955078/rl-k8s-scheduler`` (see SURVEY.md): the reference's CSV-replay
cluster simulator becomes a pure-functional, vmappable environment; its Ray
RLlib PPO (plus a DQN variant) become fused jit-compiled rollout+update loops;
its empty scheduler-extender stub becomes a real serving path.

Layout
------
- ``data/``      — synthetic trace generation, normalization, device loaders
- ``env/``       — functional env core, vectorized env, Gymnasium adapter
- ``models/``    — policy zoo: MLP, permutation-invariant transformer, GNN
- ``ops/``       — GAE, losses, returns (lax.scan / pallas)
- ``agent/``     — PPO / DQN trainers, presets, evaluation
- ``parallel/``  — mesh construction, shard_map data/tensor parallel layers
- ``scheduler/`` — k8s scheduler-extender server + backends
- ``utils/``     — checkpointing (orbax), metrics, profiling
"""

__version__ = "0.1.0"
