"""Batch-minor set-transformer apply: the fast config-4 training path.

WHY (round-3 finding, superseding the round-2 diagnosis): on the bench
TPU the per-XLA-op cost of this policy's many small tensors dominates —
honest device-time measurement (window-slope, see ``docs/status.md``)
puts the flax ``SetTransformerPolicy`` minibatch fwd+bwd at ~17 ms
against a ~0.5 ms matmul / ~1.6 ms traffic-inclusive roofline
(arithmetic in ``docs/roofline.md``: the residual ~5x over the achieved
8.7 ms is the measured per-op overhead floor of XLA on these
[8, 64, B] shapes). The round-2 Pallas lane-slice kernel suite measured
~48 ms on the same body and was deleted in round 4 after a final regime
search (single-head-only, loses 3.2x at N=8, fails to compile at N=16 —
negative-result note in docs/status.md row 4; code in git history). The round-2
numbers that motivated those kernels were taken with
``jax.block_until_ready``, which does NOT synchronize on this backend;
measured honestly, the win comes from a cheaper *formulation*, not a
different *dispatch strategy*.

HOW: every activation lives as ``[N, D, B]`` with the batch in the
minor-most (lane) dimension. The batch-major layouts (``[B, N, D]``
activations, ``[B, N, N]`` attention scores) put 8- and 64-wide dims in
lanes, so each of the ~65 ops in the body pads its trailing dim to the
128-lane tile and pays relayout/padding traffic; batch-minor tensors
are perfectly lane-aligned at every step. Combined with bfloat16 block
compute this measures ~2x faster per minibatch than the flax module
(8.7 ms vs 16.8 ms fwd+bwd+adam, slope-timed on the round-3 bench
chip).

Numerics: the same function as ``SetTransformerPolicy(num_heads=1)``
(flax LayerNorm fast-variance semantics, eps 1e-6, approximate gelu) up
to float reassociation — the chunked attention sums reductions in a
different order, so float32 parity is tolerance-level (within the
rtol/atol 1e-5 asserted by ``tests/test_set_fast.py``), not bitwise. The parameter tree is the flax
module's own, so checkpoints trained here serve and
evaluate everywhere a ``SetTransformerPolicy`` checkpoint does
(reference parity anchor: the policy the reference trains/serves is one
network regardless of backend — ``rl_scheduler/agent/train_ppo.py`` /
``final_evaluation.py``).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from jax import lax

_LN_EPS = 1e-6


def _validate_single_head(params: dict, who: str, flag: str) -> None:
    """Reject multi-head parameter trees with an actionable message
    instead of failing deep inside an einsum/kernel (shared by the
    batch-minor and fused-block fast paths)."""
    qk = params["params"]["block_0"]["MultiHeadDotProductAttention_0"][
        "query"]["kernel"]
    if qk.ndim == 3 and qk.shape[1] != 1:
        raise ValueError(
            f"{who} is single-head; this parameter tree has "
            f"num_heads={qk.shape[1]} (query kernel {qk.shape}). "
            f"Re-train with num_heads=1 or drop {flag}."
        )


def _ln_feature(h: jnp.ndarray, ln: dict) -> jnp.ndarray:
    """flax ``nn.LayerNorm`` (fast variance) over the feature axis of a
    batch-minor ``[N, D, B]`` activation.

    Statistics and affine run in float32 regardless of the activation
    dtype — flax's ``nn.LayerNorm`` (f32 params, ``dtype=None``) promotes
    to f32 the same way, and eps 1e-6 is below bf16 resolution. The
    caller casts the result back to its compute dtype.
    """
    h = h.astype(jnp.float32)
    mean = h.mean(axis=1, keepdims=True)
    var = jnp.maximum((h * h).mean(axis=1, keepdims=True) - mean * mean, 0.0)
    inv = lax.rsqrt(var + _LN_EPS)
    return (h - mean) * inv * ln["scale"][None, :, None] + ln["bias"][None, :, None]


def _w2(leaf: jnp.ndarray) -> jnp.ndarray:
    """Squeeze the flax single-head DenseGeneral axis:
    ``[D, 1, D]`` (q/k/v) or ``[1, D, D]`` (out) -> ``[D, D]``."""
    if leaf.ndim == 3:
        if leaf.shape[0] == 1:
            return leaf.reshape(-1, leaf.shape[-1])
        if leaf.shape[1] == 1:
            return leaf.reshape(leaf.shape[0], -1)
    return leaf


def _proj(tree: dict, x: jnp.ndarray) -> jnp.ndarray:
    """Shared-weight per-node Dense on ``[N, D, B]``: one batched matmul
    over the node axis (weights ``[in, out]``, flax convention)."""
    w = _w2(tree["kernel"])
    return jnp.einsum("de,ndb->neb", w, x) + tree["bias"].reshape(-1)[None, :, None]


# Above this node count the attention scores run as batched matmuls
# (einsum over the feature axis) instead of the per-query-node chunk
# loop: the loop's VPU mul+reduce wins at tiny N (its [8,64]x[64,8]
# matmul alternative underfills the MXU and measured 3 ms/minibatch
# slower at N=8), but it unrolls O(N) chunks per block — at fleet N the
# [N,dim]x[dim,N] matmuls are MXU-shaped and the unrolled loop is the
# pathology (compile time and per-op overhead both O(N)).
CHUNKED_ATTN_MAX_N = 16


def _block(pb: dict, pb_f32: dict, h: jnp.ndarray, dim: int,
           attn_impl: str | None = None) -> jnp.ndarray:
    """One pre-LN transformer block, batch-minor.

    ``pb`` holds compute-dtype weights for the matmuls; ``pb_f32`` is the
    same block's float32 tree for the LayerNorms (see :func:`_ln_feature`).
    ``attn_impl``: ``"chunked"`` / ``"matmul"`` / None (auto by node
    count at :data:`CHUNKED_ATTN_MAX_N`).
    """
    attn = pb["MultiHeadDotProductAttention_0"]
    hn = _ln_feature(h, pb_f32["LayerNorm_0"]).astype(h.dtype)
    q = _proj(attn["query"], hn)
    k = _proj(attn["key"], hn)
    v = _proj(attn["value"], hn)
    scale = dim ** -0.5
    num_nodes = h.shape[0]
    if attn_impl is None:
        attn_impl = "chunked" if num_nodes <= CHUNKED_ATTN_MAX_N else "matmul"
    if attn_impl == "matmul":
        # Batched-matmul scores over the batch lanes: [N,N,B] materializes,
        # but each matmul is [N,dim]x[dim,N] per lane — MXU-shaped at
        # fleet N. Softmax in f32 over the key axis.
        s = jnp.einsum("ndb,mdb->nmb", q, k) * scale
        p = jax.nn.softmax(s.astype(jnp.float32), axis=1).astype(v.dtype)
        ctx = jnp.einsum("nmb,mdb->ndb", p, v)
    else:
        # Attention CHUNKED over query nodes: scores as elementwise
        # multiply + feature-axis reduction instead of
        # einsum('ndb,mdb->nmb'), which XLA lowers to B tiny batched
        # [N,dim]x[dim,N] matmuls — measured 3 ms/minibatch slower at
        # 32768x8x64 than these lane-shaped VPU reductions.
        outs = []
        for n in range(num_nodes):
            s_n = (q[n][None] * k).sum(axis=1) * scale   # [N(keys), B]
            p_n = jax.nn.softmax(s_n, axis=0)            # over the key axis
            outs.append((p_n[:, None, :] * v).sum(axis=0))  # [dim, B]
        ctx = jnp.stack(outs)
    h = h + _proj(attn["out"], ctx)
    m = _ln_feature(h, pb_f32["LayerNorm_1"]).astype(h.dtype)
    m = jnp.einsum("dh,ndb->nhb", pb["Dense_0"]["kernel"], m) \
        + pb["Dense_0"]["bias"][None, :, None]
    m = jax.nn.gelu(m)
    m = jnp.einsum("hd,nhb->ndb", pb["Dense_1"]["kernel"], m) \
        + pb["Dense_1"]["bias"][None, :, None]
    return h + m


def batch_minor_forward(
    params: dict,
    obs: jnp.ndarray,
    depth: int = 2,
    dim: int = 64,
    dtype: Any = None,
    attn_impl: str | None = None,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """``obs [B, N, F] -> (logits [B, N], value [B])``; internals batch-minor.

    ``dtype`` (e.g. ``jnp.bfloat16``) casts the embed/block compute;
    LayerNorm statistics and the pointer/value heads stay float32, the
    same contract as ``SetTransformerPolicy.dtype``. ``attn_impl``
    selects the attention formulation (see :func:`_block`; default auto
    by node count).
    """
    if attn_impl not in (None, "chunked", "matmul"):
        # Validate once at the entry point: a typo must not silently run
        # the chunk loop (the fleet-N pathology: 709 vs 420 ms/update
        # at N=64).
        raise ValueError(f"unknown attn_impl {attn_impl!r}; "
                         "use 'chunked', 'matmul', or None (auto)")
    p = params["params"]
    x = obs.astype(jnp.float32).transpose(1, 2, 0)      # [N, F, B]
    pc = p
    if dtype is not None:
        x = x.astype(dtype)
        pc = jax.tree.map(lambda l: l.astype(dtype), p)
    h = jnp.einsum("fd,nfb->ndb", pc["embed"]["kernel"], x) \
        + pc["embed"]["bias"][None, :, None]
    for i in range(depth):
        h = _block(pc[f"block_{i}"], p[f"block_{i}"], h, dim, attn_impl)
    h = h.astype(jnp.float32)
    h = _ln_feature(h, p["final_norm"])
    head = p["head"]
    logits = (jnp.einsum("do,ndb->nob", head["score_head"]["kernel"], h)[:, 0]
              + head["score_head"]["bias"][0])          # [N, B]
    pooled = h.mean(axis=0)                             # [D, B]
    v1 = jnp.tanh(
        jnp.einsum("de,db->eb", head["value_hidden"]["kernel"], pooled)
        + head["value_hidden"]["bias"][:, None]
    )
    value = (jnp.einsum("do,db->ob", head["value_head"]["kernel"], v1)[0]
             + head["value_head"]["bias"][0])           # [B]
    return logits.T, value


class BatchMinorSetPolicy:
    """Drop-in for ``SetTransformerPolicy`` (num_heads=1) computing the
    identical function in batch-minor layout — the config-4 training
    fast path (``train_ppo --fused-set``).

    ``init`` delegates to the flax module so parameter trees (and
    checkpoints) are identical; ``apply`` handles batched and unbatched
    obs like the flax module. Single-head only: multi-head checkpoints
    are rejected at apply time with an actionable message rather than
    failing deep inside an einsum.

    ``dtype`` defaults to ``None`` (float32 — bitwise the flax default,
    so default construction really is a drop-in); the train CLI passes
    ``jnp.bfloat16`` for the measured fast path.
    """

    num_heads = 1  # the train CLI's resume guard reads this

    def __init__(self, dim: int = 64, depth: int = 2, dtype: Any = None,
                 attn_impl: str | None = None):
        from rl_scheduler_tpu.models import SetTransformerPolicy

        self.inner = SetTransformerPolicy(dim=dim, depth=depth, num_heads=1)
        self.dim = dim
        self.depth = depth
        self.dtype = dtype
        self.attn_impl = attn_impl

    def init(self, key, obs):
        return self.inner.init(key, obs)

    def _validate(self, params):
        _validate_single_head(params, "BatchMinorSetPolicy", "--fused-set")

    def apply(self, params, obs):
        from rl_scheduler_tpu.models.heads import apply_with_optional_batch

        self._validate(params)
        return apply_with_optional_batch(
            lambda o: batch_minor_forward(params, o, self.depth, self.dim,
                                          self.dtype, self.attn_impl),
            obs,
        )


class FusedBlockSetPolicy:
    """Drop-in for ``SetTransformerPolicy`` (num_heads=1) running the
    whole-network fused Pallas kernel (``ops/pallas_set_block.py``) — the
    fleet-N training fast path (``train_ppo --fused-set-block``).

    Where :class:`BatchMinorSetPolicy` re-FORMULATES the network for
    XLA's per-op execution (the measured N=8 winner), this path re-
    DISPATCHES it: one kernel per forward/backward with every
    intermediate VMEM-resident, targeting the fleet shapes (N >= 32)
    where the [N, dim] tiles are MXU-shaped and the ~65-op XLA body pays
    an order of magnitude in per-op HBM traffic (docs/roofline.md,
    round-5 fleet rows). The kernel refuses non-fleet N at construction.

    ``init`` delegates to the flax module so parameter trees (and
    checkpoints) are identical; ``dtype`` selects the in-kernel matmul
    precision (``jnp.bfloat16`` for the perf recipe; LayerNorm stats,
    softmax, and heads stay f32 either way). Single-head only, like the
    batch-minor path.
    """

    num_heads = 1  # the train CLI's resume guard reads this

    def __init__(self, num_nodes: int, dim: int = 64, depth: int = 2,
                 dtype: Any = None, block_b: int | None = None,
                 interpret: bool | None = None):
        from rl_scheduler_tpu.models import SetTransformerPolicy
        from rl_scheduler_tpu.ops.pallas_set_block import make_fused_set_apply

        self.inner = SetTransformerPolicy(dim=dim, depth=depth, num_heads=1)
        self.num_nodes = num_nodes
        self.dim = dim
        self.depth = depth
        self.dtype = dtype  # compute dtype (mirrors the other policies)
        self._apply = make_fused_set_apply(
            num_nodes=num_nodes, dim=dim, depth=depth, block_b=block_b,
            interpret=interpret,
            compute_dtype=dtype if dtype is not None else jnp.float32,
        )

    def init(self, key, obs):
        return self.inner.init(key, obs)

    def apply(self, params, obs):
        _validate_single_head(params, "FusedBlockSetPolicy",
                              "--fused-set-block")
        return self._apply(params, obs)
