"""Graph neural network policy over cluster topology (BASELINE config 5).

Message passing over the cluster graph's (static) adjacency: each layer
mixes a node's own embedding with a degree-normalized aggregate of its
neighbors — the GCN rule ``H' = act(H W_self + Â H W_nbr)`` with
``Â = D^-1 A``. The adjacency is a dense ``[N, N]`` matrix (cluster graphs
are small and dense-ish), so aggregation is a plain matmul: MXU-shaped,
fuses with everything else under jit, and vmaps over thousands of envs.

The env's per-node features already include relational signals
(hops-to-affinity, degree), but the *policy* still needs message passing
to reason about neighborhood load ("the affinity node's neighbors are
saturated — place two hops out"), which pure per-node MLPs cannot see.

Heads mirror the set transformer: per-node pointer logits (permutation-
equivariant w.r.t. graph isomorphism) + mean-pooled value.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp
import numpy as np

from rl_scheduler_tpu.models.heads import (
    PointerActorCriticHead,
    apply_with_optional_batch,
)


class GraphConvLayer(nn.Module):
    dim: int
    dtype: Any = None  # compute dtype; params stay f32

    @nn.compact
    def __call__(self, h, norm_adj):  # h: [..., N, dim_in], norm_adj: [N, N]
        self_msg = nn.Dense(self.dim, dtype=self.dtype, name="w_self")(h)
        nbr = jnp.einsum("ij,...jd->...id", norm_adj.astype(h.dtype), h)
        nbr_msg = nn.Dense(self.dim, dtype=self.dtype, name="w_nbr")(nbr)
        return nn.relu(self_msg + nbr_msg)


class GNNPolicy(nn.Module):
    """Actor-critic GNN. The adjacency is a static module attribute (one
    topology per trained policy, like a CNN's geometry), passed as a plain
    numpy array so the module hashes/compares cleanly under jit.

    Input ``[B, N, feat]`` or ``[N, feat]``; returns
    ``(logits [B, N], value [B])``.
    """

    adjacency: tuple  # nested tuple form of the [N, N] 0/1 matrix
    dim: int = 64
    depth: int = 3
    dtype: Any = None  # compute dtype for embed/conv layers (heads stay f32)

    @staticmethod
    def from_adjacency(adj, dim: int = 64, depth: int = 3,
                       dtype: Any = None) -> "GNNPolicy":
        adj = np.asarray(adj, np.float32)
        return GNNPolicy(
            adjacency=tuple(tuple(float(x) for x in row) for row in adj),
            dim=dim,
            depth=depth,
            dtype=dtype,
        )

    @nn.compact
    def __call__(self, obs):
        adj = jnp.asarray(self.adjacency, jnp.float32)
        degree = jnp.maximum(adj.sum(axis=1, keepdims=True), 1.0)
        norm_adj = adj / degree  # D^-1 A
        head = PointerActorCriticHead(self.dim, name="head")

        def forward(batched_obs):
            h = nn.relu(nn.Dense(self.dim, dtype=self.dtype,
                                 name="embed")(batched_obs))
            for i in range(self.depth):
                h = GraphConvLayer(self.dim, self.dtype,
                                   name=f"conv_{i}")(h, norm_adj)
            return head(h.astype(jnp.float32))

        return apply_with_optional_batch(forward, obs)
