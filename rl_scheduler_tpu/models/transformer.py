"""Permutation-invariant set-transformer policy (BASELINE config 4).

Policy over a *set* of candidate nodes: the observation is
``[num_nodes, feat]`` with no meaningful node order, so the network uses
self-attention with NO positional encoding — outputs are permutation-
*equivariant* in the logits (per-node scores move with their node) and
permutation-*invariant* in the value (mean-pooled), which the tests assert
exactly.

TPU notes: attention over a handful of nodes is tiny; the win is that the
whole thing is dense matmul + softmax, fusing into the same XLA program as
the vmapped env and PPO update. ``dot_product_attention`` batches over
``[B, heads, N, d]`` — MXU-shaped, bfloat16-friendly. For large sets the
same module shards over the mesh via the sequence-parallel attention in
``parallel/ring_attention.py``.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from rl_scheduler_tpu.models.heads import (
    PointerActorCriticHead,
    apply_with_optional_batch,
)


class SelfAttentionBlock(nn.Module):
    """Pre-LN multi-head self-attention + MLP (standard transformer block,
    no positional anything)."""

    dim: int
    num_heads: int = 4
    mlp_ratio: int = 2

    @nn.compact
    def __call__(self, x):  # [..., N, dim]
        h = nn.LayerNorm()(x)
        h = nn.MultiHeadDotProductAttention(
            num_heads=self.num_heads, qkv_features=self.dim
        )(h, h)
        x = x + h
        h = nn.LayerNorm()(x)
        h = nn.Dense(self.dim * self.mlp_ratio)(h)
        h = nn.gelu(h)
        h = nn.Dense(self.dim)(h)
        return x + h


class SetTransformerPolicy(nn.Module):
    """Actor-critic over node sets.

    Input ``[B, N, feat]`` (or unbatched ``[N, feat]``); returns
    ``(logits [B, N], value [B])`` — one logit per candidate node
    (pointer-style head), value from the mean-pooled set embedding.
    """

    dim: int = 64
    depth: int = 2
    num_heads: int = 4

    @nn.compact
    def __call__(self, obs):
        head = PointerActorCriticHead(self.dim, name="head")

        def forward(batched_obs):
            x = nn.Dense(self.dim, name="embed")(batched_obs)  # [B, N, dim]
            for i in range(self.depth):
                x = SelfAttentionBlock(self.dim, self.num_heads, name=f"block_{i}")(x)
            x = nn.LayerNorm(name="final_norm")(x)
            return head(x)

        return apply_with_optional_batch(forward, obs)
