"""Permutation-invariant set-transformer policy (BASELINE config 4).

Policy over a *set* of candidate nodes: the observation is
``[num_nodes, feat]`` with no meaningful node order, so the network uses
self-attention with NO positional encoding — outputs are permutation-
*equivariant* in the logits (per-node scores move with their node) and
permutation-*invariant* in the value (mean-pooled), which the tests assert
exactly.

TPU notes: attention over a handful of nodes is tiny; the win is that the
whole thing is dense matmul + softmax, fusing into the same XLA program as
the vmapped env and PPO update. ``dot_product_attention`` batches over
``[B, heads, N, d]`` — MXU-shaped, bfloat16-friendly. For large sets the
same module shards over the mesh via the sequence-parallel attention in
``parallel/ring_attention.py``.
"""

from __future__ import annotations

from typing import Any, Callable

import flax.linen as nn
import jax.numpy as jnp

from rl_scheduler_tpu.models.heads import (
    PointerActorCriticHead,
    apply_with_optional_batch,
)


class SelfAttentionBlock(nn.Module):
    """Pre-LN multi-head self-attention + MLP (standard transformer block,
    no positional anything).

    ``attention_fn``: optional override for the attention inner — the
    sequence-parallel path injects ring attention here; ``None`` keeps
    flax's dense ``dot_product_attention``.

    ``num_heads`` defaults to 1: multi-head adds no measurable quality
    at dim 64 but its head-split tensors tax the fused PPO update on
    TPU — measured 3x slower end to end at 4096 envs x 8 nodes (162k vs
    495k env-steps/s) and still 1.7x slower at fleet N=64 (147k vs 252k,
    round-5 same-process A/B: head_dim-16 tensors stay layout-hostile
    even when the node axis fills the tiles). Raising it only makes
    sense with a wider dim where per-head subspaces earn their cost.
    """

    dim: int
    num_heads: int = 1
    mlp_ratio: int = 2
    attention_fn: Callable | None = None
    dtype: Any = None  # compute dtype; params stay f32

    @nn.compact
    def __call__(self, x):  # [..., N, dim]
        h = nn.LayerNorm()(x)
        attn_kwargs = {}
        if self.attention_fn is not None:
            attn_kwargs["attention_fn"] = self.attention_fn
        h = nn.MultiHeadDotProductAttention(
            num_heads=self.num_heads, qkv_features=self.dim,
            dtype=self.dtype, **attn_kwargs
        )(h, h)
        x = x + h
        h = nn.LayerNorm()(x)
        h = nn.Dense(self.dim * self.mlp_ratio, dtype=self.dtype)(h)
        h = nn.gelu(h)
        h = nn.Dense(self.dim, dtype=self.dtype)(h)
        return x + h


class SetTransformerPolicy(nn.Module):
    """Actor-critic over node sets.

    Input ``[B, N, feat]`` (or unbatched ``[N, feat]``); returns
    ``(logits [B, N], value [B])`` — one logit per candidate node
    (pointer-style head), value from the mean-pooled set embedding.

    ``axis_name``: set to a mesh axis name to run SEQUENCE-PARALLEL under
    ``shard_map`` — the node axis of ``obs`` sharded over that axis,
    params replicated. Attention goes through ring attention
    (``parallel/ring_attention.py``: K/V rotate over ICI with online
    softmax, exact result) and the value pool ``pmean``s over the axis;
    everything else (embed, LayerNorm, MLP, scores) is per-node and needs
    no communication. Parameter shapes are identical with/without
    ``axis_name``, so a single-chip checkpoint serves sharded and back.
    """

    dim: int = 64
    depth: int = 2
    num_heads: int = 1  # see SelfAttentionBlock: multi-head is a 3x slowdown
    axis_name: str | None = None
    # "flash": single-chip Pallas flash attention (ops/flash_attention.py)
    # — for N >= 1024 node sets where the dense [B, N, N] score tensor is
    # the memory wall; measured 5x SLOWER below it, so None (dense) is
    # the right default through fleet N (docs/scaling.md §3).
    attn_impl: str | None = None
    dtype: Any = None  # compute dtype for blocks (pointer/value heads stay f32)

    @nn.compact
    def __call__(self, obs):
        head = PointerActorCriticHead(
            self.dim, pool_axis_name=self.axis_name, name="head"
        )
        if self.attn_impl not in (None, "flash"):
            raise ValueError(
                f"unknown attn_impl {self.attn_impl!r}; use 'flash' or "
                "None (dense)"
            )
        attention_fn = None
        if self.axis_name is not None:
            if self.attn_impl is not None:
                raise ValueError(
                    "attn_impl and axis_name cannot combine: ring "
                    "attention owns the sharded node axis (drop one)"
                )
            from rl_scheduler_tpu.parallel.ring_attention import (
                make_flax_attention_fn,
            )

            attention_fn = make_flax_attention_fn(self.axis_name)
        elif self.attn_impl == "flash":
            from rl_scheduler_tpu.ops.flash_attention import (
                make_flax_flash_attention_fn,
            )

            attention_fn = make_flax_flash_attention_fn()

        def forward(batched_obs):
            x = nn.Dense(self.dim, dtype=self.dtype,
                         name="embed")(batched_obs)  # [B, N, dim]
            for i in range(self.depth):
                x = SelfAttentionBlock(
                    self.dim, self.num_heads,
                    attention_fn=attention_fn, dtype=self.dtype,
                    name=f"block_{i}",
                )(x)
            x = nn.LayerNorm(name="final_norm")(x)
            return head(x.astype(jnp.float32))

        return apply_with_optional_batch(forward, obs)
