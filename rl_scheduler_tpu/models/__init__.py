"""Policy zoo: MLP actor-critic, Q-network, set transformer, cluster GNN."""

from rl_scheduler_tpu.models.mlp import ActorCritic, QNetwork

__all__ = ["ActorCritic", "QNetwork"]
