"""Policy zoo: MLP actor-critic, Q-network, set transformer, cluster GNN."""

from rl_scheduler_tpu.models.mlp import ActorCritic, QNetwork
from rl_scheduler_tpu.models.transformer import SetTransformerPolicy
from rl_scheduler_tpu.models.gnn import GNNPolicy


def build_flat_policy_net(algo: str, num_actions: int, hidden: tuple):
    """The flat-obs network family for a checkpoint's ``algo`` meta key —
    the single source of truth shared by evaluation and serving (greedy
    argmax over the net's action scores is the decision either way)."""
    if algo == "dqn":
        return QNetwork(num_actions=num_actions, hidden=hidden)
    if algo == "ppo":
        return ActorCritic(num_actions=num_actions, hidden=hidden)
    raise ValueError(f"unknown algo {algo!r}; choose ppo|dqn")


__all__ = [
    "ActorCritic",
    "QNetwork",
    "SetTransformerPolicy",
    "GNNPolicy",
    "build_flat_policy_net",
]
