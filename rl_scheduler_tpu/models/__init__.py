"""Policy zoo: MLP actor-critic, Q-network, set transformer, cluster GNN."""

from rl_scheduler_tpu.models.mlp import ActorCritic, QNetwork
from rl_scheduler_tpu.models.transformer import SetTransformerPolicy
from rl_scheduler_tpu.models.gnn import GNNPolicy

__all__ = ["ActorCritic", "QNetwork", "SetTransformerPolicy", "GNNPolicy"]
