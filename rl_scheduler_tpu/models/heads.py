"""Shared actor-critic output head for set/graph policies.

Both the set transformer and the GNN end the same way: a per-node pointer
logit (permutation-equivariant) and a pooled value (invariant). One module
owns that contract so the two policies cannot silently diverge.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp
from jax import lax


class PointerActorCriticHead(nn.Module):
    """``[B, N, dim] -> (logits [B, N], value [B])``.

    Per-node scalar score from a shared Dense (pointer head, small init so
    initial policy is near-uniform); value from a tanh MLP over the
    mean-pooled node embeddings.

    ``pool_axis_name``: when the node axis is sharded over a mesh axis
    (sequence parallelism, ``parallel/ring_attention.py``), the value
    pool must average over the GLOBAL set — equal shards mean a ``pmean``
    of local means is exactly the global mean. Logits stay local (one
    score per local node; the caller's out-spec reassembles them).
    """

    dim: int = 64
    pool_axis_name: str | None = None

    @nn.compact
    def __call__(self, h):
        logits = nn.Dense(1, kernel_init=nn.initializers.orthogonal(0.01),
                          name="score_head")(h)[..., 0]
        pooled = h.mean(axis=-2)
        if self.pool_axis_name is not None:
            pooled = lax.pmean(pooled, self.pool_axis_name)
        v = nn.tanh(nn.Dense(self.dim, name="value_hidden")(pooled))
        value = nn.Dense(1, kernel_init=nn.initializers.orthogonal(1.0),
                         name="value_head")(v)[..., 0]
        return logits, value


def apply_with_optional_batch(module_fn, obs):
    """Run ``module_fn`` on ``[B, N, F]`` obs, squeezing an unbatched
    ``[N, F]`` input back to unbatched outputs."""
    squeeze = obs.ndim == 2
    if squeeze:
        obs = obs[None]
    logits, value = module_fn(obs)
    if squeeze:
        return logits[0], value[0]
    return logits, value
