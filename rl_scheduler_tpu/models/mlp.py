"""MLP policies (BASELINE configs 1-3).

The reference uses RLlib's default torch MLP (2x256 tanh, separate value
branch) over the 6-dim observation. These are the flax equivalents; at this
scale the matmuls are tiny, so everything fuses into one XLA program with the
env step — the win is structural (no Ray worker boundary), not per-matmul.
"""

from __future__ import annotations

from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp


class MLPTorso(nn.Module):
    """``dtype`` is the COMPUTE dtype (params stay f32): ``jnp.bfloat16``
    runs the torso matmuls on the MXU's native precision — the throughput
    lever for the big TPU presets; ``None`` keeps full f32."""

    hidden: Sequence[int] = (256, 256)
    activation: str = "tanh"
    dtype: Any = None

    @nn.compact
    def __call__(self, x):
        act = getattr(nn, self.activation)
        for h in self.hidden:
            x = act(nn.Dense(h, kernel_init=nn.initializers.orthogonal(jnp.sqrt(2)),
                             dtype=self.dtype)(x))
        return x


class ActorCritic(nn.Module):
    """Separate actor/critic torsos (RLlib PPO default: vf_share_layers=False).

    Returns ``(logits [..., num_actions], value [...])``. With ``dtype=
    jnp.bfloat16`` the torsos compute in bf16 while the output heads (and
    therefore log-probs and values, which feed the PPO ratios) stay f32.
    """

    num_actions: int = 2
    hidden: Sequence[int] = (256, 256)
    activation: str = "tanh"
    dtype: Any = None

    @nn.compact
    def __call__(self, obs):
        pi = MLPTorso(self.hidden, self.activation, self.dtype, name="actor_torso")(obs)
        logits = nn.Dense(
            self.num_actions, kernel_init=nn.initializers.orthogonal(0.01), name="actor_head"
        )(pi.astype(jnp.float32))
        v = MLPTorso(self.hidden, self.activation, self.dtype, name="critic_torso")(obs)
        value = nn.Dense(1, kernel_init=nn.initializers.orthogonal(1.0), name="critic_head")(
            v.astype(jnp.float32)
        )
        return logits, jnp.squeeze(value, -1)


class QNetwork(nn.Module):
    """Q-value MLP for DQN (BASELINE config 1: 2-layer MLP)."""

    num_actions: int = 2
    hidden: Sequence[int] = (64, 64)
    activation: str = "relu"

    @nn.compact
    def __call__(self, obs):
        x = MLPTorso(self.hidden, self.activation)(obs)
        return nn.Dense(self.num_actions, kernel_init=nn.initializers.orthogonal(1.0))(x)
