"""Synthetic multi-cloud price/latency trace generation.

Capability parity with the reference data generator
(``generate_real_pricing.py:1-18`` in the reference repo): 100 steps of
per-cloud cost drawn uniformly around public on-demand anchors (AWS t3.micro
$0.0104/hr, Azure B2s $0.0208/hr) and latency around 70ms/60ms. With the
default seed (42) and NumPy's global-RNG draw order, the output reproduces the
reference's shipped ``data/real_prices.csv`` / ``data/real_latencies.csv``
bit-for-bit, which the golden-value tests rely on.
"""

from __future__ import annotations

from pathlib import Path

import numpy as np
import pandas as pd

# Public on-demand pricing anchors (USD/hr) and latency anchors (ms).
AWS_COST_BASE = 0.0104     # AWS t3.micro
AZURE_COST_BASE = 0.0208   # Azure B2s
COST_JITTER = 0.001
AWS_LATENCY_BASE = 70.0
AZURE_LATENCY_BASE = 60.0
LATENCY_JITTER = 10.0
DEFAULT_STEPS = 100
DEFAULT_SEED = 42


def generate_prices(steps: int = DEFAULT_STEPS, rng: np.random.RandomState | None = None) -> pd.DataFrame:
    """Generate per-step cost traces for both clouds.

    Draw order matters for bit-parity with the reference: cost_aws first,
    then cost_azure, each as one vectorized uniform draw.
    """
    rng = rng or np.random.RandomState(DEFAULT_SEED)
    return pd.DataFrame(
        {
            "step": range(steps),
            "cost_aws": AWS_COST_BASE + rng.uniform(-COST_JITTER, COST_JITTER, steps),
            "cost_azure": AZURE_COST_BASE + rng.uniform(-COST_JITTER, COST_JITTER, steps),
        }
    )


def generate_latencies(prices: pd.DataFrame, rng: np.random.RandomState) -> pd.DataFrame:
    """Append latency columns to a price frame (same draw order as reference)."""
    steps = len(prices)
    df = prices.copy()
    df["latency_aws"] = AWS_LATENCY_BASE + rng.uniform(-LATENCY_JITTER, LATENCY_JITTER, steps)
    df["latency_azure"] = AZURE_LATENCY_BASE + rng.uniform(-LATENCY_JITTER, LATENCY_JITTER, steps)
    return df


def generate_all(
    out_dir: str | Path,
    steps: int = DEFAULT_STEPS,
    seed: int = DEFAULT_SEED,
) -> pd.DataFrame:
    """Generate and write ``real_prices.csv`` and ``real_latencies.csv``.

    Returns the combined frame (step, cost_aws, cost_azure, latency_aws,
    latency_azure).
    """
    out_dir = Path(out_dir)
    out_dir.mkdir(parents=True, exist_ok=True)
    rng = np.random.RandomState(seed)
    prices = generate_prices(steps, rng)
    prices.to_csv(out_dir / "real_prices.csv", index=False)
    full = generate_latencies(prices, rng)
    full.to_csv(out_dir / "real_latencies.csv", index=False)
    return full


def generate_load_history(
    out_path: str | Path,
    steps: int = 297,
    max_users: int = 50,
    seed: int = DEFAULT_SEED,
) -> pd.DataFrame:
    """Synthesize a Locust-style load-test history export.

    Capability parity with the reference's load-generator artifacts
    (``locustfile.py`` + ``data/local_*_load_stats_history.csv``): a user ramp
    to ``max_users``, per-user request rate ~0.5 req/s (1-3s wait between
    GETs), and response times that grow with load. Deterministic given seed.
    """
    rng = np.random.RandomState(seed)
    t = np.arange(steps)
    users = np.minimum(max_users, (t // 3) * 5).astype(np.int64)
    rps = users * rng.uniform(0.4, 0.6, steps)
    base_rt = 3.0 + 0.05 * users
    avg_rt = base_rt + rng.exponential(2.0, steps)
    df = pd.DataFrame(
        {
            "Timestamp": 1_765_110_856 + t,
            "User Count": users,
            "Requests/s": rps,
            "Total Average Response Time": avg_rt,
        }
    )
    out_path = Path(out_path)
    out_path.parent.mkdir(parents=True, exist_ok=True)
    df.to_csv(out_path, index=False)
    return df


if __name__ == "__main__":
    from rl_scheduler_tpu.data.loader import default_data_dir
    from rl_scheduler_tpu.data.loadtest import generate_load_stats

    df = generate_all(default_data_dir())
    counts = generate_load_stats(default_data_dir())
    print(f"Generated {len(df)} steps of price/latency data in {default_data_dir()}")
    print(f"Synthesized Locust exports (failures: {counts})")
