"""Synthetic multi-cloud price/latency trace generation.

Capability parity with the reference data generator
(``generate_real_pricing.py:1-18`` in the reference repo): 100 steps of
per-cloud cost drawn uniformly around public on-demand anchors (AWS t3.micro
$0.0104/hr, Azure B2s $0.0208/hr) and latency around 70ms/60ms. With the
default seed (42) and NumPy's global-RNG draw order, the output reproduces the
reference's shipped ``data/real_prices.csv`` / ``data/real_latencies.csv``
bit-for-bit, which the golden-value tests rely on.
"""

from __future__ import annotations

from pathlib import Path

import numpy as np
import pandas as pd

# Public on-demand pricing anchors (USD/hr) and latency anchors (ms).
AWS_COST_BASE = 0.0104     # AWS t3.micro
AZURE_COST_BASE = 0.0208   # Azure B2s
COST_JITTER = 0.001
AWS_LATENCY_BASE = 70.0
AZURE_LATENCY_BASE = 60.0
LATENCY_JITTER = 10.0
DEFAULT_STEPS = 100
DEFAULT_SEED = 42


def generate_prices(steps: int = DEFAULT_STEPS, rng: np.random.RandomState | None = None) -> pd.DataFrame:
    """Generate per-step cost traces for both clouds.

    Draw order matters for bit-parity with the reference: cost_aws first,
    then cost_azure, each as one vectorized uniform draw.
    """
    rng = rng or np.random.RandomState(DEFAULT_SEED)
    return pd.DataFrame(
        {
            "step": range(steps),
            "cost_aws": AWS_COST_BASE + rng.uniform(-COST_JITTER, COST_JITTER, steps),
            "cost_azure": AZURE_COST_BASE + rng.uniform(-COST_JITTER, COST_JITTER, steps),
        }
    )


def generate_latencies(prices: pd.DataFrame, rng: np.random.RandomState) -> pd.DataFrame:
    """Append latency columns to a price frame (same draw order as reference)."""
    steps = len(prices)
    df = prices.copy()
    df["latency_aws"] = AWS_LATENCY_BASE + rng.uniform(-LATENCY_JITTER, LATENCY_JITTER, steps)
    df["latency_azure"] = AZURE_LATENCY_BASE + rng.uniform(-LATENCY_JITTER, LATENCY_JITTER, steps)
    return df


def generate_all(
    out_dir: str | Path,
    steps: int = DEFAULT_STEPS,
    seed: int = DEFAULT_SEED,
) -> pd.DataFrame:
    """Generate and write ``real_prices.csv`` and ``real_latencies.csv``.

    Returns the combined frame (step, cost_aws, cost_azure, latency_aws,
    latency_azure).
    """
    out_dir = Path(out_dir)
    out_dir.mkdir(parents=True, exist_ok=True)
    rng = np.random.RandomState(seed)
    prices = generate_prices(steps, rng)
    prices.to_csv(out_dir / "real_prices.csv", index=False)
    full = generate_latencies(prices, rng)
    full.to_csv(out_dir / "real_latencies.csv", index=False)
    return full


def decaying_bursts(events: np.ndarray, magnitudes: np.ndarray,
                    decay: float) -> np.ndarray:
    """Exponentially-relaxing excursion level from a 0/1 event train —
    the shared spike shape (spot-price crunches here, load bursts in
    ``scenarios/families.py``). One implementation so the two spike
    processes cannot silently diverge."""
    level = 0.0
    out = np.zeros(len(events))
    for t in range(len(events)):
        level = level * decay + (magnitudes[t] if events[t] else 0.0)
        out[t] = level
    return out


def generate_price_spikes(
    steps: int = DEFAULT_STEPS,
    seed: int = DEFAULT_SEED,
    spike_prob: float = 0.04,
    spike_mult: float = 4.0,
    decay: float = 0.7,
    anti_correlated: bool = True,
) -> pd.DataFrame:
    """Price traces with seeded spot-market spike regimes (scenario family 4).

    The flat generator above draws i.i.d. jitter around the on-demand
    anchors; real spot markets instead show rare multiplicative spikes
    that decay over hours (capacity crunches). Each cloud gets an
    independent Bernoulli(``spike_prob``) spike process whose excursions
    multiply the base price by up to ``spike_mult`` and relax
    geometrically (``decay`` per step). ``anti_correlated=True`` delays
    Azure's spike stream by half the trace so the two clouds rarely
    spike together — the regime where a price-aware scheduler has
    something to win.

    Deterministic given ``seed`` (one ``RandomState``, fixed draw order);
    returns the same frame schema as :func:`generate_prices` so
    ``normalize.build_normalized_table`` and the cluster-graph env's raw
    replay both consume it unchanged.
    """
    rng = np.random.RandomState(seed)
    base = generate_prices(steps, rng)
    for i, col in enumerate(("cost_aws", "cost_azure")):
        events = rng.uniform(size=steps) < spike_prob
        magnitude = rng.uniform(1.0, spike_mult - 1.0, steps)
        if anti_correlated and i == 1:
            events = np.roll(events, steps // 2)
            magnitude = np.roll(magnitude, steps // 2)
        base[col] = base[col] * (1.0 + decaying_bursts(events, magnitude,
                                                       decay))
    return base


# Column order of a Locust --csv stats_history export (verified against the
# reference's data/local_*_load_stats_history.csv header).
LOCUST_HISTORY_COLUMNS = (
    "Timestamp", "User Count", "Type", "Name", "Requests/s", "Failures/s",
    "50%", "66%", "75%", "80%", "90%", "95%", "98%", "99%", "99.9%",
    "99.99%", "100%", "Total Request Count", "Total Failure Count",
    "Total Median Response Time", "Total Average Response Time",
    "Total Min Response Time", "Total Max Response Time",
    "Total Average Content Size",
)


def generate_load_history(
    out_path: str | Path,
    steps: int = 297,
    max_users: int = 50,
    seed: int = DEFAULT_SEED,
) -> pd.DataFrame:
    """Synthesize a Locust-style ``stats_history`` export (full schema).

    Capability parity with the reference's load-generator artifacts
    (``locustfile.py`` + ``data/local_*_load_stats_history.csv``): a user ramp
    to ``max_users``, per-user request rate ~0.5 req/s (1-3s wait between
    GETs), and response times that grow with load. Emits every column of
    Locust's ``--csv`` history export in the reference's order so the full
    data schema round-trips; deterministic given seed.
    """
    rng = np.random.RandomState(seed)
    t = np.arange(steps)
    users = np.minimum(max_users, (t // 3) * 5).astype(np.int64)
    rps = users * rng.uniform(0.4, 0.6, steps)
    base_rt = 3.0 + 0.05 * users
    avg_rt = base_rt + rng.exponential(2.0, steps)
    fail_frac = rng.uniform(0.0, 0.06, steps)
    total_requests = np.cumsum(rps).astype(np.int64)
    total_failures = np.cumsum(rps * fail_frac).astype(np.int64)
    max_rt = np.round(avg_rt * 10)
    df = pd.DataFrame(
        {
            "Timestamp": 1_765_110_856 + t,
            "User Count": users,
            "Type": "",
            "Name": "Aggregated",
            "Requests/s": rps,
            "Failures/s": rps * fail_frac,
            "Total Request Count": total_requests,
            "Total Failure Count": total_failures,
            "Total Median Response Time": np.round(avg_rt),
            "Total Average Response Time": avg_rt,
            "Total Min Response Time": avg_rt / 5,
            "Total Max Response Time": max_rt,
            "Total Average Content Size": 0.0,
        }
    )
    # Response-time percentiles fan out above the average (crudely, but the
    # monotone ordering a real export has holds), capped at the max; the
    # 100% column IS the max — the Locust invariant consumers may check.
    sub_max_pcts = LOCUST_HISTORY_COLUMNS[6:16]  # 50% .. 99.99%
    for i, pct in enumerate(sub_max_pcts):
        df[pct] = np.minimum(np.round(avg_rt * (1 + 0.4 * i)), max_rt)
    df["100%"] = max_rt
    df = df[list(LOCUST_HISTORY_COLUMNS)]
    out_path = Path(out_path)
    out_path.parent.mkdir(parents=True, exist_ok=True)
    df.to_csv(out_path, index=False)
    return df


def generate_load_histories(
    out_dir: str | Path,
    overwrite: bool = False,
    seed: int = DEFAULT_SEED,
) -> list[Path]:
    """Write ``local_{aws,azure}_load_stats_history.csv`` for both clouds.

    Completes the reference's data-directory schema
    (``/root/reference/data/`` ships a history per cloud). Per-cloud seeds
    differ so the two clouds' load shapes are not identical copies. Real
    Locust exports already present are not clobbered unless ``overwrite``.
    """
    out_dir = Path(out_dir)
    written = []
    for i, cloud in enumerate(("aws", "azure")):
        path = out_dir / f"local_{cloud}_load_stats_history.csv"
        if path.exists() and not overwrite:
            continue
        generate_load_history(path, seed=seed + i)
        written.append(path)
    return written


if __name__ == "__main__":
    from rl_scheduler_tpu.data.loader import default_data_dir
    from rl_scheduler_tpu.data.loadtest import (
        generate_load_exceptions,
        generate_load_stats,
    )

    df = generate_all(default_data_dir())
    counts = generate_load_stats(default_data_dir())
    histories = generate_load_histories(default_data_dir())
    exceptions = generate_load_exceptions(default_data_dir())
    print(f"Generated {len(df)} steps of price/latency data in {default_data_dir()}")
    print(f"Synthesized Locust exports (failures: {counts}, "
          f"histories: {len(histories)}, exceptions: {len(exceptions)})")
