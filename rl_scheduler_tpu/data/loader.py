"""Device loaders: normalized CSV table -> jnp arrays for the functional env.

Replaces the reference's per-env ``pd.read_csv`` + per-step ``.iloc`` row
access (``rl_scheduler/env/k8s_multi_cloud_env.py:54-66,118`` in the
reference) with a single host-side load into device arrays; the env core then
does O(1) gathers inside jit.
"""

from __future__ import annotations

from pathlib import Path
from typing import NamedTuple

import jax.numpy as jnp
import numpy as np
import pandas as pd

TABLE_COLUMNS = ["cost_aws", "cost_azure", "latency_aws", "latency_azure"]


def default_data_dir() -> Path:
    """<repo root>/data, resolved relative to this file."""
    return Path(__file__).resolve().parents[2] / "data"


class CloudTable(NamedTuple):
    """Normalized multi-cloud trace as device arrays.

    ``costs``/``latencies``/``cpu`` are ``[T, C]`` float32 in [0, 1], where
    ``C`` is the number of clouds (2: AWS, Azure).
    """

    costs: jnp.ndarray
    latencies: jnp.ndarray
    cpu: jnp.ndarray

    @property
    def num_steps(self) -> int:
        return self.costs.shape[0]

    @property
    def num_clouds(self) -> int:
        return self.costs.shape[1]


def _validate(df: pd.DataFrame) -> None:
    missing = [c for c in TABLE_COLUMNS if c not in df.columns]
    if missing:
        raise ValueError(f"normalized table missing columns: {missing}")
    sub = df[TABLE_COLUMNS]
    if sub.isna().any().any():
        raise ValueError("normalized table contains NaNs in cost/latency columns")
    if len(sub) < 2:
        raise ValueError("normalized table needs at least 2 rows (episode length >= 1)")
    lo, hi = float(sub.min().min()), float(sub.max().max())
    if lo < -1e-6 or hi > 1.0 + 1e-6:
        raise ValueError(f"normalized table out of [0,1] range: [{lo}, {hi}]")


def ensure_dataset(data_dir: str | Path | None = None) -> Path:
    """Regenerate the full dataset from scratch if the processed CSV is absent.

    The pipeline is fully deterministic (seeded), so a fresh checkout
    bootstraps itself to the exact table the tests and benchmarks expect.
    """
    from rl_scheduler_tpu.data.generate import generate_all
    from rl_scheduler_tpu.data.normalize import build_normalized_table

    data_dir = Path(data_dir) if data_dir is not None else default_data_dir()
    processed = data_dir / "processed" / "normalized_rl_data.csv"
    if not processed.exists():
        if not (data_dir / "real_latencies.csv").exists():
            generate_all(data_dir)
        build_normalized_table(data_dir)
    return processed


def load_table(path: str | Path | None = None) -> CloudTable:
    """Load the normalized table as a :class:`CloudTable` of device arrays."""
    if path is None:
        path = ensure_dataset()
    df = pd.read_csv(path)
    _validate(df)
    costs = df[["cost_aws", "cost_azure"]].to_numpy(np.float32)
    lats = df[["latency_aws", "latency_azure"]].to_numpy(np.float32)
    if {"cpu_aws", "cpu_azure"}.issubset(df.columns):
        cpu = df[["cpu_aws", "cpu_azure"]].fillna(0.0).to_numpy(np.float32)
    else:
        cpu = np.zeros_like(costs)
    return CloudTable(jnp.asarray(costs), jnp.asarray(lats), jnp.asarray(cpu))


def load_raw_prices(path: str | Path | None = None) -> jnp.ndarray:
    """Load UNnormalized dollar prices as ``[T, 2]`` ($/hr for aws, azure).

    The cluster-graph env (BASELINE config 5) rewards in real dollars from
    ``real_prices.csv`` (the reference synthesizes these around AWS t3.micro
    $0.0104/hr and Azure B2s $0.0208/hr, ``generate_real_pricing.py:5-12``).
    """
    if path is None:
        ensure_dataset()
        path = default_data_dir() / "real_prices.csv"
        if not path.exists():
            # ensure_dataset only guarantees the processed table; a checkout
            # that kept it but pruned the raw CSVs still needs the generator.
            from rl_scheduler_tpu.data.generate import generate_all

            generate_all(default_data_dir())
    df = pd.read_csv(path)
    prices = df[["cost_aws", "cost_azure"]].to_numpy(np.float32)
    if np.isnan(prices).any() or (prices <= 0).any():
        raise ValueError(f"raw price table at {path} has NaN/non-positive entries")
    return jnp.asarray(prices)


def load_single_cluster_trace(path: str | Path | None = None) -> jnp.ndarray:
    """Load a Locust-style load-history export as a ``[T, 3]`` feature trace.

    Features (each MinMax-normalized to [0,1]): user count, requests/sec,
    average response time. Drives the single-cluster env (BASELINE config 1).
    Synthesizes a deterministic load ramp if no export exists.
    """
    if path is None:
        path = default_data_dir() / "local_aws_load_stats_history.csv"
    path = Path(path)
    if not path.exists():
        from rl_scheduler_tpu.data.generate import generate_load_history

        generate_load_history(path)
    df = pd.read_csv(path)
    cols = {}
    for name, candidates in {
        "users": ["User Count", "users"],
        "rps": ["Requests/s", "rps"],
        "rt": ["Total Average Response Time", "Average Response Time", "avg_response_time"],
    }.items():
        col = next((c for c in candidates if c in df.columns), None)
        if col is None:
            raise ValueError(f"load history missing any of {candidates}")
        cols[name] = pd.to_numeric(df[col], errors="coerce").fillna(0.0).to_numpy(np.float32)
    feats = np.stack([cols["users"], cols["rps"], cols["rt"]], axis=1)
    lo = feats.min(axis=0, keepdims=True)
    hi = feats.max(axis=0, keepdims=True)
    span = np.where(hi - lo == 0, 1.0, hi - lo)
    return jnp.asarray((feats - lo) / span, dtype=jnp.float32)
