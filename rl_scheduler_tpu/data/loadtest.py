"""Load-test failure telemetry → fault-injection calibration.

SURVEY.md §5.3: the reference records rich failure data from its Locust
runs (2980 connection-refused on AWS, 2955 remote-disconnects on Azure —
``data/local_*_load_failures.csv``) and then never reads it. Here the same
exports calibrate the simulator's fault injection: ``failure_rate`` reads
the standard Locust stats schema ("Request Count" / "Failure Count") and
the train CLI's ``--fault-from-loadtest`` maps it onto
``EnvConfig.fault_prob``.

Note the reference's own recorded run measured a **100% failure rate**
(its kind clusters were unreachable; ``local_aws_load_stats.csv`` shows
2980/2980 failures) — calibrating from that data trains against
always-down clusters, which is faithful but useless. The synthetic
generator therefore emits partial failure fractions by default; real
Locust exports dropped into ``data/`` take precedence.
"""

from __future__ import annotations

from pathlib import Path

import numpy as np
import pandas as pd

CLOUDS = ("aws", "azure")
# Plausible defaults for the synthetic exports (per-cloud failure fraction).
SYNTH_FAILURE_FRACTIONS = {"aws": 0.032, "azure": 0.027}
SYNTH_REQUESTS = 2980  # request volume matching the reference's recorded run


def failure_rate(data_dir: str | Path | None = None) -> float | None:
    """Aggregate failure fraction across all ``local_*_load_stats.csv``.

    Sums "Failure Count" / "Request Count" over each cloud's Aggregated row.
    Returns ``None`` when no stats exports exist (callers decide whether
    that is an error or a fall-back to the configured ``fault_prob``).
    """
    if data_dir is None:
        from rl_scheduler_tpu.data.loader import default_data_dir

        data_dir = default_data_dir()
    data_dir = Path(data_dir)
    requests = failures = 0
    for cloud in CLOUDS:
        path = data_dir / f"local_{cloud}_load_stats.csv"
        if not path.exists():
            continue
        df = pd.read_csv(path)
        if df.empty:  # header-only export (run killed before first flush)
            continue
        agg = df[df["Name"] == "Aggregated"]
        row = agg.iloc[0] if len(agg) else df.iloc[-1]
        requests += int(row["Request Count"])
        failures += int(row["Failure Count"])
    if requests == 0:
        return None
    return failures / requests


def generate_load_stats(
    out_dir: str | Path,
    requests: int = SYNTH_REQUESTS,
    failure_fractions: dict | None = None,
    seed: int = 42,
    overwrite: bool = False,
) -> dict:
    """Synthesize Locust-schema stats + failures exports for both clouds.

    Writes ``local_{cloud}_load_stats.csv`` (GET + Aggregated rows, the
    column layout Locust's ``--csv`` emits) and
    ``local_{cloud}_load_failures.csv``. Deterministic given ``seed``.
    Returns ``{cloud: failure_count}`` for the clouds written.

    Existing exports are NOT clobbered unless ``overwrite=True`` — real
    Locust telemetry dropped into ``data/`` takes precedence over synthetic
    data (the RNG still draws per cloud, so which clouds already exist does
    not change what the others get).
    """
    out_dir = Path(out_dir)
    out_dir.mkdir(parents=True, exist_ok=True)
    fractions = failure_fractions or SYNTH_FAILURE_FRACTIONS
    rng = np.random.RandomState(seed)
    counts = {}
    for cloud in CLOUDS:
        fails = int(rng.binomial(requests, fractions[cloud]))
        if (out_dir / f"local_{cloud}_load_stats.csv").exists() and not overwrite:
            continue
        counts[cloud] = fails
        avg_rt = float(rng.uniform(2.5, 4.5))
        row = {
            "Type": "GET", "Name": "/",
            "Request Count": requests, "Failure Count": fails,
            "Median Response Time": round(avg_rt), "Average Response Time": avg_rt,
            "Min Response Time": avg_rt / 5, "Max Response Time": avg_rt * 150,
            "Average Content Size": 0.0,
            "Requests/s": 9.94, "Failures/s": 9.94 * fails / requests,
        }
        pcts = {p: round(avg_rt * (1 + i)) for i, p in enumerate(
            ("50%", "66%", "75%", "80%", "90%", "95%", "98%", "99%", "99.9%",
             "99.99%", "100%"))}
        stats = pd.DataFrame([
            {**row, **pcts},
            {**row, **pcts, "Type": "", "Name": "Aggregated"},
        ])
        stats.to_csv(out_dir / f"local_{cloud}_load_stats.csv", index=False)
        failures = pd.DataFrame([
            {
                "Method": "GET", "Name": "/",
                "Error": "ConnectionRefusedError(61, 'Connection refused')",
                "Occurrences": fails,
            }
        ])
        failures.to_csv(out_dir / f"local_{cloud}_load_failures.csv", index=False)
    return counts


# Header of a Locust --csv exceptions export (matches the reference's
# data/local_*_load_exceptions.csv, which are header-only: its recorded run
# raised no Python-level exceptions, only HTTP failures).
LOCUST_EXCEPTIONS_COLUMNS = ("Count", "Message", "Traceback", "Nodes")


def generate_load_exceptions(
    out_dir: str | Path,
    overwrite: bool = False,
) -> list[Path]:
    """Write header-only ``local_{cloud}_load_exceptions.csv`` per cloud.

    Locust's exceptions export records *client-side Python exceptions*
    (distinct from HTTP failures); a clean run produces just the header,
    which is exactly what the reference shipped. Emitting the empty schema
    keeps the data directory a faithful round-trip of a Locust ``--csv``
    session.
    """
    out_dir = Path(out_dir)
    out_dir.mkdir(parents=True, exist_ok=True)
    written = []
    for cloud in CLOUDS:
        path = out_dir / f"local_{cloud}_load_exceptions.csv"
        if path.exists() and not overwrite:
            continue
        pd.DataFrame(columns=list(LOCUST_EXCEPTIONS_COLUMNS)).to_csv(path, index=False)
        written.append(path)
    return written
