"""Data pipeline: synthetic trace generation, normalization, device loading."""

from rl_scheduler_tpu.data.generate import generate_prices, generate_latencies, generate_all
from rl_scheduler_tpu.data.normalize import normalize, build_normalized_table
from rl_scheduler_tpu.data.loader import (
    CloudTable,
    load_table,
    default_data_dir,
    load_single_cluster_trace,
)

__all__ = [
    "generate_prices",
    "generate_latencies",
    "generate_all",
    "normalize",
    "build_normalized_table",
    "CloudTable",
    "load_table",
    "default_data_dir",
    "load_single_cluster_trace",
]
