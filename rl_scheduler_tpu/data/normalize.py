"""MinMax normalization of raw traces into the RL table.

Capability parity with the reference normalizer (``normalize_data.py:1-31``):
concatenates prices + latencies + a CPU-load proxy (mean Locust "Average
Response Time"), MinMax-scales every column to [0, 1], and writes
``data/processed/normalized_rl_data.csv``.

Reference bug fixed here (SURVEY.md §7.0.3): the reference concatenates a
1-row CPU frame against 100-row frames, leaving ``cpu_aws``/``cpu_azure`` NaN
for rows 1-99. We broadcast the proxy to every row instead (the env never
reads these columns, but downstream loaders validate no-NaN). A
``legacy_nan_cpu=True`` flag reproduces the reference output bit-for-bit for
parity tests.
"""

from __future__ import annotations

from pathlib import Path

import numpy as np
import pandas as pd

# Mean "Average Response Time" (ms) from the reference's Locust load-test
# exports (data/local_{aws,azure}_load_stats.csv, column 6) — recorded
# measurement constants used as the CPU-load proxy, exactly as the reference
# normalizer computes them.
AWS_CPU_PROXY_MS = 2.823189363967051
AZURE_CPU_PROXY_MS = 4.402036151729363


def _minmax(df: pd.DataFrame) -> pd.DataFrame:
    """Column-wise MinMax scale to [0,1]; constant columns map to 0."""
    lo = df.min()
    hi = df.max()
    span = (hi - lo).replace(0.0, 1.0)
    out = (df - lo) / span
    return out


def cpu_proxy_from_locust(stats_csv: str | Path) -> float:
    """Mean 'Average Response Time' from a Locust stats export."""
    return float(pd.read_csv(stats_csv)[["Average Response Time"]].mean().iloc[0])


def normalize(
    raw: pd.DataFrame,
    aws_cpu: float = AWS_CPU_PROXY_MS,
    azure_cpu: float = AZURE_CPU_PROXY_MS,
    legacy_nan_cpu: bool = False,
) -> pd.DataFrame:
    """Normalize a combined raw frame into the [0,1] RL table.

    ``raw`` must have columns step, cost_aws, cost_azure, latency_aws,
    latency_azure (the output of ``generate.generate_all``).
    """
    n = len(raw)
    if legacy_nan_cpu:
        cpu = pd.DataFrame({"cpu_aws": [aws_cpu], "cpu_azure": [azure_cpu]})
    else:
        cpu = pd.DataFrame({"cpu_aws": np.full(n, aws_cpu), "cpu_azure": np.full(n, azure_cpu)})
    df = pd.concat(
        [
            raw[["step", "cost_aws", "cost_azure"]].reset_index(drop=True),
            raw[["latency_aws", "latency_azure"]].reset_index(drop=True),
            cpu,
        ],
        axis=1,
    )
    return _minmax(df)


def build_normalized_table(
    data_dir: str | Path,
    out_path: str | Path | None = None,
    legacy_nan_cpu: bool = False,
) -> pd.DataFrame:
    """Read raw traces from ``data_dir``, normalize, write the processed CSV.

    Prefers live Locust stats exports for the CPU proxy when present; falls
    back to the recorded measurement constants.
    """
    data_dir = Path(data_dir)
    raw = pd.read_csv(data_dir / "real_latencies.csv")

    aws_stats = data_dir / "local_aws_load_stats.csv"
    azure_stats = data_dir / "local_azure_load_stats.csv"
    aws_cpu = cpu_proxy_from_locust(aws_stats) if aws_stats.exists() else AWS_CPU_PROXY_MS
    azure_cpu = cpu_proxy_from_locust(azure_stats) if azure_stats.exists() else AZURE_CPU_PROXY_MS

    table = normalize(raw, aws_cpu, azure_cpu, legacy_nan_cpu=legacy_nan_cpu)

    if out_path is None:
        out_path = data_dir / "processed" / "normalized_rl_data.csv"
    out_path = Path(out_path)
    out_path.parent.mkdir(parents=True, exist_ok=True)
    table.to_csv(out_path, index=False)
    return table


if __name__ == "__main__":
    from rl_scheduler_tpu.data.loader import default_data_dir

    t = build_normalized_table(default_data_dir())
    print(f"Normalized table with {len(t)} rows written to {default_data_dir() / 'processed'}")
