"""Mesh construction helpers.

A v4-8 exposes 4 chips over ICI; tests simulate 8 CPU devices via
``--xla_force_host_platform_device_count=8``. Axis convention:
``dp`` = data parallel (env batch, ``parallel/sharding.py``),
``sp`` = sequence parallel (the structured policies' node axis via ring
attention, ``make_seq_parallel_ppo``), ``tp`` = tensor parallel (wide
MLP policy weights column/row-sharded, ``parallel/tensor_parallel.py``).
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh


def device_count() -> int:
    return len(jax.devices())


def shard_map_compat(f, mesh: Mesh, in_specs, out_specs):
    """``jax.shard_map`` with the replication check off, tolerant of the
    pre-0.5 API surface (``jax.experimental.shard_map`` with its
    ``check_rep`` spelling of the same flag). The build containers and
    the bench chips do not always run the same JAX release; tests that
    must verify sharded-path NUMERICS on both (e.g. the fused-block
    dp x sp gradient equivalence, ``tests/test_pallas_set_block.py``)
    shard through this instead of ``jax.shard_map`` directly."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
    from jax.experimental.shard_map import shard_map

    return shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                     check_rep=False)


def make_mesh(axes: dict[str, int] | None = None) -> Mesh:
    """Build a mesh from ``{axis_name: size}``; -1 means "all remaining".

    Default: all devices on one ``dp`` axis.
    """
    devices = jax.devices()
    if axes is None:
        axes = {"dp": len(devices)}
    names = list(axes)
    sizes = list(axes.values())
    if sizes.count(-1) > 1:
        raise ValueError("at most one axis size may be -1")
    known = int(np.prod([s for s in sizes if s != -1]))
    if -1 in sizes:
        if len(devices) % known:
            raise ValueError(f"{len(devices)} devices not divisible by {known}")
        sizes[sizes.index(-1)] = len(devices) // known
    total = int(np.prod(sizes))
    if total > len(devices):
        raise ValueError(f"mesh needs {total} devices, have {len(devices)}")
    mesh_devices = np.asarray(devices[:total]).reshape(sizes)
    return Mesh(mesh_devices, tuple(names))
