"""Mesh construction helpers.

A v4-8 exposes 4 chips over ICI; tests simulate 8 CPU devices via
``--xla_force_host_platform_device_count=8``. Axis convention:
``dp`` = data parallel (env batch, ``parallel/sharding.py``),
``sp`` = sequence parallel (the structured policies' node axis via ring
attention, ``make_seq_parallel_ppo``), ``tp`` = tensor parallel (wide
MLP policy weights column/row-sharded, ``parallel/tensor_parallel.py``).
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh


def device_count() -> int:
    return len(jax.devices())


def make_mesh(axes: dict[str, int] | None = None) -> Mesh:
    """Build a mesh from ``{axis_name: size}``; -1 means "all remaining".

    Default: all devices on one ``dp`` axis.
    """
    devices = jax.devices()
    if axes is None:
        axes = {"dp": len(devices)}
    names = list(axes)
    sizes = list(axes.values())
    if sizes.count(-1) > 1:
        raise ValueError("at most one axis size may be -1")
    known = int(np.prod([s for s in sizes if s != -1]))
    if -1 in sizes:
        if len(devices) % known:
            raise ValueError(f"{len(devices)} devices not divisible by {known}")
        sizes[sizes.index(-1)] = len(devices) // known
    total = int(np.prod(sizes))
    if total > len(devices):
        raise ValueError(f"mesh needs {total} devices, have {len(devices)}")
    mesh_devices = np.asarray(devices[:total]).reshape(sizes)
    return Mesh(mesh_devices, tuple(names))
