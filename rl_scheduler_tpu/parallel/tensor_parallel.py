"""Tensor parallelism: policy weights sharded over the ``tp`` mesh axis.

The reference has no model parallelism of any kind (SURVEY.md §2 #17: its
policy is RLlib's default MLP and its only scale-out is Ray rollout
actors). This module supplies the TPU-native ``tp`` axis the mesh
convention reserves (``parallel/mesh.py``): Megatron-style column/row
sharding of wide MLP torsos under ``shard_map`` —

- **column-parallel** layer: kernel ``[in, H/tp]`` per device, each member
  computing its slice of the hidden activation (activation fn is
  elementwise, so it applies locally);
- **row-parallel** layer: kernel ``[H/tp, out]`` per device, partial
  products summed with an ICI all-reduce into the replicated output.

The two collective boundary ops are the classic Megatron ``f``/``g``
functions, expressed as ``jax.custom_vjp`` so LOCAL autodiff inside
``shard_map`` produces the EXACT global gradient with no post-hoc scaling:

- :func:`copy_to_tp`: forward identity, backward ``psum`` — entering a
  column-parallel region, the replicated input's cotangent must sum each
  member's path contribution.
- :func:`reduce_from_tp`: forward ``psum``, backward identity — leaving a
  row-parallel region, the replicated output's cotangent passes straight
  to each member's partial (a raw ``psum``'s transpose is ``psum``, which
  would overcount by ``tp``).

Gradients of tp-sharded leaves are therefore exact locally, and replicated
leaves (output heads) get identical gradients on every member — so the
data-parallel ``pmean`` over ``dp`` alone is the correct full sync, see
:func:`make_tensor_parallel_ppo`. The one optimizer-side ``tp`` collective
is the global-norm grad clip (:func:`tp_clip_by_global_norm`), whose norm
psums sharded-leaf squares over ``tp`` so every member applies the same
scale.
"""

from __future__ import annotations

import dataclasses
from functools import partial as _partial
from typing import Any, Callable, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from rl_scheduler_tpu.agent.ppo import (
    PPOTrainConfig,
    RunnerState,
    make_optimizer,
    make_ppo_bundle,
)
from rl_scheduler_tpu.env.bundle import EnvBundle
from rl_scheduler_tpu.parallel.mesh import make_mesh


# ----------------------------------------------------------------- f / g ops


def copy_to_tp(x: jnp.ndarray, axis_name: str | None) -> jnp.ndarray:
    """Forward identity / backward ``psum`` over ``axis_name`` (Megatron
    ``f``): marks replicated activations entering a column-parallel region."""
    if axis_name is None:
        return x
    return _copy_to_tp(x, axis_name)


def reduce_from_tp(x: jnp.ndarray, axis_name: str | None) -> jnp.ndarray:
    """Forward ``psum`` / backward identity (Megatron ``g``): reassembles a
    row-parallel region's partial sums into the replicated output."""
    if axis_name is None:
        return x
    return _reduce_from_tp(x, axis_name)


@_partial(jax.custom_vjp, nondiff_argnums=(1,))
def _copy_to_tp(x, axis_name):
    return x


def _copy_fwd(x, axis_name):
    return x, None


def _copy_bwd(axis_name, _, g):
    return (lax.psum(g, axis_name),)


_copy_to_tp.defvjp(_copy_fwd, _copy_bwd)


@_partial(jax.custom_vjp, nondiff_argnums=(1,))
def _reduce_from_tp(x, axis_name):
    return lax.psum(x, axis_name)


def _reduce_fwd(x, axis_name):
    return lax.psum(x, axis_name), None


def _reduce_bwd(axis_name, _, g):
    return (g,)


_reduce_from_tp.defvjp(_reduce_fwd, _reduce_bwd)


# ------------------------------------------------------------------ modules


class TPMLPTorso(nn.Module):
    """MLP torso with hidden widths sharded over ``tp_axis``.

    ``hidden`` must have even length: consecutive entries form
    (column-parallel, row-parallel) pairs — the classic Megatron MLP block
    — so activations re-replicate after every pair. Layer names ``col{i}``
    / ``row{i}`` / ``row_bias{i}`` are the contract
    :func:`tp_param_spec_fn` keys off. With ``tp_axis=None`` (and
    ``tp_size=1``) this is an ordinary full-width MLP computing the exact
    same function as the sharded one given concatenated weights.
    """

    hidden: Sequence[int] = (256, 256)
    activation: str = "tanh"
    tp_axis: str | None = None
    tp_size: int = 1
    dtype: Any = None

    @nn.compact
    def __call__(self, x):
        if len(self.hidden) % 2:
            raise ValueError(
                f"TPMLPTorso needs col/row layer pairs; got odd "
                f"len(hidden)={len(self.hidden)}"
            )
        act = getattr(nn, self.activation)
        for i in range(0, len(self.hidden), 2):
            h_col, h_row = self.hidden[i], self.hidden[i + 1]
            if h_col % self.tp_size:
                raise ValueError(
                    f"hidden[{i}]={h_col} not divisible by tp={self.tp_size}"
                )
            x = copy_to_tp(x, self.tp_axis)
            x = act(
                nn.Dense(
                    h_col // self.tp_size,
                    kernel_init=nn.initializers.orthogonal(jnp.sqrt(2)),
                    dtype=self.dtype,
                    name=f"col{i // 2}",
                )(x)
            )
            partial_out = nn.Dense(
                h_row,
                use_bias=False,  # bias once, after the reduce — adding it
                # per member before psum would scale it by tp
                kernel_init=nn.initializers.orthogonal(jnp.sqrt(2)),
                dtype=self.dtype,
                name=f"row{i // 2}",
            )(x)
            out = reduce_from_tp(partial_out, self.tp_axis)
            bias = self.param(
                f"row_bias{i // 2}", nn.initializers.zeros, (h_row,), jnp.float32
            )
            x = act(out + bias.astype(out.dtype))
        return x


class TPActorCritic(nn.Module):
    """Actor-critic with tensor-parallel torsos and replicated f32 heads.

    The tp counterpart of ``models.mlp.ActorCritic`` (same separate
    actor/critic torsos, same head inits); for wide ``hidden`` the torso
    matmuls dominate, and those are what shard.
    """

    num_actions: int = 2
    hidden: Sequence[int] = (256, 256)
    activation: str = "tanh"
    tp_axis: str | None = None
    tp_size: int = 1
    dtype: Any = None

    @nn.compact
    def __call__(self, obs):
        def torso(name):
            return TPMLPTorso(
                self.hidden, self.activation, self.tp_axis, self.tp_size,
                self.dtype, name=name,
            )

        pi = torso("actor_torso")(obs)
        logits = nn.Dense(
            self.num_actions, kernel_init=nn.initializers.orthogonal(0.01),
            name="actor_head",
        )(pi.astype(jnp.float32))
        v = torso("critic_torso")(obs)
        value = nn.Dense(
            1, kernel_init=nn.initializers.orthogonal(1.0), name="critic_head"
        )(v.astype(jnp.float32))
        return logits, jnp.squeeze(value, -1)


# ---------------------------------------------------------------- sharding


def tp_param_spec_fn(tp_axis: str) -> Callable:
    """Per-leaf PartitionSpec rule for trees carrying TPMLPTorso params
    (works on the params tree AND on optimizer states mirroring it, since
    Adam's mu/nu subtrees keep the flax dict paths)."""

    def spec_for(path, leaf) -> P:
        keys = [getattr(k, "key", getattr(k, "name", "")) for k in path]
        names = [k for k in keys if isinstance(k, str)]
        layer = next((n for n in names if n.startswith(("col", "row"))), None)
        param_name = names[-1] if names else ""
        if layer is None or getattr(leaf, "ndim", 0) == 0:
            return P()
        if layer.startswith("row_bias"):
            return P()  # applied after the reduce: replicated
        if layer.startswith("col"):
            # kernel [in, H/tp] shards its OUTPUT features; bias [H/tp] too
            return P(None, tp_axis) if param_name == "kernel" else P(tp_axis)
        # row kernel [H/tp, out] shards its INPUT features (no bias)
        return P(tp_axis, None)

    return spec_for


def _spec_tree(abstract_tree, tp_axis: str):
    return jax.tree_util.tree_map_with_path(tp_param_spec_fn(tp_axis), abstract_tree)


def tp_clip_by_global_norm(
    max_norm: float, tp_axis: str, is_replicated: Any
) -> optax.GradientTransformation:
    """``optax.clip_by_global_norm`` made exact under tensor parallelism.

    Inside ``shard_map`` each tp member holds SLICES of the sharded
    kernels, so a per-member ``optax.clip_by_global_norm`` would compute a
    different (under-)norm per member and scale the replicated head leaves
    differently — silently desyncing them (the failure round 2 refused).
    The correct global norm is:

        ||g||^2 = psum_tp( sum of sharded-leaf squares )
                  + sum of replicated-leaf squares

    — sharded leaves partition the logical matrix, so their local squares
    psum to the true total; replicated leaves are identical on every
    member and count once. The resulting scale is identical on every
    member, so replicated leaves stay in lockstep.

    ``is_replicated``: pytree of bools matching the gradient tree
    (True = leaf replicated over tp), as built by
    :func:`make_tensor_parallel_ppo` from the PartitionSpec tree.
    """

    def init_fn(params):
        del params
        return optax.EmptyState()

    def update_fn(updates, state, params=None):
        del params
        sq_sharded = sum(
            jnp.sum(jnp.square(g))
            for g, rep in zip(jax.tree.leaves(updates),
                              jax.tree.leaves(is_replicated))
            if not rep
        )
        sq_replicated = sum(
            jnp.sum(jnp.square(g))
            for g, rep in zip(jax.tree.leaves(updates),
                              jax.tree.leaves(is_replicated))
            if rep
        )
        norm = jnp.sqrt(lax.psum(sq_sharded, tp_axis) + sq_replicated)
        # optax.clip_by_global_norm semantics: scale by max_norm/norm when
        # norm exceeds max_norm, identity otherwise.
        scale = jnp.minimum(1.0, max_norm / jnp.maximum(norm, 1e-16))
        return jax.tree.map(lambda g: g * scale, updates), state

    return optax.GradientTransformation(init_fn, update_fn)


def make_tp_optimizer(
    cfg: PPOTrainConfig, tp_axis: str, is_replicated: Any
) -> optax.GradientTransformation:
    """The tp counterpart of ``agent.ppo.make_optimizer``: same adam, with
    the grad clip (when configured) computed tp-aware. The optimizer STATE
    structure matches ``make_optimizer``'s chain shape, so checkpoints
    restore across both (``tp_abstract_state``)."""
    tx = optax.adam(cfg.lr, eps=1e-7)
    if cfg.max_grad_norm is not None:
        tx = optax.chain(
            tp_clip_by_global_norm(cfg.max_grad_norm, tp_axis, is_replicated), tx
        )
    return tx


def tp_tree_to_actor_critic(params: dict) -> dict:
    """Convert a TPActorCritic parameter tree (full global matrices, as
    checkpoints store them) into the ``models.mlp.ActorCritic`` layout.

    The two modules compute the identical function: a (col, row, row_bias)
    Megatron pair at tp=1 is ``act(x @ Wcol + bcol)`` then
    ``act(x @ Wrow + row_bias)`` — exactly two ``ActorCritic`` Dense
    layers. This mapping is what lets every serving backend (numpy /
    native C++ / torch / jax AOT) and the evaluator consume tp-trained
    checkpoints unchanged (VERDICT r2 item 3: tp train -> evaluate ->
    serve round-trip).
    """
    out = {}
    for name, sub in params.items():
        if not name.endswith("_torso"):
            out[name] = sub  # heads: identical layout
            continue
        pairs = sorted(
            int(k[len("col"):]) for k in sub if k.startswith("col")
        )
        torso = {}
        for i in pairs:
            torso[f"Dense_{2 * i}"] = {
                "kernel": sub[f"col{i}"]["kernel"],
                "bias": sub[f"col{i}"]["bias"],
            }
            torso[f"Dense_{2 * i + 1}"] = {
                "kernel": sub[f"row{i}"]["kernel"],
                "bias": sub[f"row_bias{i}"],
            }
        out[name] = torso
    return out


def untp_checkpoint_tree(meta: dict, tree: dict) -> dict:
    """The one checkpoint-consumer hook: convert a restored variables tree
    to ActorCritic layout IF its meta says the run was tensor-parallel,
    pass it through untouched otherwise. Shared by the evaluator and the
    scheduler extender so the conversion contract lives in one place."""
    if (meta.get("tp") or 1) > 1:
        return {"params": tp_tree_to_actor_critic(tree["params"])}
    return tree


def tp_abstract_state(bundle: EnvBundle, cfg: PPOTrainConfig) -> dict:
    """``{"params", "opt_state"}`` abstract (eval_shape) trees of a
    tp-trained run's checkpoint — the resume path's restore target
    (``agent.train_ppo``). Shapes are the GLOBAL matrices; the optimizer
    state mirrors :func:`make_tp_optimizer`'s chain structure."""
    probe = TPActorCritic(
        num_actions=bundle.num_actions, hidden=cfg.hidden,
        tp_axis=None, tp_size=1,
    )
    dummy = jnp.zeros((1, *bundle.obs_shape), jnp.float32)
    abstract_params = jax.eval_shape(
        lambda k: probe.init(k, dummy), jax.random.PRNGKey(0)
    )
    is_replicated = jax.tree.map(
        lambda s: s == P(), _spec_tree(abstract_params, "tp")
    )
    tx = make_tp_optimizer(cfg, "tp", is_replicated)
    return {
        "params": abstract_params,
        "opt_state": jax.eval_shape(tx.init, abstract_params),
    }


def make_tensor_parallel_ppo(
    bundle: EnvBundle,
    cfg: PPOTrainConfig,
    mesh: Mesh | None = None,
    dp_axis: str = "dp",
    tp_axis: str = "tp",
    net_kwargs: dict | None = None,
):
    """PPO over a ``dp x tp`` mesh: env batch over ``dp``, the
    :class:`TPActorCritic` torso weights over ``tp``.

    Envs and rollout RNG are replicated over tp (keys fold by the dp
    coordinate only — every tp member steps identical env copies and
    samples identical actions from the replicated logits); the parameter
    initialization key ALSO folds by the tp coordinate so weight shards
    are distinct slices, with replicated leaves (heads, row biases)
    re-synced to member 0's values.

    Gradient sync is ``pmean`` over ``dp`` only: the custom-vjp boundary
    ops (module docstring) make tp-sharded leaf gradients exact locally
    and replicated-leaf gradients identical across tp.
    """
    mesh = mesh or make_mesh({dp_axis: -1, tp_axis: 1})
    ndp = mesh.shape[dp_axis]
    ntp = mesh.shape[tp_axis]
    if cfg.num_envs % ndp:
        raise ValueError(f"num_envs={cfg.num_envs} not divisible by dp={ndp}")
    if cfg.minibatch_size % ndp:
        raise ValueError(
            f"minibatch_size={cfg.minibatch_size} not divisible by dp={ndp}"
        )
    local_cfg = dataclasses.replace(
        cfg, num_envs=cfg.num_envs // ndp, minibatch_size=cfg.minibatch_size // ndp
    )
    net_kwargs = dict(net_kwargs or {})
    if "dtype" not in net_kwargs and cfg.compute_dtype != "float32":
        # Honor the config knob the same way make_ppo_bundle's default
        # ActorCritic does (params stay f32; torso matmuls in bf16).
        net_kwargs["dtype"] = {"bfloat16": jnp.bfloat16}[cfg.compute_dtype]
    net = TPActorCritic(
        num_actions=bundle.num_actions, hidden=cfg.hidden,
        tp_axis=tp_axis, tp_size=ntp, **net_kwargs,
    )

    # Spec trees come from a structure probe: the UNSHARDED twin module has
    # the identical param tree structure (only leaf shapes differ), and
    # eval_shape needs no mesh because it runs no collectives.
    probe = TPActorCritic(
        num_actions=bundle.num_actions, hidden=cfg.hidden,
        tp_axis=None, tp_size=1, **(net_kwargs or {}),
    )
    dummy = jnp.zeros((1, *bundle.obs_shape), jnp.float32)
    abstract_params = jax.eval_shape(
        lambda k: probe.init(k, dummy), jax.random.PRNGKey(0)
    )
    param_specs = _spec_tree(abstract_params, tp_axis)
    is_replicated = jax.tree.map(lambda s: s == P(), param_specs)
    # Grad clipping (when configured) must see the GLOBAL norm: sharded
    # leaves psum over tp, replicated leaves count once (round 2 refused
    # this combination; tp_clip_by_global_norm makes it exact).
    tx = (
        make_tp_optimizer(local_cfg, tp_axis, is_replicated)
        if ntp > 1
        else make_optimizer(local_cfg)
    )
    init_fn, update_fn, net = make_ppo_bundle(
        bundle, local_cfg, net=net, axis_name=dp_axis, tx=tx
    )
    abstract_opt = jax.eval_shape(tx.init, abstract_params)
    opt_specs = _spec_tree(abstract_opt, tp_axis)
    specs = RunnerState(
        params=param_specs,
        opt_state=opt_specs,
        env_state=P(dp_axis),
        obs=P(dp_axis),
        key=P(dp_axis),
        ep_return=P(dp_axis),
        update_idx=P(),
    )

    def local_init(key):
        dp_key = jax.random.fold_in(key, lax.axis_index(dp_axis))
        r = init_fn(dp_key)
        # Re-init params with a tp-distinct key (shards must be DIFFERENT
        # slices of the logical matrix, not tp copies of one block), then
        # broadcast member 0's values back onto the replicated leaves.
        tp_key = jax.random.fold_in(
            jax.random.fold_in(key, 7), lax.axis_index(tp_axis)
        )
        params = net.init(tp_key, dummy)

        def sync_replicated(leaf, rep):
            # graftlint: disable=GL003 -- rep is a host-side Python bool leaf of the is_replicated tree (tree.map metadata), never a tracer
            if not rep:
                return leaf
            return lax.index_in_dim(
                lax.all_gather(leaf, tp_axis), 0, keepdims=False
            )

        params = jax.tree.map(sync_replicated, params, is_replicated)
        return r._replace(
            params=params, opt_state=tx.init(params), key=r.key[None]
        )

    def local_update(runner: RunnerState):
        r = runner._replace(key=runner.key[0])
        r, metrics = update_fn(r)
        return r._replace(key=r.key[None]), metrics

    sharded_init = jax.shard_map(
        local_init, mesh=mesh, in_specs=P(), out_specs=specs, check_vma=False
    )
    sharded_update = jax.shard_map(
        local_update, mesh=mesh, in_specs=(specs,), out_specs=(specs, P()),
        check_vma=False,
    )
    return sharded_init, sharded_update, net
