"""Multi-host (DCN) initialization for ``jax.distributed``.

The reference's only distribution story is Ray actors on one machine
(SURVEY.md §5.8). Here single-host multi-chip needs nothing (XLA sees all
local chips over ICI); spanning hosts — a v4 pod slice, or CPU fleets —
goes through ``jax.distributed.initialize`` so every host contributes its
local devices to one global mesh and collectives route ICI-first,
DCN-across-hosts. Meshes built with :func:`~rl_scheduler_tpu.parallel.mesh.make_mesh`
then transparently span hosts (``jax.devices()`` becomes global).

Call :func:`maybe_initialize_distributed` once at process start. It is a
no-op (returns ``False``) unless multi-host coordinates are provided
explicitly or via environment — safe to call unconditionally from every
entry point.
"""

from __future__ import annotations

import logging
import os

import jax

logger = logging.getLogger(__name__)

_ENV_COORDINATOR = "RL_SCHED_COORDINATOR"   # host:port of process 0
_ENV_NUM_PROCS = "RL_SCHED_NUM_PROCESSES"
_ENV_PROC_ID = "RL_SCHED_PROCESS_ID"


def maybe_initialize_distributed(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> bool:
    """Initialize ``jax.distributed`` when multi-host coordinates exist.

    Resolution order: explicit arguments, then environment variables, then
    JAX's own auto-detection on managed TPU pods (where
    ``jax.distributed.initialize()`` needs no arguments — detected via
    the standard TPU pod metadata envs). Returns ``True`` iff
    initialization ran.

    The environment contract (set all three on EVERY process):

    - ``RL_SCHED_COORDINATOR`` — ``host:port`` of process 0's coordinator
      service (any free port on the rank-0 host; the other processes
      connect to it over DCN).
    - ``RL_SCHED_NUM_PROCESSES`` — total process (host) count.
    - ``RL_SCHED_PROCESS_ID`` — this process's rank, ``0 .. N-1``,
      unique per process.

    Example — a 4-host launch (one line per host)::

        RL_SCHED_COORDINATOR=10.0.0.1:8476 RL_SCHED_NUM_PROCESSES=4 \
            RL_SCHED_PROCESS_ID=0 python -m rl_scheduler_tpu.agent.train_ppo ...
        RL_SCHED_COORDINATOR=10.0.0.1:8476 RL_SCHED_NUM_PROCESSES=4 \
            RL_SCHED_PROCESS_ID=1 python -m rl_scheduler_tpu.agent.train_ppo ...
        # ... ranks 2 and 3 likewise

    After initialization ``jax.devices()`` is GLOBAL (all hosts' chips),
    so a ``make_mesh({"dp": -1})`` spans the fleet and collectives route
    ICI within a host, DCN across. On managed TPU pod slices none of this
    is needed: the TPU metadata envs (``TPU_WORKER_HOSTNAMES`` with >1
    worker, or ``MEGASCALE_COORDINATOR_ADDRESS``) trigger argument-less
    auto-init. ``tests/test_multihost.py`` exercises both 2x4 and 4x2
    process/device topologies through exactly this contract.
    """
    coordinator_address = coordinator_address or os.environ.get(_ENV_COORDINATOR)
    if num_processes is None and os.environ.get(_ENV_NUM_PROCS):
        num_processes = int(os.environ[_ENV_NUM_PROCS])
    if process_id is None and os.environ.get(_ENV_PROC_ID):
        process_id = int(os.environ[_ENV_PROC_ID])

    if coordinator_address is None:
        # Managed TPU pods export their own topology envs and need no
        # explicit coordinates. Require MORE THAN ONE worker hostname:
        # single-chip runtimes (e.g. a tunneled dev chip) also export
        # TPU_WORKER_HOSTNAMES, and initialize() would fail there.
        hostnames = os.environ.get("TPU_WORKER_HOSTNAMES", "")
        multihost_pod = len([h for h in hostnames.split(",") if h.strip()]) > 1
        if multihost_pod or os.environ.get("MEGASCALE_COORDINATOR_ADDRESS"):
            try:
                jax.distributed.initialize()
            except (ValueError, RuntimeError) as e:
                # Auto-detection is best-effort; a single-host run must
                # never die on it.
                logger.warning("jax.distributed auto-init skipped: %s", e)
                return False
            logger.info("jax.distributed initialized from TPU pod metadata")
            return True
        return False

    if num_processes is None or process_id is None:
        raise ValueError(
            f"{_ENV_COORDINATOR} is set but the coordinate triple is "
            f"incomplete: also set {_ENV_NUM_PROCS} and {_ENV_PROC_ID} "
            "(or pass num_processes/process_id explicitly)"
        )
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    logger.info(
        "jax.distributed initialized: process %s/%s via %s",
        process_id, num_processes, coordinator_address,
    )
    return True
