"""Ring attention: sequence-parallel self-attention over a mesh axis.

The reference has no sequence models at all (SURVEY.md §5.7), but this
framework's set-transformer policy (BASELINE config 4) attends over
pod/node sets, and at datacenter scale a "set" is tens of thousands of
nodes — too large for one chip's VMEM-friendly attention. The TPU-native
answer is ring attention: shard the node/sequence axis over a mesh axis,
keep Q local, and rotate K/V blocks around the ring with
``lax.ppermute`` (ICI neighbor exchange) while accumulating the softmax
online (flash-attention style running max/sum), so the full quadratic
attention is computed exactly — never materializing the global
``[N, N]`` score matrix on any chip — with communication overlapping
compute around the ring.

Layouts follow flax: ``[..., seq, heads, head_dim]``. All math runs in
f32 accumulation regardless of input dtype (bf16-safe).

Use :func:`make_flax_attention_fn` to drop this into
``nn.MultiHeadDotProductAttention(attention_fn=...)`` — the set
transformer threads it through via its ``axis_name`` field.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax

if hasattr(lax, "pcast"):
    def _to_varying(x: jnp.ndarray, axis_name: str) -> jnp.ndarray:
        return lax.pcast(x, axis_name, to="varying")
elif hasattr(lax, "pvary"):  # JAX < 0.9: pvary is the only spelling
    def _to_varying(x: jnp.ndarray, axis_name: str) -> jnp.ndarray:
        return lax.pvary(x, axis_name)
else:  # pre-varying-check JAX: everything is already "varying"
    def _to_varying(x: jnp.ndarray, axis_name: str) -> jnp.ndarray:
        return x

if hasattr(lax, "axis_size"):
    _axis_size = lax.axis_size
else:  # pre-0.5 spelling: the trace-time axis env carries the size
    def _axis_size(axis_name: str) -> int:
        import jax.core as core

        size = core.axis_frame(axis_name)
        # axis_frame returned the frame object in some 0.4.x point
        # releases and the bare size in others.
        return getattr(size, "size", size)


def _dense_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                     scale: float) -> jnp.ndarray:
    scores = jnp.einsum("...qhd,...khd->...hqk", q, k) * scale
    probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1)
    return jnp.einsum("...hqk,...khd->...qhd", probs.astype(v.dtype), v)


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: str | None = None,
) -> jnp.ndarray:
    """Exact global softmax attention with the sequence axis sharded.

    ``q``/``k``/``v``: local shards ``[..., n_local, H, D]`` inside a
    ``shard_map`` whose mesh has ``axis_name``; every device ends with the
    attention output for ITS queries against the GLOBAL key/value set.
    With ``axis_name=None`` (or ring size 1) this is plain dense attention
    — the single-chip fallback, numerically identical.
    """
    scale = 1.0 / (q.shape[-1] ** 0.5)
    if axis_name is None:
        return _dense_attention(q, k, v, scale)
    ring = _axis_size(axis_name)
    if ring == 1:
        return _dense_attention(q, k, v, scale)

    f32 = jnp.float32
    # Running accumulators (flash-attention online softmax), f32:
    #   m [..., H, n_q]      running row max
    #   l [..., H, n_q]      running sum of exp(scores - m)
    #   acc [..., n_q, H, D] running weighted values
    batch_hq = (*q.shape[:-3], q.shape[-2], q.shape[-3])
    # The accumulators are constant-initialized but become device-varying
    # inside the ring loop; shard_map's varying-axis check requires the
    # fori_loop carry to be varying from the start.
    m = _to_varying(jnp.full(batch_hq, -jnp.inf, f32), axis_name)
    l = _to_varying(jnp.zeros(batch_hq, f32), axis_name)
    acc = _to_varying(jnp.zeros(q.shape, f32), axis_name)
    qf = q.astype(f32)

    perm = [(i, (i + 1) % ring) for i in range(ring)]

    def ring_step(_, carry):
        k, v, m, l, acc = carry
        scores = jnp.einsum("...qhd,...khd->...hqk", qf, k.astype(f32)) * scale
        m_new = jnp.maximum(m, scores.max(axis=-1))
        correction = jnp.exp(m - m_new)
        p = jnp.exp(scores - m_new[..., None])
        l = l * correction + p.sum(axis=-1)
        weighted = jnp.einsum("...hqk,...khd->...qhd", p, v.astype(f32))
        corr_qh = jnp.swapaxes(correction, -2, -1)[..., None]  # [..., n_q, H, 1]
        acc = acc * corr_qh + weighted
        # Rotate K/V one hop around the ring (ICI neighbor exchange). The
        # final rotation returns each block to its owner — one redundant
        # hop in exchange for an O(1)-size program: fori_loop keeps the
        # HLO constant in ring size (a pod-scale ring would otherwise
        # unroll hundreds of step bodies per attention call).
        k = lax.ppermute(k, axis_name, perm)
        v = lax.ppermute(v, axis_name, perm)
        return k, v, m_new, l, acc

    _, _, _, l, acc = lax.fori_loop(0, ring, ring_step, (k, v, m, l, acc))

    out = acc / jnp.swapaxes(l, -2, -1)[..., None]
    return out.astype(q.dtype)


def make_flax_attention_fn(axis_name: str | None) -> Callable:
    """An ``attention_fn`` for ``nn.MultiHeadDotProductAttention``.

    Supports the set-policy use case: no bias/mask (sets are unpadded
    here), no attention dropout. Anything else is a loud error rather
    than silently-wrong attention.
    """

    def attention_fn(query, key, value, bias=None, mask=None,
                     dropout_rate: float = 0.0, **_ignored):
        if bias is not None or mask is not None:
            raise NotImplementedError(
                "ring attention_fn does not support bias/mask"
            )
        if dropout_rate:
            raise NotImplementedError(
                "ring attention_fn does not support attention dropout"
            )
        return ring_attention(query, key, value, axis_name=axis_name)

    return attention_fn
