"""Data-parallel PPO over a device mesh via ``shard_map``.

Replaces the reference's Ray rollout-worker data parallelism
(``train_final.py:9``: 6 worker processes x 4 envs, object-store transfer)
with SPMD: each device runs the full fused rollout+update on its local env
shard, and gradients pmean-reduce over the ``dp`` mesh axis (ICI
all-reduce) inside every SGD minibatch — the same math RLlib does on the
driver, without the process boundary.

Layout:
- ``params`` / ``opt_state`` / ``update_idx``: replicated.
- ``env_state`` / ``obs`` / ``ep_return``: sharded over ``dp`` (leading
  env axis).
- ``key``: per-device (folded with the device's axis index at init),
  carried with a leading device axis so specs stay uniform.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from rl_scheduler_tpu.agent.ppo import (
    PPOTrainConfig,
    RunnerState,
    make_ppo,
    make_ppo_bundle,
)
from rl_scheduler_tpu.env import core as env_core
from rl_scheduler_tpu.env.bundle import EnvBundle, multi_cloud_bundle
from rl_scheduler_tpu.parallel.mesh import make_mesh


def _runner_specs(axis: str) -> RunnerState:
    """PartitionSpec pytree-prefix for RunnerState.

    ``collect_params`` (graftpipe's stale behavior-params slot,
    ``PPOTrainConfig.overlap_collect``) replicates like ``params``; with
    overlap off the slot is ``None`` — an empty pytree node the replicated
    spec matches vacuously, so the unpipelined layout is untouched.
    """
    return RunnerState(
        params=P(),
        opt_state=P(),
        env_state=P(axis),
        obs=P(axis),
        key=P(axis),
        ep_return=P(axis),
        update_idx=P(),
        collect_params=P(),
    )


def make_data_parallel_ppo_bundle(
    bundle: EnvBundle,
    cfg: PPOTrainConfig,
    mesh: Mesh | None = None,
    axis: str = "dp",
    net=None,
    sp_axis: str | None = None,
):
    """Build ``(init_fn, update_fn, net)`` sharded over ``mesh[axis]`` for
    ANY :class:`EnvBundle` (the generalization of :func:`make_data_parallel_ppo`
    that BASELINE configs 4-5 need — set-transformer and GNN policies train
    data-parallel through this).

    ``cfg.num_envs`` is the GLOBAL env count; it must divide evenly over the
    mesh axis. The returned functions take/return a global ``RunnerState``
    whose batch leaves are sharded over ``axis`` — call them under ``jax.jit``
    as usual; XLA lays the collectives on ICI.

    ``sp_axis``: name of an additional SEQUENCE-PARALLEL mesh axis sharding
    the policy's node axis (see :func:`make_seq_parallel_ppo`, which fills
    it in). Env batch leaves stay replicated over it.
    """
    mesh = mesh or make_mesh({axis: -1})
    ndev = mesh.shape[axis]
    if cfg.num_envs % ndev:
        raise ValueError(f"num_envs={cfg.num_envs} not divisible by {ndev} devices")
    if cfg.minibatch_size % ndev == 0:
        local_mb = cfg.minibatch_size // ndev
    else:
        raise ValueError(
            f"minibatch_size={cfg.minibatch_size} not divisible by {ndev} devices"
        )
    local_cfg = dataclasses.replace(
        cfg, num_envs=cfg.num_envs // ndev, minibatch_size=local_mb
    )
    local_init, local_update, specs, net = make_local_ppo(
        bundle, local_cfg, axis, net=net, sp_axis=sp_axis
    )
    sharded_init = jax.shard_map(
        local_init, mesh=mesh, in_specs=P(), out_specs=specs, check_vma=False
    )
    sharded_update = jax.shard_map(
        local_update,
        mesh=mesh,
        in_specs=(specs,),
        out_specs=(specs, P()),
        check_vma=False,
    )
    return sharded_init, sharded_update, net


def make_local_ppo(
    bundle: EnvBundle,
    local_cfg: PPOTrainConfig,
    axis: str = "dp",
    net=None,
    sp_axis: str | None = None,
):
    """The per-member ``(local_init, local_update, specs, net)`` that
    :func:`make_data_parallel_ppo_bundle` wraps in ``jax.shard_map`` —
    exposed so version-compat tests can wrap the SAME functions through
    ``parallel/mesh.shard_map_compat`` on older-JAX containers instead of
    re-deriving them (``local_cfg`` is already the per-member config).
    """
    # Gradient/metric sync spans every parallel axis: dp shards the batch,
    # sp (when present) shards the policy's node compute — pmean over both
    # is the exact global gradient (derivation at make_seq_parallel_ppo).
    axis_name = axis if sp_axis is None else (axis, sp_axis)
    init_fn, update_fn, net = make_ppo_bundle(
        bundle, local_cfg, net=net, axis_name=axis_name
    )
    specs = _runner_specs(axis)

    def local_init(key):
        # Fold by the dp coordinate only: each dp shard gets distinct env
        # resets/rollout RNG, while sp members (which must step identical
        # replicated envs) share the stream.
        dp_key = jax.random.fold_in(key, jax.lax.axis_index(axis))
        r = init_fn(dp_key)
        # The replicated leaves (params, optimizer state, graftpipe's
        # collect_params slot) must be IDENTICAL on every member, so they
        # come from the UNFOLDED key: the folded init above seeds each
        # member with different weights, which pmean'd-gradient training
        # never re-syncs — every member would train its own divergent
        # replica while the layout claims replication (the tp path's
        # sync_replicated broadcast exists for exactly this; XLA dead-
        # code-eliminates the unused halves of the two init calls).
        shared = init_fn(key)
        r = r._replace(params=shared.params, opt_state=shared.opt_state,
                       collect_params=shared.collect_params)
        return r._replace(key=r.key[None])  # leading device axis

    def local_update(runner: RunnerState):
        r = runner._replace(key=runner.key[0])
        r, metrics = update_fn(r)
        return r._replace(key=r.key[None]), metrics

    return local_init, local_update, specs, net


def make_data_parallel_ppo(
    env_params: env_core.EnvParams,
    cfg: PPOTrainConfig,
    mesh: Mesh | None = None,
    axis: str = "dp",
    net=None,
):
    """:func:`make_data_parallel_ppo_bundle` specialized to the flagship
    multi-cloud env."""
    return make_data_parallel_ppo_bundle(
        multi_cloud_bundle(env_params), cfg, mesh, axis, net
    )


class SeqParallelNet:
    """Node-axis-sharded wrapper around a structured policy (duck-typed
    flax surface: ``init``/``apply``), used INSIDE ``shard_map``.

    The observation arrives replicated over the ``sp`` axis as
    ``[B, N, feat]``; each sp member slices ITS node block, runs the inner
    policy (built with ``axis_name=sp``, so attention is ring attention
    over ICI and the value pool pmeans to the global mean), and
    all-gathers the per-node logits back to the full ``[B, N]`` — so the
    trainer around it (action sampling, PPO loss) sees exactly the
    single-chip interface. Parameter shapes are identical to the unsharded
    module (ring attention does not change them), so checkpoints are
    interchangeable.
    """

    def __init__(self, inner, sp_axis: str, sp_size: int):
        self.inner = inner
        self.sp_axis = sp_axis
        self.sp_size = sp_size

    def _local_nodes(self, obs):
        n = obs.shape[-2]
        if n % self.sp_size:
            raise ValueError(
                f"node axis {n} not divisible by sp={self.sp_size}"
            )
        n_local = n // self.sp_size
        idx = lax.axis_index(self.sp_axis)
        return lax.dynamic_slice_in_dim(obs, idx * n_local, n_local, axis=-2)

    def init(self, key, dummy_obs):
        return self.inner.init(key, self._local_nodes(dummy_obs))

    def apply(self, params, obs):
        logits_local, value = self.inner.apply(params, self._local_nodes(obs))
        logits = lax.all_gather(
            logits_local, self.sp_axis, axis=logits_local.ndim - 1, tiled=True
        )
        return logits, value


def make_seq_parallel_ppo(
    bundle: EnvBundle,
    cfg: PPOTrainConfig,
    net,
    mesh: Mesh | None = None,
    dp_axis: str = "dp",
    sp_axis: str = "sp",
):
    """PPO over a ``dp x sp`` mesh: env batch sharded over ``dp``, the
    policy's NODE axis sharded over ``sp`` (sequence/context parallelism —
    ring attention over ICI, ``parallel/ring_attention.py``).

    ``net`` must be the inner structured policy constructed with
    ``axis_name=sp_axis`` (e.g. ``SetTransformerPolicy(axis_name="sp")``).
    Envs are replicated over sp (every sp member steps identical copies —
    RNG folds by the dp coordinate only), so only the policy forward/backward
    communicates over sp.

    Gradient sync is ``pmean`` over BOTH axes, which is exact:

    - The local loss is replicated over sp (logits all-gathered, value
      pmean-pooled), so every member's backward starts from the same
      cotangent.
    - Params reached through node-sharded compute (embed, attention,
      pointer scores): the all-gather/pmean transposes hand each member
      ``sp`` times its shard's true cotangent, and pmean's ``1/sp``
      cancels that into the exact sum over shards.
    - Params reached through sp-replicated compute (the value head):
      every member computes the full true gradient, which pmean preserves.
    """
    mesh = mesh or make_mesh({dp_axis: -1, sp_axis: 1})
    wrapped = SeqParallelNet(net, sp_axis, mesh.shape[sp_axis])
    return make_data_parallel_ppo_bundle(
        bundle, cfg, mesh, dp_axis, net=wrapped, sp_axis=sp_axis
    )


def dp_ppo_train(
    env_params: env_core.EnvParams,
    cfg: PPOTrainConfig,
    num_iterations: int,
    mesh: Mesh | None = None,
    seed: int = 0,
    log_fn=None,
):
    """Host loop for the data-parallel path (mirrors ``agent.ppo.ppo_train``).

    Metrics follow the GL009 discipline: device results queue during the
    loop and ONE batched ``jax.device_get`` fetches them all at the end —
    the demo loop must not re-teach the per-iteration-sync pattern the real
    loop (``agent/loop.py``) batches away. ``log_fn`` therefore fires after
    the loop finishes, which is fine for the tests/demos this serves (the
    production path with live logging is ``ppo_train(mesh=...)``).
    """
    init_fn, update_fn, _ = make_data_parallel_ppo(env_params, cfg, mesh)
    runner = jax.jit(init_fn)(jax.random.PRNGKey(seed))
    update = jax.jit(update_fn, donate_argnums=0)
    pending = []
    for _ in range(num_iterations):
        runner, metrics = update(runner)
        pending.append(metrics)
    history = [{k: float(v) for k, v in row.items()}
               for row in jax.device_get(pending)]
    if log_fn is not None:
        for i, row in enumerate(history):
            log_fn(i, row)
    return runner, history
