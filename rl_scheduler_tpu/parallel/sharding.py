"""Data-parallel PPO over a device mesh via ``shard_map``.

Replaces the reference's Ray rollout-worker data parallelism
(``train_final.py:9``: 6 worker processes x 4 envs, object-store transfer)
with SPMD: each device runs the full fused rollout+update on its local env
shard, and gradients pmean-reduce over the ``dp`` mesh axis (ICI
all-reduce) inside every SGD minibatch — the same math RLlib does on the
driver, without the process boundary.

Layout:
- ``params`` / ``opt_state`` / ``update_idx``: replicated.
- ``env_state`` / ``obs`` / ``ep_return``: sharded over ``dp`` (leading
  env axis).
- ``key``: per-device (folded with the device's axis index at init),
  carried with a leading device axis so specs stay uniform.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
from jax.sharding import Mesh, PartitionSpec as P

from rl_scheduler_tpu.agent.ppo import PPOTrainConfig, RunnerState, make_ppo
from rl_scheduler_tpu.env import core as env_core
from rl_scheduler_tpu.parallel.mesh import make_mesh


def _runner_specs(axis: str) -> RunnerState:
    """PartitionSpec pytree-prefix for RunnerState."""
    return RunnerState(
        params=P(),
        opt_state=P(),
        env_state=P(axis),
        obs=P(axis),
        key=P(axis),
        ep_return=P(axis),
        update_idx=P(),
    )


def make_data_parallel_ppo(
    env_params: env_core.EnvParams,
    cfg: PPOTrainConfig,
    mesh: Mesh | None = None,
    axis: str = "dp",
    net=None,
):
    """Build ``(init_fn, update_fn, net)`` sharded over ``mesh[axis]``.

    ``cfg.num_envs`` is the GLOBAL env count; it must divide evenly over the
    mesh axis. The returned functions take/return a global ``RunnerState``
    whose batch leaves are sharded over ``axis`` — call them under ``jax.jit``
    as usual; XLA lays the collectives on ICI.
    """
    mesh = mesh or make_mesh({axis: -1})
    ndev = mesh.shape[axis]
    if cfg.num_envs % ndev:
        raise ValueError(f"num_envs={cfg.num_envs} not divisible by {ndev} devices")
    if cfg.minibatch_size % ndev == 0:
        local_mb = cfg.minibatch_size // ndev
    else:
        raise ValueError(
            f"minibatch_size={cfg.minibatch_size} not divisible by {ndev} devices"
        )
    local_cfg = dataclasses.replace(
        cfg, num_envs=cfg.num_envs // ndev, minibatch_size=local_mb
    )
    init_fn, update_fn, net = make_ppo(env_params, local_cfg, net=net, axis_name=axis)
    specs = _runner_specs(axis)

    def local_init(key):
        key = jax.random.fold_in(key, jax.lax.axis_index(axis))
        r = init_fn(key)
        return r._replace(key=r.key[None])  # leading device axis

    def local_update(runner: RunnerState):
        r = runner._replace(key=runner.key[0])
        r, metrics = update_fn(r)
        return r._replace(key=r.key[None]), metrics

    sharded_init = jax.shard_map(
        local_init, mesh=mesh, in_specs=P(), out_specs=specs, check_vma=False
    )
    sharded_update = jax.shard_map(
        local_update,
        mesh=mesh,
        in_specs=(specs,),
        out_specs=(specs, P()),
        check_vma=False,
    )
    return sharded_init, sharded_update, net


def dp_ppo_train(
    env_params: env_core.EnvParams,
    cfg: PPOTrainConfig,
    num_iterations: int,
    mesh: Mesh | None = None,
    seed: int = 0,
    log_fn=None,
):
    """Host loop for the data-parallel path (mirrors ``agent.ppo.ppo_train``)."""
    init_fn, update_fn, _ = make_data_parallel_ppo(env_params, cfg, mesh)
    runner = jax.jit(init_fn)(jax.random.PRNGKey(seed))
    update = jax.jit(update_fn, donate_argnums=0)
    history = []
    for i in range(num_iterations):
        runner, metrics = update(runner)
        metrics = {k: float(v) for k, v in metrics.items()}
        history.append(metrics)
        if log_fn is not None:
            log_fn(i, metrics)
    return runner, history
