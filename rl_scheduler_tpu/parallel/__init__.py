"""Device-mesh parallelism: mesh construction, sharded training, multi-host.

The reference's only scale-out is Ray rollout-worker actors over gRPC
(SURVEY.md §2 #17-18). Here scale-out is SPMD over a ``jax.sharding.Mesh``:
the env batch shards over the ``dp`` axis, gradients all-reduce over ICI via
``pmean`` inside ``shard_map``, and larger policies shard their weights over
a ``tp`` axis. Multi-host (DCN) growth goes through ``jax.distributed``
(``distributed.py``).
"""

from rl_scheduler_tpu.parallel.mesh import make_mesh, device_count
from rl_scheduler_tpu.parallel.sharding import (
    make_data_parallel_ppo,
    make_data_parallel_ppo_bundle,
    make_seq_parallel_ppo,
)
from rl_scheduler_tpu.parallel.ring_attention import (
    ring_attention,
    make_flax_attention_fn,
)
from rl_scheduler_tpu.parallel.tensor_parallel import (
    TPActorCritic,
    make_tensor_parallel_ppo,
)
from rl_scheduler_tpu.parallel.distributed import maybe_initialize_distributed

__all__ = [
    "make_mesh",
    "device_count",
    "make_data_parallel_ppo",
    "make_data_parallel_ppo_bundle",
    "make_seq_parallel_ppo",
    "make_tensor_parallel_ppo",
    "TPActorCritic",
    "ring_attention",
    "make_flax_attention_fn",
    "maybe_initialize_distributed",
]
