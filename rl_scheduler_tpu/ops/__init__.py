"""Core RL ops: advantage estimation, returns, losses. All scan/jit-native."""

from rl_scheduler_tpu.ops.gae import gae, discounted_returns
from rl_scheduler_tpu.ops.losses import ppo_loss, dqn_loss, PPOLossConfig

__all__ = ["gae", "discounted_returns", "ppo_loss", "dqn_loss", "PPOLossConfig"]
